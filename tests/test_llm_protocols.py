"""LLM protocol layer tests: SSE, aggregators, tokenizer streaming, stop
jail, preprocessor/backend pipeline (modeled on the reference's
lib/llm/tests/{aggregators,preprocessor,tokenizers}.rs)."""

import pytest

from dynamo_tpu.llm.backend import Backend, StopJail
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.tokenizer import ByteTokenizer, DecodeStream
from dynamo_tpu.protocols.aggregator import (
    aggregate_chat_chunks,
    aggregate_completion_chunks,
)
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    RequestError,
    chat_chunk,
)
from dynamo_tpu.protocols.sse import (
    SseParser,
    encode_data,
    encode_done,
    encode_event,
    parse_sse_stream,
)
from dynamo_tpu.runtime import AsyncEngine, Context, collect, link


# ---------------- SSE ----------------


def test_sse_roundtrip():
    raw = encode_data({"x": 1}) + encode_event("error", {"msg": "boom"}) + encode_done()
    events = parse_sse_stream(raw)
    assert events[0].json() == {"x": 1}
    assert events[1].event == "error" and events[1].json() == {"msg": "boom"}
    assert events[2].is_done()


def test_sse_incremental_split_feed():
    raw = encode_data({"long": "x" * 100})
    p = SseParser()
    events = []
    for i in range(0, len(raw), 7):
        events.extend(p.feed(raw[i : i + 7]))
    assert len(events) == 1 and events[0].json()["long"] == "x" * 100


# ---------------- aggregators ----------------


def test_chat_aggregation():
    chunks = [
        chat_chunk("id1", "m", {"role": "assistant", "content": "Hel"}),
        chat_chunk("id1", "m", {"content": "lo"}),
        chat_chunk("id1", "m", {}, finish_reason="stop"),
    ]
    full = aggregate_chat_chunks(chunks)
    assert full["object"] == "chat.completion"
    assert full["choices"][0]["message"]["content"] == "Hello"
    assert full["choices"][0]["finish_reason"] == "stop"


def test_completion_aggregation():
    from dynamo_tpu.protocols.openai import completion_chunk

    chunks = [
        completion_chunk("c1", "m", "a"),
        completion_chunk("c1", "m", "b", finish_reason="length"),
    ]
    full = aggregate_completion_chunks(chunks)
    assert full["choices"][0]["text"] == "ab"
    assert full["choices"][0]["finish_reason"] == "length"


def test_tool_call_merging():
    chunks = [
        chat_chunk("i", "m", {"tool_calls": [{"index": 0, "id": "call_1",
                   "function": {"name": "get_w", "arguments": '{"a"'}}]}),
        chat_chunk("i", "m", {"tool_calls": [{"index": 0,
                   "function": {"arguments": ': 1}'}}]}),
        chat_chunk("i", "m", {}, finish_reason="tool_calls"),
    ]
    full = aggregate_chat_chunks(chunks)
    tc = full["choices"][0]["message"]["tool_calls"][0]
    assert tc["id"] == "call_1"
    assert tc["function"]["name"] == "get_w"
    assert tc["function"]["arguments"] == '{"a": 1}'


# ---------------- request parsing ----------------


def test_chat_request_parsing_and_validation():
    req = ChatCompletionRequest.from_dict(
        {
            "model": "llama",
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 7,
            "temperature": 0.5,
            "stop": "END",
            "nvext": {"ignore_eos": True, "annotations": ["token_ids"]},
        }
    )
    assert req.stops.max_tokens == 7
    assert req.stops.stop == ["END"]
    assert req.stops.ignore_eos is True
    assert req.sampling.temperature == 0.5
    with pytest.raises(RequestError):
        ChatCompletionRequest.from_dict({"model": "m", "messages": []})
    with pytest.raises(RequestError):
        ChatCompletionRequest.from_dict({"messages": [{"role": "user"}]})


def test_preprocessed_request_roundtrip():
    pre = PreprocessedRequest(token_ids=[1, 2, 3], model="m")
    pre.stop_conditions.max_tokens = 5
    again = PreprocessedRequest.from_dict(pre.to_dict())
    assert again.token_ids == [1, 2, 3]
    assert again.stop_conditions.max_tokens == 5


# ---------------- incremental detokenization ----------------


def test_decode_stream_multibyte_utf8():
    tok = ByteTokenizer()
    # snowman is 3 bytes: e2 98 83
    ids = tok.encode("a☃b")
    ds = DecodeStream(tok)
    pieces = [ds.step(i) for i in ids]
    text = "".join(p for p in pieces if p)
    tail = ds.flush()
    assert text + (tail or "") == "a☃b"
    # intermediate steps never emitted replacement chars
    assert all("�" not in p for p in pieces if p)


def test_stop_jail_partial_and_full_match():
    jail = StopJail(["STOP"])
    emit, hit = jail.push("hello S")
    assert emit == "hello " and not hit
    emit, hit = jail.push("T")  # held "ST"
    assert emit == "" and not hit
    emit, hit = jail.push("OP and more")
    assert hit and emit == ""
    # diverging prefix gets released
    jail2 = StopJail(["STOP"])
    emit, hit = jail2.push("a ST")
    assert emit == "a "
    emit, hit = jail2.push("YLE")
    assert emit == "STYLE" and not hit


# ---------------- pipeline: preprocessor -> backend -> engine ----------------


class TokenEchoEngine(AsyncEngine):
    """Yields the prompt's token ids back one at a time, then EOS-finishes
    (echo_core-style, ref launch/dynamo-run/src/output/echo_core.rs)."""

    async def generate(self, request: Context):
        req: PreprocessedRequest = request.data
        n = 0
        maxt = req.stop_conditions.max_tokens or len(req.token_ids)
        for tid in req.token_ids:
            if n >= maxt:
                break
            n += 1
            final = n == maxt or n == len(req.token_ids)
            yield LLMEngineOutput(
                token_ids=[tid],
                finish_reason=FinishReason.LENGTH if final else None,
                prompt_tokens=len(req.token_ids) if final else None,
                completion_tokens=n if final else None,
            )


def test_full_pipeline_chat(run):
    async def main():
        tok = ByteTokenizer()
        engine = link(OpenAIPreprocessor(tok), Backend(tok), TokenEchoEngine())
        req = ChatCompletionRequest.from_dict(
            {
                "model": "echo",
                "messages": [{"role": "user", "content": "hi"}],
                "stream": True,
                "stream_options": {"include_usage": True},
                "nvext": {"use_raw_prompt": True, "annotations": ["formatted_prompt"]},
            }
        )
        out = await collect(engine.generate(Context(req)))
        # first item is the formatted_prompt annotation
        assert out[0].event == "formatted_prompt"
        chunks = [a.data for a in out if a.data is not None]
        full = aggregate_chat_chunks(chunks)
        assert full["choices"][0]["message"]["content"] == "hi"
        assert full["choices"][0]["finish_reason"] == "length"
        assert full["usage"]["prompt_tokens"] == 2

    run(main())


def test_full_pipeline_stop_sequence(run):
    async def main():
        tok = ByteTokenizer()
        engine = link(OpenAIPreprocessor(tok), Backend(tok), TokenEchoEngine())
        req = CompletionRequest.from_dict(
            {"model": "echo", "prompt": "abcSTOPxyz", "stop": ["STOP"]}
        )
        out = await collect(engine.generate(Context(req)))
        chunks = [a.data for a in out if a.data is not None]
        full = aggregate_completion_chunks(chunks)
        assert full["choices"][0]["text"] == "abc"
        assert full["choices"][0]["finish_reason"] == "stop"

    run(main())


def test_chat_template_tools_passthrough():
    """request.tools reach the chat template context (function-calling
    templates render the schemas; the engines the reference wraps pass
    tools through the same HF API)."""
    from dynamo_tpu.llm.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    tools = [{"type": "function", "function": {"name": "get_weather"}}]
    with_tools = tok.apply_chat_template(
        [{"role": "user", "content": "hi"}], tools=tools
    )
    without = tok.apply_chat_template([{"role": "user", "content": "hi"}])
    assert "get_weather" in with_tools
    assert "get_weather" not in without


def test_n_parallel_completions(run):
    """OpenAI n>1: the preprocessor fans out n engine sub-streams with
    distinct seeds, multiplexes indexed chunks under one id, and the
    aggregator folds them into n choices with summed usage."""
    import asyncio

    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.tokenizer import ByteTokenizer
    from dynamo_tpu.protocols.aggregator import aggregate_chat_chunks
    from dynamo_tpu.protocols.common import FinishReason, LLMEngineOutput
    from dynamo_tpu.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.runtime import Annotated, AsyncEngine, Context, collect

    class SeedEchoEngine(AsyncEngine):
        """Emits tokens derived from the per-choice seed so choices differ."""

        async def generate(self, request: Context):
            seed = request.data.sampling_options.seed or 0
            for t in range(3):
                tok = ord("a") + (seed + t) % 26
                yield Annotated.from_data(
                    LLMEngineOutput(token_ids=[tok], text=chr(tok))
                )
            yield Annotated.from_data(
                LLMEngineOutput(finish_reason=FinishReason.LENGTH,
                                prompt_tokens=2, completion_tokens=3)
            )

    async def main():
        pre = OpenAIPreprocessor(ByteTokenizer())
        req = ChatCompletionRequest.from_dict({
            "model": "m",
            "messages": [{"role": "user", "content": "hi"}],
            "n": 3,
            "seed": 5,
            "temperature": 0.9,
        })
        items = await collect(pre.generate(Context(req), SeedEchoEngine()))
        chunks = [a.data for a in items if isinstance(a.data, dict)]
        indexes = {
            c["choices"][0]["index"] for c in chunks if c.get("choices")
        }
        assert indexes == {0, 1, 2}
        ids = {c["id"] for c in chunks if c.get("id")}
        assert len(ids) == 1
        full = aggregate_chat_chunks(chunks)
        assert len(full["choices"]) == 3
        texts = {c["message"]["content"] for c in full["choices"]}
        assert len(texts) == 3  # distinct seeds -> distinct choices
        assert full["usage"]["completion_tokens"] == 9
        # the summed-usage chunk reports the preprocessor's own prompt
        # token count (the engine's per-choice usage is suppressed)
        assert full["usage"]["prompt_tokens"] > 0

    run(main())


def test_logprob_request_validation():
    """Malformed logprob params must 400 (RequestError), and top_logprobs=0
    means chosen-token logprobs with no alternates."""
    with pytest.raises(RequestError):
        CompletionRequest.from_dict(
            {"model": "m", "prompt": "x", "logprobs": "two"}
        )
    with pytest.raises(RequestError):
        ChatCompletionRequest.from_dict({
            "model": "m", "messages": [{"role": "user", "content": "x"}],
            "logprobs": True, "top_logprobs": 99,
        })
    req = ChatCompletionRequest.from_dict({
        "model": "m", "messages": [{"role": "user", "content": "x"}],
        "logprobs": True, "top_logprobs": 0,
    })
    assert req.sampling.logprobs == 0  # on, no alternates
    req2 = CompletionRequest.from_dict(
        {"model": "m", "prompt": "x", "logprobs": 0}
    )
    assert req2.sampling.logprobs == 0
    req3 = CompletionRequest.from_dict({"model": "m", "prompt": "x"})
    assert req3.sampling.logprobs is None


def test_completion_logprobs_block_dedup_and_offsets():
    """Regression (advisor r2 low): top_logprobs entries whose token ids
    decode to the same string must keep the MAX logprob (not silently
    drop one), and text_offset must be populated alongside tokens."""
    from dynamo_tpu.protocols.openai import completion_logprobs_block

    entries = [
        {"token": "he", "logprob": -0.1,
         "top": [{"token": "he", "logprob": -0.1},
                 {"token": " ", "logprob": -2.0},
                 {"token": " ", "logprob": -1.5}]},  # byte-piece collision
        {"token": "llo", "logprob": -0.2,
         "top": [{"token": "llo", "logprob": -0.2}]},
    ]
    block = completion_logprobs_block(entries, start_offset=4)
    assert block["tokens"] == ["he", "llo"]
    assert block["token_logprobs"] == [-0.1, -0.2]
    # collision kept the higher (max) logprob
    assert block["top_logprobs"][0] == {"he": -0.1, " ": -1.5}
    # offsets: start at the caller's running offset, advance by token text
    assert block["text_offset"] == [4, 6]


def test_n_fanout_dedupes_prefill(run):
    """VERDICT r2 #8: n>1 must not race n identical prefills — choice 0's
    prefill runs first, siblings admit through the prefix cache. With
    n=4 and a 16-token prompt (4 full hashed blocks), the engine must
    count exactly 3 sibling prefix hits."""
    import asyncio

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.tokenizer import ByteTokenizer
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.protocols.openai import CompletionRequest
    from dynamo_tpu.runtime import Context, collect

    async def main():
        engine = JaxEngine(
            EngineConfig(
                model=ModelConfig.tiny(), num_blocks=64, block_size=4,
                max_batch_size=4, max_context=64, prefill_chunk=16,
            ),
            seed=0,
        )
        pre = OpenAIPreprocessor(ByteTokenizer())
        req = CompletionRequest.from_dict({
            "model": "m",
            "prompt": "abcdabcdabcdabcd",  # 16 byte tokens = 4 blocks
            "n": 4,
            "max_tokens": 4,
            "seed": 3,
            "temperature": 0.8,
        })
        items = await collect(pre.generate(Context(req), engine))
        chunks = [a.data for a in items if isinstance(a.data, dict)]
        indexes = {
            c["choices"][0]["index"] for c in chunks if c.get("choices")
        }
        assert indexes == {0, 1, 2, 3}
        # choice 0 prefills cold (0 hits); each sibling hits the hashed
        # prefix = the prompt's full blocks excluding its final token
        # (the tokenizer may add BOS, so derive from the reported count)
        usage = [c for c in chunks if c.get("usage")][-1]["usage"]
        p = usage["prompt_tokens"]
        expect = 3 * (((p - 1) // 4) * 4)
        assert engine.stats["prefix_cache_hits_tokens"] == expect, (
            p, engine.stats
        )
        await engine.close()

    run(main())

"""dynlint + runtime sanitizer tests (dynamo_tpu/analysis/).

Contract per docs/static_analysis.md: every rule has at least one BAD
fixture proving it fires and a GOOD fixture proving the sanctioned
pattern passes; suppression comments work line-, next-line- and
file-wide; and the meta-test at the bottom pins the real tree clean —
the CI gate (scripts/check.sh) is `python -m dynamo_tpu.analysis
dynamo_tpu/ tests/` exiting 0.
"""

import asyncio
import json
import os
import textwrap
import time

import pytest

from dynamo_tpu.analysis import lint_paths, lint_source
from dynamo_tpu.analysis.__main__ import main as lint_main
from dynamo_tpu.analysis import sanitizer
from dynamo_tpu.analysis.rules import FaultpointCoverageRule

REPO = os.path.join(os.path.dirname(__file__), "..")

# default virtual path: event-loop package, so loop-scoped rules apply
ENGINE_PATH = "dynamo_tpu/engine/fake.py"


def rules_fired(code, path=ENGINE_PATH):
    vs, _ = lint_source(path, textwrap.dedent(code))
    return [v.rule for v in vs]


def violations(code, path=ENGINE_PATH):
    vs, _ = lint_source(path, textwrap.dedent(code))
    return vs


# ---------------------------------------------------------------------------
# rule 1: async-blocking-call
# ---------------------------------------------------------------------------


def test_async_blocking_call_fires():
    bad = """
    import time
    async def pump():
        time.sleep(0.1)
    """
    assert rules_fired(bad) == ["async-blocking-call"]


def test_async_blocking_call_tobytes_and_block_until_ready():
    bad = """
    async def send(arr, jax):
        buf = arr.tobytes()
        jax.block_until_ready(arr)
    """
    assert rules_fired(bad) == ["async-blocking-call"] * 2


def test_async_blocking_call_np_asarray_in_async():
    bad = """
    import numpy as np
    async def land(seg):
        return np.asarray(seg)
    """
    assert rules_fired(bad) == ["async-blocking-call"]


def test_async_blocking_call_socket_receiver_filter():
    bad = """
    async def pump(sock, s, conn):
        sock.recv(4)
        s.sendall(b"x")
        conn.accept()
    """
    assert rules_fired(bad) == ["async-blocking-call"] * 3
    # non-socket receivers with socket-ish method names must NOT fire
    # (nor should every `self.*` — the filter is name-based, not "any
    # receiver containing the letter s")
    good = """
    async def pump(self):
        self.results.accept()
        await self.stream.recv()
    """
    assert rules_fired(good) == []


def test_async_blocking_call_good_patterns():
    good = """
    import asyncio
    import numpy as np
    async def pump(arr):
        await asyncio.sleep(0.1)          # async sleep is fine
        loop = asyncio.get_running_loop()
        host = await loop.run_in_executor(None, lambda: np.asarray(arr))
        return host

    def sync_helper(arr):
        return np.asarray(arr)            # sync scope: not the loop
    """
    assert rules_fired(good) == []


def test_async_blocking_call_scoped_to_event_loop_packages():
    bad = """
    import time
    async def f():
        time.sleep(1)
    """
    # ops/ and models/ are compute modules, not event-loop code
    assert rules_fired(bad, "dynamo_tpu/ops/fake.py") == []
    assert rules_fired(bad, "dynamo_tpu/models/fake.py") == []
    assert rules_fired(bad, "dynamo_tpu/disagg/fake.py") == [
        "async-blocking-call"
    ]


# ---------------------------------------------------------------------------
# rule 2: await-in-lock
# ---------------------------------------------------------------------------


def test_await_in_lock_fires_on_network_await():
    bad = """
    async def step(self, writer, msg):
        async with self._device_lock:
            await writer.drain()
    """
    assert rules_fired(bad) == ["await-in-lock"]


def test_await_in_lock_fires_on_queue_await():
    bad = """
    async def step(self):
        async with self._lock:
            item = await self.sendq.get()
    """
    assert rules_fired(bad) == ["await-in-lock"]


def test_await_in_lock_blames_the_lock_item_not_items0():
    bad = """
    import asyncio
    async def step(self, writer):
        async with asyncio.timeout(5), self._device_lock:
            await writer.drain()
    """
    vs = violations(bad)
    assert [v.rule for v in vs] == ["await-in-lock"]
    assert "_device_lock" in vs[0].message  # not asyncio.timeout(5)


def test_await_in_lock_allows_executor_dispatch():
    good = """
    import asyncio
    async def step(self, steps):
        async with self._device_lock:
            toks = await asyncio.get_running_loop().run_in_executor(
                None, self._dispatch, steps
            )
        await self.out_queue.put(toks)   # after release: fine
    """
    assert rules_fired(good) == []


def test_await_in_lock_ignores_nested_function_bodies():
    good = """
    async def step(self):
        async with self._device_lock:
            async def later(writer):
                await writer.drain()      # runs OUTSIDE the lock
            self.cb = later
    """
    assert rules_fired(good) == []


# ---------------------------------------------------------------------------
# rule 3: jit-in-function
# ---------------------------------------------------------------------------


def test_jit_in_function_fires():
    bad = """
    import jax
    def admit(fn):
        wrapped = jax.jit(fn)
        return wrapped
    """
    assert rules_fired(bad, "dynamo_tpu/engine/fake.py") == [
        "jit-in-function"
    ]


def test_jit_partial_in_function_fires():
    bad = """
    import functools, jax
    async def admit(fn):
        return functools.partial(jax.jit, static_argnames=("n",))(fn)
    """
    assert rules_fired(bad) == ["jit-in-function"]


def test_jit_module_scope_and_decorators_pass():
    good = """
    import functools, jax

    _sample = jax.jit(lambda x: x)

    @functools.partial(jax.jit, static_argnames=("n",))
    def step(x, n):
        return x

    @jax.jit
    def other(x):
        return x

    class Model:
        @functools.partial(jax.jit, static_argnames=("self",))
        def fwd(self, x):
            return x
    """
    assert rules_fired(good) == []


def test_jit_nested_def_decorator_is_runtime():
    bad = """
    import jax
    def build():
        @jax.jit
        def inner(x):
            return x
        return inner
    """
    assert rules_fired(bad) == ["jit-in-function"]


# ---------------------------------------------------------------------------
# rule 4: raw-header-subscript
# ---------------------------------------------------------------------------

DECODER_PATH = "dynamo_tpu/disagg/transfer.py"


def test_raw_header_subscript_fires():
    bad = """
    def decode(frame):
        header = frame.header_json()
        return header["n_blocks"]
    """
    assert rules_fired(bad, DECODER_PATH) == ["raw-header-subscript"]


def test_raw_header_subscript_or_default_idiom_tracked():
    bad = """
    def decode(frame):
        h = frame.header_json() or {}
        return h["b0"]
    """
    assert rules_fired(bad, DECODER_PATH) == ["raw-header-subscript"]


def test_raw_header_subscript_good_and_scope():
    good = """
    def decode(frame):
        h = frame.header_json() or {}
        b0 = h.get("b0")
        v = frame.header_field("version", 0)
        h2 = {}
        h2["build"] = 1     # store: building a header is fine
        return b0, v
    """
    assert rules_fired(good, DECODER_PATH) == []
    # outside decoder modules the name `header` is unconstrained
    bad_elsewhere = """
    def f(header):
        return header["x"]
    """
    assert rules_fired(bad_elsewhere, "dynamo_tpu/planner/fake.py") == []


# ---------------------------------------------------------------------------
# rule 5: writer-wait-closed
# ---------------------------------------------------------------------------


def test_writer_wait_closed_fires():
    bad = """
    async def handle(reader, writer):
        writer.write(b"x")
        writer.close()
    """
    assert rules_fired(bad) == ["writer-wait-closed"]


def test_writer_wait_closed_good():
    good = """
    async def handle(reader, writer):
        try:
            writer.write(b"x")
        finally:
            writer.close()
            await writer.wait_closed()

    async def teardown(self):
        self._server.close()
        await self._server.wait_closed()

    async def hard_abort(writer):
        writer.close()
        writer.abort()     # hard teardown: transport drops synchronously
    """
    assert rules_fired(good) == []


def test_writer_wait_closed_ignores_non_writers():
    good = """
    async def f(self):
        self._wal.close()
        self.store.close()
    """
    assert rules_fired(good) == []


# ---------------------------------------------------------------------------
# rule 6: faultpoint-test-coverage (project rule)
# ---------------------------------------------------------------------------

FAULTPOINTS_SRC = """
POINTS = (
    "admission",
    "mid_decode",
)
"""


def test_faultpoint_coverage_fires_for_unreferenced_point():
    files = {
        "dynamo_tpu/resilience/faultpoints.py": FAULTPOINTS_SRC,
        "tests/test_x.py": "faultpoints.arm('admission')",
    }
    vs = FaultpointCoverageRule().check_project(files)
    assert [v.rule for v in vs] == ["faultpoint-test-coverage"]
    assert "mid_decode" in vs[0].message


def test_faultpoint_coverage_clean_when_all_referenced():
    files = {
        "dynamo_tpu/resilience/faultpoints.py": FAULTPOINTS_SRC,
        "tests/test_x.py": "arm('admission'); arm('mid_decode')",
    }
    assert FaultpointCoverageRule().check_project(files) == []


def test_faultpoint_coverage_skipped_without_tests_in_path_set():
    files = {"dynamo_tpu/resilience/faultpoints.py": FAULTPOINTS_SRC}
    assert FaultpointCoverageRule().check_project(files) == []


# ---------------------------------------------------------------------------
# rule 7: swallowed-exception
# ---------------------------------------------------------------------------


def test_swallowed_exception_fires():
    bad = """
    def loop():
        try:
            work()
        except Exception:
            pass
    """
    assert rules_fired(bad) == ["swallowed-exception"]


def test_swallowed_exception_bare_except_fires():
    bad = """
    def loop():
        try:
            work()
        except:
            pass
    """
    assert rules_fired(bad) == ["swallowed-exception"]


def test_swallowed_exception_good():
    good = """
    import logging
    logger = logging.getLogger(__name__)
    def loop():
        try:
            work()
        except Exception:
            logger.debug("work failed", exc_info=True)
        try:
            other()
        except (ConnectionResetError, BrokenPipeError):
            pass    # narrow type: an explicit decision, not a swallow
    """
    assert rules_fired(good) == []


# ---------------------------------------------------------------------------
# rule 8: blocking-disk-io
# ---------------------------------------------------------------------------


def test_blocking_disk_io_fires():
    bad = """
    import os
    async def land(path, h):
        with open(path, "rb") as f:
            raw = f.read()
        os.remove(path)
        return raw
    """
    # open() + f.read() (file-shaped receiver) + os.remove
    assert rules_fired(bad) == ["blocking-disk-io"] * 3


def test_blocking_disk_io_pathlib_and_file_receivers():
    bad = """
    async def demote(p, fh):
        p.write_bytes(b"x")
        fh.write(b"y")
        fh.flush()
    """
    assert rules_fired(bad) == ["blocking-disk-io"] * 3


def test_blocking_disk_io_good_patterns():
    """Executor dispatch passes a function REFERENCE (the sanctioned
    pattern for the disk tier), sync helpers may do file I/O freely,
    and asyncio StreamWriter/StreamReader write/read never fire."""
    good = """
    import asyncio
    def disk_put(store, h, k, v):   # sync helper: runs on the executor
        with open(store.path, "wb") as f:
            f.write(k)
    async def promote(loop, store, hashes):
        await loop.run_in_executor(None, store.promote_chain, hashes)
    async def send(writer, reader):
        writer.write(b"frame")       # StreamWriter: non-blocking
        await writer.drain()
        return await reader.read(4)  # StreamReader: awaited, fine
    """
    assert rules_fired(good) == []


def test_blocking_disk_io_scoped_to_event_loop_packages():
    bad = """
    async def snapshot(path):
        open(path)
    """
    assert rules_fired(bad, path="dynamo_tpu/deploy/builder.py") == []
    assert rules_fired(bad, path="dynamo_tpu/engine/offload.py") == [
        "blocking-disk-io"
    ]


# ---------------------------------------------------------------------------
# rule 9: span-leak
# ---------------------------------------------------------------------------


def test_span_leak_fires_on_unended_handle():
    bad = """
    from .. import tracing
    async def handle(req):
        sp = tracing.span("worker.handle", request_id=req.id)
        await work(req)
    """
    assert rules_fired(bad) == ["span-leak"]


def test_span_leak_fires_on_discarded_span():
    bad = """
    from .. import tracing
    def f():
        tracing.span("dropped")
    """
    assert rules_fired(bad) == ["span-leak"]


def test_span_leak_good_patterns():
    good = """
    from .. import tracing
    async def ctx(req):
        with tracing.span("prefill.compute"):
            await work(req)

    async def manual(req):
        sp = tracing.span("worker.handle")
        try:
            await work(req)
        finally:
            sp.end()

    async def handle_as_ctx(req):
        sp = tracing.span("send")
        with sp:
            await work(req)
    """
    assert rules_fired(good) == []


# ---------------------------------------------------------------------------
# rule 10: mesh-capture
# ---------------------------------------------------------------------------


def test_mesh_capture_fires_on_module_scope():
    bad = """
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from ..parallel.mesh import make_mesh

    MESH = Mesh(jax.devices(), ("tp",))
    CACHE_SH = NamedSharding(MESH, P(None, "tp"))
    DEFAULT = make_mesh()
    """
    assert rules_fired(bad) == ["mesh-capture"] * 3


def test_mesh_capture_fires_on_class_scope_and_defaults():
    bad = """
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from ..parallel.mesh import cache_sharding

    class Engine:
        # class bodies execute at import: this placement outlives any
        # morph the instances perform
        sharding = NamedSharding(MESH, P("tp"))

    def scatter(x, sh=cache_sharding(MESH, CFG)):
        return x
    """
    assert rules_fired(bad) == ["mesh-capture"] * 2


def test_mesh_capture_good_patterns():
    good = """
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # logical specs ARE the layer module scope may hold (mesh-free)
    CACHE_SPEC = P(None, "tp", None)
    SPECS = {"wq": P(None, "tp")}

    def resolve(mesh, cfg):
        # call-time resolution against the CURRENT mesh: the pattern
        # LogicalLayout/ MeshMorpher institutionalize
        return NamedSharding(mesh, CACHE_SPEC)

    class Mover:
        def _dst(self, devs):
            return NamedSharding(Mesh(devs, ("ici",)), P())

        def inner_default(self):
            # nested defaults evaluate at call time, not import
            def f(sh=NamedSharding(self.mesh, P())):
                return sh
            return f
    """
    assert rules_fired(good) == []


def test_mesh_capture_skips_defs_nested_in_module_level_blocks():
    good = """
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # conditional definition: the def EXECUTES at import (so its
    # defaults would be import-time) but its BODY is call time — a
    # walk that descends module-level if/try statements wholesale
    # would false-positive here and break CI on a correct pattern
    try:
        from fast import resolve
    except ImportError:
        def resolve(mesh):
            return NamedSharding(mesh, P("tp"))

    if True:
        fallback = lambda mesh: NamedSharding(mesh, P())
    """
    assert rules_fired(good) == []
    bad = """
    from jax.sharding import Mesh

    # ...but a def nested in a module-level block still evaluates its
    # DEFAULTS at import, and a bare call in the block body executes
    try:
        def scatter(x, sh=Mesh(devices, ("tp",))):
            return x
    except Exception:
        MESH = Mesh(devices, ("tp",))
    """
    assert rules_fired(bad) == ["mesh-capture"] * 2


def test_mesh_capture_scoped_to_engine_ops_packages():
    bad = """
    from jax.sharding import Mesh
    MESH = Mesh(devices, ("tp",))
    """
    # outside the placement-bearing packages (e.g. the launch CLI or a
    # test helper) the rule stays quiet
    assert rules_fired(bad, path="dynamo_tpu/launch/fake.py") == []
    assert rules_fired(bad, path="dynamo_tpu/ops/fake.py") == ["mesh-capture"]


# ---------------------------------------------------------------------------
# suppressions + report plumbing
# ---------------------------------------------------------------------------


def test_suppression_same_line_and_counted():
    code = """
    import time
    async def f():
        time.sleep(1)  # dynlint: disable=async-blocking-call -- test fixture
    """
    vs, suppressed = lint_source(ENGINE_PATH, textwrap.dedent(code))
    assert vs == [] and suppressed == 1


def test_suppression_next_line():
    code = """
    import time
    async def f():
        # dynlint: disable=async-blocking-call -- justified
        time.sleep(1)
    """
    vs, suppressed = lint_source(ENGINE_PATH, textwrap.dedent(code))
    assert vs == [] and suppressed == 1


def test_suppression_file_wide_and_star():
    code = """
    # dynlint: disable-file=swallowed-exception
    import time
    async def f():
        time.sleep(1)  # dynlint: disable=* -- everything on this line
        try:
            work()
        except Exception:
            pass
    """
    vs, suppressed = lint_source(ENGINE_PATH, textwrap.dedent(code))
    assert vs == [] and suppressed == 2


def test_suppression_wrong_rule_does_not_cover():
    code = """
    import time
    async def f():
        time.sleep(1)  # dynlint: disable=span-leak -- wrong rule name
    """
    vs, _ = lint_source(ENGINE_PATH, textwrap.dedent(code))
    assert [v.rule for v in vs] == ["async-blocking-call"]


def test_syntax_error_reported_as_violation():
    vs, _ = lint_source(ENGINE_PATH, "def broken(:\n")
    assert [v.rule for v in vs] == ["syntax-error"]


def test_lint_paths_and_cli_on_fixture_tree(tmp_path, capsys):
    pkg = tmp_path / "dynamo_tpu" / "engine"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\nasync def f():\n    time.sleep(1)\n"
    )
    report = lint_paths([str(tmp_path)], root=str(tmp_path))
    assert not report.ok
    assert [v.rule for v in report.violations] == ["async-blocking-call"]
    assert report.violations[0].path == "dynamo_tpu/engine/bad.py"
    # CLI: exit 1 + JSON shape
    rc = lint_main(["--json", str(tmp_path)])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is False and len(out["violations"]) == 1
    # fix it -> exit 0
    (pkg / "bad.py").write_text(
        "import asyncio\nasync def f():\n    await asyncio.sleep(1)\n"
    )
    assert lint_main([str(tmp_path)]) == 0


def test_cli_unknown_rule_and_list_rules(capsys):
    assert lint_main(["--rule", "no-such-rule", "."]) == 2
    capsys.readouterr()
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "async-blocking-call" in out and "faultpoint-test-coverage" in out


# ---------------------------------------------------------------------------
# the meta-test: the real tree is clean
# ---------------------------------------------------------------------------


def test_real_tree_is_lint_clean():
    """THE acceptance gate: `python -m dynamo_tpu.analysis dynamo_tpu/
    tests/` exits 0 on this tree. Every deliberate exception carries an
    inline `dynlint: disable` with a justification — if this fails, you
    introduced a new violation of a PR 1-6 invariant (or found a rule
    bug; either way, look before you suppress)."""
    report = lint_paths(
        [os.path.join(REPO, "dynamo_tpu"), os.path.join(REPO, "tests")]
    )
    assert report.files_checked > 100
    msgs = "\n".join(
        f"{v.path}:{v.line}: [{v.rule}] {v.message}"
        for v in report.violations
    )
    assert not report.violations, f"dynlint violations:\n{msgs}"
    assert not report.errors


# ---------------------------------------------------------------------------
# runtime sanitizer
# ---------------------------------------------------------------------------


def test_sanitizer_detects_loop_stall_with_stack():
    async def stall():
        await asyncio.sleep(0.01)
        time.sleep(0.25)  # dynlint: disable=async-blocking-call -- the fixture IS the stall
        await asyncio.sleep(0.01)

    with pytest.raises(sanitizer.SanitizerError) as ei:
        sanitizer.run_sanitized(stall(), stall_s=0.1, strict_stalls=True)
    msg = str(ei.value)
    assert "event-loop stall" in msg
    # the watchdog snapshots the loop thread DURING the stall: the
    # report names the blocking frame, not just a duration
    assert "test_analysis" in msg or "time.sleep" in msg


def test_sanitizer_records_without_strict():
    async def stall():
        time.sleep(0.15)  # dynlint: disable=async-blocking-call -- fixture

    before = sanitizer.counters()["san_loop_stalls"]
    sanitizer.run_sanitized(stall(), stall_s=0.05, strict_stalls=False)
    assert sanitizer.counters()["san_loop_stalls"] > before


def test_sanitizer_lock_hold_histogram_and_naming():
    san = sanitizer.LoopSanitizer(stall_threshold_s=0)

    async def main():
        san.activate()
        lock = sanitizer.name_lock(asyncio.Lock(), "device_lock")
        anon = asyncio.Lock()
        async with lock:
            await asyncio.sleep(0.03)
        async with anon:
            pass

    asyncio.run(main())
    report = san.deactivate()
    assert "device_lock" in report.lock_holds
    h = report.lock_holds["device_lock"]
    assert h.total == 1 and 0.02 < h.max_s < 1.0
    # the anonymous lock histogrammed under its acquire site
    assert len(report.lock_holds) == 2


def test_sanitizer_detects_leaked_writer():
    async def leak():
        server = await asyncio.start_server(
            lambda r, w: None, "127.0.0.1", 0
        )
        port = server.sockets[0].getsockname()[1]
        _r, _w = await asyncio.open_connection("127.0.0.1", port)
        server.close()
        await server.wait_closed()
        # _w never closed -> leak

    with pytest.raises(sanitizer.SanitizerError) as ei:
        sanitizer.run_sanitized(leak(), stall_s=0, strict_writers=True)
    assert "never closed" in str(ei.value)


def test_sanitizer_clean_run_passes_strict():
    async def clean():
        server = await asyncio.start_server(
            lambda r, w: w.close(), "127.0.0.1", 0
        )
        port = server.sockets[0].getsockname()[1]
        r, w = await asyncio.open_connection("127.0.0.1", port)
        w.close()
        await w.wait_closed()
        server.close()
        await server.wait_closed()
        return "ok"

    assert sanitizer.run_sanitized(
        clean(), stall_s=0.5, strict_stalls=True, strict_writers=True
    ) == "ok"
    # patches restored: plain asyncio still works after deactivation
    assert asyncio.run(asyncio.sleep(0, result=1)) == 1
    assert asyncio.Lock.acquire.__qualname__.startswith("Lock.")


def test_sanitizer_pending_task_snapshot():
    async def leaves_task():
        async def forever():
            await asyncio.Event().wait()

        t = asyncio.get_running_loop().create_task(forever())
        t.set_name("orphan")
        await asyncio.sleep(0.01)

    san = sanitizer.LoopSanitizer(stall_threshold_s=0)

    async def main():
        san.activate()
        try:
            await leaves_task()
        finally:
            san.before_shutdown()

    asyncio.run(main())
    report = san.deactivate()
    assert any("orphan" in p for p in report.pending_tasks)


def test_sanitizer_counters_flow_into_engine_load_metrics():
    """The production wiring (satellite): engine load_metrics exports the
    san_* counters, the aggregator folds them into WorkerLoad, and the
    metrics component renders the gauges."""
    from dynamo_tpu.kv_router.scheduler import WorkerLoad

    sanitizer.COUNTERS["san_loop_stalls"] += 1
    sanitizer.COUNTERS["san_loop_stall_max_ms"] = max(
        sanitizer.COUNTERS["san_loop_stall_max_ms"], 123.0
    )
    snap = sanitizer.counters()
    assert snap["san_loop_stalls"] >= 1
    # the WorkerLoad schema carries the sanitizer surface
    w = WorkerLoad(
        worker_id=1,
        loop_stalls=snap["san_loop_stalls"],
        loop_stall_max_ms=snap["san_loop_stall_max_ms"],
        lock_hold_max_ms=snap["san_lock_hold_max_ms"],
        writers_leaked=snap["san_writers_leaked"],
    )
    assert w.loop_stall_max_ms >= 123.0


def test_engine_load_metrics_exports_sanitizer_counters(run):
    from dynamo_tpu.engine.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.config import ModelConfig

    # constructed OUTSIDE the sanitized coroutine: the ctor's first
    # eager ops jit-compile, and test_analysis runs stall-STRICT
    e = JaxEngine(
        EngineConfig(
            model=ModelConfig.tiny(), num_blocks=16, block_size=4,
            max_batch_size=2, max_context=64, prefill_chunk=16,
        ),
        seed=0,
    )

    async def main():
        lm = e.load_metrics()
        for k in ("san_loop_stalls", "san_loop_stall_max_ms",
                  "san_lock_hold_max_ms", "san_writers_leaked"):
            assert k in lm, f"load_metrics missing {k}"
        # the device lock is registered under a stable histogram name
        assert getattr(e._device_lock, "_dyn_san_name", None) == "device_lock"
        await e.close()

    run(main())


def test_metrics_component_renders_sanitizer_gauges():
    from dynamo_tpu.observability.component import MetricsComponent
    from dynamo_tpu.kv_router.scheduler import ProcessedEndpoints, WorkerLoad

    mc = MetricsComponent.__new__(MetricsComponent)
    mc.prefix = "dynamo_tpu"
    mc.aggregator = type(
        "A", (), {
            "endpoints": ProcessedEndpoints([
                WorkerLoad(worker_id=7, loop_stalls=3,
                           loop_stall_max_ms=250.5, lock_hold_max_ms=12.25,
                           writers_leaked=1),
            ])
        },
    )()
    mc.hit_events = mc.hit_isl_blocks = mc.hit_overlap_blocks = 0
    mc.planner_decision = mc.planner_watermark = None
    mc.planner_decisions_total = 0
    mc.tracing = None
    text = mc.render()
    assert 'dynamo_tpu_loop_stalls_total{worker="7"} 3' in text
    assert 'dynamo_tpu_loop_stall_max_ms{worker="7"} 250.5' in text
    assert 'dynamo_tpu_lock_hold_max_ms{worker="7"} 12.25' in text
    assert 'dynamo_tpu_writers_leaked_total{worker="7"} 1' in text


# ===========================================================================
# dynflow: the whole-program contract checker (analysis/program.py +
# analysis/contracts.py). Every cross-file rule gets a firing BAD
# fixture and a passing GOOD fixture over an in-memory file set; the
# meta-test at the bottom pins the real tree clean in --program mode.
# ===========================================================================

from dynamo_tpu.analysis.contracts import check_contracts
from dynamo_tpu.analysis.engine import check_program


def contracts_fired(files, rule=None):
    fs = {p: textwrap.dedent(s) for p, s in files.items()}
    vs = check_contracts(fs)
    if rule is not None:
        vs = [v for v in vs if v.rule == rule]
    return vs


# ---------------------------------------------------------------------------
# subject-without-subscriber
# ---------------------------------------------------------------------------

_PUB_ONLY = {
    "dynamo_tpu/kv_router/fakeproto.py": """
    FOO_SUBJECT = "foo-events"
    """,
    "dynamo_tpu/kv_router/fakepub.py": """
    from .fakeproto import FOO_SUBJECT

    class Pub:
        def __init__(self, drt, component):
            self.drt = drt
            self.subject = component.event_subject(FOO_SUBJECT)

        def send(self, ev):
            self.drt.bus.publish(self.subject, ev)
    """,
}


def test_subject_without_subscriber_fires():
    vs = contracts_fired(_PUB_ONLY, "subject-without-subscriber")
    assert len(vs) == 1
    v = vs[0]
    # anchored at the constant declaration, evidence = the publish end
    assert v.path == "dynamo_tpu/kv_router/fakeproto.py"
    assert "published but nothing" in v.message
    assert any(s.path.endswith("fakepub.py") for s in v.evidence)


def test_subject_with_subscriber_passes():
    files = dict(_PUB_ONLY)
    files["dynamo_tpu/observability/fakesub.py"] = """
    from ..kv_router.fakeproto import FOO_SUBJECT

    class Sub:
        def __init__(self, drt, component):
            self.sub = drt.bus.subscribe(component.event_subject(FOO_SUBJECT))
    """
    assert contracts_fired(files, "subject-without-subscriber") == []


def test_subject_subscribed_never_published_fires():
    files = {
        "dynamo_tpu/kv_router/fakeproto.py": "BAR_SUBJECT = 'bar'\n",
        "dynamo_tpu/kv_router/fakesub.py": """
        from .fakeproto import BAR_SUBJECT

        class Sub:
            def __init__(self, drt, component):
                self.sub = drt.bus.subscribe(
                    component.event_subject(BAR_SUBJECT))
        """,
    }
    vs = contracts_fired(files, "subject-without-subscriber")
    assert len(vs) == 1 and "waits forever" in vs[0].message


def test_subject_declared_unused_fires_and_decl_comment_satisfies():
    files = {"dynamo_tpu/kv_router/fakeproto.py": "DEAD_SUBJECT = 'dead'\n"}
    vs = contracts_fired(files, "subject-without-subscriber")
    assert len(vs) == 1 and "neither published nor subscribed" in vs[0].message
    # a constructor-injected publisher declares itself by comment
    # (the BusExporter pattern) — resolvable by declaration, and the
    # pub-without-sub direction then fires instead
    files["dynamo_tpu/tracing/fakeexp.py"] = """
    class Exporter:
        def flush(self, batch):
            # dynflow: publishes=DEAD_SUBJECT
            self.bus.publish(self.subject, batch)
    """
    vs = contracts_fired(files, "subject-without-subscriber")
    assert len(vs) == 1 and "published but nothing" in vs[0].message


def test_subject_property_pattern_resolves():
    """TraceCollector's shape: the subject lives behind a property; the
    subscribe through self.<property> must still resolve."""
    files = {
        "dynamo_tpu/tracing/fakeproto.py": "EVT_SUBJECT = 'evt'\n",
        "dynamo_tpu/tracing/fakecol.py": """
        from .fakeproto import EVT_SUBJECT

        class Col:
            @property
            def subject(self):
                return self.component.event_subject(EVT_SUBJECT)

            async def start(self, drt):
                self._sub = drt.bus.subscribe(self.subject)
        """,
    }
    vs = contracts_fired(files, "subject-without-subscriber")
    assert len(vs) == 1 and "waits forever" in vs[0].message  # sub, no pub


# ---------------------------------------------------------------------------
# header-write-without-tolerant-read
# ---------------------------------------------------------------------------


def test_header_write_without_any_read_fires():
    files = {
        "dynamo_tpu/disagg/transfer.py": """
        import json

        async def send(writer, rid):
            head = {"request_id": rid, "mystery": 1}
            await write_frame(writer, json.dumps(head).encode())

        async def receive(reader):
            h = json.loads((await read_frame(reader)).header)
            rid = h.get("request_id")
            return rid
        """,
    }
    vs = contracts_fired(files, "header-write-without-tolerant-read")
    assert len(vs) == 1
    assert "'mystery'" in vs[0].message and "no " in vs[0].message


def test_header_write_with_only_subscript_read_fires_with_evidence():
    files = {
        "dynamo_tpu/disagg/transfer.py": """
        import json

        async def send(writer, rid):
            head = {"geometry": [1, 2]}
            await write_frame(writer, json.dumps(head).encode())

        async def receive(reader):
            head = json.loads((await read_frame(reader)).header)
            return head["geometry"]
        """,
    }
    vs = contracts_fired(files, "header-write-without-tolerant-read")
    assert len(vs) == 1
    assert "intolerantly" in vs[0].message
    # the evidence chain points at the intolerant read end
    assert any(s.note.startswith("intolerant") for s in vs[0].evidence)


def test_header_write_with_tolerant_read_passes():
    files = {
        "dynamo_tpu/disagg/transfer.py": """
        import json

        async def send(writer, rid):
            head = {"geometry": [1, 2]}
            await write_frame(writer, json.dumps(head).encode())

        async def receive(reader):
            head = json.loads((await read_frame(reader)).header)
            return head.get("geometry")
        """,
    }
    assert contracts_fired(files, "header-write-without-tolerant-read") == []


def test_header_plane_scoped_to_wire_modules():
    # the same dict outside the wire-module set is not a header
    files = {
        "dynamo_tpu/planner/whatever.py": """
        def build():
            head = {"not_a_wire_key": 1}
            return head
        """,
    }
    assert contracts_fired(files, "header-write-without-tolerant-read") == []


# ---------------------------------------------------------------------------
# unscraped-stat / stat-scrape-without-producer
# ---------------------------------------------------------------------------

_SCHED_FROM_STATS = """
from dataclasses import dataclass

@dataclass
class WorkerLoad:
    worker_id: int
    cool: int = 0

    @staticmethod
    def from_stats(worker_id, d, ts=None):
        return WorkerLoad(
            worker_id=worker_id,
            cool=d.get("cool_stat", 0),
        )
"""


def test_unscraped_stat_fires_with_evidence():
    files = {
        "dynamo_tpu/engine/engine.py": """
        class E:
            def load_metrics(self):
                return {"cool_stat": 1, "forgotten_stat": 2}
        """,
        "dynamo_tpu/kv_router/scheduler.py": _SCHED_FROM_STATS,
    }
    vs = contracts_fired(files, "unscraped-stat")
    assert len(vs) == 1
    assert "'forgotten_stat'" in vs[0].message
    assert vs[0].path == "dynamo_tpu/engine/engine.py"
    # evidence names the scrape-mapping end
    assert any(s.path.endswith("scheduler.py") for s in vs[0].evidence)


def test_unscraped_stat_all_scraped_passes():
    files = {
        "dynamo_tpu/engine/engine.py": """
        class E:
            def load_metrics(self):
                return {"cool_stat": 1}
        """,
        "dynamo_tpu/kv_router/scheduler.py": _SCHED_FROM_STATS,
    }
    assert contracts_fired(files, "unscraped-stat") == []


def test_unscraped_stat_silent_without_scrape_mapping():
    # partial file set (no from_stats): nothing to judge
    files = {
        "dynamo_tpu/engine/engine.py": """
        class E:
            def load_metrics(self):
                return {"anything": 1}
        """,
    }
    assert contracts_fired(files, "unscraped-stat") == []


def test_stat_scrape_without_producer_fires():
    files = {
        "dynamo_tpu/engine/engine.py": """
        class E:
            def load_metrics(self):
                return {"cool_stat": 1}
        """,
        "dynamo_tpu/kv_router/scheduler.py": _SCHED_FROM_STATS.replace(
            '"cool_stat"', '"ghost_stat"'
        ),
    }
    vs = contracts_fired(files, "stat-scrape-without-producer")
    assert len(vs) == 1
    assert "'ghost_stat'" in vs[0].message and "lies" in vs[0].message


def test_stats_dict_attribute_producer_counts():
    # the DisaggEngine shape: a stats dict literal on self, exported
    # wholesale — its keys are producers too
    files = {
        "dynamo_tpu/disagg/worker.py": """
        class D:
            def __init__(self):
                self.stats = {"cool_stat": 0}
        """,
        "dynamo_tpu/kv_router/scheduler.py": _SCHED_FROM_STATS,
    }
    assert contracts_fired(files, "stat-scrape-without-producer") == []
    assert contracts_fired(files, "unscraped-stat") == []


# ---------------------------------------------------------------------------
# unrendered-gauge
# ---------------------------------------------------------------------------

_WL_TWO_FIELDS = """
from dataclasses import dataclass

@dataclass
class WorkerLoad:
    worker_id: int
    shown: int = 0
    dead_field: int = 0

    @staticmethod
    def from_stats(worker_id, d, ts=None):
        return WorkerLoad(worker_id=worker_id, shown=d.get("shown", 0),
                          dead_field=d.get("dead_field", 0))
"""


def test_unrendered_gauge_fires():
    files = {
        "dynamo_tpu/kv_router/scheduler.py": _WL_TWO_FIELDS,
        "dynamo_tpu/engine/engine.py": """
        class E:
            def load_metrics(self):
                return {"shown": 1, "dead_field": 2}
        """,
        "dynamo_tpu/observability/component.py": """
        def render(ep):
            return [w.shown for w in ep.loads]
        """,
    }
    vs = contracts_fired(files, "unrendered-gauge")
    assert len(vs) == 1 and "dead_field" in vs[0].message


def test_unrendered_gauge_consumption_elsewhere_passes():
    files = {
        "dynamo_tpu/kv_router/scheduler.py": _WL_TWO_FIELDS,
        "dynamo_tpu/engine/engine.py": """
        class E:
            def load_metrics(self):
                return {"shown": 1, "dead_field": 2}
        """,
        "dynamo_tpu/observability/component.py": """
        def render(ep):
            return [w.shown for w in ep.loads]
        """,
        # a planner reads the field: consumed, even though not a gauge
        "dynamo_tpu/planner/fake.py": """
        def policy(load):
            return load.dead_field > 0
        """,
    }
    assert contracts_fired(files, "unrendered-gauge") == []


def test_unrendered_gauge_silent_without_render_module():
    files = {"dynamo_tpu/kv_router/scheduler.py": _WL_TWO_FIELDS}
    assert contracts_fired(files, "unrendered-gauge") == []


# ---------------------------------------------------------------------------
# dead-wire-field — including the exact MorphDecision.pool shape
# ---------------------------------------------------------------------------

_MORPH_PROTO = """
import json
from dataclasses import dataclass, field

@dataclass
class MorphDecision:
    ts: float = 0.0
    worker_id: int = 0
    pool: str = "decode"
    tp: int = 1

    def to_bytes(self):
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_bytes(raw):
        d = json.loads(raw)
        return MorphDecision(**d)
"""

_MORPH_LISTENER_NO_POOL_FILTER = """
from ..planner.protocols import MorphDecision

class ReshardListener:
    async def _consume(self, sub):
        async for msg in sub:
            decision = MorphDecision.from_bytes(msg.payload)
            if decision.worker_id not in (0, self.worker_id):
                continue
            if decision.ts < 0:
                continue
            await self._apply(decision.tp)
"""


def test_dead_wire_field_reproduces_morphdecision_pool():
    """The PR 12 bug verbatim: MorphDecision.pool serialized, listener
    filters worker_id but never pool — a decode-pool grow would morph
    prefill workers sharing the subject."""
    files = {
        "dynamo_tpu/planner/protocols.py": _MORPH_PROTO,
        "dynamo_tpu/resilience/reshard.py": _MORPH_LISTENER_NO_POOL_FILTER,
    }
    vs = contracts_fired(files, "dead-wire-field")
    assert len(vs) == 1
    assert "MorphDecision.pool" in vs[0].message
    assert vs[0].path == "dynamo_tpu/planner/protocols.py"


def test_dead_wire_field_clean_when_listener_filters_pool():
    files = {
        "dynamo_tpu/planner/protocols.py": _MORPH_PROTO,
        "dynamo_tpu/resilience/reshard.py":
            _MORPH_LISTENER_NO_POOL_FILTER.replace(
                "if decision.ts < 0:",
                "if decision.pool != self.pool or decision.ts < 0:",
            ),
    }
    assert contracts_fired(files, "dead-wire-field") == []


def test_dead_wire_field_traces_self_attr_and_annotation():
    # the MetricsComponent shape: from_bytes lands on a self attr, a
    # later local alias reads the fields; annotations type parameters
    files = {
        "dynamo_tpu/planner/protocols.py": _MORPH_PROTO,
        "dynamo_tpu/observability/fake.py": """
        from ..planner.protocols import MorphDecision

        class C:
            def consume(self, raw):
                self.last = MorphDecision.from_bytes(raw)

            def render(self):
                d = self.last
                return (d.pool, d.tp, d.ts)

        def apply(decision: MorphDecision):
            return decision.worker_id
        """,
    }
    assert contracts_fired(files, "dead-wire-field") == []


def test_dead_wire_field_test_only_reads_do_not_count():
    files = {
        "dynamo_tpu/planner/protocols.py": _MORPH_PROTO,
        "dynamo_tpu/resilience/reshard.py": _MORPH_LISTENER_NO_POOL_FILTER,
        # a test reads .pool — production is still dead
        "tests/test_fake.py": """
        from dynamo_tpu.planner.protocols import MorphDecision

        def test_pool():
            assert MorphDecision.from_bytes(b'{}').pool == "decode"
        """,
    }
    vs = contracts_fired(files, "dead-wire-field")
    assert len(vs) == 1 and "MorphDecision.pool" in vs[0].message


# ---------------------------------------------------------------------------
# version-advertised-unchecked
# ---------------------------------------------------------------------------


def test_version_advertised_unchecked_fires():
    files = {
        "dynamo_tpu/disagg/worker.py": """
        class D:
            def _connection(self):
                conn = {"address": self.addr}
                conn["kv_flux"] = 3
                return conn
        """,
    }
    vs = contracts_fired(files, "version-advertised-unchecked")
    fired = {v.message.split("'")[1] for v in vs}
    assert "kv_flux" in fired


def test_version_advertised_checked_passes():
    files = {
        "dynamo_tpu/disagg/worker.py": """
        class D:
            def _connection(self):
                conn = {}
                conn["kv_flux"] = 3
                return conn
        """,
        "dynamo_tpu/disagg/ici.py": """
        def negotiated(connection):
            return int(connection.get("kv_flux") or 0) >= 3
        """,
    }
    assert contracts_fired(files, "version-advertised-unchecked") == []


# ---------------------------------------------------------------------------
# commit-block-purity
# ---------------------------------------------------------------------------


def _engine_with_commit(body):
    return {
        "dynamo_tpu/engine/engine.py": f"""
        class E:
            def _commit(self, req, new_k, new_v):
                staged = req["staged"]
                # dynflow: commit-block -- test fixture
{textwrap.indent(textwrap.dedent(body), "                ")}
                # dynflow: end-commit-block
                return True
        """,
    }


def test_commit_block_call_fires():
    vs = contracts_fired(
        _engine_with_commit("self.use_pallas = self._use_pallas_for(req)"),
        "commit-block-purity",
    )
    assert len(vs) == 1 and "call" in vs[0].message
    # evidence anchors the block's begin marker
    assert any("commit-block" in s.note for s in vs[0].evidence)


def test_commit_block_await_and_nonlocal_subscript_fire():
    bad = """
    self.params = await self.stage()
    self.stats["resharded"] += 1
    """
    vs = contracts_fired(_engine_with_commit(bad), "commit-block-purity")
    kinds = sorted(v.message.split(" ", 1)[0] for v in vs)
    assert len(vs) >= 2
    assert any("await" in v.message for v in vs)
    assert any("non-local" in v.message for v in vs)


def test_commit_block_statement_type_fires():
    vs = contracts_fired(
        _engine_with_commit("for x in req:\n    self.y = x"),
        "commit-block-purity",
    )
    assert len(vs) == 1 and "For statement" in vs[0].message


def test_commit_block_pure_assignments_pass():
    good = """
    self.params = staged
    self.k_cache, self.v_cache = new_k, new_v
    if new_k is not None:
        self.mesh = req["mesh"]
    self.use_pallas = staged
    """
    assert contracts_fired(
        _engine_with_commit(good), "commit-block-purity"
    ) == []


def test_commit_block_markers_in_tests_ignored():
    files = {
        "tests/test_fake.py": """
        def f(q):
            # dynflow: commit-block
            q.pop()
            # dynflow: end-commit-block
        """,
    }
    assert contracts_fired(files, "commit-block-purity") == []


# ---------------------------------------------------------------------------
# dashboard-metric-without-producer
# ---------------------------------------------------------------------------

_RENDER_MODULE = {
    "dynamo_tpu/http/metrics.py": """
    REQUESTS_TOTAL = "http_service_requests_total"

    class Metrics:
        def render(self):
            return REQUESTS_TOTAL
    """,
    "dynamo_tpu/observability/component.py": """
    WORKER_HIST_FAMILIES = ("worker_queue_wait_ms",)

    class C:
        def render(self):
            lines = []

            def gauge(name, value):
                lines.append(name + " " + str(value))

            gauge("worker_count", 1)
            return lines
    """,
}


def _dashboard_json(*exprs):
    panels = [
        {"type": "stat", "targets": [{"expr": e, "refId": "A"}]}
        for e in exprs
    ]
    return json.dumps({"title": "t", "panels": panels})


def test_dashboard_metric_without_producer_fires():
    files = dict(_RENDER_MODULE)
    files["dynamo_tpu/deploy/metrics/grafana-dashboard.json"] = (
        _dashboard_json("sum(rate(dynamo_tpu_ghost_series_total[1m]))")
    )
    vs = contracts_fired(files, "dashboard-metric-without-producer")
    assert len(vs) == 1
    v = vs[0]
    assert v.path.endswith("grafana-dashboard.json")
    assert "dynamo_tpu_ghost_series_total" in v.message
    # evidence names the render surface the series is absent from
    assert any("metrics.py" in s.path or "component.py" in s.path
               for s in v.evidence)


def test_dashboard_metric_with_producer_passes():
    files = dict(_RENDER_MODULE)
    files["dynamo_tpu/deploy/metrics/grafana-dashboard.json"] = (
        _dashboard_json(
            # gauge() literal, ALL_CAPS constant, and a histogram
            # family resolved through suffix stripping + the declared
            # WORKER_HIST_FAMILIES tuple
            "dynamo_tpu_worker_count",
            "sum by (status) (rate(dynamo_tpu_http_service_requests_total[1m]))",
            "histogram_quantile(0.99, sum by (le) "
            "(rate(dynamo_tpu_worker_queue_wait_ms_bucket[5m])))",
        )
    )
    assert contracts_fired(files, "dashboard-metric-without-producer") == []


def test_dashboard_rule_quiet_without_render_modules():
    """A partial file set (dashboard alone) has no producer surface to
    judge against — the rule must stay quiet, not fire on everything."""
    files = {
        "dynamo_tpu/deploy/metrics/grafana-dashboard.json": (
            _dashboard_json("dynamo_tpu_anything_at_all")
        ),
    }
    assert contracts_fired(files, "dashboard-metric-without-producer") == []


def test_dashboard_rule_real_tree_collects_dashboard():
    """read_files picks the shipped dashboard up next to the .py tree,
    and the real dashboard's every query resolves (the acceptance
    invariant this rule exists to hold)."""
    from dynamo_tpu.analysis.engine import read_files

    files, _ = read_files([os.path.join(REPO, "dynamo_tpu")])
    assert any(p.endswith("grafana-dashboard.json") for p in files)
    vs = [
        v for v in check_contracts(files)
        if v.rule == "dashboard-metric-without-producer"
    ]
    assert vs == [], [v.message for v in vs]


# ---------------------------------------------------------------------------
# program-mode suppressions, CLI, JSON
# ---------------------------------------------------------------------------


def _write_tree(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def test_program_suppression_counts(tmp_path):
    _write_tree(tmp_path, {
        "dynamo_tpu/kv_router/fakeproto.py":
            "DEAD_SUBJECT = 'dead'  "
            "# dynlint: disable=subject-without-subscriber -- fixture\n",
    })
    report = check_program([str(tmp_path / "dynamo_tpu")])
    assert report.ok and report.suppressed == 1


def test_program_cli_exit_codes_and_json(tmp_path, capsys):
    _write_tree(tmp_path, {
        "dynamo_tpu/kv_router/fakeproto.py": "DEAD_SUBJECT = 'dead'\n",
    })
    rc = lint_main(["--program", str(tmp_path / "dynamo_tpu")])
    out = capsys.readouterr().out
    assert rc == 1 and "subject-without-subscriber" in out
    assert "dynflow:" in out

    rc = lint_main(["--program", "--json", str(tmp_path / "dynamo_tpu")])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1 and data["ok"] is False
    assert data["violations"][0]["rule"] == "subject-without-subscriber"

    # evidence chains ride the JSON for findings that carry them
    _write_tree(tmp_path, {
        "dynamo_tpu/planner/protocols.py": _MORPH_PROTO,
        "dynamo_tpu/resilience/reshard.py": _MORPH_LISTENER_NO_POOL_FILTER,
    })
    rc = lint_main([
        "--program", "--json", "--rule", "dead-wire-field",
        str(tmp_path / "dynamo_tpu"),
    ])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert data["violations"][0]["evidence"][0]["path"].endswith(
        "protocols.py"
    )


def test_program_cli_rule_filter_and_conflicts(capsys):
    assert lint_main(["--program", "--rule", "nope"]) == 2
    assert lint_main(["--program", "--changed"]) == 2
    capsys.readouterr()


def test_changed_cli_on_clean_repo_is_fast(tmp_path, capsys):
    # outside a git repo: falls back to the full walk (still correct)
    import subprocess

    _write_tree(tmp_path, {"dynamo_tpu/engine/ok.py": "x = 1\n"})
    subprocess.run(["git", "init", "-q"], cwd=tmp_path, check=True)
    subprocess.run(["git", "add", "-A"], cwd=tmp_path, check=True)
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-qm", "seed"],
        cwd=tmp_path, check=True,
    )
    old = os.getcwd()
    os.chdir(tmp_path)
    try:
        rc = lint_main(["--changed", "dynamo_tpu/"])
        out = capsys.readouterr().out
        assert rc == 0 and "0 changed files" in out
        # touch a file with a violation: --changed picks exactly it up
        bad = tmp_path / "dynamo_tpu/engine/bad.py"
        bad.write_text("import time\nasync def f():\n    time.sleep(1)\n")
        rc = lint_main(["--changed", "dynamo_tpu/"])
        out = capsys.readouterr().out
        assert rc == 1 and "async-blocking-call" in out
        assert "1 file" in out
        # from a SUBDIRECTORY: git emits repo-root-relative paths, so
        # resolving them against the invocation cwd silently dropped
        # every touched file — a false-clean pre-commit gate (review
        # finding, reproduced). Paths must re-anchor at the repo root.
        os.chdir(tmp_path / "dynamo_tpu")
        rc = lint_main(["--changed", "dynamo_tpu/", "tests/"])
        out = capsys.readouterr().out
        assert rc == 1 and "async-blocking-call" in out
    finally:
        os.chdir(old)


# ---------------------------------------------------------------------------
# the meta-test: the real tree is contract-clean in --program mode
# ---------------------------------------------------------------------------


def test_real_tree_is_contract_clean():
    """The second acceptance gate: `python -m dynamo_tpu.analysis
    --program dynamo_tpu/ tests/` exits 0 on this tree. Every
    suppression carries a written justification — the report counts
    them, so a tree 'cleaned' by silencing is visible as such."""
    report = check_program(
        [os.path.join(REPO, "dynamo_tpu"), os.path.join(REPO, "tests")]
    )
    assert report.files_checked > 100
    msgs = "\n".join(
        f"{v.path}:{v.line}: [{v.rule}] {v.message}"
        for v in report.violations
    )
    assert not report.violations, f"dynflow violations:\n{msgs}"
    assert not report.errors
    # the suppression inventory is deliberate, not accidental silence:
    # every one is a reviewed diagnostic-surface or audit-field call
    assert report.suppressed >= 20


def test_real_tree_model_extracts_every_plane():
    """The model is only as good as its extraction: prove each plane is
    populated on the real tree (an extractor regression that silently
    stops seeing a plane would otherwise read as 'clean')."""
    from dynamo_tpu.analysis.engine import read_files
    from dynamo_tpu.analysis.program import build_model

    files, _ = read_files([os.path.join(REPO, "dynamo_tpu")])
    m = build_model(files)
    assert len(m.subject_constants) >= 8
    assert set(m.subjects_published) >= {
        "KV_EVENT_SUBJECT", "KV_PREFETCH_SUBJECT", "KV_PEER_FETCH_SUBJECT",
        "KV_HIT_RATE_SUBJECT", "PLANNER_RESHARD_SUBJECT",
        "PLANNER_DECISION_SUBJECT", "PLANNER_WATERMARK_SUBJECT",
        "TRACE_EVENTS_SUBJECT",
    }
    assert set(m.subjects_subscribed) >= set(m.subjects_published)
    assert {"request_id", "stream", "b0", "fin", "ici"} <= set(
        m.header_writes
    )
    assert "kv_slice_fp" in m.stats_produced
    assert "kv_slice_fp" in m.stats_scraped
    assert len(m.workerload_fields) >= 45
    assert "MorphDecision" in m.wire_classes
    assert "pool" in m.wire_field_reads.get("MorphDecision", {})
    assert {"kv_stream", "kv_ici", "ici_fp"} <= set(m.conn_advertised)
    assert {"kv_stream", "kv_ici", "ici_fp"} <= set(m.conn_checked)
    # the dashboard contract's producer surface (ISSUE 15): frontend
    # histogram families + component gauges + worker hist families
    assert {
        "http_service_first_token_seconds", "http_service_requests_total",
        "slo_breaches_total", "worker_count", "worker_queue_wait_ms",
        "hbm_bytes_in_use", "xla_compiles_total",
    } <= set(m.metrics_rendered)
    assert any(
        cb.path.endswith("engine/engine.py") for cb in m.commit_blocks
    )


# ---------------------------------------------------------------------------
# executor pressure (sanitizer satellite): register -> counter -> scrape
# -> WorkerLoad -> gauge
# ---------------------------------------------------------------------------


def test_register_executor_tracks_pending_max():
    import threading
    from concurrent.futures import ThreadPoolExecutor

    before = sanitizer.COUNTERS["san_executor_pending_max"]
    ex = ThreadPoolExecutor(max_workers=1)
    sanitizer.register_executor(ex, "test-pool")
    sanitizer.register_executor(ex, "test-pool")  # idempotent
    gate = threading.Event()
    futs = [ex.submit(gate.wait, 5) for _ in range(4)]
    try:
        pend = sanitizer.executor_pending()["test-pool"]
        assert pend["max"] >= 4
        assert sanitizer.COUNTERS["san_executor_pending_max"] >= max(before, 4)
    finally:
        gate.set()
        for f in futs:
            f.result(timeout=5)
        ex.shutdown(wait=True)
    # drained: live pending returns to zero, high-water stays
    assert sanitizer.executor_pending()["test-pool"]["pending"] == 0
    assert sanitizer.executor_pending()["test-pool"]["max"] >= 4


def test_executor_pending_flows_to_workerload_and_gauge():
    from dynamo_tpu.kv_router.scheduler import ProcessedEndpoints, WorkerLoad
    from dynamo_tpu.observability.component import MetricsComponent

    w = WorkerLoad.from_stats(7, {
        "san_executor_pending_max": 9,
        "san_lock_holds": 4,
        "disk_corrupt_discards": 2,
        "disk_demotions_total": 11,
        "peer_serve_blocks_total": 13,
        "drain_handoffs": 5,
    })
    assert w.executor_pending_max == 9
    assert w.lock_holds == 4

    mc = MetricsComponent.__new__(MetricsComponent)
    mc.prefix = "dynamo_tpu"
    mc.aggregator = type(
        "A", (), {"endpoints": ProcessedEndpoints([w])}
    )()
    mc.hit_events = mc.hit_isl_blocks = mc.hit_overlap_blocks = 0
    mc.planner_decision = mc.planner_watermark = None
    mc.planner_decisions_total = 0
    mc.tracing = None
    text = mc.render()
    assert 'dynamo_tpu_executor_pending_max{worker="7"} 9' in text
    assert 'dynamo_tpu_lock_holds_total{worker="7"} 4' in text
    # the PR 9 chain the unscraped-stat rule found dropped mid-pipeline
    assert 'dynamo_tpu_disk_corrupt_discards_total{worker="7"} 2' in text
    assert 'dynamo_tpu_disk_demotions_total{worker="7"} 11' in text
    assert 'dynamo_tpu_peer_serve_blocks_total{worker="7"} 13' in text
    assert 'dynamo_tpu_drain_handoffs_total{worker="7"} 5' in text


def test_offload_executor_registers_for_pressure_tracking():
    from dynamo_tpu.engine.offload import OffloadManager

    om = OffloadManager(host_blocks=4)
    try:
        om._executor()
        assert "offload" in sanitizer.executor_pending()
    finally:
        om.close()


def test_planner_decision_slo_view_renders():
    """PlannerDecision.ttft_p99_ms/itl_p99_ms/prompt_token_rate rode the
    wire unread (dynflow dead-wire-field finding) — now rendered."""
    from dynamo_tpu.kv_router.scheduler import ProcessedEndpoints
    from dynamo_tpu.observability.component import MetricsComponent
    from dynamo_tpu.planner.protocols import PlannerDecision

    mc = MetricsComponent.__new__(MetricsComponent)
    mc.prefix = "dynamo_tpu"
    mc.aggregator = type("A", (), {"endpoints": ProcessedEndpoints([])})()
    mc.hit_events = mc.hit_isl_blocks = mc.hit_overlap_blocks = 0
    mc.planner_decision = PlannerDecision(
        decode_replicas=2, prefill_replicas=1, ttft_p99_ms=321.5,
        itl_p99_ms=12.25, prompt_token_rate=1000.0,
    )
    mc.planner_watermark = None
    mc.planner_decisions_total = 3
    mc.tracing = None
    text = mc.render()
    assert "dynamo_tpu_planner_ttft_p99_ms 321.5" in text
    assert "dynamo_tpu_planner_itl_p99_ms 12.25" in text
    assert "dynamo_tpu_planner_prompt_token_rate 1000.0" in text

"""dynlint + runtime sanitizer tests (dynamo_tpu/analysis/).

Contract per docs/static_analysis.md: every rule has at least one BAD
fixture proving it fires and a GOOD fixture proving the sanctioned
pattern passes; suppression comments work line-, next-line- and
file-wide; and the meta-test at the bottom pins the real tree clean —
the CI gate (scripts/check.sh) is `python -m dynamo_tpu.analysis
dynamo_tpu/ tests/` exiting 0.
"""

import asyncio
import json
import os
import textwrap
import time

import pytest

from dynamo_tpu.analysis import lint_paths, lint_source
from dynamo_tpu.analysis.__main__ import main as lint_main
from dynamo_tpu.analysis import sanitizer
from dynamo_tpu.analysis.rules import FaultpointCoverageRule

REPO = os.path.join(os.path.dirname(__file__), "..")

# default virtual path: event-loop package, so loop-scoped rules apply
ENGINE_PATH = "dynamo_tpu/engine/fake.py"


def rules_fired(code, path=ENGINE_PATH):
    vs, _ = lint_source(path, textwrap.dedent(code))
    return [v.rule for v in vs]


def violations(code, path=ENGINE_PATH):
    vs, _ = lint_source(path, textwrap.dedent(code))
    return vs


# ---------------------------------------------------------------------------
# rule 1: async-blocking-call
# ---------------------------------------------------------------------------


def test_async_blocking_call_fires():
    bad = """
    import time
    async def pump():
        time.sleep(0.1)
    """
    assert rules_fired(bad) == ["async-blocking-call"]


def test_async_blocking_call_tobytes_and_block_until_ready():
    bad = """
    async def send(arr, jax):
        buf = arr.tobytes()
        jax.block_until_ready(arr)
    """
    assert rules_fired(bad) == ["async-blocking-call"] * 2


def test_async_blocking_call_np_asarray_in_async():
    bad = """
    import numpy as np
    async def land(seg):
        return np.asarray(seg)
    """
    assert rules_fired(bad) == ["async-blocking-call"]


def test_async_blocking_call_socket_receiver_filter():
    bad = """
    async def pump(sock, s, conn):
        sock.recv(4)
        s.sendall(b"x")
        conn.accept()
    """
    assert rules_fired(bad) == ["async-blocking-call"] * 3
    # non-socket receivers with socket-ish method names must NOT fire
    # (nor should every `self.*` — the filter is name-based, not "any
    # receiver containing the letter s")
    good = """
    async def pump(self):
        self.results.accept()
        await self.stream.recv()
    """
    assert rules_fired(good) == []


def test_async_blocking_call_good_patterns():
    good = """
    import asyncio
    import numpy as np
    async def pump(arr):
        await asyncio.sleep(0.1)          # async sleep is fine
        loop = asyncio.get_running_loop()
        host = await loop.run_in_executor(None, lambda: np.asarray(arr))
        return host

    def sync_helper(arr):
        return np.asarray(arr)            # sync scope: not the loop
    """
    assert rules_fired(good) == []


def test_async_blocking_call_scoped_to_event_loop_packages():
    bad = """
    import time
    async def f():
        time.sleep(1)
    """
    # ops/ and models/ are compute modules, not event-loop code
    assert rules_fired(bad, "dynamo_tpu/ops/fake.py") == []
    assert rules_fired(bad, "dynamo_tpu/models/fake.py") == []
    assert rules_fired(bad, "dynamo_tpu/disagg/fake.py") == [
        "async-blocking-call"
    ]


# ---------------------------------------------------------------------------
# rule 2: await-in-lock
# ---------------------------------------------------------------------------


def test_await_in_lock_fires_on_network_await():
    bad = """
    async def step(self, writer, msg):
        async with self._device_lock:
            await writer.drain()
    """
    assert rules_fired(bad) == ["await-in-lock"]


def test_await_in_lock_fires_on_queue_await():
    bad = """
    async def step(self):
        async with self._lock:
            item = await self.sendq.get()
    """
    assert rules_fired(bad) == ["await-in-lock"]


def test_await_in_lock_blames_the_lock_item_not_items0():
    bad = """
    import asyncio
    async def step(self, writer):
        async with asyncio.timeout(5), self._device_lock:
            await writer.drain()
    """
    vs = violations(bad)
    assert [v.rule for v in vs] == ["await-in-lock"]
    assert "_device_lock" in vs[0].message  # not asyncio.timeout(5)


def test_await_in_lock_allows_executor_dispatch():
    good = """
    import asyncio
    async def step(self, steps):
        async with self._device_lock:
            toks = await asyncio.get_running_loop().run_in_executor(
                None, self._dispatch, steps
            )
        await self.out_queue.put(toks)   # after release: fine
    """
    assert rules_fired(good) == []


def test_await_in_lock_ignores_nested_function_bodies():
    good = """
    async def step(self):
        async with self._device_lock:
            async def later(writer):
                await writer.drain()      # runs OUTSIDE the lock
            self.cb = later
    """
    assert rules_fired(good) == []


# ---------------------------------------------------------------------------
# rule 3: jit-in-function
# ---------------------------------------------------------------------------


def test_jit_in_function_fires():
    bad = """
    import jax
    def admit(fn):
        wrapped = jax.jit(fn)
        return wrapped
    """
    assert rules_fired(bad, "dynamo_tpu/engine/fake.py") == [
        "jit-in-function"
    ]


def test_jit_partial_in_function_fires():
    bad = """
    import functools, jax
    async def admit(fn):
        return functools.partial(jax.jit, static_argnames=("n",))(fn)
    """
    assert rules_fired(bad) == ["jit-in-function"]


def test_jit_module_scope_and_decorators_pass():
    good = """
    import functools, jax

    _sample = jax.jit(lambda x: x)

    @functools.partial(jax.jit, static_argnames=("n",))
    def step(x, n):
        return x

    @jax.jit
    def other(x):
        return x

    class Model:
        @functools.partial(jax.jit, static_argnames=("self",))
        def fwd(self, x):
            return x
    """
    assert rules_fired(good) == []


def test_jit_nested_def_decorator_is_runtime():
    bad = """
    import jax
    def build():
        @jax.jit
        def inner(x):
            return x
        return inner
    """
    assert rules_fired(bad) == ["jit-in-function"]


# ---------------------------------------------------------------------------
# rule 4: raw-header-subscript
# ---------------------------------------------------------------------------

DECODER_PATH = "dynamo_tpu/disagg/transfer.py"


def test_raw_header_subscript_fires():
    bad = """
    def decode(frame):
        header = frame.header_json()
        return header["n_blocks"]
    """
    assert rules_fired(bad, DECODER_PATH) == ["raw-header-subscript"]


def test_raw_header_subscript_or_default_idiom_tracked():
    bad = """
    def decode(frame):
        h = frame.header_json() or {}
        return h["b0"]
    """
    assert rules_fired(bad, DECODER_PATH) == ["raw-header-subscript"]


def test_raw_header_subscript_good_and_scope():
    good = """
    def decode(frame):
        h = frame.header_json() or {}
        b0 = h.get("b0")
        v = frame.header_field("version", 0)
        h2 = {}
        h2["build"] = 1     # store: building a header is fine
        return b0, v
    """
    assert rules_fired(good, DECODER_PATH) == []
    # outside decoder modules the name `header` is unconstrained
    bad_elsewhere = """
    def f(header):
        return header["x"]
    """
    assert rules_fired(bad_elsewhere, "dynamo_tpu/planner/fake.py") == []


# ---------------------------------------------------------------------------
# rule 5: writer-wait-closed
# ---------------------------------------------------------------------------


def test_writer_wait_closed_fires():
    bad = """
    async def handle(reader, writer):
        writer.write(b"x")
        writer.close()
    """
    assert rules_fired(bad) == ["writer-wait-closed"]


def test_writer_wait_closed_good():
    good = """
    async def handle(reader, writer):
        try:
            writer.write(b"x")
        finally:
            writer.close()
            await writer.wait_closed()

    async def teardown(self):
        self._server.close()
        await self._server.wait_closed()

    async def hard_abort(writer):
        writer.close()
        writer.abort()     # hard teardown: transport drops synchronously
    """
    assert rules_fired(good) == []


def test_writer_wait_closed_ignores_non_writers():
    good = """
    async def f(self):
        self._wal.close()
        self.store.close()
    """
    assert rules_fired(good) == []


# ---------------------------------------------------------------------------
# rule 6: faultpoint-test-coverage (project rule)
# ---------------------------------------------------------------------------

FAULTPOINTS_SRC = """
POINTS = (
    "admission",
    "mid_decode",
)
"""


def test_faultpoint_coverage_fires_for_unreferenced_point():
    files = {
        "dynamo_tpu/resilience/faultpoints.py": FAULTPOINTS_SRC,
        "tests/test_x.py": "faultpoints.arm('admission')",
    }
    vs = FaultpointCoverageRule().check_project(files)
    assert [v.rule for v in vs] == ["faultpoint-test-coverage"]
    assert "mid_decode" in vs[0].message


def test_faultpoint_coverage_clean_when_all_referenced():
    files = {
        "dynamo_tpu/resilience/faultpoints.py": FAULTPOINTS_SRC,
        "tests/test_x.py": "arm('admission'); arm('mid_decode')",
    }
    assert FaultpointCoverageRule().check_project(files) == []


def test_faultpoint_coverage_skipped_without_tests_in_path_set():
    files = {"dynamo_tpu/resilience/faultpoints.py": FAULTPOINTS_SRC}
    assert FaultpointCoverageRule().check_project(files) == []


# ---------------------------------------------------------------------------
# rule 7: swallowed-exception
# ---------------------------------------------------------------------------


def test_swallowed_exception_fires():
    bad = """
    def loop():
        try:
            work()
        except Exception:
            pass
    """
    assert rules_fired(bad) == ["swallowed-exception"]


def test_swallowed_exception_bare_except_fires():
    bad = """
    def loop():
        try:
            work()
        except:
            pass
    """
    assert rules_fired(bad) == ["swallowed-exception"]


def test_swallowed_exception_good():
    good = """
    import logging
    logger = logging.getLogger(__name__)
    def loop():
        try:
            work()
        except Exception:
            logger.debug("work failed", exc_info=True)
        try:
            other()
        except (ConnectionResetError, BrokenPipeError):
            pass    # narrow type: an explicit decision, not a swallow
    """
    assert rules_fired(good) == []


# ---------------------------------------------------------------------------
# rule 8: blocking-disk-io
# ---------------------------------------------------------------------------


def test_blocking_disk_io_fires():
    bad = """
    import os
    async def land(path, h):
        with open(path, "rb") as f:
            raw = f.read()
        os.remove(path)
        return raw
    """
    # open() + f.read() (file-shaped receiver) + os.remove
    assert rules_fired(bad) == ["blocking-disk-io"] * 3


def test_blocking_disk_io_pathlib_and_file_receivers():
    bad = """
    async def demote(p, fh):
        p.write_bytes(b"x")
        fh.write(b"y")
        fh.flush()
    """
    assert rules_fired(bad) == ["blocking-disk-io"] * 3


def test_blocking_disk_io_good_patterns():
    """Executor dispatch passes a function REFERENCE (the sanctioned
    pattern for the disk tier), sync helpers may do file I/O freely,
    and asyncio StreamWriter/StreamReader write/read never fire."""
    good = """
    import asyncio
    def disk_put(store, h, k, v):   # sync helper: runs on the executor
        with open(store.path, "wb") as f:
            f.write(k)
    async def promote(loop, store, hashes):
        await loop.run_in_executor(None, store.promote_chain, hashes)
    async def send(writer, reader):
        writer.write(b"frame")       # StreamWriter: non-blocking
        await writer.drain()
        return await reader.read(4)  # StreamReader: awaited, fine
    """
    assert rules_fired(good) == []


def test_blocking_disk_io_scoped_to_event_loop_packages():
    bad = """
    async def snapshot(path):
        open(path)
    """
    assert rules_fired(bad, path="dynamo_tpu/deploy/builder.py") == []
    assert rules_fired(bad, path="dynamo_tpu/engine/offload.py") == [
        "blocking-disk-io"
    ]


# ---------------------------------------------------------------------------
# rule 9: span-leak
# ---------------------------------------------------------------------------


def test_span_leak_fires_on_unended_handle():
    bad = """
    from .. import tracing
    async def handle(req):
        sp = tracing.span("worker.handle", request_id=req.id)
        await work(req)
    """
    assert rules_fired(bad) == ["span-leak"]


def test_span_leak_fires_on_discarded_span():
    bad = """
    from .. import tracing
    def f():
        tracing.span("dropped")
    """
    assert rules_fired(bad) == ["span-leak"]


def test_span_leak_good_patterns():
    good = """
    from .. import tracing
    async def ctx(req):
        with tracing.span("prefill.compute"):
            await work(req)

    async def manual(req):
        sp = tracing.span("worker.handle")
        try:
            await work(req)
        finally:
            sp.end()

    async def handle_as_ctx(req):
        sp = tracing.span("send")
        with sp:
            await work(req)
    """
    assert rules_fired(good) == []


# ---------------------------------------------------------------------------
# rule 10: mesh-capture
# ---------------------------------------------------------------------------


def test_mesh_capture_fires_on_module_scope():
    bad = """
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from ..parallel.mesh import make_mesh

    MESH = Mesh(jax.devices(), ("tp",))
    CACHE_SH = NamedSharding(MESH, P(None, "tp"))
    DEFAULT = make_mesh()
    """
    assert rules_fired(bad) == ["mesh-capture"] * 3


def test_mesh_capture_fires_on_class_scope_and_defaults():
    bad = """
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from ..parallel.mesh import cache_sharding

    class Engine:
        # class bodies execute at import: this placement outlives any
        # morph the instances perform
        sharding = NamedSharding(MESH, P("tp"))

    def scatter(x, sh=cache_sharding(MESH, CFG)):
        return x
    """
    assert rules_fired(bad) == ["mesh-capture"] * 2


def test_mesh_capture_good_patterns():
    good = """
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # logical specs ARE the layer module scope may hold (mesh-free)
    CACHE_SPEC = P(None, "tp", None)
    SPECS = {"wq": P(None, "tp")}

    def resolve(mesh, cfg):
        # call-time resolution against the CURRENT mesh: the pattern
        # LogicalLayout/ MeshMorpher institutionalize
        return NamedSharding(mesh, CACHE_SPEC)

    class Mover:
        def _dst(self, devs):
            return NamedSharding(Mesh(devs, ("ici",)), P())

        def inner_default(self):
            # nested defaults evaluate at call time, not import
            def f(sh=NamedSharding(self.mesh, P())):
                return sh
            return f
    """
    assert rules_fired(good) == []


def test_mesh_capture_skips_defs_nested_in_module_level_blocks():
    good = """
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # conditional definition: the def EXECUTES at import (so its
    # defaults would be import-time) but its BODY is call time — a
    # walk that descends module-level if/try statements wholesale
    # would false-positive here and break CI on a correct pattern
    try:
        from fast import resolve
    except ImportError:
        def resolve(mesh):
            return NamedSharding(mesh, P("tp"))

    if True:
        fallback = lambda mesh: NamedSharding(mesh, P())
    """
    assert rules_fired(good) == []
    bad = """
    from jax.sharding import Mesh

    # ...but a def nested in a module-level block still evaluates its
    # DEFAULTS at import, and a bare call in the block body executes
    try:
        def scatter(x, sh=Mesh(devices, ("tp",))):
            return x
    except Exception:
        MESH = Mesh(devices, ("tp",))
    """
    assert rules_fired(bad) == ["mesh-capture"] * 2


def test_mesh_capture_scoped_to_engine_ops_packages():
    bad = """
    from jax.sharding import Mesh
    MESH = Mesh(devices, ("tp",))
    """
    # outside the placement-bearing packages (e.g. the launch CLI or a
    # test helper) the rule stays quiet
    assert rules_fired(bad, path="dynamo_tpu/launch/fake.py") == []
    assert rules_fired(bad, path="dynamo_tpu/ops/fake.py") == ["mesh-capture"]


# ---------------------------------------------------------------------------
# suppressions + report plumbing
# ---------------------------------------------------------------------------


def test_suppression_same_line_and_counted():
    code = """
    import time
    async def f():
        time.sleep(1)  # dynlint: disable=async-blocking-call -- test fixture
    """
    vs, suppressed = lint_source(ENGINE_PATH, textwrap.dedent(code))
    assert vs == [] and suppressed == 1


def test_suppression_next_line():
    code = """
    import time
    async def f():
        # dynlint: disable=async-blocking-call -- justified
        time.sleep(1)
    """
    vs, suppressed = lint_source(ENGINE_PATH, textwrap.dedent(code))
    assert vs == [] and suppressed == 1


def test_suppression_file_wide_and_star():
    code = """
    # dynlint: disable-file=swallowed-exception
    import time
    async def f():
        time.sleep(1)  # dynlint: disable=* -- everything on this line
        try:
            work()
        except Exception:
            pass
    """
    vs, suppressed = lint_source(ENGINE_PATH, textwrap.dedent(code))
    assert vs == [] and suppressed == 2


def test_suppression_wrong_rule_does_not_cover():
    code = """
    import time
    async def f():
        time.sleep(1)  # dynlint: disable=span-leak -- wrong rule name
    """
    vs, _ = lint_source(ENGINE_PATH, textwrap.dedent(code))
    assert [v.rule for v in vs] == ["async-blocking-call"]


def test_syntax_error_reported_as_violation():
    vs, _ = lint_source(ENGINE_PATH, "def broken(:\n")
    assert [v.rule for v in vs] == ["syntax-error"]


def test_lint_paths_and_cli_on_fixture_tree(tmp_path, capsys):
    pkg = tmp_path / "dynamo_tpu" / "engine"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "import time\nasync def f():\n    time.sleep(1)\n"
    )
    report = lint_paths([str(tmp_path)], root=str(tmp_path))
    assert not report.ok
    assert [v.rule for v in report.violations] == ["async-blocking-call"]
    assert report.violations[0].path == "dynamo_tpu/engine/bad.py"
    # CLI: exit 1 + JSON shape
    rc = lint_main(["--json", str(tmp_path)])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] is False and len(out["violations"]) == 1
    # fix it -> exit 0
    (pkg / "bad.py").write_text(
        "import asyncio\nasync def f():\n    await asyncio.sleep(1)\n"
    )
    assert lint_main([str(tmp_path)]) == 0


def test_cli_unknown_rule_and_list_rules(capsys):
    assert lint_main(["--rule", "no-such-rule", "."]) == 2
    capsys.readouterr()
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "async-blocking-call" in out and "faultpoint-test-coverage" in out


# ---------------------------------------------------------------------------
# the meta-test: the real tree is clean
# ---------------------------------------------------------------------------


def test_real_tree_is_lint_clean():
    """THE acceptance gate: `python -m dynamo_tpu.analysis dynamo_tpu/
    tests/` exits 0 on this tree. Every deliberate exception carries an
    inline `dynlint: disable` with a justification — if this fails, you
    introduced a new violation of a PR 1-6 invariant (or found a rule
    bug; either way, look before you suppress)."""
    report = lint_paths(
        [os.path.join(REPO, "dynamo_tpu"), os.path.join(REPO, "tests")]
    )
    assert report.files_checked > 100
    msgs = "\n".join(
        f"{v.path}:{v.line}: [{v.rule}] {v.message}"
        for v in report.violations
    )
    assert not report.violations, f"dynlint violations:\n{msgs}"
    assert not report.errors


# ---------------------------------------------------------------------------
# runtime sanitizer
# ---------------------------------------------------------------------------


def test_sanitizer_detects_loop_stall_with_stack():
    async def stall():
        await asyncio.sleep(0.01)
        time.sleep(0.25)  # dynlint: disable=async-blocking-call -- the fixture IS the stall
        await asyncio.sleep(0.01)

    with pytest.raises(sanitizer.SanitizerError) as ei:
        sanitizer.run_sanitized(stall(), stall_s=0.1, strict_stalls=True)
    msg = str(ei.value)
    assert "event-loop stall" in msg
    # the watchdog snapshots the loop thread DURING the stall: the
    # report names the blocking frame, not just a duration
    assert "test_analysis" in msg or "time.sleep" in msg


def test_sanitizer_records_without_strict():
    async def stall():
        time.sleep(0.15)  # dynlint: disable=async-blocking-call -- fixture

    before = sanitizer.counters()["san_loop_stalls"]
    sanitizer.run_sanitized(stall(), stall_s=0.05, strict_stalls=False)
    assert sanitizer.counters()["san_loop_stalls"] > before


def test_sanitizer_lock_hold_histogram_and_naming():
    san = sanitizer.LoopSanitizer(stall_threshold_s=0)

    async def main():
        san.activate()
        lock = sanitizer.name_lock(asyncio.Lock(), "device_lock")
        anon = asyncio.Lock()
        async with lock:
            await asyncio.sleep(0.03)
        async with anon:
            pass

    asyncio.run(main())
    report = san.deactivate()
    assert "device_lock" in report.lock_holds
    h = report.lock_holds["device_lock"]
    assert h.total == 1 and 0.02 < h.max_s < 1.0
    # the anonymous lock histogrammed under its acquire site
    assert len(report.lock_holds) == 2


def test_sanitizer_detects_leaked_writer():
    async def leak():
        server = await asyncio.start_server(
            lambda r, w: None, "127.0.0.1", 0
        )
        port = server.sockets[0].getsockname()[1]
        _r, _w = await asyncio.open_connection("127.0.0.1", port)
        server.close()
        await server.wait_closed()
        # _w never closed -> leak

    with pytest.raises(sanitizer.SanitizerError) as ei:
        sanitizer.run_sanitized(leak(), stall_s=0, strict_writers=True)
    assert "never closed" in str(ei.value)


def test_sanitizer_clean_run_passes_strict():
    async def clean():
        server = await asyncio.start_server(
            lambda r, w: w.close(), "127.0.0.1", 0
        )
        port = server.sockets[0].getsockname()[1]
        r, w = await asyncio.open_connection("127.0.0.1", port)
        w.close()
        await w.wait_closed()
        server.close()
        await server.wait_closed()
        return "ok"

    assert sanitizer.run_sanitized(
        clean(), stall_s=0.5, strict_stalls=True, strict_writers=True
    ) == "ok"
    # patches restored: plain asyncio still works after deactivation
    assert asyncio.run(asyncio.sleep(0, result=1)) == 1
    assert asyncio.Lock.acquire.__qualname__.startswith("Lock.")


def test_sanitizer_pending_task_snapshot():
    async def leaves_task():
        async def forever():
            await asyncio.Event().wait()

        t = asyncio.get_running_loop().create_task(forever())
        t.set_name("orphan")
        await asyncio.sleep(0.01)

    san = sanitizer.LoopSanitizer(stall_threshold_s=0)

    async def main():
        san.activate()
        try:
            await leaves_task()
        finally:
            san.before_shutdown()

    asyncio.run(main())
    report = san.deactivate()
    assert any("orphan" in p for p in report.pending_tasks)


def test_sanitizer_counters_flow_into_engine_load_metrics():
    """The production wiring (satellite): engine load_metrics exports the
    san_* counters, the aggregator folds them into WorkerLoad, and the
    metrics component renders the gauges."""
    from dynamo_tpu.kv_router.scheduler import WorkerLoad

    sanitizer.COUNTERS["san_loop_stalls"] += 1
    sanitizer.COUNTERS["san_loop_stall_max_ms"] = max(
        sanitizer.COUNTERS["san_loop_stall_max_ms"], 123.0
    )
    snap = sanitizer.counters()
    assert snap["san_loop_stalls"] >= 1
    # the WorkerLoad schema carries the sanitizer surface
    w = WorkerLoad(
        worker_id=1,
        loop_stalls=snap["san_loop_stalls"],
        loop_stall_max_ms=snap["san_loop_stall_max_ms"],
        lock_hold_max_ms=snap["san_lock_hold_max_ms"],
        writers_leaked=snap["san_writers_leaked"],
    )
    assert w.loop_stall_max_ms >= 123.0


def test_engine_load_metrics_exports_sanitizer_counters(run):
    from dynamo_tpu.engine.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.config import ModelConfig

    # constructed OUTSIDE the sanitized coroutine: the ctor's first
    # eager ops jit-compile, and test_analysis runs stall-STRICT
    e = JaxEngine(
        EngineConfig(
            model=ModelConfig.tiny(), num_blocks=16, block_size=4,
            max_batch_size=2, max_context=64, prefill_chunk=16,
        ),
        seed=0,
    )

    async def main():
        lm = e.load_metrics()
        for k in ("san_loop_stalls", "san_loop_stall_max_ms",
                  "san_lock_hold_max_ms", "san_writers_leaked"):
            assert k in lm, f"load_metrics missing {k}"
        # the device lock is registered under a stable histogram name
        assert getattr(e._device_lock, "_dyn_san_name", None) == "device_lock"
        await e.close()

    run(main())


def test_metrics_component_renders_sanitizer_gauges():
    from dynamo_tpu.observability.component import MetricsComponent
    from dynamo_tpu.kv_router.scheduler import ProcessedEndpoints, WorkerLoad

    mc = MetricsComponent.__new__(MetricsComponent)
    mc.prefix = "dynamo_tpu"
    mc.aggregator = type(
        "A", (), {
            "endpoints": ProcessedEndpoints([
                WorkerLoad(worker_id=7, loop_stalls=3,
                           loop_stall_max_ms=250.5, lock_hold_max_ms=12.25,
                           writers_leaked=1),
            ])
        },
    )()
    mc.hit_events = mc.hit_isl_blocks = mc.hit_overlap_blocks = 0
    mc.planner_decision = mc.planner_watermark = None
    mc.planner_decisions_total = 0
    mc.tracing = None
    text = mc.render()
    assert 'dynamo_tpu_loop_stalls_total{worker="7"} 3' in text
    assert 'dynamo_tpu_loop_stall_max_ms{worker="7"} 250.5' in text
    assert 'dynamo_tpu_lock_hold_max_ms{worker="7"} 12.25' in text
    assert 'dynamo_tpu_writers_leaked_total{worker="7"} 1' in text

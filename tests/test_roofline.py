"""Roofline model regression tests (VERDICT r4 #1: the numeric chip-free
perf case).

The modeled tokens/s/chip + MFU table (benchmarks/roofline_model.json,
docs/performance.md) is only as trustworthy as its two mechanical
inputs: cost_analysis() FLOPs with the two documented repricings
(ragged_dot dense-overcount, cumsum reduce_window overcount), and the
analytic byte stream.  These tests pin each input:

* both repricing corrections are validated against the mispricing they
  claim to fix (negative controls: if an XLA upgrade fixes the pricing,
  the control FAILS and the correction must be deleted — same honesty
  contract as test_compiled_perf.py's scatter detector);
* the corrected full-depth FLOPs match a from-first-principles count of
  the 8B config within tight tolerance;
* the committed JSON regenerates from the current code for the cheap
  scenario (catches code/artifact drift without re-lowering 70B-class
  programs in CI).
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.perf import roofline as R

ART = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                   "roofline_model.json")


# ---------------------------------------------------------------------------
# the two cost-model corrections stay pinned to real mispricings
# ---------------------------------------------------------------------------


def test_ragged_dot_is_priced_dense_by_cost_analysis():
    """Negative control for the MoE correction: HLO cost analysis must
    still price ragged_dot at X× the executed group-GEMM work.  If this
    fails, XLA learned to price it correctly — DELETE _ragged_overcount."""
    T, H, F, X = 64, 128, 256, 8
    f = jax.jit(lambda x, w, g: lax.ragged_dot(x, w, g))
    ca = f.lower(
        jax.ShapeDtypeStruct((T, H), jnp.bfloat16),
        jax.ShapeDtypeStruct((X, H, F), jnp.bfloat16),
        jax.ShapeDtypeStruct((X,), jnp.int32),
    ).cost_analysis()
    dense = 2.0 * T * H * F * X
    assert ca["flops"] == pytest.approx(dense, rel=0.02), (
        f"ragged_dot no longer priced dense ({ca['flops']:.3g} vs "
        f"{dense:.3g}) — delete the _ragged_overcount correction"
    )


def test_cumsum_is_priced_quadratic_by_cost_analysis():
    """Negative control for the sampling correction: a [1, V] cumsum must
    still be priced ~V² (reduce_window pricing).  If this fails, delete
    _cumulative_overcount."""
    V = 4096
    ca = jax.jit(lambda x: jnp.cumsum(x, axis=-1)).lower(
        jax.ShapeDtypeStruct((1, V), jnp.float32)).cost_analysis()
    assert ca["flops"] >= 0.9 * V * V, (
        f"cumsum priced at {ca['flops']:.3g} ≪ V²={V*V} — delete the "
        "_cumulative_overcount correction"
    )


def test_cumulative_overcount_detects_the_window_cumsum():
    """The detector must find exactly the top-p cumsum in the real
    decode_window lowering (one [B, V] reduce_window)."""
    cfg = ModelConfig.tiny()
    lo = R._decode_lower(
        ModelConfig.tiny(num_layers=1), batch=2, ctx=32)
    over = R._cumulative_overcount(lo, 2, cfg.vocab_size)
    V = cfg.vocab_size
    expect = 2.0 * V * V - 2.0 * 2 * V
    assert over == pytest.approx(expect), (
        "expected exactly ONE [B,V] cumsum (the top-p nucleus mask) in "
        f"the decode window; detector returned {over} (≈{over/expect:.2f}×)"
    )


# ---------------------------------------------------------------------------
# corrected FLOPs match first principles
# ---------------------------------------------------------------------------


def _analytic_decode_flops_per_token(cfg: ModelConfig, ctx: int) -> float:
    """Hand count: 2·(matmul params beyond the embedding gather) plus
    attention score/value dots over the live context."""
    H, Hkv, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    E, F, L, V = (cfg.hidden_size, cfg.intermediate_size, cfg.num_layers,
                  cfg.vocab_size)
    proj = E * (H * D) + 2 * E * (Hkv * D) + (H * D) * E  # q, k, v, o
    ffn = 3 * E * F
    mm = L * (proj + ffn) + E * V  # + lm_head
    # qk and av dots, GQA-expanded to H heads, padded to the block grid
    ctx_pad = math.ceil(ctx / 16) * 16
    attn = L * 2 * H * D * ctx_pad
    return 2.0 * (mm + attn)


def test_decode_flops_match_first_principles_8b():
    cfg = ModelConfig.llama3_8b()
    got = R.decode_flops_per_token(cfg, batch=8, ctx=3075)
    want = _analytic_decode_flops_per_token(cfg, 3075)
    assert got["flops_per_token"] == pytest.approx(want, rel=0.05), (
        f"corrected cost-analysis FLOPs {got['flops_per_token']:.4g} vs "
        f"analytic {want:.4g}"
    )


def test_prefill_flops_match_first_principles_tiny():
    cfg = ModelConfig.tiny()
    seq = 128
    got = R.prefill_flops_per_token(cfg, seq)
    H, D, L = cfg.num_heads, cfg.head_dim, cfg.num_layers
    E, F, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    proj = E * H * D + 2 * E * cfg.num_kv_heads * D + H * D * E
    # lm_head runs once per SEQUENCE (prefill returns last-position
    # logits).  The chunk attention scores [T, M·bs + T]: the cache
    # pages the chunk will occupy are attended (masked, but computed),
    # so the score width is seq (padded pages) + seq (the chunk)
    S = math.ceil(seq / 16) * 16 + seq
    mm = L * (proj + 3 * E * F) + E * V / seq
    attn = L * 2 * H * D * S
    want = 2.0 * (mm + attn)
    assert got["flops_per_token"] == pytest.approx(want, rel=0.15)


def test_moe_flops_scale_with_topk_not_experts():
    """After the ragged correction, doubling the expert count at fixed
    top-k must leave decode FLOPs within a few percent (router grows by
    X, expert GEMMs don't)."""
    base = dict(num_experts=8, num_experts_per_tok=2, hidden_size=256,
                num_heads=4, num_kv_heads=2, head_dim=64,
                moe_intermediate_size=1024)
    f8 = R.decode_flops_per_token(ModelConfig.tiny(**base), 4, 64)
    f64 = R.decode_flops_per_token(
        ModelConfig.tiny(**{**base, "num_experts": 64}), 4, 64)
    # cost-analysis crumbs (~X·rows·F gather pricing) keep this from
    # exact equality at tiny shapes; the property under test is that the
    # 8× expert growth does NOT show up as ~8× FLOPs (dense dispatch)
    assert f64["flops_per_token"] < 1.3 * f8["flops_per_token"]
    assert f64["flops_per_token"] > 0.9 * f8["flops_per_token"]


# ---------------------------------------------------------------------------
# byte accounting
# ---------------------------------------------------------------------------


def test_param_bytes_8b_quant_halves_projections():
    cfg = ModelConfig.llama3_8b()
    bf16 = R.param_bytes(cfg, "none")
    int8 = R.param_bytes(cfg, "int8")
    # ~8B params: bf16 total ~16G; int8 keeps embed+lm_head bf16
    assert 15.5e9 < bf16["total"] < 16.5e9
    assert int8["total"] < 0.6 * bf16["total"]
    # lm_head is NOT in _QUANT_KEYS: streams bf16 in both
    assert int8["dense_stream"] > cfg.vocab_size * cfg.hidden_size * 2


def test_kv_row_bytes_mla_is_latent_sized():
    cfg = ModelConfig.deepseek_r1()
    row = R.kv_row_bytes(cfg, "model")
    assert row == (cfg.kv_lora_rank + cfg.qk_rope_head_dim) * 2 * cfg.num_layers
    # the latent cache is tiny next to a dense-head equivalent
    dense_row = 2 * cfg.num_kv_heads * (128 + 64) * 2 * cfg.num_layers
    assert row < dense_row / 50


def test_expected_experts_touched_limits():
    assert R.expected_experts_touched(8, 2, 1) == pytest.approx(2.0)
    assert R.expected_experts_touched(8, 2, 10**6) == pytest.approx(8.0)
    # monotone in batch
    seq = [R.expected_experts_touched(256, 8, b) for b in (1, 8, 64, 512)]
    assert all(a < b for a, b in zip(seq, seq[1:]))


# ---------------------------------------------------------------------------
# the committed artifact regenerates from the current code
# ---------------------------------------------------------------------------


def test_committed_artifact_matches_regeneration():
    with open(ART) as f:
        committed = {r["scenario"]: r for r in json.load(f)}
    sc = R.DEFAULT_SCENARIOS[0]
    assert sc.name in committed, "cheap scenario missing from artifact"
    fresh = R.analyze(sc)
    old = committed[sc.name]
    for key in ("flops_per_token", "bytes_per_step",
                "decode_tok_s_chip_modeled", "decode_mfu_modeled",
                "ttft_prefill_modeled_ms"):
        # rel=2e-3, not 1e-6: cost_analysis() FLOPs drift ~1e-4 across
        # XLA point releases (observed: 16872976896 -> 16871197184 after
        # the PR 5-era toolchain bump — a 0.01% repricing of the same
        # program). The test still catches real code/artifact drift
        # (any modeling change moves these keys percents, not basis
        # points); chasing toolchain noise with regeneration would churn
        # the committed table every env bump.
        assert fresh[key] == pytest.approx(old[key], rel=2e-3), (
            f"{key}: committed {old[key]} vs regenerated {fresh[key]} — "
            "beyond toolchain-drift tolerance; rerun "
            "scripts/roofline_report.py and commit the new table"
        )


def test_docs_table_matches_committed_artifact():
    """The published docs/performance.md table must be exactly
    to_markdown() of the committed JSON — regenerating one without the
    other (or hand-editing a row) is the split-brain this catches.
    scripts/roofline_report.py --write refreshes both."""
    with open(ART) as f:
        recs = json.load(f)
    doc_path = os.path.join(os.path.dirname(__file__), "..", "docs",
                            "performance.md")
    with open(doc_path) as f:
        doc = f.read()
    table = R.to_markdown(recs)
    assert table in doc, (
        "docs/performance.md roofline table drifted from "
        "benchmarks/roofline_model.json — run "
        "scripts/roofline_report.py --write and commit both"
    )


def test_committed_artifact_sanity():
    with open(ART) as f:
        recs = json.load(f)
    names = {r["scenario"] for r in recs}
    # all five BASELINE configs represented
    assert {"8b-int8-v5e1", "8b-bf16-v5e4-tp4", "8b-int8-v5e-disagg",
            "70b-bf16-v5p8-tp8", "r1-v5p64-ep16tp4"} <= names
    for r in recs:
        assert r["hbm_fits"], f"{r['scenario']} does not fit HBM"
        assert 0.0 < r["decode_mfu_modeled"] < 0.56, r["scenario"]
        assert r["decode_tok_s_chip_modeled"] <= r["decode_tok_s_chip_bound"]
        # the XLA fallback's unfused byte bound must dwarf the Pallas
        # stream (that delta IS the merged-decode win being priced)
        assert (r["xla_unfused_bytes_per_step"]
                > 2 * r["bytes_per_step"]), r["scenario"]


def test_batch_sweep_shape_and_saturation():
    """The provisioning curve: throughput rises with batch while the
    weight stream amortizes, and HBM capacity caps the feasible batch."""
    sweep = R.batch_sweep(R.DEFAULT_SCENARIOS[0],
                          batches=(1, 4, 16, 64, 256))
    rows = sweep["rows"]
    feasible = [r for r in rows if r["hbm_fits"]]
    assert feasible, "no feasible batch at all"
    # monotone non-decreasing tok/s over the feasible prefix (weight
    # stream amortizes; KV reads grow linearly, never reversing it
    # before capacity runs out on this config)
    ts = [r["tok_s_chip"] for r in feasible]
    assert all(a <= b * 1.001 for a, b in zip(ts, ts[1:]))
    # the 16 GiB v5e must cap batch well below 256 at 3k context
    assert sweep["max_feasible_batch"] < 256
    assert rows[0]["bound"] == "hbm"  # B=1 decode is weight-stream bound


def test_committed_sweep_matches_regeneration():
    """benchmarks/roofline_sweep.json must regenerate from the current
    code (cheap scenario only — same convention as the model artifact),
    and its row at the scenario's own batch must agree with the
    committed model record (one pricing implementation)."""
    sweep_path = os.path.join(os.path.dirname(__file__), "..",
                              "benchmarks", "roofline_sweep.json")
    with open(sweep_path) as f:
        committed = {s["scenario"]: s for s in json.load(f)}
    sc = R.DEFAULT_SCENARIOS[0]
    fresh = R.batch_sweep(sc)
    old = committed[sc.name]
    assert fresh["max_feasible_batch"] == old["max_feasible_batch"]
    for a, b in zip(fresh["rows"], old["rows"]):
        assert a["batch"] == b["batch"]
        # rows round to 0.1 tok/s; a toolchain-level FLOPs drift (see
        # test_committed_artifact_matches_regeneration) can flip one
        # rounding step at a boundary — allow exactly that, no more
        assert a["tok_s_chip"] == pytest.approx(
            b["tok_s_chip"], abs=0.11
        ), (
            "sweep artifact drifted — rerun scripts/roofline_report.py "
            "--write"
        )
    with open(ART) as f:
        model = {r["scenario"]: r for r in json.load(f)}
    at_b = next(r for r in fresh["rows"] if r["batch"] == sc.batch)
    # sweep rows round to 0.1 tok/s; the model record is full precision
    assert at_b["tok_s_chip"] == pytest.approx(
        model[sc.name]["decode_tok_s_chip_modeled"], abs=0.05)


def test_windowed_layers_shrink_kv_read_stream():
    """gpt-oss-style alternating sliding windows must halve-plus the
    modeled KV READ bytes at long context (the paged kernels skip
    superblocks below the window floor — real traffic, not masking),
    while the WRITE stream (one row per layer) is unchanged."""
    ctx = 4096
    win = ModelConfig.gptoss_20b()
    full = ModelConfig.gptoss_20b(layer_windows=())
    s_win = R.decode_stream_bytes(win, 8, ctx)
    s_full = R.decode_stream_bytes(full, 8, ctx)
    assert s_win["kv_write"] == s_full["kv_write"]
    # half the layers read 128 tokens instead of 4096
    expect = (0.5 + 0.5 * 128 / ctx)
    assert s_win["kv_read"] / s_full["kv_read"] == pytest.approx(
        expect, rel=1e-6)
    # homogeneous sliding_window path too
    sw = R.kv_read_tokens_per_layer_sum(
        ModelConfig.tiny(sliding_window=64), 1000)
    assert sw == ModelConfig.tiny().num_layers * 64

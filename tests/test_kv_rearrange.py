"""KV head-layout rearrangement (ref vllm patch kv_rearrange, :743-810).

The TPU design ships KV as global arrays, so TP mismatch per se needs no
kernel — what's covered here is head-order regrouping (blocked vs
interleaved shard layouts), GQA replication, and the disagg delivery path
applying the regroup when prefill and decode engines disagree.
"""

import numpy as np

from dynamo_tpu.ops.kv_rearrange import (
    expand_kv_heads,
    rearrange_for_decode,
    regroup_heads,
)


def _stack(heads=8, L=2, n=3, bs=4, D=5):
    # value at [l,h,...] encodes the head id so permutations are visible
    x = np.zeros((L, heads, n, bs, D), np.float32)
    for h in range(heads):
        x[:, h] = h
    return x


def test_regroup_blocked_to_interleaved_roundtrip():
    x = _stack(heads=8)
    y = regroup_heads(x, tp=4, src_layout="blocked", dst_layout="interleaved")
    # blocked shard-major list: 0..7; interleaved shard 0 must own heads
    # {0, 4} of the *blocked* world placed at its positions
    back = regroup_heads(y, tp=4, src_layout="interleaved", dst_layout="blocked")
    np.testing.assert_array_equal(back, x)
    assert not np.array_equal(y, x)


def test_regroup_shard_contents_match():
    """After blocked->interleaved regroup with tp shards, shard i's slice
    of the output holds exactly the heads the interleaved layout assigns
    it (i, i+tp, ...), in order."""
    heads, tp = 8, 4
    x = _stack(heads=heads)
    y = regroup_heads(x, tp=tp, src_layout="blocked", dst_layout="interleaved")
    per = heads // tp
    for shard in range(tp):
        ids = y[:, shard * per : (shard + 1) * per, 0, 0, 0][0]
        assert list(ids) == [shard + j * tp for j in range(per)]


def test_identity_when_layouts_match():
    x = _stack()
    assert regroup_heads(x, tp=2) is x
    assert expand_kv_heads(x, 1) is x


def test_expand_kv_heads_replicates():
    x = _stack(heads=4)
    y = expand_kv_heads(x, 2)
    assert y.shape[1] == 8
    assert list(y[0, :, 0, 0, 0]) == [0, 0, 1, 1, 2, 2, 3, 3]


def test_rearrange_for_decode_never_expands():
    """The decode cache is a global [L, Hkv, ...] array — GQA replication
    is a mesh-sharding concern; rearrange must preserve the head count."""
    x = _stack(heads=4)
    y = rearrange_for_decode(x, src_tp=2, dst_tp=8)
    assert y.shape[1] == 4


import pytest


@pytest.mark.parametrize("streamed", [True, False])
def test_disagg_delivery_applies_regroup(run, streamed):
    """A tp=2 prefill engine whose gathered KV arrives in *interleaved*
    head order (simulated by permuting the gather output, since the native
    engine stores heads naturally) feeding a blocked decode engine: the
    delivery-side regroup must undo the permutation, giving greedy tokens
    identical to an all-local run — on BOTH wire flavors. The streamed
    path regroups each segment on arrival in the scatter sink (ISSUE 9:
    mismatched peers stream too, no more buffered-bulk downgrade); the
    bulk path keeps the delivery-time full-stack regroup."""

    from dynamo_tpu.disagg import (
        ConditionalDisaggRouter, DisaggConfig, DisaggEngine, LocalKvPipe,
        PrefillQueue, PrefillWorker,
    )
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshConfig
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.runtime import Context, DistributedRuntime, collect

    def make_req(prompt):
        return PreprocessedRequest(
            token_ids=list(prompt),
            stop_conditions=StopConditions(max_tokens=4),
            sampling_options=SamplingOptions(temperature=0.0),
            eos_token_ids=[511],
        )

    async def main():
        mcfg = ModelConfig.tiny(num_kv_heads=4)
        drt = await DistributedRuntime.from_settings()
        prefill_engine = JaxEngine(
            EngineConfig(
                model=mcfg, num_blocks=64, block_size=4, max_batch_size=2,
                max_context=128, mesh=MeshConfig(tp=2),
            ),
            seed=0,
        )
        # simulate an engine that physically stores heads interleaved:
        # permute what the natural-order gather returns — patched at the
        # GATHER so both the bulk extract and the streamed per-segment
        # extract ship permuted data
        orig_gather = prefill_engine._gather_device

        def interleaved_gather(idxs, keep_on_device=False):
            k, v = orig_gather(idxs, keep_on_device)
            k = regroup_heads(k, tp=2, src_layout="blocked",
                              dst_layout="interleaved")
            v = regroup_heads(v, tp=2, src_layout="blocked",
                              dst_layout="interleaved")
            return k, v

        prefill_engine._gather_device = interleaved_gather

        decode_engine = JaxEngine(
            EngineConfig(
                model=mcfg, num_blocks=64, block_size=4, max_batch_size=2,
                max_context=128, kv_head_layout="blocked",
            ),
            seed=0,
        )
        router = ConditionalDisaggRouter(
            drt, "t", "m", DisaggConfig(max_local_prefill_length=8)
        )
        pipe = LocalKvPipe()
        queue = PrefillQueue(drt.bus, "t")
        worker = PrefillWorker(
            prefill_engine, queue, local_pipe=pipe,
            head_layout="interleaved", kv_stream=streamed,
        )
        worker.start()
        disagg = DisaggEngine(
            decode_engine, router, queue, pipe, kv_stream=streamed
        )

        prompt = list(range(40, 72))  # 32 tokens > threshold -> remote
        out = await collect(disagg.generate(Context(make_req(prompt))))
        toks = [t for o in out for t in o.token_ids]
        assert disagg.stats["remote_prefills"] == 1
        if streamed:
            # the mismatch must no longer downgrade to buffered bulk:
            # segments landed incrementally, each regrouped on arrival
            assert disagg.stats["streamed_deliveries"] == 1
            assert disagg.stats["kv_stream_regroups"] >= 1
            assert disagg.stats["kv_stream_segments"] >= 1
        else:
            assert disagg.stats["bulk_deliveries"] == 1
            assert disagg.stats["kv_stream_regroups"] == 0

        # reference: same request served fully locally on a fresh engine
        local_engine = JaxEngine(
            EngineConfig(
                model=mcfg, num_blocks=64, block_size=4, max_batch_size=2,
                max_context=128,
            ),
            seed=0,
        )
        ref = await collect(local_engine.generate(Context(make_req(prompt))))
        ref_toks = [t for o in ref for t in o.token_ids]
        assert toks == ref_toks
        await worker.close()
        await disagg.engine.close()
        await local_engine.close()
        await prefill_engine.close()
        await drt.shutdown()

    run(main())


def test_native_engine_rejects_foreign_layout():
    import pytest

    from dynamo_tpu.engine import EngineConfig
    from dynamo_tpu.models.config import ModelConfig

    with pytest.raises(ValueError, match="blocked"):
        EngineConfig(model=ModelConfig.tiny(), kv_head_layout="interleaved")


def test_interleaved_same_layout_different_tp_not_identity():
    """interleaved(tp=2) -> interleaved(tp=4) is a real permutation —
    the delivery guard must not treat same-layout as same-order."""
    x = _stack(heads=8)
    y = rearrange_for_decode(x, src_tp=2, dst_tp=4,
                             src_layout="interleaved", dst_layout="interleaved")
    assert not np.array_equal(y, x)
    back = rearrange_for_decode(y, src_tp=4, dst_tp=2,
                                src_layout="interleaved", dst_layout="interleaved")
    np.testing.assert_array_equal(back, x)

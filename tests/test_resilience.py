"""Resilient serving: migration, graceful drain, fault injection.

The fault-point matrix is the subsystem's acceptance test: a worker
killed at each request-lifecycle stage (admission, mid-prefill,
mid-decode) must yield a client stream that CONTINUES on a surviving
worker to a single finish chunk, with the greedy token sequence
bit-exact against an unkilled reference run — no token lost, none
duplicated across the seam. Alongside it: resume-annotation continuity
(seeded sampling + penalties), graceful drain (finish and hand-off
flavors), the drain coordinator sequence, the hub watch_resumed marker,
and disagg prefill redelivery under a mid-transfer kill.
"""

import asyncio

import pytest

from dynamo_tpu.disagg.protocols import RemotePrefillRequest
from dynamo_tpu.disagg.queue import PrefillQueue
from dynamo_tpu.disagg.worker import PrefillWorker
from dynamo_tpu.engine import EngineConfig, JaxEngine
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.protocols.common import (
    PreprocessedRequest, SamplingOptions, StopConditions,
)
from dynamo_tpu.resilience import (
    MIGRATION_SIGNAL,
    DrainCoordinator,
    FailureKind,
    FaultInjected,
    MigratingEngine,
    MigrationPolicy,
    classify_failure,
    faultpoints,
)
from dynamo_tpu.runtime import (
    Annotated,
    AsyncEngine,
    Context,
    DistributedRuntime,
    EngineClient,
    LocalBus,
    LocalStore,
)
from dynamo_tpu.runtime.hub import HubServer, connect_hub
from dynamo_tpu.runtime.store import EventKind

pytestmark = pytest.mark.faultinject

#: ONE tiny config shared by every engine in the module — ModelConfig
#: hashes by identity (jit static arg), so sharing it shares the
#: compiled program cache across all workers/tests here
TINY = ModelConfig.tiny()


def make_engine(**kw):
    cfg = EngineConfig(
        model=TINY, num_blocks=64, block_size=4, max_batch_size=4,
        max_context=128, prefill_chunk=32, **kw,
    )
    return JaxEngine(cfg, seed=0)


def make_req(tokens=None, max_tokens=10, temperature=0.0, seed=None,
             annotations=None, **so):
    return PreprocessedRequest(
        token_ids=list(tokens if tokens is not None else range(100, 116)),
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(
            temperature=temperature, seed=seed, **so
        ),
        eos_token_ids=[511],
        annotations=annotations or {},
    )


@pytest.fixture(autouse=True)
def _fault_hygiene():
    faultpoints.reset()
    yield
    faultpoints.reset()


def _chunk(item):
    """Normalize a stream item (LLMEngineOutput or Annotated[dict]) to
    (token_ids, finish_reason, text, error)."""
    if isinstance(item, Annotated):
        if item.is_error():
            return [], None, None, item.error or "error"
        d = item.data or {}
        return (
            list(d.get("token_ids") or []), d.get("finish_reason"),
            d.get("text"), None,
        )
    fr = item.finish_reason.value if item.finish_reason else None
    return list(item.token_ids or []), fr, item.text, None


async def drive(engine, req, annotations=None):
    """-> (tokens, finishes:list, errors:list, final_chunk_fields)."""
    toks, finishes, errors, final = [], [], [], {}
    async for item in engine.generate(Context(req, annotations=annotations)):
        t, fr, text, err = _chunk(item)
        if err is not None:
            errors.append(err)
            continue
        toks.extend(t)
        if fr is not None:
            finishes.append(fr)
            if isinstance(item, Annotated):
                final = dict(item.data or {})
            else:
                final = {
                    "prompt_tokens": item.prompt_tokens,
                    "completion_tokens": item.completion_tokens,
                    "text": item.text,
                }
    return toks, finishes, errors, final


async def reference_tokens(engine, req):
    """Drive ``req`` on a dedicated engine (constructed OUTSIDE the
    stall-guarded coroutine — the ctor's device work blocks the loop)."""
    toks, finishes, errors, _ = await drive(engine, req)
    assert finishes and not errors
    await engine.close()
    return toks


# ---------------------------------------------------------------------------
# fault-point registry semantics
# ---------------------------------------------------------------------------


def test_faultpoints_deterministic_counters(run):
    async def main():
        faultpoints.arm("mid_decode", "kill", after=3, times=1)
        fired = []
        for i in range(1, 7):
            try:
                faultpoints.hit_sync("mid_decode")
            except FaultInjected as e:
                fired.append((i, e.hit))
        # fires on exactly the 3rd hit, exactly once
        assert fired == [(3, 3)]
        # async delay action actually sleeps
        faultpoints.reset()
        faultpoints.arm("mid_kv_transfer", "delay", delay_s=0.02)
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await faultpoints.hit("mid_kv_transfer")
        assert loop.time() - t0 >= 0.015
        # spec grammar round-trips
        faultpoints.reset()
        faultpoints.FAULTS.arm_from_spec("mid_decode:kill@4x2,admission:delay=0.1")
        arms = faultpoints.FAULTS._arms
        assert arms["mid_decode"].after == 4 and arms["mid_decode"].times == 2
        assert arms["admission"].action == "delay"
        assert arms["admission"].delay_s == 0.1
        with pytest.raises(ValueError):
            faultpoints.arm("nonsense_point")

    run(main())


def test_classify_failure_taxonomy():
    assert classify_failure(
        "response stream truncated: worker connection lost"
    ) is FailureKind.WORKER_LOST
    assert classify_failure("worker shutdown: stream aborted").retryable
    assert classify_failure(MIGRATION_SIGNAL).retryable
    assert classify_failure(
        "fault injected: worker killed at mid_decode (hit 1)"
    ).retryable
    assert classify_failure(exc=ConnectionError("hub connection lost")) \
        is FailureKind.TRANSIENT
    assert classify_failure("some model error") is FailureKind.FATAL
    assert not classify_failure("some model error").retryable

    class _FakeClient:
        def __init__(self, ids):
            self._ids = ids

        def instance_ids(self):
            return self._ids

    # worker still registered -> TCP blip, not lease loss
    assert classify_failure(
        "response stream truncated: worker connection reset",
        worker_id=7, client=_FakeClient([7, 8]),
    ) is FailureKind.TRANSIENT
    assert classify_failure(
        "response stream truncated: worker connection reset",
        worker_id=7, client=_FakeClient([8]),
    ) is FailureKind.LEASE_LOST


# ---------------------------------------------------------------------------
# resume-annotation continuity (the splice contract, engine side)
# ---------------------------------------------------------------------------


def test_resume_annotation_continuity_sampled_with_penalties(run):
    """A resumed request (prompt + tokens-so-far + resume annotation) on
    a FRESH engine continues the original sampled stream exactly: the
    per-step keys fold_in(seed, generated) pick up at the seam and the
    frequency-penalty state rebuilds from the true prompt/output split."""

    req = make_req(max_tokens=10, temperature=0.9, seed=11,
                   frequency_penalty=0.6)
    cuts = (1, 4, 9)
    # all engines constructed outside the stall-guarded coroutine
    ref_engine = make_engine(decode_window=1)
    resume_engines = {cut: make_engine(decode_window=1) for cut in cuts}

    async def main():
        ref = await reference_tokens(ref_engine, req)
        assert len(ref) == 10
        for cut in cuts:
            resumed = make_req(
                tokens=req.token_ids + ref[:cut], max_tokens=10,
                temperature=0.9, seed=11, frequency_penalty=0.6,
                annotations={"resume": {"prompt_len": len(req.token_ids)}},
            )
            e = resume_engines[cut]
            toks, finishes, errors, final = await drive(e, resumed)
            assert not errors and finishes == ["length"]
            assert toks == ref[cut:], f"cut={cut}"
            # usage counts from the ORIGINAL prompt, not the splice
            assert final["prompt_tokens"] == len(req.token_ids)
            assert final["completion_tokens"] == 10
            assert e.stats["migration_resumes"] == 1
            await e.close()

    run(main())


# ---------------------------------------------------------------------------
# the kill matrix: worker death at each lifecycle stage, through the
# full distributed stack (bus ingress + TCP response plane + migration)
# ---------------------------------------------------------------------------


async def _two_worker_stack(engines):
    store, bus = LocalStore(), LocalBus()
    drts, handles = [], []
    for e in engines:
        drt = await DistributedRuntime.from_settings(store=store, bus=bus)
        h = await drt.namespace("res").component("w").endpoint("gen").serve(
            e, stats_handler=e.load_metrics
        )
        drts.append(drt)
        handles.append(h)
    front = await DistributedRuntime.from_settings(store=store, bus=bus)
    client = (
        await front.namespace("res").component("w").endpoint("gen")
        .client().start()
    )
    await client.wait_for_instances(timeout=5)
    return drts, handles, front, client


async def _teardown_stack(drts, front, engines):
    for e in engines:
        await e.close()
    for drt in drts:
        await drt.shutdown()
    await front.shutdown()


@pytest.mark.parametrize(
    "point,after,min_pre_tokens",
    [
        ("admission", 1, 0),
        ("mid_prefill", 1, 0),
        ("mid_decode", 4, 2),  # several tokens on the wire before death
    ],
)
def test_kill_matrix_stream_continues_bit_exact(run, point, after,
                                                min_pre_tokens):
    req = make_req(max_tokens=10)
    engines = [make_engine(decode_window=1) for _ in range(2)]
    ref_engine = make_engine(decode_window=1)

    async def main():
        ref = await reference_tokens(ref_engine, req)
        drts, handles, front, client = await _two_worker_stack(engines)
        mig = MigratingEngine(
            EngineClient(client), MigrationPolicy(max_migrations=3),
            client=client,
        )
        faultpoints.arm(point, "kill", after=after, times=1)
        # dict payload: the bus envelope is JSON (what real frontends send)
        toks, finishes, errors, _final = await drive(mig, req.to_dict())
        # the fault actually fired and migration picked the stream up
        assert faultpoints.FAULTS.history, "fault point never fired"
        assert mig.stats["migrations_total"] >= 1
        # the client saw: zero errors, exactly one finish chunk, and the
        # exact greedy token sequence — no loss, no duplication
        assert errors == []
        assert finishes == ["length"]
        assert toks == ref
        assert len(toks) == 10
        faultpoints.reset()
        await _teardown_stack(drts, front, engines)

    run(main())


@pytest.mark.parametrize(
    "after,dies,on_new_layout",
    [
        (1, False, False),  # pre_stage: staging kill, loop untouched
        (2, True, False),   # quiesced: dies wholly on the old layout
        (3, True, False),   # kv_staged: staged, not committed -> old
        (4, True, True),    # committed: dies wholly on the new layout
    ],
)
def test_mid_reshard_kill_matrix_stream_migrates_bit_exact(
    run, after, dies, on_new_layout
):
    """ISSUE 12 crash-atomicity rule through the FULL distributed stack:
    a worker killed at each live-reshard phase must (a) land wholly on
    exactly one layout, and (b) when the kill takes the serving loop
    with it, its in-flight stream continues on the surviving worker to
    one finish chunk, bit-exact — a morph crash is just a worker death
    to the migration layer."""
    from dynamo_tpu.parallel.mesh import MeshConfig

    req = make_req(max_tokens=40)
    engines = [make_engine(decode_window=1) for _ in range(2)]
    ref_engine = make_engine(decode_window=1)

    async def main():
        ref = await reference_tokens(ref_engine, req)
        drts, handles, front, client = await _two_worker_stack(engines)
        mig = MigratingEngine(
            EngineClient(client), MigrationPolicy(max_migrations=3),
            client=client,
        )
        task = asyncio.ensure_future(drive(mig, req.to_dict()))
        victim = None
        for _ in range(600):
            victim = next(
                (e for e in engines if e._n_active >= 1), None)
            if victim is not None:
                break
            await asyncio.sleep(0.01)
        assert victim is not None, "stream never reached a decode batch"
        faultpoints.arm("mid_reshard", "kill", after=after, times=1)
        # stall the victim's decode at the device lock while the morph
        # stages + posts, so the kill deterministically catches the
        # stream IN FLIGHT at the commit boundary
        async with victim._device_lock:
            morph = asyncio.ensure_future(victim.reshard(MeshConfig(tp=2)))
            for _ in range(800):
                if victim._reshard_req is not None or morph.done():
                    break
                await asyncio.sleep(0.01)
        with pytest.raises(FaultInjected):
            await morph
        toks, finishes, errors, _final = await drive_task(task)
        assert errors == []
        assert finishes == ["length"]
        assert toks == ref
        # all-or-nothing layout, whichever side of the commit the kill hit
        assert victim.cfg.mesh == (MeshConfig(tp=2) if on_new_layout
                                   else None)
        if dies:
            assert victim._dead is not None
            assert mig.stats["migrations_total"] >= 1
        else:
            assert victim._dead is None
            assert mig.stats["migrations_total"] == 0
        faultpoints.reset()
        await _teardown_stack(drts, front, engines)

    run(main())


async def drive_task(task):
    return await task


def test_kill_after_death_requests_fail_fast_not_hang(run):
    """A fault-killed engine must bounce subsequent dispatches with a
    retryable signature immediately (not park them on a dead queue)."""
    e = make_engine(decode_window=1)

    async def main():
        faultpoints.arm("mid_decode", "kill", after=1, times=1)
        toks, finishes, errors, final = await drive(e, make_req())
        assert finishes == ["error"]
        assert "fault injected" in (final.get("text") or "")
        # next request: immediate worker-lost bounce, no hang
        toks2, finishes2, _errors2, final2 = await drive(e, make_req())
        assert toks2 == [] and finishes2 == ["error"]
        assert "fault injected" in (final2.get("text") or "")
        await e.close()

    run(main())


# ---------------------------------------------------------------------------
# graceful drain
# ---------------------------------------------------------------------------


def test_drain_lets_inflight_finish_and_bounces_new_work(run):
    e = make_engine(decode_window=1)

    async def main():
        req = make_req(max_tokens=8)
        stream_task = asyncio.ensure_future(drive(e, req))
        # wait until the request is actually running
        while e.stats["requests_total"] == 0:
            await asyncio.sleep(0.005)
        res = await e.drain(deadline_s=30.0, handoff=True)
        toks, finishes, errors, _ = await stream_task
        # generous deadline: the stream finished NATURALLY, no handoff
        assert finishes == ["length"] and len(toks) == 8 and not errors
        assert res["handed_off"] == 0
        assert e.stats["drains_total"] == 1
        assert e.load_metrics()["draining"] == 1
        # new work during/after drain bounces with the migration signal
        toks2, finishes2, _e2, final2 = await drive(e, make_req())
        assert toks2 == [] and finishes2 == ["error"]
        assert final2.get("text") == MIGRATION_SIGNAL
        await e.close()

    run(main())


def test_drain_deadline_hands_off_and_migration_resumes(run):
    """DrainCoordinator on worker 1 with a tiny deadline: the in-flight
    stream is handed off mid-decode and the migration layer finishes it
    on worker 2, bit-exact, with the lease revoked only afterwards."""
    engines = [make_engine(decode_window=1) for _ in range(2)]
    ref_engine = make_engine(decode_window=1)
    req = make_req(max_tokens=16)

    async def main():
        ref = await reference_tokens(ref_engine, make_req(max_tokens=16))
        drts, handles, front, client = await _two_worker_stack(engines)
        e1 = engines[0]
        mig = MigratingEngine(
            EngineClient(client), MigrationPolicy(max_migrations=4),
            client=client,
        )
        stream_task = asyncio.ensure_future(drive(mig, req.to_dict()))
        # round robin sends the first request to the first-leased worker;
        # wait until it is streaming tokens
        while e1.stats["tokens_generated"] < 3:
            await asyncio.sleep(0.005)
        coord = DrainCoordinator(
            drts[0], engines=[e1], handles=[handles[0]], deadline_s=0.0,
        )
        res = await coord.drain()
        assert res["handed_off"] >= 1
        toks, finishes, errors, _ = await stream_task
        assert errors == []
        assert finishes == ["length"]
        assert toks == ref
        assert mig.stats["migrations_total"] >= 1
        # the drained worker left discovery (lease revoked last)
        for _ in range(100):
            if len(client.instance_ids()) == 1:
                break
            await asyncio.sleep(0.02)
        assert client.instance_ids() == [drts[1].primary_lease_id]
        await engines[1].close()
        await drts[1].shutdown()
        await front.shutdown()

    run(main())


def test_mid_drain_fault_aborts_drain(run):
    """Arming ``mid_drain`` kills the coordinator right after it leaves
    discovery: the drain aborts (counted in drain_errors), the engines
    are never drained — surviving streams take the worker-death path and
    migrate anyway — and the aborted sequence must NOT revoke the lease
    or stop the ingress (a real mid-drain crash dies before those)."""

    class _Handle:
        def __init__(self):
            self.deregistered = False
            self.stopped = False

        async def deregister(self):
            self.deregistered = True

        def inflight_count(self):
            return 0

        async def stop(self):
            self.stopped = True

    class _Drt:
        def __init__(self):
            self.shutdowns = 0

        async def shutdown(self):
            self.shutdowns += 1

    class _Engine:
        def __init__(self):
            self.drained = 0

        async def drain(self, deadline_s=0.0, handoff=True):
            self.drained += 1
            return {"handed_off": 0}

    async def main():
        h, drt, e = _Handle(), _Drt(), _Engine()
        coord = DrainCoordinator(
            drt, engines=[e], handles=[h], deadline_s=0.0
        )
        faultpoints.arm("mid_drain", "kill")
        await coord.trigger()
        assert h.deregistered  # step 1 ran: discovery keys deleted
        assert e.drained == 0  # fault fired before the engine drain
        assert not h.stopped and drt.shutdowns == 0  # sequence aborted
        assert coord.stats["drain_errors"] == 1
        # delay flavor: the drain survives (slow, not dead) and runs the
        # full sequence through lease revocation
        faultpoints.reset()
        faultpoints.arm("mid_drain", "delay", delay_s=0.01)
        h2, drt2, e2 = _Handle(), _Drt(), _Engine()
        coord2 = DrainCoordinator(
            drt2, engines=[e2], handles=[h2], deadline_s=0.0
        )
        res = await coord2.drain()
        assert res["drained"] and e2.drained == 1
        assert h2.stopped and drt2.shutdowns == 1

    run(main())


# ---------------------------------------------------------------------------
# migration policy edges
# ---------------------------------------------------------------------------


class _ScriptedEngine(AsyncEngine):
    """Inner engine driven by a list of per-attempt scripts."""

    def __init__(self, scripts):
        self.scripts = list(scripts)
        self.requests = []

    async def generate(self, request):
        self.requests.append(request)
        script = self.scripts.pop(0) if self.scripts else ["finish"]
        for step in script:
            if step == "finish":
                yield Annotated.from_data(
                    {"token_ids": [], "finish_reason": "length"}
                )
                return
            if step == "truncate":
                return  # end with neither finish nor error
            if isinstance(step, tuple) and step[0] == "error":
                yield Annotated.from_error(step[1])
                return
            yield Annotated.from_data({"token_ids": [step]})


def test_migration_truncation_resumes_with_splice(run):
    async def main():
        inner = _ScriptedEngine([[1, 2, 3, "truncate"], [4, 5, "finish"]])
        mig = MigratingEngine(inner, MigrationPolicy(max_migrations=2))
        req = make_req(tokens=[10, 11, 12])
        toks, finishes, errors, _ = await drive(mig, req)
        assert toks == [1, 2, 3, 4, 5] and finishes == ["length"]
        assert errors == []
        # the re-dispatch carried prompt + tokens-so-far + resume marker
        assert len(inner.requests) == 2
        resumed = inner.requests[1].data
        assert resumed["token_ids"] == [10, 11, 12, 1, 2, 3]
        assert resumed["annotations"]["resume"]["prompt_len"] == 3

    run(main())


def test_migration_redispatch_avoids_failed_worker(run):
    """A killed worker stays in discovery until its lease TTL lapses, and
    radix prefix affinity would re-pick the corpse every time — the
    re-dispatch must carry the failed worker id so the router steers
    around it (the e2e SIGKILL-with-live-lease scenario)."""

    class _RoutedEngine(_ScriptedEngine):
        # mimic KvRoutedEngine: stamp the pinned instance, then fail
        async def generate(self, request):
            request.annotations["routed_worker_id"] = 7
            async for item in super().generate(request):
                yield item

    async def main():
        inner = _RoutedEngine([[1, 2, "truncate"], ["finish"]])
        mig = MigratingEngine(inner, MigrationPolicy(max_migrations=2))
        _toks, finishes, errors, _ = await drive(mig, make_req())
        assert finishes == ["length"] and errors == []
        assert len(inner.requests) == 2
        resumed = inner.requests[1]
        # worker 7 ate the first attempt: the router must avoid it, and
        # the stale pin must not leak into the re-dispatch
        assert resumed.annotations["migration.avoid_workers"] == [7]

    run(main())


def test_migration_fatal_error_not_retried_and_budget_bounds(run):
    async def main():
        # deterministic engine error: surfaced unchanged, inner called once
        inner = _ScriptedEngine([[("error", "some model error")]])
        mig = MigratingEngine(inner, MigrationPolicy(max_migrations=3))
        _toks, _fin, errors, _ = await drive(mig, make_req())
        assert errors == ["some model error"]
        assert len(inner.requests) == 1
        assert mig.stats["migrations_total"] == 0

        # endless truncation: bounded by max_migrations, then surfaced
        inner = _ScriptedEngine([["truncate"]] * 10)
        mig = MigratingEngine(inner, MigrationPolicy(max_migrations=2))
        _toks, _fin, errors, _ = await drive(mig, make_req())
        assert len(errors) == 1 and "migration budget exhausted" in errors[0]
        assert len(inner.requests) == 3  # original + 2 re-dispatches

        # off-switch: the first retryable failure surfaces as-is
        inner = _ScriptedEngine([["truncate"]])
        mig = MigratingEngine(inner, MigrationPolicy(enabled=False))
        _toks, _fin, errors, _ = await drive(mig, make_req())
        assert len(errors) == 1 and "truncated" in errors[0]
        assert len(inner.requests) == 1

    run(main())


# ---------------------------------------------------------------------------
# store watch resume marker (satellite: closes the stale-watch window)
# ---------------------------------------------------------------------------


def test_hub_restart_emits_watch_resumed(run, tmp_path):
    async def main():
        hub = HubServer(data_dir=str(tmp_path / "hub"))
        await hub.start()
        port = int(hub.address.rsplit(":", 1)[1])
        store, _bus, conn = await connect_hub(hub.address)
        w = await store.watch_prefix("res/")
        await store.kv_put("res/a", b"1")
        ev = await asyncio.wait_for(w.__anext__(), 5)
        assert ev.kind == EventKind.PUT and ev.key == "res/a"

        await hub.close()
        hub = HubServer(data_dir=str(tmp_path / "hub"), port=port)
        await hub.start()

        # reconnect reconcile: the durable key re-PUTs, then the
        # watch_resumed marker closes the gap
        kinds = []
        while True:
            ev = await asyncio.wait_for(w.__anext__(), 10)
            kinds.append((ev.kind, ev.key))
            if ev.kind == EventKind.RESUMED:
                assert ev.key == "res/"
                break
        assert (EventKind.PUT, "res/a") in kinds
        # the watch is LIVE again, not silently stale
        await store.kv_put("res/b", b"2")
        ev = await asyncio.wait_for(w.__anext__(), 5)
        assert ev.kind == EventKind.PUT and ev.key == "res/b"
        await conn.close()
        await hub.close()

    run(main())


# ---------------------------------------------------------------------------
# disagg: the prefill WAL item outlives a worker killed mid-transfer
# ---------------------------------------------------------------------------


class _StubPrefillEngine:
    class _Cfg:
        mesh = None
        kv_head_layout = "blocked"

    cfg = _Cfg()

    async def prefill_extract(self, req, ctx, skip_blocks=0,
                              keep_on_device=False, timings=None):
        return 7, None, None, None


class _FlakyPipe:
    """LocalKvPipe stand-in whose first delivery dies mid-transfer."""

    def __init__(self, fail_first=1):
        self.calls = 0
        self.fail_first = fail_first
        self.delivered = []

    async def deliver(self, request_id, first, k, v, **kw):
        self.calls += 1
        if self.calls <= self.fail_first:
            raise ConnectionResetError("decode host hung up mid-transfer")
        self.delivered.append((request_id, first))


def _rpr(request_id="r1"):
    return RemotePrefillRequest(
        request_id=request_id, request=make_req().to_dict(), skip_blocks=0,
        connection={"local": True}, engine_id=0,
    )


def test_prefill_handoff_failure_redelivers_not_drops(run):
    async def main():
        queue = PrefillQueue(LocalBus(), "res", redeliver_after=30.0)
        pipe = _FlakyPipe()
        worker = PrefillWorker(_StubPrefillEngine(), queue, local_pipe=pipe)
        await queue.enqueue(_rpr())
        # attempt 1: the handoff stage dies -> the item must NACK (the
        # pre-fix behavior acked-with-error and stranded the decode side)
        await worker._run_once()
        assert worker.stats["nacks"] == 1 and pipe.delivered == []
        assert await queue.get_depth() == 1
        # attempt 2 (redelivery): commits, then acks
        await worker._run_once()
        assert [r for r, _ in pipe.delivered] == ["r1"]
        assert await queue.get_depth() == 0

    run(main())


def test_prefill_kill_mid_transfer_leaves_item_inflight(run):
    async def main():
        queue = PrefillQueue(LocalBus(), "res", redeliver_after=0.05)
        pipe = _FlakyPipe(fail_first=0)
        worker = PrefillWorker(_StubPrefillEngine(), queue, local_pipe=pipe)
        await queue.enqueue(_rpr("r2"))
        faultpoints.arm("mid_kv_transfer", "kill", times=1)
        # the kill propagates like a crash: no ack, no nack, no error
        with pytest.raises(FaultInjected):
            await worker._run_once()
        assert pipe.delivered == []
        # visibility timeout expires -> the item redelivers and commits
        await asyncio.sleep(0.1)
        await worker._run_once()
        assert [r for r, _ in pipe.delivered] == ["r2"]
        assert await queue.get_depth() == 0

    run(main())

"""Hub (TCP store+bus) tests: the multi-process control plane.

The same DistributedRuntime/component code paths as test_distributed.py,
but store+bus accessed over real TCP through the hub server — this is the
multi-host wiring (worker hosts connect to the coordinator's hub over DCN).
"""

import asyncio

import pytest

from dynamo_tpu.runtime import Annotated, AsyncEngine, Context, DistributedRuntime, collect
from dynamo_tpu.runtime.hub import HubServer, connect_hub
from dynamo_tpu.runtime.store import KeyExists


class EchoEngine(AsyncEngine):
    async def generate(self, request: Context):
        for ch in request.data["text"]:
            yield Annotated.from_data({"token": ch})


def test_remote_store_ops(run):
    async def main():
        hub = HubServer()
        await hub.start()
        store, bus, conn = await connect_hub(hub.address)

        lease = await store.grant_lease(5.0)
        await store.kv_create("a/b", b"v1", lease_id=lease)
        with pytest.raises(KeyExists):
            await store.kv_create("a/b", b"v2")
        entry = await store.kv_get("a/b")
        assert entry.value == b"v1" and entry.lease_id == lease

        w = await store.watch_prefix("a/")
        assert [e.key for e in w.snapshot] == ["a/b"]
        await store.kv_put("a/c", b"v3")
        ev = await asyncio.wait_for(w.__anext__(), 2)
        assert (ev.key, ev.value) == ("a/c", b"v3")

        assert [e.key for e in await store.kv_get_prefix("a/")] == ["a/b", "a/c"]
        await conn.close()
        await hub.close()

    run(main())


def test_remote_bus_pubsub_request_queue_objects(run):
    async def main():
        hub = HubServer()
        await hub.start()
        store_a, bus_a, conn_a = await connect_hub(hub.address)
        store_b, bus_b, conn_b = await connect_hub(hub.address)

        # pub/sub across connections
        sub = bus_b.subscribe("events.kv")
        await asyncio.sleep(0.05)  # allow subscribe to land
        bus_a.publish("events.kv", b"stored")
        msg = await sub.next(2)
        assert msg.payload == b"stored"

        # request/reply across connections
        svc = bus_b.subscribe("svc.gen", group="workers")
        await asyncio.sleep(0.05)

        async def server():
            m = await svc.next(2)
            bus_b.respond(m, b"pong:" + m.payload)

        t = asyncio.get_running_loop().create_task(server())
        reply = await bus_a.request("svc.gen", b"ping", timeout=2)
        assert reply == b"pong:ping"
        await t

        # work queue across connections
        qa = bus_a.work_queue("prefill")
        qb = bus_b.work_queue("prefill")
        await qa.push(b"job")
        item = await qb.pop(timeout=2)
        assert item.payload == b"job"
        assert await qb.ack(item.id)

        # object store
        await bus_a.object_put("mdc", "m1", b"card")
        assert await bus_b.object_get("mdc", "m1") == b"card"
        assert await bus_b.object_list("mdc") == ["m1"]

        await conn_a.close()
        await conn_b.close()
        await hub.close()

    run(main())


def test_full_serving_over_hub(run):
    async def main():
        hub = HubServer()
        await hub.start()
        ws, wb, wconn = await connect_hub(hub.address)
        fs, fb, fconn = await connect_hub(hub.address)

        worker = await DistributedRuntime.from_settings(store=ws, bus=wb)
        front = await DistributedRuntime.from_settings(store=fs, bus=fb)

        await worker.namespace("ns").component("gen").endpoint("g").serve(EchoEngine())
        client = await front.namespace("ns").component("gen").endpoint("g").client().start()
        await client.wait_for_instances(5)

        out = await collect(await client.round_robin(Context({"text": "tpu"})))
        assert [a.data["token"] for a in out] == ["t", "p", "u"]

        # clean shutdown revokes the worker's lease EXPLICITLY ->
        # discovery removes the instance at once (an unclean death would
        # instead expire by TTL — sessions no longer revoke on
        # disconnect, so reconnecting clients keep their keys)
        await worker.shutdown()
        await wconn.close()
        await asyncio.sleep(0.1)
        assert client.instance_ids() == []

        await front.shutdown()
        await fconn.close()
        await hub.close()

    run(main())


def test_store_persistence_roundtrip(tmp_path, run):
    """Snapshot+WAL: KV and leases survive a store restart; restored
    leases restart their TTL clock (downtime is not liveness time);
    torn WAL tail lines are tolerated."""
    from dynamo_tpu.runtime.store import LocalStore

    async def main():
        d = str(tmp_path)
        s1 = LocalStore(data_dir=d)
        lease = s1.grant_lease(5.0)
        s1.kv_put("disc/w1", b"addr1", lease)
        s1.kv_put("cfg/x", b"42")
        s1.kv_put("cfg/y", b"dead")
        s1.kv_delete("cfg/y")
        dead = s1.grant_lease(5.0)
        s1.kv_put("disc/w2", b"addr2", dead)
        s1.revoke_lease(dead)
        # crash: no clean close/snapshot — restore replays the WAL,
        # including a torn final line
        s1._wal.write('{"op":"put","k":"torn"')
        s1._wal.flush()

        s2 = LocalStore(data_dir=d)
        assert s2.kv_get("disc/w1").value == b"addr1"
        assert s2.kv_get("disc/w1").lease_id == lease
        assert s2.kv_get("cfg/x").value == b"42"
        assert s2.kv_get("cfg/y") is None
        assert s2.kv_get("disc/w2") is None  # died with its lease
        assert s2.kv_get("torn") is None
        # the restored lease is alive with a fresh deadline
        assert s2.keep_alive(lease)
        # ids never collide with restored state — including the REVOKED
        # lease's id, which must stay burned (a stale holder of it would
        # otherwise control a new client's lease)
        assert s2.grant_lease(1.0) > max(lease, dead)
        # expiry still works post-restore
        s2._leases[lease].deadline = 0.0
        s2.expire_leases()
        assert s2.kv_get("disc/w1") is None
        await s2.close()
        # clean close compacted: a third open sees the same state
        s3 = LocalStore(data_dir=d)
        assert s3.kv_get("cfg/x").value == b"42"
        assert s3.kv_get("disc/w1") is None
        await s3.close()

    run(main())


def test_hub_restart_mid_serving(tmp_path, run):
    """VERDICT r3 #5 e2e: kill + restart the hub (same port, same
    data_dir) while a worker and frontend stay up — the next request
    must succeed WITHOUT restarting either: clients redial, the session
    (subscriptions, watches) re-establishes, the durable store revived
    the worker's lease and registration."""

    async def main():
        hub = HubServer(data_dir=str(tmp_path))
        await hub.start()
        port = int(hub.address.rsplit(":", 1)[1])
        ws, wb, wconn = await connect_hub(hub.address)
        fs, fb, fconn = await connect_hub(hub.address)
        worker = await DistributedRuntime.from_settings(store=ws, bus=wb)
        front = await DistributedRuntime.from_settings(store=fs, bus=fb)
        await worker.namespace("ns").component("gen").endpoint("g").serve(
            EchoEngine()
        )
        client = (
            await front.namespace("ns").component("gen").endpoint("g")
            .client().start()
        )
        await client.wait_for_instances(5)
        out = await collect(await client.round_robin(Context({"text": "aa"})))
        assert len(out) == 2

        await hub.close()  # the bounce: every client connection drops
        hub2 = HubServer(data_dir=str(tmp_path), port=port)
        await hub2.start()

        # the clients' reconnect loops redial + rebuild; first request
        # may race the rebuild, so poll briefly
        deadline = asyncio.get_running_loop().time() + 10.0
        last = None
        while True:
            try:
                out = await asyncio.wait_for(
                    collect(await client.round_robin(Context({"text": "tpu"}))),
                    timeout=3.0,
                )
                break
            except Exception as e:  # noqa: BLE001 — retried until deadline
                last = e
                if asyncio.get_running_loop().time() > deadline:
                    raise AssertionError(
                        f"request never succeeded after hub restart: {last}"
                    )
                await asyncio.sleep(0.3)
        assert [a.data["token"] for a in out] == ["t", "p", "u"]
        # discovery stayed intact (no re-registration happened)
        assert client.instance_ids() != []

        await worker.shutdown()
        await front.shutdown()
        await wconn.close()
        await fconn.close()
        await hub2.close()

    run(main())


def test_store_wal_replay_lease_migration(tmp_path, run):
    """A key re-registered under a NEW lease within one WAL generation:
    after restore, the OLD lease's expiry must not delete it (the replay
    has to detach the key from its previous owner, like live kv_put)."""
    from dynamo_tpu.runtime.store import LocalStore

    async def main():
        d = str(tmp_path)
        s1 = LocalStore(data_dir=d)
        a = s1.grant_lease(5.0)
        b = s1.grant_lease(5.0)
        s1.kv_put("disc/w", b"via-a", a)
        s1.kv_put("disc/w", b"via-b", b)  # re-registration: b owns it now

        s2 = LocalStore(data_dir=d)  # crash-restore (WAL replay)
        s2._leases[a].deadline = 0.0  # a's owner never returns
        s2.expire_leases()
        assert s2.kv_get("disc/w").value == b"via-b"
        assert s2.kv_get("disc/w").lease_id == b
        s2._leases[b].deadline = 0.0
        s2.expire_leases()
        assert s2.kv_get("disc/w") is None
        await s2.close()

    run(main())

"""Hub (TCP store+bus) tests: the multi-process control plane.

The same DistributedRuntime/component code paths as test_distributed.py,
but store+bus accessed over real TCP through the hub server — this is the
multi-host wiring (worker hosts connect to the coordinator's hub over DCN).
"""

import asyncio

import pytest

from dynamo_tpu.runtime import Annotated, AsyncEngine, Context, DistributedRuntime, collect
from dynamo_tpu.runtime.hub import HubServer, connect_hub
from dynamo_tpu.runtime.store import KeyExists


class EchoEngine(AsyncEngine):
    async def generate(self, request: Context):
        for ch in request.data["text"]:
            yield Annotated.from_data({"token": ch})


def test_remote_store_ops(run):
    async def main():
        hub = HubServer()
        await hub.start()
        store, bus, conn = await connect_hub(hub.address)

        lease = await store.grant_lease(5.0)
        await store.kv_create("a/b", b"v1", lease_id=lease)
        with pytest.raises(KeyExists):
            await store.kv_create("a/b", b"v2")
        entry = await store.kv_get("a/b")
        assert entry.value == b"v1" and entry.lease_id == lease

        w = await store.watch_prefix("a/")
        assert [e.key for e in w.snapshot] == ["a/b"]
        await store.kv_put("a/c", b"v3")
        ev = await asyncio.wait_for(w.__anext__(), 2)
        assert (ev.key, ev.value) == ("a/c", b"v3")

        assert [e.key for e in await store.kv_get_prefix("a/")] == ["a/b", "a/c"]
        await conn.close()
        await hub.close()

    run(main())


def test_remote_bus_pubsub_request_queue_objects(run):
    async def main():
        hub = HubServer()
        await hub.start()
        store_a, bus_a, conn_a = await connect_hub(hub.address)
        store_b, bus_b, conn_b = await connect_hub(hub.address)

        # pub/sub across connections
        sub = bus_b.subscribe("events.kv")
        await asyncio.sleep(0.05)  # allow subscribe to land
        bus_a.publish("events.kv", b"stored")
        msg = await sub.next(2)
        assert msg.payload == b"stored"

        # request/reply across connections
        svc = bus_b.subscribe("svc.gen", group="workers")
        await asyncio.sleep(0.05)

        async def server():
            m = await svc.next(2)
            bus_b.respond(m, b"pong:" + m.payload)

        t = asyncio.get_running_loop().create_task(server())
        reply = await bus_a.request("svc.gen", b"ping", timeout=2)
        assert reply == b"pong:ping"
        await t

        # work queue across connections
        qa = bus_a.work_queue("prefill")
        qb = bus_b.work_queue("prefill")
        await qa.push(b"job")
        item = await qb.pop(timeout=2)
        assert item.payload == b"job"
        assert await qb.ack(item.id)

        # object store
        await bus_a.object_put("mdc", "m1", b"card")
        assert await bus_b.object_get("mdc", "m1") == b"card"
        assert await bus_b.object_list("mdc") == ["m1"]

        await conn_a.close()
        await conn_b.close()
        await hub.close()

    run(main())


def test_full_serving_over_hub(run):
    async def main():
        hub = HubServer()
        await hub.start()
        ws, wb, wconn = await connect_hub(hub.address)
        fs, fb, fconn = await connect_hub(hub.address)

        worker = await DistributedRuntime.from_settings(store=ws, bus=wb)
        front = await DistributedRuntime.from_settings(store=fs, bus=fb)

        await worker.namespace("ns").component("gen").endpoint("g").serve(EchoEngine())
        client = await front.namespace("ns").component("gen").endpoint("g").client().start()
        await client.wait_for_instances(5)

        out = await collect(await client.round_robin(Context({"text": "tpu"})))
        assert [a.data["token"] for a in out] == ["t", "p", "u"]

        # hub-side session cleanup: dropping the worker connection revokes
        # its lease -> discovery removes the instance
        await worker.shutdown()
        await wconn.close()
        await asyncio.sleep(0.1)
        assert client.instance_ids() == []

        await front.shutdown()
        await fconn.close()
        await hub.close()

    run(main())

"""Compiled-program perf-property tests (no chip required).

The merged one-write decode's whole value claim is structural: the KV
caches are DONATED through the jit boundary and appended IN PLACE by one
Mosaic kernel per step, instead of 2L full-cache XLA scatter copies
(docs/performance.md "decode killer #2": ~0.55 GB copied per scatter on
the 1B config). A TPU relay outage must not leave that claim untestable
(VERDICT r3 #2), so these tests assert it on the artifacts a chip-free
box CAN produce:

  * ``jax.export`` with ``platforms=["tpu"]`` — Mosaic lowering is
    hardware-independent, so the TPU StableHLO module is inspectable on
    CPU: the Pallas kernels must appear as ``tpu_custom_call``s whose
    cache operands carry ``output_operand_alias`` (the in-place RMW),
    with ZERO full-cache-shaped ``stablehlo.scatter`` ops left;
  * a real CPU ``.lower().compile()`` — the executable's
    ``input_output_alias`` header must map both cache parameters to
    outputs (donation survived to the buffer assignment).

A negative control locks the regexes themselves: the XLA fallback path
(``use_pallas=False``) MUST trip the scatter detector — if it stops
doing so, the detector has rotted, not the product.
"""

import re
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import export as jexport
from jax.sharding import Mesh

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig

B, BLOCK, CTX, NSTEPS = 2, 16, 128, 4


def _decode_inputs(cfg):
    M = CTX // BLOCK
    num_blocks = B * M + 1
    params = llama.init_params(cfg, jax.random.key(0))
    k_cache, v_cache = llama.init_kv_cache(cfg, num_blocks, BLOCK)
    tables = jnp.asarray(np.arange(1, num_blocks, dtype=np.int32).reshape(B, M))
    return dict(
        params=params, k_cache=k_cache, v_cache=v_cache, tables=tables,
        tokens=jnp.zeros(B, jnp.int32),
        positions=jnp.full((B,), 10, jnp.int32),
        seq_lens=jnp.full((B,), 11, jnp.int32),
        seeds=jnp.zeros(B, jnp.int32), steps=jnp.zeros(B, jnp.int32),
        temps=jnp.zeros(B, jnp.float32), top_ks=jnp.zeros(B, jnp.int32),
        top_ps=jnp.ones(B, jnp.float32),
    )


def _export_tpu_text(cfg, inp, *, use_pallas, merged, mesh=None):
    """TPU-platform StableHLO of the real ``llama.decode_window`` jit
    (donate_argnames and all), as text."""
    exp = jexport.export(llama.decode_window, platforms=["tpu"])(
        inp["params"], cfg, inp["tokens"], inp["positions"], inp["tables"],
        inp["seq_lens"], inp["seeds"], inp["steps"], inp["temps"],
        inp["top_ks"], inp["top_ps"], inp["k_cache"], inp["v_cache"],
        n_steps=NSTEPS, use_pallas=use_pallas, merged=merged, mesh=mesh,
    )
    return exp.mlir_module()


def _cache_shape_res(*caches):
    # stablehlo type syntax: tensor<2x2x17x16x128xbf16>
    return [
        "x".join(str(d) for d in c.shape) + "x" + ("bf16" if c.dtype == jnp.bfloat16 else str(c.dtype))
        for c in caches
    ]


def _full_cache_scatters(text, shape_res):
    """Scatter ops whose type signature touches a full-cache shape. The
    stablehlo.scatter op prints MULTI-LINE (its update-computation region
    sits between the op name and the trailing type signature), so the
    detector scans a bounded window after each occurrence rather than a
    single line."""
    hits = []
    idx = 0
    while True:
        i = text.find("stablehlo.scatter", idx)
        if i < 0:
            break
        window = text[i : i + 4000]
        if any(s in window for s in shape_res):
            hits.append(window.split("\n", 1)[0][:160])
        idx = i + 1
    return hits


def test_merged_decode_is_scatter_free_on_tpu():
    """The headline path (use_pallas, merged): every per-step cache write
    is one aliased Mosaic custom call; no full-cache scatter survives
    lowering. head_dim=128 matches the engine's kernel gate."""
    cfg = ModelConfig.tiny(dtype="bfloat16", head_dim=128)
    inp = _decode_inputs(cfg)
    text = _export_tpu_text(cfg, inp, use_pallas=True, merged=True)
    shape_res = _cache_shape_res(inp["k_cache"], inp["v_cache"])

    assert text.count("tpu_custom_call") >= 2, (
        "expected Mosaic kernels (paged attention + cache append) in the "
        "TPU lowering; the Pallas path silently fell back to XLA"
    )
    # the append kernel RMWs both caches in place
    assert text.count("output_operand_alias") >= 2
    scatters = _full_cache_scatters(text, shape_res)
    assert not scatters, (
        "full-cache scatter(s) back in the merged decode path — the "
        f"~0.55GB/step copy regression: {scatters}"
    )
    # donation intent on both caches survives to the exported module
    donors = text.count("jax.buffer_donor") + text.count("tf.aliasing_output")
    assert donors >= 2


def test_merged_decode_sharded_tp_is_scatter_free_on_tpu():
    """Same property under the tp shard_map (kv-head-parallel kernels)."""
    cfg = ModelConfig.tiny(dtype="bfloat16", head_dim=128)
    inp = _decode_inputs(cfg)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    text = _export_tpu_text(cfg, inp, use_pallas=True, merged=True, mesh=mesh)
    shape_res = _cache_shape_res(inp["k_cache"], inp["v_cache"])
    assert text.count("tpu_custom_call") >= 2
    assert text.count("output_operand_alias") >= 2
    assert not _full_cache_scatters(text, shape_res)


def test_mla_merged_decode_is_scatter_free_on_tpu():
    """The MLA latent merged path: all layers' latent writes batch into
    one aliased append (kv_lora_rank=128 engages the engine gate)."""
    cfg = ModelConfig.tiny_mla(dtype="bfloat16", kv_lora_rank=128)
    inp = _decode_inputs(cfg)
    text = _export_tpu_text(cfg, inp, use_pallas=True, merged=True)
    shape_res = _cache_shape_res(inp["k_cache"], inp["v_cache"])
    assert text.count("tpu_custom_call") >= 2
    assert text.count("output_operand_alias") >= 2
    assert not _full_cache_scatters(text, shape_res)


def test_xla_fallback_trips_the_scatter_detector():
    """Negative control: the XLA path DOES contain full-cache scatters
    (that's why the Pallas append exists). If this stops failing the
    detector, the regexes rotted and the positive tests prove nothing."""
    cfg = ModelConfig.tiny(dtype="bfloat16", head_dim=128)
    inp = _decode_inputs(cfg)
    text = _export_tpu_text(cfg, inp, use_pallas=False, merged=False)
    shape_res = _cache_shape_res(inp["k_cache"], inp["v_cache"])
    assert _full_cache_scatters(text, shape_res), (
        "scatter detector no longer matches the known-scatter XLA path"
    )


def test_cpu_compiled_executable_aliases_both_caches():
    """Donation must survive all the way into the compiled executable's
    buffer assignment: the HloModule header's input_output_alias has to
    map two parameters with exactly the cache shapes. (A donation that
    XLA could not honor is silently dropped — caches would be COPIED
    every window.)"""
    cfg = ModelConfig.tiny(dtype="bfloat16")
    inp = _decode_inputs(cfg)
    compiled = llama.decode_window.lower(
        inp["params"], cfg, inp["tokens"], inp["positions"], inp["tables"],
        inp["seq_lens"], inp["seeds"], inp["steps"], inp["temps"],
        inp["top_ks"], inp["top_ps"], inp["k_cache"], inp["v_cache"],
        n_steps=NSTEPS, use_pallas=False, merged=True,
    ).compile()
    text = compiled.as_text()
    header = text.splitlines()[0]
    m = re.search(r"input_output_alias=\{(.*?)\}, entry_computation", header)
    assert m, f"no input_output_alias in compiled module header: {header[:200]}"
    param_idxs = [int(p) for p in re.findall(r"\((\d+), \{\}", m.group(1))]
    assert len(param_idxs) >= 2, f"expected both caches aliased: {m.group(1)}"
    # map the aliased parameter indices back to shapes via the entry params
    shape_of = dict(
        (int(idx), shape)
        for shape, idx in re.findall(
            r"(\S+\[[0-9,]*\])\{[0-9,]*\} parameter\((\d+)\)", text
        )
    )
    cache_shape = "bf16[" + ",".join(str(d) for d in inp["k_cache"].shape) + "]"
    aliased_shapes = [shape_of.get(i) for i in param_idxs]
    assert aliased_shapes.count(cache_shape) >= 2, (
        f"aliased params {param_idxs} have shapes {aliased_shapes}, "
        f"expected two of {cache_shape}"
    )


def test_mixed_step_program_count_bounded():
    """Shape-bucketing guard for the fused mixed prefill+decode step
    (ISSUEs 3 + 9): across every reachable (decode-batch x
    segment-count-bucket x prefill-bucket) dispatch shape, the number
    of distinct XLA programs must equal segment-count buckets x prefill
    buckets — the decode batch is ALWAYS padded to max_batch_size and
    lengths/positions/histories/valids are traced values, so nothing
    else (in particular NOT the live segment-length mixture) may key a
    recompile. A regression here (e.g. an accidentally-static chunk
    length, or per-mixture shapes) multiplies warmup/compile time by
    the request mix and injects 20-40s XLA stalls mid-serving."""
    cfg = ModelConfig.tiny(dtype="float32")
    M = CTX // BLOCK
    MP_MAX = 2
    num_blocks = (B + MP_MAX) * M + 1
    params = llama.init_params(cfg, jax.random.key(0))
    k_cache, v_cache = llama.init_kv_cache(cfg, num_blocks, BLOCK)
    d_tables = jnp.asarray(
        np.arange(1, B * M + 1, dtype=np.int32).reshape(B, M)
    )
    p_tables = jnp.asarray(
        np.arange(B * M + 1, (B + MP_MAX) * M + 1, dtype=np.int32)
        .reshape(MP_MAX, M)
    )
    seg_buckets = (1, 2)
    buckets = (16, 32)
    base = llama.mixed_step._cache_size()
    for MP in seg_buckets:
        for T in buckets:
            # two dispatches per bucket pair with DIFFERENT traced
            # values (active rows, lengths, per-segment fill/history,
            # dead pad segments) — only the bucket pair may recompile
            variants = (
                (11, (0,) * MP, (T - 3,) + (2,) * (MP - 1)),
                (7, (T // 2,) * MP, (2,) + (0,) * (MP - 1)),
            )
            for sl, hists, valids in variants:
                out = llama.mixed_step(
                    params, cfg,
                    jnp.zeros(B, jnp.int32),
                    jnp.full((B,), sl - 1, jnp.int32),
                    d_tables,
                    jnp.full((B,), sl, jnp.int32),
                    jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
                    jnp.zeros(B, jnp.float32), jnp.zeros(B, jnp.int32),
                    jnp.ones(B, jnp.float32),
                    jnp.zeros((MP, T), jnp.int32), p_tables[:MP],
                    jnp.asarray(hists, jnp.int32),
                    jnp.asarray(valids, jnp.int32),
                    k_cache, v_cache,
                    use_pallas=False,
                )
                _, _, k_cache, v_cache = out[:4]
    grown = llama.mixed_step._cache_size() - base
    limit = len(seg_buckets) * len(buckets)
    assert grown == limit, (
        f"mixed_step compiled {grown} programs for {len(seg_buckets)} "
        f"segment-count buckets x {len(buckets)} prefill buckets "
        f"(expected {limit}) — a traced value leaked into the static "
        "shape key"
    )


def test_mixed_step_program_count_bounded_quantized_kv():
    """Quantized-KV twin of the bucketing guard (ISSUE 14): a
    float8_e4m3 cache (the quantized device-KV mode the Pallas gate now
    keeps on the kernel path) must compile exactly the same
    (segment-count x prefill-bucket) program grid as bf16 — per-DTYPE
    programs are expected (different cache types ARE different
    programs), but traced-value variation under a quantized cache must
    never add more."""
    cfg = ModelConfig.tiny(dtype="float32")
    M = CTX // BLOCK
    MP_MAX = 2
    num_blocks = (B + MP_MAX) * M + 1
    params = llama.init_params(cfg, jax.random.key(0))
    k_cache, v_cache = llama.init_kv_cache(
        cfg, num_blocks, BLOCK, dtype=jnp.float8_e4m3fn
    )
    d_tables = jnp.asarray(
        np.arange(1, B * M + 1, dtype=np.int32).reshape(B, M)
    )
    p_tables = jnp.asarray(
        np.arange(B * M + 1, (B + MP_MAX) * M + 1, dtype=np.int32)
        .reshape(MP_MAX, M)
    )
    seg_buckets = (1, 2)
    buckets = (16, 32)
    base = llama.mixed_step._cache_size()
    for MP in seg_buckets:
        for T in buckets:
            variants = (
                (11, (0,) * MP, (T - 3,) + (2,) * (MP - 1)),
                (7, (T // 2,) * MP, (2,) + (0,) * (MP - 1)),
            )
            for sl, hists, valids in variants:
                out = llama.mixed_step(
                    params, cfg,
                    jnp.zeros(B, jnp.int32),
                    jnp.full((B,), sl - 1, jnp.int32),
                    d_tables,
                    jnp.full((B,), sl, jnp.int32),
                    jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
                    jnp.zeros(B, jnp.float32), jnp.zeros(B, jnp.int32),
                    jnp.ones(B, jnp.float32),
                    jnp.zeros((MP, T), jnp.int32), p_tables[:MP],
                    jnp.asarray(hists, jnp.int32),
                    jnp.asarray(valids, jnp.int32),
                    k_cache, v_cache,
                    use_pallas=False,
                )
                _, _, k_cache, v_cache = out[:4]
                assert k_cache.dtype == jnp.float8_e4m3fn
    grown = llama.mixed_step._cache_size() - base
    limit = len(seg_buckets) * len(buckets)
    assert grown == limit, (
        f"quantized-KV mixed_step compiled {grown} programs for "
        f"{len(seg_buckets)} segment-count buckets x {len(buckets)} "
        f"prefill buckets (expected {limit}) — the quantized cache "
        "leaked a traced value into the static shape key"
    )


def test_mixed_step_program_count_bounded_int8_scales_kv():
    """int8-with-scales twin of the bucketing guard (ISSUE 18): the
    int8 device cache threads two [L, N] f32 scale planes through every
    mixed dispatch and returns them grown — the planes are TRACED
    operands, so across the same (segment-count x prefill-bucket) grid
    the program count must stay exactly the bucket grid. A regression
    here (a plane shape or a scale value leaking into the static key)
    multiplies compiles by the page-recycling pattern."""
    cfg = ModelConfig.tiny(dtype="float32")
    M = CTX // BLOCK
    MP_MAX = 2
    num_blocks = (B + MP_MAX) * M + 1
    params = llama.init_params(cfg, jax.random.key(0))
    k_cache, v_cache = llama.init_kv_cache(
        cfg, num_blocks, BLOCK, dtype=jnp.int8
    )
    k_scales = jnp.full((cfg.num_layers, num_blocks), 1e-12, jnp.float32)
    v_scales = k_scales
    d_tables = jnp.asarray(
        np.arange(1, B * M + 1, dtype=np.int32).reshape(B, M)
    )
    p_tables = jnp.asarray(
        np.arange(B * M + 1, (B + MP_MAX) * M + 1, dtype=np.int32)
        .reshape(MP_MAX, M)
    )
    seg_buckets = (1, 2)
    buckets = (16, 32)
    base = llama.mixed_step._cache_size()
    for MP in seg_buckets:
        for T in buckets:
            variants = (
                (11, (0,) * MP, (T - 3,) + (2,) * (MP - 1)),
                (7, (T // 2,) * MP, (2,) + (0,) * (MP - 1)),
            )
            for sl, hists, valids in variants:
                out = llama.mixed_step(
                    params, cfg,
                    jnp.zeros(B, jnp.int32),
                    jnp.full((B,), sl - 1, jnp.int32),
                    d_tables,
                    jnp.full((B,), sl, jnp.int32),
                    jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
                    jnp.zeros(B, jnp.float32), jnp.zeros(B, jnp.int32),
                    jnp.ones(B, jnp.float32),
                    jnp.zeros((MP, T), jnp.int32), p_tables[:MP],
                    jnp.asarray(hists, jnp.int32),
                    jnp.asarray(valids, jnp.int32),
                    k_cache, v_cache,
                    use_pallas=False,
                    k_scales=k_scales, v_scales=v_scales,
                )
                _, _, k_cache, v_cache, k_scales, v_scales, _ = out[:7]
                assert k_cache.dtype == jnp.int8
                assert k_scales.dtype == jnp.float32
    grown = llama.mixed_step._cache_size() - base
    limit = len(seg_buckets) * len(buckets)
    assert grown == limit, (
        f"int8+scales mixed_step compiled {grown} programs for "
        f"{len(seg_buckets)} segment-count buckets x {len(buckets)} "
        f"prefill buckets (expected {limit}) — the scale planes leaked "
        "a traced value into the static shape key"
    )


def test_mixed_step_tpu_lowering_uses_ragged_kernel_quantized_kv():
    """The quantized-cache TPU path must still lower the ragged Mosaic
    kernel — engine/engine.py's capability gate now keeps fp8 caches on
    the Pallas path, and this pins that the lowering actually holds
    (the in-kernel `.astype(f32)` page cast is the fused dequant)."""
    cfg = ModelConfig.tiny(dtype="bfloat16", head_dim=128)
    M = CTX // BLOCK
    MP = 2
    num_blocks = (B + MP) * M + 1
    params = llama.init_params(cfg, jax.random.key(0))
    k_cache, v_cache = llama.init_kv_cache(
        cfg, num_blocks, BLOCK, dtype=jnp.float8_e4m3fn
    )
    d_tables = jnp.asarray(
        np.arange(1, B * M + 1, dtype=np.int32).reshape(B, M)
    )
    p_tables = jnp.asarray(
        np.arange(B * M + 1, (B + MP) * M + 1, dtype=np.int32)
        .reshape(MP, M)
    )
    T = 32
    exp = jexport.export(llama.mixed_step, platforms=["tpu"])(
        params, cfg,
        jnp.zeros(B, jnp.int32), jnp.full((B,), 10, jnp.int32), d_tables,
        jnp.full((B,), 11, jnp.int32),
        jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
        jnp.zeros(B, jnp.float32), jnp.zeros(B, jnp.int32),
        jnp.ones(B, jnp.float32),
        jnp.zeros((MP, T), jnp.int32), p_tables,
        jnp.zeros(MP, jnp.int32), jnp.full((MP,), T, jnp.int32),
        k_cache, v_cache, use_pallas=True,
    )
    text = exp.mlir_module()
    assert text.count("tpu_custom_call") >= 1, (
        "no Mosaic kernel in the quantized-KV mixed step's TPU "
        "lowering — the fp8 cache silently fell back to XLA"
    )


def test_mixed_step_tpu_lowering_uses_ragged_kernel():
    """The fused step's TPU path must actually lower the ragged
    mixed-attention Mosaic kernel (head_dim=128 matches the engine's
    kernel gate) — a silent fall-through to the XLA pair would ship the
    fusion's scheduling without its single-kernel attention."""
    cfg = ModelConfig.tiny(dtype="bfloat16", head_dim=128)
    M = CTX // BLOCK
    MP = 2  # a multi-segment pack must still lower the ONE ragged kernel
    num_blocks = (B + MP) * M + 1
    params = llama.init_params(cfg, jax.random.key(0))
    k_cache, v_cache = llama.init_kv_cache(cfg, num_blocks, BLOCK)
    d_tables = jnp.asarray(
        np.arange(1, B * M + 1, dtype=np.int32).reshape(B, M)
    )
    p_tables = jnp.asarray(
        np.arange(B * M + 1, (B + MP) * M + 1, dtype=np.int32)
        .reshape(MP, M)
    )
    T = 32
    exp = jexport.export(llama.mixed_step, platforms=["tpu"])(
        params, cfg,
        jnp.zeros(B, jnp.int32), jnp.full((B,), 10, jnp.int32), d_tables,
        jnp.full((B,), 11, jnp.int32),
        jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
        jnp.zeros(B, jnp.float32), jnp.zeros(B, jnp.int32),
        jnp.ones(B, jnp.float32),
        jnp.zeros((MP, T), jnp.int32), p_tables,
        jnp.zeros(MP, jnp.int32), jnp.full((MP,), T, jnp.int32),
        k_cache, v_cache, use_pallas=True,
    )
    text = exp.mlir_module()
    assert text.count("tpu_custom_call") >= 1, (
        "no Mosaic kernel in the mixed step's TPU lowering — the ragged "
        "paged-attention path silently fell back to XLA"
    )
    # donation intent on both caches survives to the exported module
    donors = text.count("jax.buffer_donor") + text.count("tf.aliasing_output")
    assert donors >= 2


def test_pp_decode_moves_activations_not_weights():
    """Locks the measured pp-decode structure (docs/performance.md,
    VERDICT r3 #8): on a pp mesh the compiled decode window must move
    ACTIVATIONS through collective-permutes and all-gather ZERO bytes of
    stage weights — a regression to weight gathering would put the whole
    stage's parameter volume on every decode step's critical path."""
    cfg = ModelConfig.tiny(dtype="float32", num_layers=4)
    inp = _decode_inputs(cfg)
    from dynamo_tpu.parallel.mesh import (
        MeshConfig, cache_sharding, make_mesh, shard_params,
    )

    mesh = make_mesh(MeshConfig(pp=2))
    params = shard_params(inp["params"], mesh)
    cs = cache_sharding(mesh, cfg)
    k_cache = jax.device_put(inp["k_cache"], cs)
    v_cache = jax.device_put(inp["v_cache"], cs)
    compiled = llama.decode_window.lower(
        params, cfg, inp["tokens"], inp["positions"], inp["tables"],
        inp["seq_lens"], inp["seeds"], inp["steps"], inp["temps"],
        inp["top_ks"], inp["top_ps"], k_cache, v_cache,
        n_steps=NSTEPS, use_pallas=False, merged=False, mesh=mesh,
    ).compile()
    text = compiled.as_text()
    assert "collective-permute" in text, (
        "pp decode no longer pipelines activations through "
        "collective-permute — partitioning regressed"
    )
    # weight all-gathers: any all-gather whose result is a 2D+ f32
    # tensor with >= 64*64 elements would be a stage-weight gather (the
    # activation permutes are [B, E] = tiny)
    big_ag = []
    for m in re.finditer(r"= f32\[([0-9,]+)\][^\n]*? all-gather", text):
        dims = [int(d) for d in m.group(1).split(",") if d]
        if np.prod(dims) >= 64 * 64:
            big_ag.append(m.group(0)[:120])
    assert not big_ag, f"stage-weight all-gathers appeared: {big_ag}"


def test_streamed_handoff_program_count_bounded(run):
    """Shape-bucketing guard for the streamed disagg handoff (ISSUE 6):
    the incremental extract's per-segment gathers and the decode side's
    per-segment scatters must compile one program per SEGMENT-GEOMETRY
    BUCKET (``_pad_idxs`` power-of-two bucketing), never per request
    shape — an accidental per-request key would inject an XLA compile
    into every streamed segment of every new prompt length."""
    import asyncio

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.engine.offload import _gather_blocks, _pad_idxs, _scatter_blocks
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime import Context

    cfg = ModelConfig.tiny(dtype="float32")

    def eng():
        return JaxEngine(
            EngineConfig(
                model=cfg, num_blocks=64, block_size=4, max_batch_size=4,
                max_context=128, prefill_chunk=8,
            ),
            seed=0,
        )

    def req(toks):
        return PreprocessedRequest(
            token_ids=list(toks),
            stop_conditions=StopConditions(max_tokens=2, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0, seed=0),
            eos_token_ids=[],
        )

    prefill, decode = eng(), eng()

    async def main():
        # prompts of DIFFERENT lengths whose chunking lands on the same
        # segment bucket (prefill_chunk 8 / block 4 -> 2-block segments)
        cases = [
            (list(range(10, 34)), 0),   # 24 tokens, per-chunk segments
            (list(range(50, 90)), 0),   # 40 tokens, same 2-block bucket
            (list(range(200, 224)), 1), # segment_blocks=1 -> new bucket
        ]
        g0, s0 = _gather_blocks._cache_size(), _scatter_blocks._cache_size()
        seen_buckets = set()
        for i, (toks, seg_blocks) in enumerate(cases):
            segs = []

            async def on_segment(b0, k, v, _segs=segs):
                _segs.append((b0, np.asarray(k), np.asarray(v)))

            await prefill.prefill_extract_stream(
                req(toks), None, segment_blocks=seg_blocks,
                on_segment=on_segment,
            )
            handle = decode.begin_remote(Context(req(toks)))
            assert handle is not None
            for b0, k, v in segs:
                seen_buckets.add(len(_pad_idxs(list(range(k.shape[2])))))
                await decode.scatter_remote_segment(handle, b0, k, v)
            decode.abort_remote(handle, "test teardown")
        g_grown = _gather_blocks._cache_size() - g0
        s_grown = _scatter_blocks._cache_size() - s0
        assert g_grown <= len(seen_buckets), (
            f"extract gathers compiled {g_grown} programs for "
            f"{len(seen_buckets)} segment buckets {sorted(seen_buckets)}"
        )
        assert s_grown <= len(seen_buckets), (
            f"segment scatters compiled {s_grown} programs for "
            f"{len(seen_buckets)} segment buckets {sorted(seen_buckets)}"
        )
        await prefill.close()
        await decode.close()

    run(main())


def test_adapter_program_count_keys_on_buckets_not_census(run):
    """Multi-LoRA bucketing guard (ISSUE 19): the adapter device stack's
    ``[L, NA, ..., rb]`` shapes are the registry's (count, rank)
    BUCKETS — zero-padded, bitwise exact — so staging, evicting and
    re-staging adapters, and dispatching ANY per-row adapter-id mixture,
    must compile exactly ONE prefill program for a fixed chunk bucket.
    A program count that scales with the live adapter census would
    inject an XLA compile into every LRU slot churn. The engine's
    dispatch key mirrors this: adapter fleets append one static
    ``("lora", count_bucket, rank_bucket)`` suffix; no-adapter engines
    append NOTHING (their key tuples — and therefore their compiled
    programs — stay byte-identical to pre-multi-model builds)."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.engine.adapters import AdapterRegistry

    cfg = ModelConfig.tiny(dtype="float32")
    reg = AdapterRegistry(("alice:4", "bob:8:7", "carol:2:3"), cfg)
    # 3 live slots -> count bucket 4; ranks {4, 8, 2} -> rank bucket 8
    assert (reg.count_bucket, reg.rank_bucket) == (4, 8)

    params = llama.init_params(cfg, jax.random.key(0))
    k_cache, v_cache = llama.init_kv_cache(cfg, 8, BLOCK)
    tables = jnp.asarray(np.arange(1, 5, dtype=np.int32))
    T = 16
    base = llama.prefill._cache_size()
    shapes0 = jax.tree.map(lambda a: a.shape, reg.device_stack())
    # every registry state x adapter-id mixture the LRU can produce:
    # cold stack, each staging, an eviction, a re-stage into the freed
    # slot — with base (-1) and adapter rows dispatched against each
    states = (
        lambda: None,
        lambda: reg.stage("alice"),
        lambda: reg.stage("bob"),
        lambda: reg.evict("alice"),
        lambda: reg.stage("carol"),
        lambda: reg.stage("alice"),
    )
    for mutate in states:
        mutate()
        assert jax.tree.map(lambda a: a.shape, reg.device_stack()) == shapes0
        for aid in (-1, 0, 2):
            _, k_cache, v_cache = llama.prefill(
                params, cfg, jnp.zeros(T, jnp.int32), tables,
                jnp.int32(0), jnp.int32(T - 3), k_cache, v_cache,
                lora=reg.device_stack(), adapter_id=jnp.int32(aid),
            )
    grown = llama.prefill._cache_size() - base
    assert grown == 1, (
        f"adapter prefill compiled {grown} programs across "
        f"{len(states)} registry states x 3 id mixtures (expected 1) — "
        "the live adapter census leaked into the static shape key"
    )

    async def engines():
        lora_eng = JaxEngine(
            EngineConfig(
                model=cfg, num_blocks=32, block_size=BLOCK,
                max_batch_size=2, max_context=128,
                adapters=("alice:4", "bob:8:7", "carol:2:3"),
                served_model_name="base",
            ),
            seed=0,
        )
        plain_eng = JaxEngine(
            EngineConfig(
                model=cfg, num_blocks=32, block_size=BLOCK,
                max_batch_size=2, max_context=128,
            ),
            seed=0,
        )
        assert lora_eng._lora_key() == (("lora", 4, 8),)
        assert plain_eng._lora_key() == ()
        await lora_eng.close()
        await plain_eng.close()

    run(engines())


def test_ici_mover_program_count_bounded(run):
    """Shape-bucketing guard for the ICI same-slice handoff (ISSUE 11):
    the decode sink's per-segment device→device mover must compile one
    program per SEGMENT-GEOMETRY BUCKET (the same ``_pad_idxs``
    power-of-two bucketing as the streamed scatter), never per segment
    size — an accidental per-shape key would inject an XLA compile into
    every segment of every new prompt length."""
    from dynamo_tpu.disagg.ici import IciSegmentMover
    from dynamo_tpu.engine.offload import _pad_idxs

    def main():
        import jax.numpy as jnp

        mover = IciSegmentMover(None, None)
        seen_buckets = set()
        # segment sizes across two buckets (1,2 -> 2; 3,4 -> 4) in a
        # fixed [L=2, H=2, n, bs=4, D=8] geometry — also odd/partial
        # tails, which the mover pads to the bucket before the compiled
        # move and slices back after
        for n in (1, 2, 3, 4, 2, 3, 1, 4):
            k = jnp.arange(2 * 2 * n * 4 * 8, dtype=jnp.float32).reshape(
                2, 2, n, 4, 8
            )
            v = k + 1
            seen_buckets.add(len(_pad_idxs(list(range(n)))))
            mk, mv = mover.move(k, v)
            assert mk.shape == k.shape and mv.shape == v.shape
            assert jnp.array_equal(mk, k) and jnp.array_equal(mv, v)
        assert mover.segments_moved == 8
        # k and v compile separately (MLA-asymmetric shapes), so the
        # bound is 2 programs per bucket
        assert mover.programs() <= 2 * len(seen_buckets), (
            f"ici mover compiled {mover.programs()} programs for "
            f"{len(seen_buckets)} segment buckets {sorted(seen_buckets)}"
        )
        # the matched-geometry (single-device) case took the explicit
        # shard_map path, not the generic reshard
        assert mover.permute_programs == mover.programs()
        assert mover.reshard_programs == 0

    async def amain():
        main()

    run(amain())

"""SLA-driven planner: deterministic control-loop simulation.

Every decision path runs under an injected fake clock with scripted
arrival traces — no silicon, no wall-clock sleeps. The acceptance
matrix from the issue: sustained TTFT-SLO breach -> scale-up decision
within the grace window; oscillating load -> ZERO flapping actions;
scale-down only after the cooldown; shed-vs-admit fairness by SLO
class under 2x offered load.
"""

import asyncio
import json

import pytest

from dynamo_tpu.deploy import (
    Autoscaling,
    DeploymentController,
    DynamoDeployment,
    ServiceDeploymentSpec,
)
from dynamo_tpu.deploy.api_server import DeploymentStore
from dynamo_tpu.http.metrics import Metrics
from dynamo_tpu.kv_router.indexer import OverlapScores
from dynamo_tpu.kv_router.publisher import ProcessedEndpoints
from dynamo_tpu.kv_router.scheduler import (
    AllWorkersBusy,
    KvScheduler,
    SchedulerConfig,
    WorkerLoad,
)
from dynamo_tpu.planner import (
    AdmissionGate,
    CallbackScaleDriver,
    CapacityModel,
    CapacityWatermark,
    GuardConfig,
    HoltForecaster,
    Planner,
    PlannerConfig,
    PlannerDecision,
    ScaleGuard,
    SloEvaluator,
    SloTargets,
    StoreScaleDriver,
    TelemetryAggregator,
    TokenBucket,
)

from conftest import FakeClock

pytestmark = pytest.mark.planner


def _load(wid, active=0, slots=8, waiting=0, kv=0.0, ts=None, draining=0,
          requests_total=0, tokens_generated=0, prompt_tokens_total=0):
    return WorkerLoad(
        worker_id=wid, kv_active_blocks=int(kv * 100), kv_total_blocks=100,
        active_requests=active, total_slots=slots, waiting=waiting,
        draining=draining, ts=ts, requests_total=requests_total,
        tokens_generated=tokens_generated,
        prompt_tokens_total=prompt_tokens_total,
    )


# ---------------- scale guard ----------------


def test_guard_up_immediate_down_gated():
    clk = FakeClock()
    g = ScaleGuard(GuardConfig(min_replicas=1, max_replicas=8,
                               up_cooldown_s=0, down_cooldown_s=20,
                               down_stable_s=10), clock=clk, initial=2)
    assert g.apply(5) == 5  # up: immediate
    assert [a.direction for a in g.actions] == ["up"]
    assert g.apply(2) == 5  # down: stability window starts now
    clk.advance(9)
    assert g.apply(2) == 5  # 9s below < 10s stable
    clk.advance(2)
    assert g.apply(2) == 5  # stable met, but 11s < 20s cooldown
    clk.advance(10)
    assert g.apply(2) == 2  # both gates open
    assert [a.direction for a in g.actions] == ["up", "down"]


def test_guard_up_cooldown_paces_consecutive_ups():
    clk = FakeClock()
    g = ScaleGuard(GuardConfig(max_replicas=16, up_cooldown_s=30),
                   clock=clk, initial=1)
    assert g.apply(2) == 2
    clk.advance(5)
    assert g.apply(4) == 2  # paced: 5s < 30s since the last up
    clk.advance(26)
    assert g.apply(4) == 4


def test_guard_oscillation_resets_stability_window():
    clk = FakeClock()
    g = ScaleGuard(GuardConfig(down_cooldown_s=0, down_stable_s=10),
                   clock=clk, initial=4)
    for _ in range(50):  # 250 s of a desire flapping 4 <-> 2 every 5 s
        clk.advance(5)
        g.apply(2)
        clk.advance(5)
        g.apply(4)
    assert g.current == 4
    assert g.actions == []  # every dip reset the window: zero churn


def test_guard_clamps_and_validates():
    clk = FakeClock()
    g = ScaleGuard(GuardConfig(min_replicas=2, max_replicas=4,
                               down_cooldown_s=0, down_stable_s=0), clock=clk)
    assert g.apply(100) == 4  # first apply seeds (clamped), no action
    assert g.actions == []
    assert g.apply(0) == 2
    with pytest.raises(ValueError):
        ScaleGuard(GuardConfig(min_replicas=5, max_replicas=2))
    with pytest.raises(ValueError):
        ScaleGuard(GuardConfig(up_cooldown_s=-1))


# ---------------- forecaster / capacity / SLO ----------------


def test_holt_forecast_extrapolates_ramp():
    f = HoltForecaster(alpha=0.6, beta=0.4)
    for y in (10, 20, 30, 40, 50):  # steady +10/update ramp
        f.update(y)
    assert f.forecast(0) > 40  # level tracks the ramp
    assert f.forecast(2) > f.forecast(0)  # trend extrapolates ahead
    assert HoltForecaster().forecast() == 0.0  # no data -> 0
    g = HoltForecaster()
    for y in (100, 50, 10, 0, 0, 0):  # collapsing load
        g.update(y)
    assert g.forecast(5) == 0.0  # floored, never negative


def test_capacity_model_replica_math_and_correction():
    m = CapacityModel(100.0, 1000.0)
    assert m.decode_replicas_for(0) == 1  # warm floor
    assert m.decode_replicas_for(400, headroom=0.8) == 5  # 400/(100*0.8)
    assert m.prefill_replicas_for(2400, headroom=0.8) == 3
    # observed fleet throughput 50% of modeled: correction folds in...
    for _ in range(50):
        m.observe_decode(100.0, replicas=2)  # modeled 200
    assert 0.45 < m.decode_corr < 0.6
    assert m.decode_replicas_for(400, headroom=1.0) > 4  # needs more chips
    # ...but one absurd sample can't wreck the plan (clamped)
    m2 = CapacityModel(100.0, 100.0, corr_bounds=(0.25, 4.0))
    m2.observe_decode(1e9, replicas=1)
    assert m2.decode_corr <= 4.0
    with pytest.raises(ValueError):
        CapacityModel(0.0, 1.0)


def test_capacity_model_from_roofline():
    from dynamo_tpu.perf.roofline import DEFAULT_SCENARIOS

    m = CapacityModel.from_roofline(DEFAULT_SCENARIOS[0])
    assert m.decode_tok_s(1) > 0
    assert m.prefill_tok_s(1) > 0


def test_slo_evaluator_grace_window():
    clk = FakeClock()
    ev = SloEvaluator(SloTargets(ttft_p99_ms=2000, itl_p99_ms=200,
                                 grace_s=10), clock=clk)
    st = ev.evaluate(5000, 100)
    assert st.ttft_breached and not st.ttft_sustained  # just started
    clk.advance(11)
    st = ev.evaluate(5000, 100)
    assert st.ttft_sustained and not st.itl_sustained
    # a gap (no samples: None) clears the breach entirely
    ev.evaluate(None, None)
    clk.advance(1)
    st = ev.evaluate(5000, None)
    assert st.ttft_breached and not st.ttft_sustained  # window restarted


# ---------------- telemetry aggregator ----------------


def test_telemetry_window_and_rates():
    clk = FakeClock()
    t = TelemetryAggregator(window_s=10.0, clock=clk)
    for _ in range(20):
        t.record_arrival(prompt_tokens=100)
        t.record_ttft(500.0)
        clk.advance(1)
    snap = t.snapshot()  # 10s window holds the last 10 arrivals
    assert snap.request_rate == pytest.approx(1.0)
    assert snap.prompt_token_rate == pytest.approx(100.0)
    assert snap.ttft_p99_ms == pytest.approx(500.0)
    clk.advance(30)  # everything ages out
    snap = t.snapshot()
    assert snap.request_rate == 0.0
    assert snap.ttft_p99_ms is None


def test_telemetry_counter_deltas_and_restart_clamp():
    clk = FakeClock()
    t = TelemetryAggregator(window_s=10.0, clock=clk)
    t.observe_loads([_load(1, requests_total=100, tokens_generated=1000,
                           prompt_tokens_total=5000)])
    clk.advance(5)
    t.observe_loads([_load(1, requests_total=110, tokens_generated=1500,
                           prompt_tokens_total=6000)])
    snap = t.snapshot()
    assert snap.request_rate == pytest.approx(10 / 10.0)
    assert snap.gen_token_rate == pytest.approx(500 / 10.0)
    assert snap.prompt_token_rate == pytest.approx(1000 / 10.0)
    # worker restart: counters reset below the baseline -> clamp to 0
    # (one lost interval), never a negative rate
    clk.advance(1)
    t.observe_loads([_load(1, requests_total=3, tokens_generated=30,
                           prompt_tokens_total=90)])
    assert t.snapshot().request_rate >= 0.0
    # a vanished worker's baseline is dropped (its comeback re-baselines)
    t.observe_loads([_load(2)])
    assert 1 not in t._counter_base


def test_telemetry_saturation_watermarks():
    clk = FakeClock()
    t = TelemetryAggregator(clock=clk)
    t.observe_loads([
        _load(1, active=8, slots=8, waiting=3),   # slots full, queue
        _load(2, active=2, slots=8, kv=0.95),     # KV pool exhausted
        _load(3, active=8, slots=8, waiting=0),   # full but no queue
        _load(4, active=8, slots=8, waiting=5, draining=1),  # draining
    ])
    snap = t.snapshot()
    assert snap.saturated_workers() == [1, 2]
    assert snap.decode_replicas == 3  # draining worker not counted
    assert snap.queue_depth == 8


# ---------------- admission gate ----------------


def test_token_bucket_refill_and_floor():
    clk = FakeClock()
    b = TokenBucket(rate=2.0, burst=4.0, clock=clk)
    assert all(b.try_take() for _ in range(4))
    assert not b.try_take()  # drained
    assert b.time_until() == pytest.approx(0.5)
    clk.advance(1.0)  # +2 tokens
    assert b.try_take() and b.try_take() and not b.try_take()
    clk.advance(2.0)  # 4 tokens, but a floor of 3 leaves only 1 takeable
    assert b.try_take(floor=3.0)
    assert not b.try_take(floor=3.0)


def test_admission_sheds_at_2x_and_recovers():
    clk = FakeClock()
    gate = AdmissionGate(rate_req_s=10.0, burst=10.0, clock=clk)
    shed = admitted = 0
    for _ in range(100):  # 10 s of 20 req/s offered against 10 req/s
        for _ in range(2):
            d = gate.admit("interactive")
            admitted += d.admitted
            shed += not d.admitted
        clk.advance(0.1)
    # capacity = 10 burst + 10 s x 10 req/s = 110; shed absorbs the rest
    assert admitted == pytest.approx(110, abs=3)
    assert shed == pytest.approx(90, abs=3)
    assert gate.admit("interactive").admitted  # last refill's token
    d = gate.admit("interactive")
    assert not d.admitted and d.reason == "rate"
    assert d.retry_after_s >= 1.0
    clk.advance(30)  # offered load stops: bucket refills, gate reopens
    assert gate.admit("interactive").admitted


def test_admission_reserve_protects_interactive():
    """Batch must not drain the bucket below its reserve floor; the
    capacity it leaves stays takeable by interactive."""
    clk = FakeClock()
    gate = AdmissionGate(rate_req_s=10.0, burst=10.0, clock=clk)
    batch_admitted = 0
    while gate.admit("batch").admitted:
        batch_admitted += 1
    # burst 10, reserve_frac 0.5 -> batch stops at the 5-token floor
    assert batch_admitted == 5
    interactive_admitted = 0
    while gate.admit("interactive").admitted:
        interactive_admitted += 1
    assert interactive_admitted == 5  # the reserve was really there


def test_admission_low_rate_gate_still_admits_batch():
    """The reserve floor is capped at burst - 1: a full bucket must
    admit one request of ANY class, even when burst < 2 (the default
    for --admission-rate < 2) would make batch's burst/2 floor
    unsatisfiable."""
    clk = FakeClock()
    gate = AdmissionGate(rate_req_s=1.0, clock=clk)  # burst defaults to 1
    d = gate.admit("batch")
    assert d.admitted, d
    # drained: the next batch request sheds, but with a FINITE retry
    d = gate.admit("batch")
    assert not d.admitted and d.retry_after_s >= 1.0
    clk.advance(60.0)  # refilled: batch admits again, forever viable
    assert gate.admit("batch").admitted


def test_admission_fairness_by_class_at_2x():
    """2x overload, mixed classes: interactive keeps a materially
    higher admit rate than batch (the reserve at work), and shed
    volume absorbs exactly the excess."""
    clk = FakeClock()
    gate = AdmissionGate(rate_req_s=10.0, burst=10.0, clock=clk)
    clk.advance(100)  # full bucket
    for _ in range(200):  # 10 s at 20 req/s offered, alternating classes
        gate.admit("interactive")
        gate.admit("batch")
        clk.advance(0.05)
    s = gate.stats
    total = s["admitted_total"] + s["shed_total"]
    assert total == 400
    # capacity ~ burst + 10 s * 10 req/s
    assert s["admitted_total"] == pytest.approx(110, abs=5)
    int_admit = s["admitted_interactive"] / (s["admitted_interactive"]
                                             + s["shed_interactive"])
    bat_admit = s["admitted_batch"] / (s["admitted_batch"]
                                       + s["shed_batch"])
    assert int_admit > 1.5 * bat_admit


def test_admission_queue_bound_and_done():
    clk = FakeClock()
    from dynamo_tpu.planner import SloClass

    gate = AdmissionGate(
        rate_req_s=1000.0, burst=1000.0,
        classes=(SloClass("interactive", max_inflight=2),), clock=clk,
    )
    assert gate.admit().admitted and gate.admit().admitted
    d = gate.admit()
    assert not d.admitted and d.reason == "queue"
    gate.done("interactive")
    assert gate.admit().admitted
    gate.done("unknown-class")  # falls back to default, never KeyError


def test_admission_classify_and_set_rate():
    clk = FakeClock()
    gate = AdmissionGate(rate_req_s=5.0, clock=clk)
    assert gate.classify(["slo:batch"]) == "batch"
    assert gate.classify(["slo:nonsense"]) == "interactive"
    assert gate.classify(None) == "interactive"
    gate.set_rate(50.0)
    assert gate.bucket.rate == 50.0
    gate.set_rate(0.0)  # planner has no mix yet: keep the current rate
    assert gate.bucket.rate == 50.0
    stats = gate.render_stats()
    assert stats["admission_rate_req_s"] == 50.0
    assert "admission_inflight_interactive" in stats


def test_metrics_feeds_planner_telemetry():
    clk = FakeClock()
    tel = TelemetryAggregator(clock=clk)
    m = Metrics()
    m.planner_telemetry = tel
    m.observe_first_token("m", "chat", 0.5)
    m.observe_inter_token("m", "chat", 0.02)
    snap = tel.snapshot()
    assert snap.ttft_p99_ms == pytest.approx(500.0)
    assert snap.itl_p99_ms == pytest.approx(20.0)


# ---------------- the control loop ----------------


def _sim(prefill_pool=False, decode_max=8, clk=None):
    clk = clk or FakeClock()
    telemetry = TelemetryAggregator(window_s=10.0, clock=clk)
    capacity = CapacityModel(100.0, 1000.0)
    driver = CallbackScaleDriver()
    cfg = PlannerConfig(
        tick_s=2.0,
        slo=SloTargets(ttft_p99_ms=2000, itl_p99_ms=200, grace_s=10),
        decode_guard=GuardConfig(min_replicas=1, max_replicas=decode_max,
                                 up_cooldown_s=0, down_cooldown_s=60,
                                 down_stable_s=20),
        prefill_guard=GuardConfig(min_replicas=0, max_replicas=8,
                                  up_cooldown_s=0, down_cooldown_s=60,
                                  down_stable_s=20),
        prefill_pool=prefill_pool,
    )
    planner = Planner(telemetry, capacity, cfg, scale_driver=driver,
                      clock=clk)
    return clk, telemetry, planner, driver


def _steady_fleet(n=2, active=4):
    return [_load(i + 1, active=active, slots=8) for i in range(n)]


def test_planner_scales_up_on_sustained_ttft_breach():
    """Acceptance: sustained TTFT-SLO breach -> scale-up decision
    within the grace window (aggregated: the decode pool grows)."""
    clk, telemetry, planner, driver = _sim(prefill_pool=False)
    breach_start = clk()
    decision = None
    for _ in range(10):  # 20 s of p99 = 5000 ms >> 2000 ms target
        telemetry.observe_loads(_steady_fleet())
        for _ in range(5):
            telemetry.record_ttft(5000.0)
        decision = planner.tick()
        if decision.reason == "ttft_breach":
            break
        clk.advance(2.0)
    assert decision.reason == "ttft_breach"
    # within grace (10 s) + one tick, not eventually-someday
    assert clk() - breach_start <= 12.0
    assert decision.decode_replicas == 3  # fleet of 2 + the SLO push
    assert ("decode", 3) in driver.applied


def test_planner_disagg_ttft_breach_grows_prefill_pool():
    """Disagg: TTFT is prefill/queue bound — the prefill pool takes
    the push, decode holds."""
    clk, telemetry, planner, _driver = _sim(prefill_pool=True)
    decision = None
    for _ in range(10):
        telemetry.observe_loads(_steady_fleet())
        for _ in range(5):
            telemetry.record_ttft(5000.0)
        decision = planner.tick()
        if decision.reason == "ttft_breach":
            break
        clk.advance(2.0)
    assert decision.reason == "ttft_breach"
    assert decision.prefill_replicas >= 1
    assert decision.decode_replicas == 2  # seeded fleet, unchanged
    assert decision.disagg_ratio == pytest.approx(
        decision.prefill_replicas
        / (decision.prefill_replicas + decision.decode_replicas)
    )


def test_planner_itl_breach_grows_decode_pool():
    clk, telemetry, planner, _driver = _sim(prefill_pool=True)
    decision = None
    for _ in range(10):
        telemetry.observe_loads(_steady_fleet())
        for _ in range(5):
            telemetry.record_itl(500.0)  # >> 200 ms target
        decision = planner.tick()
        if decision.reason == "itl_breach":
            break
        clk.advance(2.0)
    assert decision.reason == "itl_breach"
    assert decision.decode_replicas == 3


def test_planner_demand_scale_up_from_token_rate():
    """No SLO breach yet — the forecasted token arrival rate alone
    must grow the pool ahead of the breach (predictive, not reactive)."""
    clk, telemetry, planner, _driver = _sim(prefill_pool=False)
    fleet = _steady_fleet()
    gen = 0
    decision = None
    for tick in range(10):
        gen += 640  # 320 tok/s on a fleet modeled at 100 tok/s/replica
        telemetry.observe_loads([
            _load(w.worker_id, active=4, slots=8, tokens_generated=gen // 2)
            for w in fleet
        ])
        decision = planner.tick()
        clk.advance(2.0)
    assert decision.decode_replicas >= 4  # ceil(320 / (100*0.8))
    assert planner.stats["scale_ups"] >= 1
    assert planner.stats["scale_downs"] == 0


def test_planner_no_flap_under_oscillating_load():
    """Acceptance: offered load oscillating every tick produces ZERO
    scale-down actions and at most one net scale-up — the fleet holds
    its high-water size through the trough."""
    clk, telemetry, planner, _driver = _sim(prefill_pool=False)
    gen = 0
    for tick in range(60):  # 120 s of on/off square-wave load
        burst = 2000 if tick % 2 == 0 else 0
        gen += burst
        telemetry.observe_loads([
            _load(1, active=4, slots=8, tokens_generated=gen),
            _load(2, active=4, slots=8),
        ])
        planner.tick()
        clk.advance(2.0)
    downs = [a for a in planner.decode_guard.actions
             if a.direction == "down"]
    assert downs == []  # zero flapping actions
    # scale-ups belong to the initial ramp only — once the fleet sits
    # at its high-water size, the oscillation produces NO more actions
    late = [a for a in planner.decode_guard.actions if a.ts > 40.0]
    assert late == []
    assert planner.stats["scale_downs"] == 0


def test_planner_scales_down_only_after_cooldown():
    clk, telemetry, planner, driver = _sim(prefill_pool=False)
    gen = 0
    for _ in range(6):  # sustained heavy load: scale up
        gen += 6400
        telemetry.observe_loads([_load(1, active=8, slots=8,
                                       tokens_generated=gen)])
        planner.tick()
        clk.advance(2.0)
    high = planner.decode_guard.current
    assert high >= 4
    sizes = []
    for _ in range(60):  # load vanishes; 120 s of idle ticks
        telemetry.observe_loads([_load(1, active=0, slots=8,
                                       tokens_generated=gen)])
        d = planner.tick()
        sizes.append((clk(), d.decode_replicas))
        clk.advance(2.0)
    acts = planner.decode_guard.actions
    ups = [a for a in acts if a.direction == "up"]
    downs = [a for a in acts if a.direction == "down"]
    assert ups and downs
    # the down waited out the full cooldown from the last action...
    assert downs[0].ts - ups[-1].ts >= 60.0
    # ...and until it fired, the fleet held its high-water size
    for ts, n in sizes:
        if ts < downs[0].ts:
            assert n == high, f"dropped at t={ts}, inside cooldown"
    assert sizes[-1][1] == 1
    assert planner.stats["scale_downs"] >= 1


def test_planner_watermarks_saturated_workers_and_scheduler_obeys():
    clk, telemetry, planner, _driver = _sim(prefill_pool=False)
    telemetry.observe_loads([
        _load(1, active=8, slots=8, waiting=4),  # saturated
        _load(2, active=2, slots=8),
    ])
    planner.tick()
    wm = planner.last_watermark
    assert wm.saturated_workers == [1]
    assert wm.cluster_utilization == pytest.approx(10 / 16)
    # the KV scheduler soft-excludes watermarked workers...
    s = KvScheduler()
    s.set_watermarks(wm.saturated_workers)
    eps = ProcessedEndpoints([_load(1, active=2), _load(2, active=2)])
    assert s.select_worker(eps, OverlapScores(scores={1: 10},
                                              total_blocks=10), 10) == 2
    # ...softly: an all-watermarked fleet still serves
    s.set_watermarks([1, 2])
    assert s.select_worker(eps, OverlapScores(), 10) in (1, 2)
    # a republished empty set clears everything
    s.set_watermarks([])
    assert s.watermarked == set()


def test_scheduler_watermarks_expire_without_planner():
    """A planner that stops publishing must not keep its last
    saturated-worker set skewing routing forever: the set ages out
    after watermark_ttl_s (same stale-authority guard as load_ttl_s)."""
    clk = FakeClock()
    s = KvScheduler(config=SchedulerConfig(watermark_ttl_s=5.0), clock=clk)
    s.set_watermarks([1])
    eps = ProcessedEndpoints([_load(1, active=2), _load(2, active=2)])
    overlaps = OverlapScores(scores={1: 10}, total_blocks=10)
    assert s.select_worker(eps, overlaps, 10) == 2  # fresh: obeyed
    s.request_finished(2)
    clk.advance(6.0)  # planner silent past the TTL: watermark expires
    assert s.select_worker(eps, overlaps, 10) == 1  # overlap wins again
    assert s.watermarked == set()


def test_planner_publishes_decisions_and_admission_rate():
    class SpyPublisher:
        def __init__(self):
            self.events = []

        def publish(self, decision, watermark):
            self.events.append((decision, watermark))

    clk = FakeClock()
    telemetry = TelemetryAggregator(window_s=10.0, clock=clk)
    planner = Planner(telemetry, CapacityModel(100.0, 1000.0),
                      PlannerConfig(), publisher=SpyPublisher(), clock=clk)
    telemetry.observe_loads(_steady_fleet())
    clk.advance(5)
    # 20 req/s arriving, 50 gen tok/req mix
    telemetry.observe_loads([
        _load(1, active=4, slots=8, requests_total=100,
              tokens_generated=5000),
        _load(2, active=4, slots=8),
    ])
    planner.tick()
    decision, wm = planner.publisher.events[-1]
    assert decision.request_rate > 0
    # admission rate = corrected capacity at headroom / mean tok/req
    mean_gen = wm.admission_rate_req_s
    assert mean_gen == pytest.approx(
        100.0 * decision.decode_replicas * 0.8 / 50.0
    )
    # wire-schema round trip (what the bus actually carries)
    d2 = PlannerDecision.from_bytes(decision.to_bytes())
    assert d2 == decision
    w2 = CapacityWatermark.from_bytes(wm.to_bytes())
    assert w2 == wm
    # forward compat: unknown keys are filtered, not fatal
    raw = json.loads(decision.to_bytes())
    raw["from_the_future"] = 1
    assert PlannerDecision.from_bytes(json.dumps(raw).encode()) == decision


def test_planner_capacity_correction_only_when_loaded():
    """An idle fleet's low throughput measures demand, not capacity —
    it must NOT shrink the capacity model."""
    clk, telemetry, planner, _driver = _sim(prefill_pool=False)
    gen = 0
    for _ in range(5):  # 50 tok/s on a near-idle fleet (util 1/16)
        gen += 500
        telemetry.observe_loads([_load(1, active=1, slots=8,
                                       tokens_generated=gen),
                                 _load(2, slots=8)])
        planner.tick()
        clk.advance(2.0)
    assert planner.capacity.decode_corr == 1.0  # untouched
    for _ in range(8):  # saturated fleet at half the modeled 200 tok/s
        gen += 200
        telemetry.observe_loads([_load(1, active=8, slots=8,
                                       tokens_generated=gen),
                                 _load(2, active=8, slots=8)])
        planner.tick()
        clk.advance(2.0)
    assert planner.capacity.decode_corr < 1.0  # now it counts


# ---------------- stale-load TTL (KV scheduler satellite) ----------------


def test_scheduler_discards_stale_worker_loads():
    clk = FakeClock(100.0)
    s = KvScheduler(config=SchedulerConfig(load_ttl_s=10.0), clock=clk)
    eps = ProcessedEndpoints([
        _load(1, active=6, ts=95.0),   # busy but alive
        _load(2, active=0, ts=50.0),   # idle-looking — died 50 s ago
    ])
    # the dead worker's attractive last report must not win
    assert s.select_worker(eps, OverlapScores(), 10) == 1
    # every load stale (metrics plane wedged): refuse -> caller falls
    # back to discovery round-robin
    eps = ProcessedEndpoints([_load(1, ts=50.0), _load(2, ts=60.0)])
    with pytest.raises(AllWorkersBusy):
        s.select_worker(eps, OverlapScores(), 10)
    # legacy producers without a stamp are trusted (ts=None)
    eps = ProcessedEndpoints([_load(1, ts=None)])
    assert s.select_worker(eps, OverlapScores(), 10) == 1
    # load_ttl_s=0 disables the check entirely
    s0 = KvScheduler(config=SchedulerConfig(load_ttl_s=0.0), clock=clk)
    eps = ProcessedEndpoints([_load(1, ts=1.0)])
    assert s0.select_worker(eps, OverlapScores(), 10) == 1


# ---------------- actuators ----------------


def test_store_scale_driver_rewrites_deployment(tmp_path):
    store = DeploymentStore(str(tmp_path))
    dep = DynamoDeployment(name="d1", services=[
        ServiceDeploymentSpec(name="worker", replicas=2),
        ServiceDeploymentSpec(name="prefill", replicas=1),
    ])
    store.put("d1", dep.to_dict(), create=True)
    drv = StoreScaleDriver(store, "d1")
    assert drv.current("decode") == 2
    assert drv.set_replicas("decode", 4) is True
    assert drv.set_replicas("prefill", 2) is True
    svcs = {s["name"]: s["replicas"] for s in store.get("d1")["services"]}
    assert svcs == {"worker": 4, "prefill": 2}
    assert drv.set_replicas("decode", 4) is False  # idempotent: no write
    assert drv.set_replicas("unknown-pool", 9) is False
    assert StoreScaleDriver(store, "ghost").set_replicas("decode", 1) is False


def test_callback_scale_driver_dedupes():
    applied = []
    drv = CallbackScaleDriver(lambda pool, n: applied.append((pool, n)))
    assert drv.set_replicas("decode", 3) is True
    assert drv.set_replicas("decode", 3) is False
    assert drv.set_replicas("decode", 4) is True
    assert applied == [("decode", 3), ("decode", 4)]


def test_controller_embeds_planner_tick(tmp_path):
    """reconcile_once ticks an embedded planner; a sick planner must
    not stop reconciliation."""
    class TickCounter:
        def __init__(self, fail=False):
            self.ticks = 0
            self.fail = fail

        def tick(self):
            self.ticks += 1
            if self.fail:
                raise RuntimeError("sick planner")

    store = DeploymentStore(str(tmp_path))
    dep = DynamoDeployment(name="d1", services=[
        ServiceDeploymentSpec(name="worker", replicas=1),
    ])
    store.put("d1", dep.to_dict(), create=True)
    spawned = []

    class P:
        rc = None

        def poll(self):
            return None

        def terminate(self):
            self.rc = -15

    ctl = DeploymentController(
        store, spawn=lambda *a: spawned.append(a) or P(),
        planner=(planner := TickCounter()),
    )
    ctl.reconcile_once()
    ctl.reconcile_once()
    assert planner.ticks == 2
    ctl.planner = TickCounter(fail=True)
    ctl.reconcile_once()  # must not raise
    assert len(spawned) == 1  # the replica was still converged


# ---------------- HTTP overload gate (end to end) ----------------


def test_http_shed_returns_429_with_retry_after(run):
    from tests.test_http_service import http_request, make_local_service

    async def main():
        clk = FakeClock()
        gate = AdmissionGate(rate_req_s=1.0, burst=2.0, clock=clk)
        svc = make_local_service()
        svc.admission = gate
        svc.metrics.register_source(gate.render_stats)
        await svc.start()
        req = json.dumps({
            "model": "echo", "messages": [{"role": "user", "content": "hi"}],
            "nvext": {"use_raw_prompt": True},
        }).encode()
        statuses = []
        for _ in range(4):
            status, headers, body = await http_request(
                svc.port, "POST", "/v1/chat/completions", req
            )
            statuses.append(status)
        assert statuses == [200, 200, 429, 429]
        assert int(headers["retry-after"]) >= 1
        err = json.loads(body)["error"]
        assert err["type"] == "overloaded"
        # shed requests are visible on /metrics, and never reached the
        # engine's inflight accounting
        status, _, body = await http_request(svc.port, "GET", "/metrics")
        text = body.decode()
        assert 'status="shed"' in text
        assert gate.stats["shed_total"] == 2
        assert gate.inflight["interactive"] == 0  # done() released all
        # the bucket refills: the gate reopens without a restart
        clk.advance(5)
        status, _, _ = await http_request(
            svc.port, "POST", "/v1/chat/completions", req
        )
        assert status == 200
        await svc.close()

    run(main())


def test_http_slo_class_annotation_routes_to_batch(run):
    from tests.test_http_service import http_request, make_local_service

    async def main():
        clk = FakeClock()
        gate = AdmissionGate(rate_req_s=10.0, burst=10.0, clock=clk)
        svc = make_local_service()
        svc.admission = gate
        await svc.start()
        req = json.dumps({
            "model": "echo", "messages": [{"role": "user", "content": "hi"}],
            "nvext": {"use_raw_prompt": True, "annotations": ["slo:batch"]},
        }).encode()
        status, _, _ = await http_request(
            svc.port, "POST", "/v1/chat/completions", req
        )
        assert status == 200
        assert gate.stats["admitted_batch"] == 1
        assert gate.inflight["batch"] == 0
        # batch may only spend down to its reserve floor: drain to it
        while gate.admit("batch").admitted:
            pass
        status, headers, _ = await http_request(
            svc.port, "POST", "/v1/chat/completions", req
        )
        assert status == 429
        assert int(headers["retry-after"]) >= 5  # batch's min_retry_after
        await svc.close()

    run(main())


def test_admission_gate_feeds_telemetry_arrivals():
    clk = FakeClock()
    tel = TelemetryAggregator(window_s=10.0, clock=clk)
    # burst 1: only the first request is admitted — but ALL five count
    # as arrivals, because offered (not served) load is what the
    # planner sizes the fleet to
    gate = AdmissionGate(rate_req_s=0.1, burst=1.0, clock=clk,
                         telemetry=tel)
    for _ in range(5):
        gate.admit("interactive", prompt_tokens=100)
    assert gate.stats["admitted_total"] == 1
    snap = tel.snapshot()
    assert snap.request_rate == pytest.approx(0.5)
    assert snap.prompt_token_rate == pytest.approx(50.0)

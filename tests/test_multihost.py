"""Multi-host bootstrap: 2 OS processes, one global mesh, served via hub.

The TPU answer to the reference's multi-node engine bootstrap (Ray/MPI/
per-rank launch, engines.rs:35-52): rank 0 leads scheduling and serves the
endpoint, rank 1 followers the SPMD dispatches, the mesh (dp=2 x tp=2)
spans both processes, and the parent (this test) plays the frontend role
through the hub.
"""

import asyncio
import os
import socket
import subprocess
import sys

import pytest

from dynamo_tpu.runtime import Context, DistributedRuntime, collect
from dynamo_tpu.runtime.hub import HubServer, connect_hub

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(rank: int, coord_port: int, hub: str,
           extra_env: dict | None = None) -> subprocess.Popen:
    env = os.environ.copy()
    env.pop("XLA_FLAGS", None)  # the worker sets its own device count
    # CPU-only workers must not touch the TPU relay at interpreter
    # startup (site hook registers axon when this is set; a wedged
    # relay then hangs every new python before main() runs)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "mh_worker.py"),
         str(rank), str(coord_port), hub],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def test_two_process_mesh_serves_mla(run):
    """The mirror must carry the MLA family across processes: asymmetric
    latent k/v cache shapes ride the broadcast frames / follower cache
    bookkeeping, and the mirrored stream equals a single-process engine
    with the same seed."""
    async def main():
        # single-process reference stream (same default-seed weights)
        from dynamo_tpu.engine import EngineConfig, JaxEngine
        from dynamo_tpu.models.config import ModelConfig
        mla_model = ModelConfig.tiny_mla()
        local = JaxEngine(EngineConfig(
            model=mla_model, num_blocks=32, block_size=16, max_batch_size=4,
        ))
        from dynamo_tpu.protocols.common import (
            PreprocessedRequest, SamplingOptions, StopConditions,
        )
        lreq = PreprocessedRequest(
            token_ids=[5, 6, 7, 8],
            stop_conditions=StopConditions(max_tokens=4, ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
            eos_token_ids=[],
        )
        ref = await collect(local.generate(Context(lreq)))
        ref_toks = [t for o in ref for t in o.token_ids]
        await local.close()

        hub = HubServer()
        await hub.start()
        coord = _free_port()
        procs = [
            _spawn(r, coord, hub.address, extra_env={"MH_MODEL": "mla"})
            for r in (0, 1)
        ]
        try:
            store, bus, conn = await connect_hub(hub.address)
            front = await DistributedRuntime.from_settings(store=store, bus=bus)
            client = await (
                front.namespace("mh").component("worker").endpoint("generate")
                .client().start()
            )
            await client.wait_for_instances(timeout=120)
            req = {
                "token_ids": [5, 6, 7, 8],
                "stop_conditions": {"max_tokens": 4, "ignore_eos": True},
                "sampling_options": {"temperature": 0.0},
            }
            for _ in range(2):  # the worker halts after two requests
                out = await asyncio.wait_for(
                    collect(await client.round_robin(Context(req))), 120
                )
            datas = [a.data for a in out if a.data]
            tokens = [t for d in datas for t in d.get("token_ids", [])]
            assert tokens == ref_toks, (tokens, ref_toks)

            await front.shutdown()
            await conn.close()
            import functools

            loop = asyncio.get_running_loop()
            for p in procs:
                out_text = (
                    await loop.run_in_executor(
                        None, functools.partial(p.communicate, timeout=150)
                    )
                )[0]
                assert p.returncode == 0, f"rank exited {p.returncode}:\n{out_text}"
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()
            await hub.close()

    run(main())


def test_two_process_mesh_serves_through_hub(run):
    async def main():
        hub = HubServer()
        await hub.start()
        coord = _free_port()
        procs = [_spawn(r, coord, hub.address) for r in (0, 1)]
        try:
            store, bus, conn = await connect_hub(hub.address)
            front = await DistributedRuntime.from_settings(store=store, bus=bus)
            client = await (
                front.namespace("mh").component("worker").endpoint("generate")
                .client().start()
            )
            await client.wait_for_instances(timeout=120)

            req = {
                "token_ids": [5, 6, 7, 8],
                "stop_conditions": {"max_tokens": 4, "ignore_eos": True},
                "sampling_options": {"temperature": 0.0},
            }
            out = await asyncio.wait_for(
                collect(await client.round_robin(Context(req))), 120
            )
            datas = [a.data for a in out if a.data]
            tokens = [t for d in datas for t in d.get("token_ids", [])]
            assert len(tokens) == 4, datas
            assert datas[-1].get("finish_reason") == "length", datas[-1]

            # second request: mirrored sampling penalties + logprobs (the
            # follower must replay the penalty-state reset and the
            # penalized/logprob program variants in lockstep)
            req2 = {
                "token_ids": [5, 6, 7, 8],
                "stop_conditions": {"max_tokens": 4, "ignore_eos": True},
                "sampling_options": {
                    "temperature": 0.0,
                    "frequency_penalty": 2.0,
                    "repetition_penalty": 1.1,
                    "logprobs": 2,
                },
            }
            out2 = await asyncio.wait_for(
                collect(await client.round_robin(Context(req2))), 120
            )
            datas2 = [a.data for a in out2 if a.data]
            tokens2 = [t for d in datas2 for t in d.get("token_ids", [])]
            assert len(tokens2) == 4, datas2
            entries2 = [e for d in datas2 for e in (d.get("logprobs") or [])]
            assert len(entries2) == len(tokens2), datas2
            assert all(len(e["top"]) == 2 for e in entries2)

            await front.shutdown()
            await conn.close()
            # both ranks must exit cleanly: leader after serving + halt
            # broadcast, follower on receiving halt. The wait must not
            # block this event loop — the hub (serving the leader's
            # shutdown traffic) lives on it.
            import functools

            loop = asyncio.get_running_loop()
            for p in procs:
                out_text = (
                    await loop.run_in_executor(
                        None, functools.partial(p.communicate, timeout=150)
                    )
                )[0]
                assert p.returncode == 0, f"rank exited {p.returncode}:\n{out_text}"
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()
            await hub.close()

    run(main())

"""KV router tests: prefix index, scheduler cost model, and the full
events -> index -> routing loop with two live JAX workers."""

import asyncio

import pytest

from dynamo_tpu.engine import EngineConfig, JaxEngine
from dynamo_tpu.engine.allocator import sequence_block_hashes
from dynamo_tpu.kv_router import (
    KvEventPublisher,
    KvIndexer,
    KvRouter,
    OverlapScores,
    PrefixIndex,
    ProcessedEndpoints,
    RouterEvent,
    WorkerLoad,
)
from dynamo_tpu.kv_router.protocols import KvCacheEvent, StoredBlock
from dynamo_tpu.kv_router.router import KvRoutedEngine
from dynamo_tpu.kv_router.scheduler import AllWorkersBusy, KvScheduler
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime import Context, DistributedRuntime, LocalBus, LocalStore, collect


def _hashes(tokens, bs=4):
    return [s for _l, s in sequence_block_hashes(tokens, bs)]


def _stored_event(worker, tokens, bs=4):
    hashes = sequence_block_hashes(tokens, bs)
    blocks = [StoredBlock(block_hash=s, tokens_hash=l) for l, s in hashes]
    return RouterEvent(worker, KvCacheEvent.stored(None, blocks))


# ---------------- index ----------------


def test_index_find_matches_depth():
    idx = PrefixIndex()
    tokens = list(range(16))  # 4 blocks
    idx.apply_event(_stored_event(1, tokens))
    idx.apply_event(_stored_event(2, tokens[:8]))  # worker 2 has 2 blocks

    scores = idx.find_matches(_hashes(tokens))
    assert scores.scores == {1: 4, 2: 2}
    assert scores.total_blocks == 4

    # divergent suffix: only shared prefix counts
    other = tokens[:8] + [99, 98, 97, 96]
    scores = idx.find_matches(_hashes(other))
    assert scores.scores == {1: 2, 2: 2}


def test_index_removed_and_remove_worker():
    idx = PrefixIndex()
    tokens = list(range(16))
    idx.apply_event(_stored_event(1, tokens))
    idx.apply_event(_stored_event(2, tokens))
    h = _hashes(tokens)
    # worker 1 evicts the second block -> its chain depth ends at 1
    idx.apply_event(RouterEvent(1, KvCacheEvent.removed([h[1]])))
    scores = idx.find_matches(h)
    assert scores.scores == {1: 1, 2: 4}
    # worker 2 dies entirely
    idx.remove_worker(2)
    scores = idx.find_matches(h)
    assert scores.scores == {1: 1}


# ---------------- scheduler ----------------


def make_eps(*loads):
    return ProcessedEndpoints([
        WorkerLoad(worker_id=i + 1, kv_active_blocks=int(u * 100), kv_total_blocks=100,
                   active_requests=a, total_slots=8, waiting=w)
        for i, (u, a, w) in enumerate(loads)
    ])


def test_scheduler_prefers_overlap_when_balanced():
    s = KvScheduler()
    eps = make_eps((0.5, 2, 0), (0.5, 2, 0))
    overlaps = OverlapScores(scores={2: 8}, total_blocks=10)
    assert s.select_worker(eps, overlaps, 10) == 2


def test_scheduler_prefers_load_in_balance_mode():
    s = KvScheduler()
    # huge load skew: worker 1 nearly full, worker 2 empty
    eps = make_eps((0.95, 7, 0), (0.05, 0, 0))
    overlaps = OverlapScores(scores={1: 10}, total_blocks=10)
    # balance mode outweighs the perfect overlap on worker 1
    assert s.select_worker(eps, overlaps, 10) == 2


def test_scheduler_avoid_set_soft_excludes():
    s = KvScheduler()
    # worker 1 wins on perfect overlap — but a migrating request that
    # already failed on it (dead, lease not yet expired) must go elsewhere
    eps = make_eps((0.5, 2, 0), (0.5, 2, 0))
    overlaps = OverlapScores(scores={1: 10}, total_blocks=10)
    assert s.select_worker(eps, overlaps, 10, avoid=frozenset({1})) == 2
    s.request_finished(2)
    # soft: when the avoid set covers every candidate, still pick one
    # (lone-worker restart) rather than refuse
    assert s.select_worker(eps, overlaps, 10, avoid=frozenset({1, 2})) in (1, 2)


def test_scheduler_all_busy_and_optimistic_bump():
    s = KvScheduler()
    eps = make_eps((0.5, 8, 3), (0.5, 8, 1))
    with pytest.raises(AllWorkersBusy):
        s.select_worker(eps, OverlapScores(), 4)
    # optimistic bumps spread ties
    eps = make_eps((0.5, 0, 0), (0.5, 0, 0))
    first = s.select_worker(eps, OverlapScores(), 4)
    second = s.select_worker(eps, OverlapScores(), 4)
    assert {first, second} == {1, 2}
    s.request_finished(first)
    s.request_finished(second)


# ---------------- end-to-end: events + metrics + routing ----------------


def make_worker_engine():
    cfg = EngineConfig(
        model=ModelConfig.tiny(), num_blocks=64, block_size=4,
        max_batch_size=4, max_context=128, prefill_chunk=32,
    )
    return JaxEngine(cfg, seed=0)


def make_req(tokens, max_tokens=3):
    return PreprocessedRequest(
        token_ids=list(tokens),
        stop_conditions=StopConditions(max_tokens=max_tokens),
        sampling_options=SamplingOptions(temperature=0.0),
        eos_token_ids=[511],
    ).to_dict()


def test_kv_routed_serving(run):
    async def main():
        store, bus = LocalStore(), LocalBus()
        front = await DistributedRuntime.from_settings(store=store, bus=bus)
        workers = []
        engines = []
        for _ in range(2):
            w = await DistributedRuntime.from_settings(store=store, bus=bus)
            engine = make_worker_engine()
            comp = w.namespace("dyn").component("worker")
            pub = KvEventPublisher(w, comp, w.primary_lease_id)
            pub.attach(engine.allocator)
            await comp.endpoint("gen").serve(engine, stats_handler=engine.load_metrics)
            workers.append(w)
            engines.append(engine)

        comp = front.namespace("dyn").component("worker")
        client = await comp.endpoint("gen").client().start()
        await client.wait_for_instances(5)
        router = await KvRouter(front, comp, block_size=4).start()
        routed = KvRoutedEngine(router, client)

        prompt = list(range(100, 124))  # 6 blocks of 4
        out1 = await collect(routed.generate(Context(make_req(prompt))))
        assert any((a.data or {}).get("finish_reason") for a in out1)
        # let kv events propagate into the index (generous: box load
        # stretches the bus consumer the same way it stretches scrapes)
        for _ in range(500):
            if router.indexer.events_applied >= 6:
                break
            await asyncio.sleep(0.02)
        assert router.indexer.events_applied >= 6

        # wait for a post-completion stats scrape: on a loaded box the
        # aggregator's last snapshot can still show the cached worker
        # with the finished request active, and the scheduler CORRECTLY
        # prefers the idle worker on that stale view — the property
        # under test is prefix routing between idle workers. Wait on
        # SCRAPES OBSERVED (the aggregator's completion event), not wall
        # time: under 4x-parallel box load the 1s scrape loop stretches
        # arbitrarily and a fixed-duration poll times out while the
        # aggregator simply hasn't run (the PR 5-era flake).
        def _all_idle():
            eps = router.metrics.endpoints
            return (len(eps.loads) == 2
                    and all(l.active_requests == 0 and l.waiting == 0
                            for l in eps.loads))

        for _ in range(30):  # 30 COMPLETED scrapes, not 30 ticks of a clock
            if _all_idle():
                break
            await router.metrics.next_scrape(timeout=30.0)
        assert _all_idle(), (
            f"workers never scraped idle after {router.metrics.scrapes_total}"
            " scrapes"
        )

        # same prompt again: must route to the worker holding the prefix
        scores = router.indexer.find_matches(_hashes(prompt))
        assert len(scores.scores) == 1
        cached_worker = next(iter(scores.scores))
        wid, overlap = await router.schedule(prompt)
        assert wid == cached_worker
        assert overlap >= 5
        router.request_finished(wid)

        # dead-worker cleanup drops its residency from the index
        router.remove_worker(cached_worker)
        assert router.indexer.find_matches(_hashes(prompt)).scores == {}

        for w in workers:
            await w.shutdown()
        await front.shutdown()

    run(main())


# ---------------- prefetch hints ----------------


def test_schedule_emits_prefetch_hint_for_uncovered_prompt(run):
    """Routing a request whose prompt extends past the chosen worker's
    device radix match must ship the block-hash chain on the component's
    kv-prefetch subject; a fully-covered prompt must not."""
    from dynamo_tpu.kv_router.protocols import (
        KV_PREFETCH_SUBJECT,
        KvPrefetchHint,
    )

    async def main():
        store, bus = LocalStore(), LocalBus()
        drt = await DistributedRuntime.from_settings(store=store, bus=bus)
        comp = drt.namespace("dyn").component("worker")
        router = await KvRouter(drt, comp, block_size=4).start()
        router.metrics.endpoints = make_eps((0.1, 1, 0))  # worker 1

        sub = bus.subscribe(comp.event_subject(KV_PREFETCH_SUBJECT))
        prompt = list(range(300, 324))  # 6 blocks, index cold
        wid, overlap = await router.schedule(prompt)
        assert wid == 1 and overlap == 0
        msg = await sub.next(1.0)
        assert msg is not None
        hint = KvPrefetchHint.from_bytes(msg.payload)
        assert hint.worker_id == 1
        pairs = sequence_block_hashes(prompt, 4)
        # block-multiple prompt: the final block can never be claimed by
        # admission (it hashes prompt[:-1]), so the hint excludes it
        assert hint.blocks == [[l, s] for l, s in pairs[:-1]]
        router.request_finished(wid)

        # full coverage: worker 1 now holds the whole chain -> no hint
        router.indexer.index.apply_event(_stored_event(1, prompt))
        wid, overlap = await router.schedule(prompt)
        assert wid == 1 and overlap == len(pairs)
        assert await sub.next(0.2) is None
        await drt.shutdown()

    run(main())


def test_prefetch_listener_filters_and_forwards(run):
    """The worker-side listener consumes only hints addressed to it and
    hands the chain to engine.prefetch_hint."""
    from dynamo_tpu.kv_router import KvPrefetchListener
    from dynamo_tpu.kv_router.protocols import (
        KV_PREFETCH_SUBJECT,
        KvPrefetchHint,
    )

    class FakeEngine:
        def __init__(self):
            self.calls = []

        async def prefetch_hint(self, blocks):
            self.calls.append(blocks)
            return len(blocks)

    async def main():
        store, bus = LocalStore(), LocalBus()
        drt = await DistributedRuntime.from_settings(store=store, bus=bus)
        comp = drt.namespace("dyn").component("worker")
        eng = FakeEngine()
        listener = await KvPrefetchListener(drt, comp, 42, eng).start()
        subject = comp.event_subject(KV_PREFETCH_SUBJECT)
        bus.publish(subject, KvPrefetchHint(99, [[1, 2]]).to_bytes())
        bus.publish(subject, KvPrefetchHint(42, [[3, 4], [5, 6]]).to_bytes())
        for _ in range(100):
            if eng.calls:
                break
            await asyncio.sleep(0.01)
        assert eng.calls == [[(3, 4), (5, 6)]]
        assert listener.hints_received == 1
        assert listener.blocks_prefetched == 2
        await listener.close()
        await drt.shutdown()

    run(main())

"""Ring attention (sequence parallel) vs dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from dynamo_tpu.parallel.ring_attention import ring_attention_sharded

NEG_INF = -1e30


def dense_reference(q, k, v, scale, causal):
    s = jnp.einsum("qhd,khd->hqk", q, k) * scale
    if causal:
        T = q.shape[0]
        mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p, v)


@pytest.fixture(scope="module")
def sp_mesh():
    devs = np.asarray(jax.devices()[:8]).reshape(8)
    return Mesh(devs, ("sp",))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("T,H,D", [(64, 4, 16), (128, 2, 32)])
def test_ring_matches_dense(sp_mesh, causal, T, H, D):
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (T, H, D), jnp.float32)
    k = jax.random.normal(kk, (T, H, D), jnp.float32)
    v = jax.random.normal(kv, (T, H, D), jnp.float32)
    scale = 1.0 / np.sqrt(D)
    out = ring_attention_sharded(q, k, v, sp_mesh, scale, causal=causal)
    ref = dense_reference(q, k, v, scale, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_bf16_close(sp_mesh):
    T, H, D = 64, 2, 16
    key = jax.random.key(1)
    q, k, v = (
        jax.random.normal(s, (T, H, D), jnp.bfloat16)
        for s in jax.random.split(key, 3)
    )
    scale = 1.0 / np.sqrt(D)
    out = ring_attention_sharded(q, k, v, sp_mesh, scale)
    ref = dense_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        scale, True,
    )
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
    )


def test_ring_jit_compiles_once(sp_mesh):
    # under jit with static mesh closure — the serving-path usage
    T, H, D = 64, 2, 16
    q = jnp.ones((T, H, D))
    fn = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, sp_mesh, 0.25)
    )
    out = fn(q, q, q)
    assert out.shape == (T, H, D)
    # causal row 0 attends only itself -> output == v row 0
    np.testing.assert_allclose(np.asarray(out[0]), np.ones((H, D)), atol=1e-6)


def test_mla_ring_matches_dense_latent(sp_mesh):
    """Latent ring (rotating compressed (c_kv, k_pe) chunks) must equal
    dense absorbed attention over the full latent stream."""
    from dynamo_tpu.parallel.ring_attention import mla_ring_attention_sharded

    T, H, C, R = 64, 4, 32, 8
    ks = jax.random.split(jax.random.key(5), 4)
    q_eff = jax.random.normal(ks[0], (T, H, C), jnp.float32)
    q_pe = jax.random.normal(ks[1], (T, H, R), jnp.float32)
    c_kv = jax.random.normal(ks[2], (T, C), jnp.float32)
    k_pe = jax.random.normal(ks[3], (T, R), jnp.float32)
    scale = 0.17
    s = (
        jnp.einsum("qhc,kc->hqk", q_eff, c_kv)
        + jnp.einsum("qhr,kr->hqk", q_pe, k_pe)
    ) * scale
    mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
    s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    ref = jnp.einsum("hqk,kc->qhc", p, c_kv)

    with sp_mesh:
        got = mla_ring_attention_sharded(
            q_eff, q_pe, c_kv, k_pe, sp_mesh, scale
        )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_mla_ring_prefill_serving_path(run):
    """The latent ring serves DeepSeek-family prompts: long prompt on an
    sp=2 mesh must reproduce the single-device greedy stream exactly,
    and cache writes stay paged (a repeat request hits the prefix
    cache)."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshConfig
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime import Context, collect

    mcfg = ModelConfig.tiny_mla(dtype="float32")
    params = llama.init_params(mcfg, jax.random.key(4))
    prompt = [(5 * i + 2) % mcfg.vocab_size for i in range(48)]

    def req(max_tokens=6):
        return PreprocessedRequest(
            token_ids=list(prompt),
            stop_conditions=StopConditions(max_tokens=max_tokens,
                                           ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
            eos_token_ids=[],
        )

    async def main():
        plain = JaxEngine(
            EngineConfig(model=mcfg, num_blocks=64, block_size=4,
                         max_batch_size=2, max_context=128,
                         prefill_chunk=16),
            params=params,
        )
        ref = await collect(plain.generate(Context(req())))
        ref_toks = [t for o in ref for t in o.token_ids]
        await plain.close()

        ring = JaxEngine(
            EngineConfig(model=mcfg, num_blocks=64, block_size=4,
                         max_batch_size=2, max_context=128,
                         prefill_chunk=16, ring_prefill_threshold=32,
                         mesh=MeshConfig(sp=2)),
            params=params,
        )
        out = await collect(ring.generate(Context(req())))
        toks = [t for o in out for t in o.token_ids]
        assert toks == ref_toks, (toks, ref_toks)

        base_hits = ring.stats["prefix_cache_hits_tokens"]
        out2 = await collect(ring.generate(Context(req())))
        toks2 = [t for o in out2 for t in o.token_ids]
        assert toks2 == ref_toks
        assert ring.stats["prefix_cache_hits_tokens"] > base_hits
        await ring.close()

    run(main())


def test_ring_prefill_serving_path(run):
    """VERDICT r2 #7: ring attention wired into SERVING prefill. A long
    prompt on an sp=2 mesh with ring_prefill_threshold set must produce
    the exact greedy stream of the plain single-device engine (ring is
    exact attention), and a later same-prefix request must still hit the
    paged prefix cache (cache writes are unchanged)."""
    import asyncio

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.parallel.mesh import MeshConfig
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime import Context, collect

    mcfg = ModelConfig.tiny(dtype="float32")
    params = llama.init_params(mcfg, jax.random.key(3))
    prompt = [(7 * i + 3) % mcfg.vocab_size for i in range(48)]

    def req(max_tokens=6):
        return PreprocessedRequest(
            token_ids=list(prompt),
            stop_conditions=StopConditions(max_tokens=max_tokens,
                                           ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0),
            eos_token_ids=[],
        )

    async def main():
        plain = JaxEngine(
            EngineConfig(model=mcfg, num_blocks=64, block_size=4,
                         max_batch_size=2, max_context=128,
                         prefill_chunk=16),
            params=params,
        )
        ref = await collect(plain.generate(Context(req())))
        ref_toks = [t for o in ref for t in o.token_ids]
        await plain.close()

        ring = JaxEngine(
            EngineConfig(model=mcfg, num_blocks=64, block_size=4,
                         max_batch_size=2, max_context=128,
                         prefill_chunk=16, ring_prefill_threshold=32,
                         mesh=MeshConfig(sp=2)),
            params=params,
        )
        out = await collect(ring.generate(Context(req())))
        toks = [t for o in out for t in o.token_ids]
        assert toks == ref_toks, (toks, ref_toks)

        # prefix-cache composition: same prompt again must reuse blocks
        # written by the ring prefill (history > 0 -> chunked path)
        base_hits = ring.stats["prefix_cache_hits_tokens"]
        out2 = await collect(ring.generate(Context(req())))
        toks2 = [t for o in out2 for t in o.token_ids]
        assert toks2 == ref_toks
        assert ring.stats["prefix_cache_hits_tokens"] > base_hits
        await ring.close()

    run(main())

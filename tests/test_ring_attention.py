"""Ring attention (sequence parallel) vs dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from dynamo_tpu.parallel.ring_attention import ring_attention_sharded

NEG_INF = -1e30


def dense_reference(q, k, v, scale, causal):
    s = jnp.einsum("qhd,khd->hqk", q, k) * scale
    if causal:
        T = q.shape[0]
        mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p, v)


@pytest.fixture(scope="module")
def sp_mesh():
    devs = np.asarray(jax.devices()[:8]).reshape(8)
    return Mesh(devs, ("sp",))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("T,H,D", [(64, 4, 16), (128, 2, 32)])
def test_ring_matches_dense(sp_mesh, causal, T, H, D):
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (T, H, D), jnp.float32)
    k = jax.random.normal(kk, (T, H, D), jnp.float32)
    v = jax.random.normal(kv, (T, H, D), jnp.float32)
    scale = 1.0 / np.sqrt(D)
    out = ring_attention_sharded(q, k, v, sp_mesh, scale, causal=causal)
    ref = dense_reference(q, k, v, scale, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_bf16_close(sp_mesh):
    T, H, D = 64, 2, 16
    key = jax.random.key(1)
    q, k, v = (
        jax.random.normal(s, (T, H, D), jnp.bfloat16)
        for s in jax.random.split(key, 3)
    )
    scale = 1.0 / np.sqrt(D)
    out = ring_attention_sharded(q, k, v, sp_mesh, scale)
    ref = dense_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        scale, True,
    )
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
    )


def test_ring_jit_compiles_once(sp_mesh):
    # under jit with static mesh closure — the serving-path usage
    T, H, D = 64, 2, 16
    q = jnp.ones((T, H, D))
    fn = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, sp_mesh, 0.25)
    )
    out = fn(q, q, q)
    assert out.shape == (T, H, D)
    # causal row 0 attends only itself -> output == v row 0
    np.testing.assert_allclose(np.asarray(out[0]), np.ones((H, D)), atol=1e-6)

"""Minimal end-to-end deployment: hub + echo worker + OpenAI HTTP frontend.

Run each role in its own process (mirrors the reference's multi-node
layout: etcd/NATS host, worker node, frontend node):

    python examples/serve_echo.py hub      --hub-port 18500
    python examples/serve_echo.py worker   --hub 127.0.0.1:18500
    python examples/serve_echo.py frontend --hub 127.0.0.1:18500 --port 18080

Then:

    curl -s localhost:18080/v1/chat/completions -d '{
      "model": "echo", "messages": [{"role": "user", "content": "hello"}]}'
"""

import argparse
import asyncio

from dynamo_tpu.http.discovery import ModelEntry, ModelWatcher, register_model
from dynamo_tpu.http.service import HttpService, ModelManager
from dynamo_tpu.llm.openai_engine import OpenAIWorkerEngine
from dynamo_tpu.llm.tokenizer import ByteTokenizer
from dynamo_tpu.protocols.common import FinishReason, LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime import AsyncEngine, Context, DistributedRuntime
from dynamo_tpu.runtime.hub import HubServer, connect_hub


class TokenEchoEngine(AsyncEngine):
    """Echo the prompt tokens back, one per step."""

    async def generate(self, request: Context):
        req: PreprocessedRequest = request.data
        n = len(req.token_ids)
        maxt = req.stop_conditions.max_tokens or n
        for i, tid in enumerate(req.token_ids[:maxt]):
            final = i == min(n, maxt) - 1
            yield LLMEngineOutput(
                token_ids=[tid],
                finish_reason=FinishReason.LENGTH if final else None,
                prompt_tokens=n if final else None,
                completion_tokens=i + 1 if final else None,
            )
            await asyncio.sleep(0)


async def run_hub(args):
    hub = HubServer(host="0.0.0.0", port=args.hub_port)
    await hub.start()
    print(f"hub listening on {hub.address}", flush=True)
    await asyncio.Event().wait()


async def run_worker(args):
    store, bus, _conn = await connect_hub(args.hub)
    drt = await DistributedRuntime.from_settings(store=store, bus=bus)
    engine = OpenAIWorkerEngine(ByteTokenizer(), TokenEchoEngine())
    await drt.namespace("dyn").component("worker").endpoint("generate").serve(
        engine, stats_handler=lambda: {"requests_active": 0}
    )
    await register_model(
        drt,
        ModelEntry(name=args.model, namespace="dyn", component="worker",
                   endpoint="generate", model_type="both"),
    )
    print(f"worker {drt.worker_id:x} serving model {args.model!r}", flush=True)
    await asyncio.Event().wait()


async def run_frontend(args):
    store, bus, _conn = await connect_hub(args.hub)
    drt = await DistributedRuntime.from_settings(store=store, bus=bus)
    svc = HttpService(ModelManager(), host="0.0.0.0", port=args.port)
    await ModelWatcher(drt, svc.models).start()
    await svc.start()
    print(f"frontend on :{svc.port}", flush=True)
    await svc.run()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("role", choices=["hub", "worker", "frontend"])
    p.add_argument("--hub", default="127.0.0.1:18500")
    p.add_argument("--hub-port", type=int, default=18500)
    p.add_argument("--port", type=int, default=18080)
    p.add_argument("--model", default="echo")
    args = p.parse_args()
    asyncio.run({"hub": run_hub, "worker": run_worker, "frontend": run_frontend}[args.role](args))


if __name__ == "__main__":
    main()

"""Minimal 3-stage SDK graph (ref examples/hello_world/hello_world.py):
Frontend -> Middle -> Backend, each stage transforming a text stream.

In-process:
    drt = await DistributedRuntime.from_settings()
    runner = await serve_graph(drt, Frontend)

Multi-process:
    python -m dynamo_tpu.sdk.cli examples.sdk_pipeline:Frontend
"""

from dynamo_tpu.sdk import depends, dynamo_endpoint, service


@service(namespace="hello")
class Backend:
    @dynamo_endpoint
    async def generate(self, request):
        text = request["text"]
        for word in text.split():
            yield {"text": f"{word}-back"}


@service(namespace="hello")
class Middle:
    backend = depends(Backend)

    @dynamo_endpoint
    async def generate(self, request):
        async for item in await self.backend.generate(request):
            yield {"text": item["text"] + "-mid"}


@service(namespace="hello")
class Frontend:
    middle = depends(Middle)

    @dynamo_endpoint
    async def generate(self, request):
        async for item in await self.middle.generate(request):
            yield {"text": item["text"] + "-front"}

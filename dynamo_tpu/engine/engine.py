"""The native JAX engine: continuous batching over a paged KV cache.

This is the TPU replacement for the reference's wrapped GPU engines (vLLM
et al.): a single background scheduler task owns the device state (params,
KV cache, block tables) and interleaves

  * **admission**: claim prefix-cache hits, allocate blocks, run (chunked,
    bucketed) prefill for new requests,
  * **decode**: one batched ``decode_step`` per iteration for all active
    sequences (continuous batching — sequences join/leave the batch at any
    step),
  * **emission**: stream sampled tokens into per-request asyncio queues
    (the AsyncEngine facade yields from them).

Static-shape discipline (XLA): prefill lengths are bucketed, the decode
batch is padded to ``max_batch_size``, block tables are a fixed
``[B, max_blocks_per_seq]`` — so there are O(#buckets + 1) compiled
programs total, reused forever. The KV cache arrays are donated through
every jit call and never leave the device.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import AsyncIterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama
from ..models.config import ModelConfig
from ..ops.sampling import make_keys, sample_first_token, sample_tokens
from ..parallel.mesh import LogicalLayout, MeshConfig, make_mesh
from ..protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from ..analysis import sanitizer
from ..observability.hist import MS_BUCKETS, Histogram
from ..resilience import faultpoints
from ..resilience.faultpoints import FaultInjected
from ..resilience.policy import MIGRATION_SIGNAL
from ..runtime.engine import AsyncEngine, Context
from .. import tracing
from .allocator import (
    Block,
    BlockAllocator,
    model_hash_salt,
    sequence_block_hashes,
)
from .offload import OffloadManager

logger = logging.getLogger(__name__)

PREFILL_BUCKETS = [16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192]

# the prefill-admission first-token sampler, jitted ONCE at module scope:
# a per-call ``jax.jit(sample_first_token)`` built a fresh wrapper (and a
# fresh trace cache) on every admission, so every prefill paid a retrace
_sample_first_jit = jax.jit(sample_first_token)


def _bucket(n: int) -> int:
    for b in PREFILL_BUCKETS:
        if n <= b:
            return b
    return ((n + 8191) // 8192) * 8192


def _seg_bucket(n: int) -> int:
    """Segment-count bucket for the fused mixed step: the smallest power
    of two covering ``n`` in-flight prefill segments. The fused program
    is keyed by (segment-count bucket x prefill-length bucket), so the
    mixture of live prompts never multiplies compiles — dead pad
    segments (valid 0, zero tables) fill the bucket."""
    b = 1
    while b < n:
        b *= 2
    return b


@jax.jit
def _reset_scale_entries(k_scales, v_scales, idxs):
    """Reset recycled pages' scale-plane entries to the codec epsilon
    (one scatter for the whole batch of allocator recycles). ``idxs`` is
    padded with the trash page 0 — resetting its scale is harmless."""
    from ..models.quant import KV_SCALE_EPS

    return (
        k_scales.at[:, idxs].set(KV_SCALE_EPS),
        v_scales.at[:, idxs].set(KV_SCALE_EPS),
    )


from functools import partial as _partial


@_partial(jax.jit, static_argnames=("dtype",))
def _dequant_gathered(pages, scales, dtype):
    """Dequantize a gathered int8 page stack ([L, Hkv, n, bs, D] codes +
    [L, n] scales) back to the model's full-width ``dtype`` — the
    device-side half of an export that must leave the device codec
    (legacy peer, disagg full-width wire)."""
    return (
        pages.astype(jnp.float32) * scales[:, None, :, None, None]
    ).astype(dtype)


@jax.jit
def _reset_pen_slot(counts, mask, slot, prompt_ids, gen_ids):
    """Rebuild one slot's penalty state: prompt-token mask from
    ``prompt_ids`` and output counts from ``gen_ids`` (non-empty after a
    prefill's first sampled token or a preemption replay). Both padded
    with vocab_size — out-of-bounds scatters drop."""
    V = mask.shape[1]
    crow = jnp.zeros((V,), jnp.int32).at[gen_ids].add(1, mode="drop")
    counts = counts.at[slot].set(crow)
    row = jnp.zeros((V,), jnp.bool_).at[prompt_ids].set(True, mode="drop")
    return counts, mask.at[slot].set(row)


@dataclass
class EngineConfig:
    model: ModelConfig
    num_blocks: int = 256
    block_size: int = 16
    max_batch_size: int = 8
    max_context: int = 0  # 0 -> model.max_position_embeddings
    prefill_chunk: int = 2048
    mesh: Optional[MeshConfig] = None
    max_queue: int = 1024
    # fused mixed prefill+decode batching (Sarathi-style chunked-prefill
    # piggybacking / ragged paged attention, PAPERS.md): while a chunked
    # prefill is in flight AND sequences are decoding, each scheduler
    # iteration dispatches ONE fused step — a budget-bounded prefill
    # chunk plus a decode token for every active sequence — instead of
    # alternating a dedicated prefill dispatch with 1-step decode
    # windows. Decode inter-token latency stops absorbing the chunk's
    # device time behind a separate dispatch, and the chunk's GEMMs
    # amortize the weight stream over the decode rows (bench.py
    # ``decode_itl_under_prefill_ms``). False = the legacy alternating
    # scheduler (escape hatch + the bench baseline). Multi-host mirrors
    # and ring-prefill chunks always take the alternating path.
    mixed_batch: bool = True
    # prefill tokens per fused mixed step (the Sarathi token budget);
    # 0 = prefill_chunk. Smaller budgets bound the fused step's device
    # time (tighter decode ITL) at more steps per prompt.
    mixed_step_budget: int = 0
    # max concurrent prompts whose prefills PACK into one fused step
    # (the full ragged formulation / Sarathi stall-free multi-prompt
    # packing): the mixed_step_budget splits across up to this many
    # in-flight prompts per iteration — admission order, per-prompt
    # minimum chunk so no prompt starves — killing head-of-line
    # blocking among queued prompts (short prompts behind a long
    # prefill get their first token without waiting it out). 1 = one
    # prefill at a time (the PR 3 behavior). Compiled program count is
    # bounded by segment-count buckets x prefill buckets, not by the
    # live mixture (test_compiled_perf).
    mixed_max_prefills: int = 4
    # host-DRAM offload tier capacity in blocks (0 = disabled); evicted
    # device blocks park here and restore on prefix hits (engine/offload.py)
    host_cache_blocks: int = 0
    # third KV tier: local disk/SSD capacity in blocks (0 = disabled;
    # requires a host tier — promotion back to device goes THROUGH host
    # DRAM so the unchanged upload/scatter restore path serves it).
    # Host-pool LRU overflow demotes here instead of dropping; disk
    # LRU/TTL overflow is the real drop (offload.DiskKvStore)
    disk_cache_blocks: int = 0
    # disk-tier directory (None = a fresh tempdir per engine); a
    # restarted worker pointed at the same path keeps its disk tier
    disk_cache_path: Optional[str] = None
    # disk-tier entry TTL in seconds (0 = LRU only): at fleet scale the
    # long tail of stale prefixes ages out instead of squatting capacity
    kv_tier_ttl_s: float = 0.0
    # async offload tier: d2h eviction flushes land via background
    # executor threads (double-buffered, budgeted) and h2d restores
    # upload from the moment admission reserves the chain — the
    # scheduler loop never blocks on a transfer (offload.py module
    # docstring). False = legacy synchronous transfers (escape hatch;
    # the multi-host mirror is always synchronous regardless).
    offload_async: bool = True
    # max OPTIONAL evicted blocks one decode dispatch gathers d2h;
    # evictions whose pages the dispatch itself overwrites always flush
    offload_flush_budget: int = 64
    # self-calibrating transfer-cost model (kv_router/costmodel.py):
    # fold observed restore/pull/handoff/prefill timings into per-link
    # bandwidth estimates and advertise them via load_metrics, so the
    # KV router can score this worker by predicted TTFT instead of raw
    # overlap. False = no observations, no advertisement — the router
    # keeps this worker on the overlap-scoring cold-start path forever.
    kv_cost_model: bool = True
    # max fused decode steps per device dispatch (lax.scan window): the
    # sampled token of step i feeds step i+1 on device, so the host syncs
    # once per window, not once per token. The scheduler drops to 1-step
    # windows whenever admission work is pending (fairness) and clamps to
    # each sequence's stop/context headroom. Power of two.
    decode_window: int = 4
    # kv-head ordering of this engine's cache. The native JAX engine
    # stores heads in natural (blocked) order — only "blocked" is valid
    # here; foreign-ordered peers declare their layout on the KV wire
    # (PrefillWorker head_layout / KvDelivery.head_layout) and the decode
    # side regroups on delivery (ops/kv_rearrange.py; ref kv_rearrange)
    kv_head_layout: str = "blocked"
    # decode layer loop: unrolled (default — in-place cache scatters, no
    # scan-ys cache re-stack) vs lax.scan (faster compiles on very deep
    # models, at a full extra KV-cache copy per step)
    decode_layer_scan: bool = False
    # merged one-write decode (flash-merged attention + single in-place
    # Pallas cache append per step); False = per-layer write-then-attend
    # (escape hatch for Mosaic kernel regressions)
    decode_merged: bool = True
    # pipelined decode: dispatch window k+1 (fed window k's last sampled
    # tokens as a device array) BEFORE the host consumes window k, hiding
    # host emission + dispatch latency behind device compute (the async
    # scheduling overlap vLLM gets from multi-step scheduling). Drained
    # whenever batch membership changes, and never the CAUSE of a
    # preemption (speculative window blocks are returned first under pool
    # pressure) — but the overlapped schedule can still shift WHICH
    # sequence a genuine preemption picks, and a replay whose prefix
    # blocks were evicted recomputes with different reduction orders than
    # the original decode (near-tie greedy tokens may flip). Default OFF
    # so the uncontended==contended bit-exactness guarantee holds;
    # opt in for throughput on pools provisioned to rarely preempt.
    # Measured: the serving-layer on/off pair lives in
    # benchmarks/serving_cpu.json (pipeline_speedup; ~1.0x on CPU where
    # dispatch gaps are a tiny share of step time) with the on-chip twin
    # queued via serve_bench --decode-pipeline in the relay battery.
    decode_pipeline: bool = False
    # speculative decoding via prompt-lookup (n-gram) drafts: propose up
    # to spec_gamma continuation tokens from the sequence's own history
    # (last spec_ngram tokens matched against earlier occurrences) and
    # verify them in ONE fused forward (llama.verify_window) — the weight
    # stream amortizes over gamma+1 tokens, so accepted runs multiply
    # decode throughput on repetitive/structured text. Greedy rows accept
    # argmax-matching proposals; sampled rows use rejection sampling
    # against the deterministic draft (lossless in distribution). Slots
    # without a match fall back to a plain single-token step inside the
    # same dispatch. Greedy streams are preserved except at exact logit
    # ties; sampled streams match plain decode in distribution, not
    # token-for-token (the standard spec-decode contract). 0 = off.
    spec_gamma: int = 0
    spec_ngram: int = 3
    # weight quantization: "none" | "int8" | "fp8_e4m3" (models/quant.py —
    # per-output-channel scales; halves decode's HBM weight streaming, the
    # ref's FP8 serving equivalent, docs/architecture.md:57-61)
    quantization: str = "none"
    # quantization covers MoE expert stacks by default (the grouped-
    # dequant Pallas kernel streams them at storage width,
    # ops/moe_gmm_pallas.py); False pins experts at the model dtype
    quant_experts: bool = True
    # KV cache storage dtype: "model" | "float8_e4m3" | "bfloat16"
    # (float8 = scale-free direct cast, vLLM fp8-KV approach; halves KV
    # HBM traffic + doubles cache capacity at some quality cost). A
    # quantized cache still runs the Pallas ragged kernels — the
    # dequant cast fuses into the kernels' KV page loads
    # (_use_pallas_for; ops/ragged_paged_attention_pallas.py)
    kv_cache_dtype: str = "model"
    # per-block KV quantization for the OFFLOAD tiers and the wire
    # (engine/kvquant.py): "none" | "int8" | "fp8". Blocks entering the
    # host pool / disk tier / peer-pull + disagg wire are stored and
    # shipped int8/fp8 with per-(layer, block) scales and dequantized
    # in the device-side scatter on restore — ~2x effective capacity
    # of every tier and the wire at once, at a measured (NOT zero)
    # logprob drift (kvquant.measure_logprob_drift gates it). Opt-in
    # per model; "none" keeps every plane bit-exact full width.
    kv_quant: str = "none"
    # sequence-parallel long-prompt prefill: prompts at least this many
    # tokens go through ring attention over the mesh's sp axis as ONE
    # history-free chunk (parallel/ring_attention.py) instead of chunked
    # dense prefill — each sp device computes T/sp query rows while KV
    # shards rotate the ICI ring. 0 = off. Requires an sp>1 mesh; full
    # attention, non-MLA models (engine falls back otherwise).
    # Measured (scripts/ablate_ring.py, benchmarks/ablate_ring.json):
    # ring wins grow with T (3.6x @ 1k -> 11.4x @ 4k on the virtual
    # mesh) and dense prefill's O(T^2) score memory becomes the binding
    # constraint near 16k — set the threshold where score memory rivals
    # a layer's weights (~8k for 8B-class) on sp>1 slices.
    ring_prefill_threshold: int = 0
    # multi-LoRA serving lane (engine/adapters.py): adapter specs, each
    # "name:rank[:seed]" (synthetic seeded weights — tests/bench) or
    # "name=/path/to/adapter.npz" (real weights). Non-empty turns on the
    # adapter registry: requests may carry a model name that resolves to
    # one of these adapters and the batch runs ONE shared base-GEMM pass
    # plus grouped per-adapter low-rank deltas (ops/lora.py). Empty ()
    # keeps every compiled program, block hash, and wire payload
    # byte-identical to a pre-multi-model fleet.
    adapters: tuple = ()
    # public name of the BASE model (what /v1/models advertises and what
    # requests resolve to adapter_id -1); "" = serve under any name the
    # frontend registered (legacy single-model behavior)
    served_model_name: str = ""
    # max adapters resident in the device stack at once (0 = all
    # configured adapters stay resident — the test/bench default).
    # Smaller than the configured count turns on LRU staging: a request
    # for an unstaged adapter pays a host->device copy unless
    # pre_stage_weights hid it beforehand.
    max_live_adapters: int = 0

    def __post_init__(self):
        if self.kv_head_layout != "blocked":
            raise ValueError(
                "JaxEngine stores kv heads in blocked (natural) order; "
                f"kv_head_layout={self.kv_head_layout!r} would mislabel the "
                "cache — foreign layouts belong on the transfer metadata"
            )
        if self.spec_gamma > 0 and self.decode_window < 2:
            raise ValueError(
                "spec_gamma requires decode_window >= 2: the speculative "
                "path only engages when the scheduler picks multi-step "
                "windows (decode_window=1 would silently disable it)"
            )
        if self.max_context == 0:
            self.max_context = self.model.max_position_embeddings
        if self.mixed_step_budget < 0:
            # a negative budget would slice empty chunks: the fused
            # prefill would never advance and admission behind it would
            # hang forever — fail loudly at construction instead
            raise ValueError(
                f"mixed_step_budget={self.mixed_step_budget} must be >= 0 "
                "(0 = prefill_chunk)"
            )
        if self.mixed_step_budget == 0:
            self.mixed_step_budget = self.prefill_chunk
        if self.disk_cache_blocks > 0 and self.host_cache_blocks <= 0:
            # the disk tier restores THROUGH host DRAM (promotion), so a
            # disk-only configuration would silently never restore —
            # fail loudly at construction
            raise ValueError(
                "disk_cache_blocks > 0 requires host_cache_blocks > 0 "
                "(disk restores promote through the host tier)"
            )
        if self.mixed_max_prefills < 1:
            raise ValueError(
                f"mixed_max_prefills={self.mixed_max_prefills} must be "
                ">= 1 (1 = single-prefill fused steps)"
            )
        from .kvquant import KV_QUANT_MODES

        if self.kv_quant not in KV_QUANT_MODES:
            raise ValueError(
                f"kv_quant must be one of {KV_QUANT_MODES}, "
                f"got {self.kv_quant!r}"
            )
        if self.adapters:
            # loud construction-time gates, matching the int8/MLA
            # precedent: every incompatible lane fails HERE, not as a
            # shape error mid-serve
            if self.spec_gamma > 0:
                raise ValueError(
                    "adapters are incompatible with speculative decoding "
                    "(spec_gamma > 0): verify_window has no LoRA lane yet"
                )
            if getattr(self.model, "is_mla", False):
                raise ValueError(
                    "adapters target the separate-QKV projection path; "
                    "MLA models have no LoRA lane yet"
                )
            if self.decode_layer_scan:
                raise ValueError(
                    "adapters require the unrolled decode layer loop "
                    "(decode_layer_scan=False): per-layer adapter stacks "
                    "are sliced statically like the quantized-KV branch"
                )
            if self.ring_prefill_threshold > 0:
                raise ValueError(
                    "adapters are incompatible with ring prefill: the "
                    "ring chunk path has no LoRA lane yet"
                )
        self.max_blocks_per_seq = (
            self.max_context + self.block_size - 1
        ) // self.block_size


class OutOfBlocks(Exception):
    """KV pool exhausted — caller should backpressure/retry (the prefill
    queue nacks the item so another worker, or this one later, retries)."""


class ReshardUnsupported(RuntimeError):
    """This engine cannot morph its mesh live (multi-host mirrors: every
    dispatch is a lockstep broadcast and the followers' device state
    can't be re-laid from the leader's loop). Callers fall back to the
    PR 4 migration path — drain with handoff so the streams continue on
    workers that CAN serve the new layout."""


@dataclass
class _Sequence:
    request: PreprocessedRequest
    context: object  # AsyncEngineContext
    out_queue: asyncio.Queue
    tokens: list[int] = field(default_factory=list)  # prompt + generated
    prompt_len: int = 0
    blocks: list[Block] = field(default_factory=list)
    committed: int = 0  # number of blocks committed (full+hashed)
    parent_hash: Optional[int] = None
    generated: int = 0
    cached_prefix: int = 0  # tokens served from prefix cache
    slot: int = -1  # decode batch slot
    # multi-LoRA lane: resolved adapter slot in the device stack (-1 =
    # base model, no delta) and the public model name the request
    # arrived under ("" = base). The name — not the slot — salts the
    # block hash chain, so staging/eviction can reshuffle slots without
    # moving any block out of its model's prefix namespace.
    adapter_id: int = -1
    model: str = ""
    finished: bool = False
    arrival_t: float = field(default_factory=time.monotonic)
    # request trace (tracing.TraceContext), captured at generate() entry
    # while the caller's contextvar is still in scope; None = untraced,
    # and every hot-path instrumentation site gates on that None first
    trace: Optional[object] = None

    @property
    def seq_len(self) -> int:
        return len(self.tokens)


class JaxEngine(AsyncEngine):
    """AsyncEngine over PreprocessedRequest -> LLMEngineOutput stream."""

    def __init__(
        self,
        cfg: EngineConfig,
        params: Optional[dict] = None,
        seed: int = 0,
        mirror=None,
    ):
        self.cfg = cfg
        mcfg = cfg.model
        # multi-host: a StepMirror (parallel/multihost.py) makes this engine
        # the leader of a process-spanning mesh — every device dispatch is
        # broadcast to follower ranks which replay the identical jit call
        self.mirror = mirror
        # the LOGICAL sharding contract (parallel/mesh.LogicalLayout):
        # placement rules carried mesh-free, resolved against whatever
        # mesh currently backs the engine — the refactor that makes
        # reshard() a first-class operation instead of a rebuild
        self.layout = LogicalLayout(mcfg)
        if mirror is not None:
            self.mesh = mirror.mesh
        else:
            self.mesh = make_mesh(cfg.mesh) if cfg.mesh else None
        if params is None:
            params = llama.init_params(mcfg, jax.random.key(seed))
        from ..models.quant import kv_cache_dtype, quantize_params

        # quantize BEFORE placement so the derived q/s leaves get their
        # own shardings (parallel/mesh.py derives them from the parent's)
        params = quantize_params(params, mcfg, cfg.quantization,
                                 experts=cfg.quant_experts)
        if mirror is not None:
            params = mirror.shard_params(params)
        else:
            params = self.layout.place_params(params, self.mesh)
        self.params = params
        cache_dt = kv_cache_dtype(mcfg, cfg.kv_cache_dtype)
        if mirror is not None:
            k, v = mirror.init_cache(cfg.num_blocks, cfg.block_size, dtype=cache_dt)
        else:
            k, v = llama.init_kv_cache(
                mcfg, cfg.num_blocks, cfg.block_size, dtype=cache_dt
            )
            sh = self.layout.cache_sharding(self.mesh)
            if sh is not None:
                k, v = jax.device_put(k, sh), jax.device_put(v, sh)
        self.k_cache, self.v_cache = k, v
        # int8-with-scales DEVICE cache (kv_cache_dtype="int8"): per-page
        # f32 scale planes [L, N] — one symmetric absmax scale per
        # (layer, physical page) per K/V, the tier codec's exact
        # granularity (engine/kvquant.py), so wire landings adopt their
        # carried scales directly and d2h exports re-encode from the
        # planes with zero full-width bounce. None for every other mode.
        self.k_scales = self.v_scales = None
        if cache_dt == jnp.int8:
            if mcfg.is_mla:
                # LOUD gate, not a silent fallback: the absorbed-matmul
                # MLA path folds W_kv^B into the query/output projections
                # and dots queries against the latent cache DIRECTLY —
                # a per-page scale would have to multiply inside the
                # absorbed einsums (and the merged latent append + the
                # bf16-gated MLA Pallas kernels have no scale stream).
                # MLA keeps the scale-free fp8 cast (kv_cache_dtype=
                # "float8_e4m3") as its low-precision option.
                raise ValueError(
                    "kv_cache_dtype='int8' is not supported for MLA "
                    "models: the absorbed-matmul latent path has no "
                    "per-page scale stream — use kv_cache_dtype="
                    "'float8_e4m3' (scale-free cast) for MLA"
                )
            if mirror is not None:
                raise ValueError(
                    "kv_cache_dtype='int8' is not supported under the "
                    "multi-host mirror (lockstep broadcasts carry no "
                    "scale planes)"
                )
            from ..models.quant import KV_SCALE_EPS

            plane = jnp.full(
                (mcfg.num_layers, cfg.num_blocks), KV_SCALE_EPS,
                jnp.float32,
            )
            if self.mesh is not None:
                # planes replicate: the page axis is unsharded and the
                # scales are kv-head-free (ops/attention._shard_tp
                # passes them as replicated scalars)
                from jax.sharding import NamedSharding, PartitionSpec

                plane = jax.device_put(
                    plane, NamedSharding(self.mesh, PartitionSpec())
                )
            self.k_scales, self.v_scales = plane, plane
        self.allocator = BlockAllocator(cfg.num_blocks, cfg.block_size)
        # recycled pages must not inherit a previous tenant's absmax
        # scale: every fresh-mutable allocation queues a scale reset,
        # flushed as ONE scatter on the next dispatch preamble
        # (_flush_scale_resets). match_prefix claims keep their scales.
        self._pending_scale_resets: list[int] = []
        # device-side accumulator of page requantizations (folded into
        # stats at scrape time — see _note_quant_step), and the last
        # folded value of the offload manager's export-bounce counter
        self._requants_dev = None
        self._offload_requants_seen = 0
        # decode-throughput EMA for the low-precision lane (lowprec_tok_s)
        self._lowprec_rate_t = 0.0
        if self.k_scales is not None:
            self.allocator.on_allocated = self._pending_scale_resets.append
            # bytes one token's K+V rows save landing int8 instead of
            # full width (per-page scale overhead is L*8 bytes per block
            # against Hkv*D*bs*itemsize — sub-1% — and is counted in
            # _hbm_stats, not here)
            full_itemsize = jnp.dtype(mcfg.dtype).itemsize
            self._kv_saved_per_token = int(
                2 * mcfg.num_layers * mcfg.num_kv_heads * mcfg.head_dim
                * (full_itemsize - 1)
            )
            if cfg.spec_gamma > 0:
                logger.warning(
                    "kv_cache_dtype='int8': speculative (prompt-lookup) "
                    "decoding is disabled — the fused verify forward "
                    "has no scale-plane stream; decode runs plain "
                    "windows"
                )
        # transfer-cost calibration (kv_router/costmodel.py): one model
        # per engine, fed by the restore/pull/handoff/prefill paths and
        # advertised through load_metrics. Block bytes from the real
        # cache geometry (k and v differ for MLA latents).
        self.kv_block_bytes = int(
            (self.k_cache.nbytes + self.v_cache.nbytes)
            // max(cfg.num_blocks, 1)
        )
        # bytes one block costs on the TIER/WIRE planes: the full-width
        # size, or the quantized payload + per-layer scales under
        # --kv-quant (engine/kvquant.py). Advertised alongside
        # kv_block_bytes so routing prices restore/pull legs at the
        # bytes that actually move
        from .kvquant import wire_block_bytes as _wire_bb

        self.kv_wire_block_bytes = _wire_bb(
            self.kv_block_bytes, self.k_cache.dtype.itemsize,
            mcfg.num_layers,
            # mirror-backed engines force the tier codec off (lockstep
            # broadcasts are full-width only) — advertise accordingly
            cfg.kv_quant if mirror is None else "none",
        )
        self.offload: Optional[OffloadManager] = None
        if cfg.host_cache_blocks > 0:
            # under the multi-host mirror, flush/restore become mirrored
            # ops and every process parks its own cache shards in host DRAM
            self.offload = OffloadManager(
                cfg.host_cache_blocks, mirror=mirror,
                flush_budget=cfg.offload_flush_budget,
                async_tier=cfg.offload_async,
                disk_blocks=cfg.disk_cache_blocks,
                disk_path=cfg.disk_cache_path,
                tier_ttl_s=cfg.kv_tier_ttl_s,
                kv_quant=cfg.kv_quant,
                block_bytes=self.kv_block_bytes,
                # the tier's FULL-WIDTH dtype: with an int8 device cache
                # the cache dtype is the quantized code, not the width
                # dequants should target — use the model's compute dtype
                full_dtype=(
                    mcfg.dtype if self.k_scales is not None
                    else str(self.k_cache.dtype)
                ),
            )
            self.allocator.on_evict = lambda h, b: self.offload.on_evict(h, b.idx)
            # tier-drop removals re-check device residency before
            # publishing (offload.flush_dropped): a stale lower-tier
            # copy aging out must not un-index a device-resident block
            self.offload.device_has = self.allocator.has_hash
            if self.k_scales is not None:
                # publish the scale planes so tier traffic speaks the
                # device codec: flushes gather int8 pages + scales (an
                # int8 tier adopts them with zero re-encode), restores
                # land payload + scales back into cache + planes
                self.offload.device_planes = (
                    lambda: (self.k_scales, self.v_scales)
                )

                def _set_planes(planes):
                    self.k_scales, self.v_scales = planes

                self.offload.device_planes_set = _set_planes
        self.cost = None
        if cfg.kv_cost_model:
            from ..kv_router.costmodel import TransferCostModel

            self.cost = TransferCostModel(block_bytes=self.kv_block_bytes)
            if self.offload is not None:
                self.offload.cost_model = self.cost
        # one-time dispatch-capability log for quantized device caches
        # (set before the first _use_pallas_for derivation below)
        self._kvq_dispatch_logged = False
        self.use_pallas = self._use_pallas_for(self.mesh)
        # multi-LoRA lane (engine/adapters.py): registry of adapter A/B
        # stacks. None when cfg.adapters is empty — every dispatch site
        # below gates on that None, so base-only fleets run programs
        # byte-identical to pre-multi-model builds.
        self.adapters = None
        if cfg.adapters:
            if mirror is not None:
                raise ValueError(
                    "adapters are not supported under the multi-host "
                    "mirror yet (lockstep dispatches carry no adapter "
                    "stacks) — serve adapters on single-host workers"
                )
            from .adapters import AdapterRegistry

            self.adapters = AdapterRegistry(
                cfg.adapters, mcfg, max_live=cfg.max_live_adapters,
            )
        self._waiting: asyncio.Queue[_Sequence] = asyncio.Queue(cfg.max_queue)
        # re-admissions (preemption replay, backpressure put-back) jump
        # the line through this explicit front buffer — consumers drain
        # it before the queue, so no reaching into asyncio.Queue._queue
        # internals (advisor r2 weak #4)
        self._waiting_front: deque[_Sequence] = deque()
        # in-flight chunked prefills, admission order. The mixed-batch
        # scheduler packs the Sarathi token budget across ALL of them
        # per fused step (up to cfg.mixed_max_prefills); the alternating
        # scheduler (mixed off / mirror / ring) only ever holds one
        self._prefill_states: list[_PrefillState] = []
        # remotely-prefilled sequences with KV landed, awaiting a batch slot
        self._remote_ready: list[_Sequence] = []
        self._active: list[Optional[_Sequence]] = [None] * cfg.max_batch_size
        self._n_active = 0
        self._loop_task: Optional[asyncio.Task] = None
        # serializes device-state mutation (k/v cache is donated through
        # every jit call — concurrent dispatch would use freed buffers);
        # contended only when disagg hooks run beside the decode loop.
        # Named for the runtime sanitizer: when active, its hold times
        # histogram under "device_lock" instead of an acquire site.
        self._device_lock = sanitizer.name_lock(asyncio.Lock(), "device_lock")
        # pipelined decode: the not-yet-drained window's device tokens
        self._inflight: Optional[dict] = None
        self._wake = asyncio.Event()
        self._closed = False
        self._backpressured = False
        # graceful drain (resilience/drain.py): _draining stops admission
        # (generate() bounces new work with the migration signal); past
        # _drain_deadline the scheduler hands off in-flight streams too.
        # _dead marks a crashed/fault-killed scheduler loop — generate()
        # then fails FAST with a worker-lost signature instead of parking
        # requests on a queue nothing will ever drain.
        self._draining = False
        self._drain_handoff = True
        self._drain_deadline = 0.0
        self._dead: Optional[str] = None
        # elastic live resharding (docs/elastic_resharding.md): a posted
        # morph request the scheduler loop commits at a step boundary;
        # _resharding is advertised through load_metrics so the router
        # soft-excludes this worker for the morph window. The morpher
        # (parallel/morph.MeshMorpher) memoizes the compiled cross-mesh
        # permutation programs across morphs, lazily built on first use.
        self._reshard_req: Optional[dict] = None
        # claimed synchronously at reshard() entry (before the staging
        # await) so concurrent calls can't both pass the overlap check
        self._reshard_busy = False
        self._resharding = False
        self.morpher = None
        # host mirrors of device-side batch state
        M = cfg.max_blocks_per_seq
        self._block_tables = np.zeros((cfg.max_batch_size, M), np.int32)
        self._seq_lens = np.zeros(cfg.max_batch_size, np.int32)
        self._last_tokens = np.zeros(cfg.max_batch_size, np.int32)
        self._seeds = np.zeros(cfg.max_batch_size, np.int32)
        self._temps = np.zeros(cfg.max_batch_size, np.float32)
        self._top_ks = np.zeros(cfg.max_batch_size, np.int32)
        self._top_ps = np.ones(cfg.max_batch_size, np.float32)
        # sampling penalties (vLLM semantics — see ops/sampling):
        # device [B, V] output-token counts + prompt-membership mask,
        # allocated lazily on the first request that asks for a penalty
        self._freq_pens = np.zeros(cfg.max_batch_size, np.float32)
        self._pres_pens = np.zeros(cfg.max_batch_size, np.float32)
        self._rep_pens = np.ones(cfg.max_batch_size, np.float32)
        self._pen_counts = None
        self._pen_mask = None
        # requested top-logprob count per slot (-1 = logprobs off;
        # 0 = chosen-token logprob only, no alternates)
        self._logprob_ks = np.full(cfg.max_batch_size, -1, np.int32)
        self._window_logprobs = None
        # per-slot adapter id (-1 = base); mirrors the device dispatch's
        # adapter_ids operand exactly like _seeds/_temps mirror theirs
        self._adapter_ids = np.full(cfg.max_batch_size, -1, np.int32)
        # live-request refcount per adapter NAME: an adapter a running
        # sequence depends on must never be LRU-evicted mid-stream
        self._adapter_refs: dict[str, int] = {}
        # metrics
        self.stats = {
            "requests_total": 0,
            "requests_active": 0,
            "requests_waiting": 0,
            "tokens_generated": 0,
            "prompt_tokens_total": 0,
            "prefix_cache_hits_tokens": 0,
            "decode_steps": 0,
            "mixed_steps": 0,
            "mixed_prefill_segments": 0,
            "preemptions": 0,
            "spec_proposed": 0,
            "spec_accepted": 0,
            "drains_total": 0,
            "drain_handoffs": 0,
            "migration_resumes": 0,
            # fleet prefix cache: blocks served to peers straight out of
            # the DEVICE tier (bounded d2h export on fetch)
            "peer_serve_d2h_blocks": 0,
            # PRESERVE weight pre-stage lane (pre_stage_weights +
            # on-demand staging in generate): requests, bytes actually
            # copied host->device, and hits — a request that arrived to
            # find its adapter already staged (the prestage did its job)
            "weight_prestage_requests": 0,
            "weight_prestage_bytes": 0,
            "weight_prestage_hits": 0,
            # elastic resharding: completed morphs, KV blocks re-laid by
            # the last morph's commit, and the last morph's client-
            # visible hold window (quiesce -> resume, weight staging
            # excluded — it overlaps serving)
            "resharded_total": 0,
            "reshard_kv_moved_blocks": 0,
            "reshard_hold_ms": 0.0,
            # worst chosen-token logprob drift the kv-quant harness
            # (engine/kvquant.measure_logprob_drift) recorded against
            # this engine's quantized tiers; 0 until a harness ran
            "kv_quant_logprob_drift_max": 0.0,
            # int8-with-scales DEVICE cache lane (docs/kv_offload.md):
            # live quantized resident pages, cumulative page
            # requantizations (scale-growth rewrites), cumulative bytes
            # the int8 landings saved vs full width, d2h exports that
            # had to requantize (tier codec mismatch — the int8->int8
            # fast path keeps this at 0), and the measured decode
            # throughput of the low-precision lane
            "kv_device_quant_pages": 0,
            "kv_device_requants_total": 0,
            "kv_device_bytes_saved_total": 0,
            "kv_device_export_requant_total": 0,
            "lowprec_tok_s": 0.0,
            # XLA compile ledger (docs/observability.md): first-dispatch
            # count + wall-ms per distinct program bucket, and the
            # warmup coverage report (_warm coverage in warmup()) —
            # cold-bucket compile stalls in production become
            # attributable instead of anonymous 20-40s TTFTs
            "xla_compiles_total": 0,
            "xla_compile_ms_total": 0.0,
            "xla_warm_buckets": 0,
            "xla_reachable_buckets": 0,
            # autopilot actuation surface (docs/autopilot.md): control-
            # plane warmups the WarmupListener ran on this engine (and
            # their wall-ms — the compile tax paid off the hot path),
            # plus the QuarantineListener's mirror of this worker's
            # quarantine state so one scrape shows a worker was pulled
            # from rotation and how often
            "autopilot_warmups_applied": 0,
            "autopilot_warmup_ms_total": 0.0,
            "autopilot_quarantined": 0,
            "autopilot_quarantines_total": 0,
        }
        # SLO observatory worker-side latency distributions
        # (docs/observability.md): fixed log-bucket histograms riding
        # load_metrics as serialized vectors -> WorkerLoad.hists -> the
        # metrics component's per-worker histogram families. Observed
        # from the loop AND device-executor threads; a lost count under
        # a rare unlocked race is acceptable for this plane (same
        # tradeoff as the sanitizer's own histograms).
        self.hist = {
            "queue_wait_ms": Histogram(MS_BUCKETS),
            "prefill_ms": Histogram(MS_BUCKETS),
            "restore_ms": Histogram(MS_BUCKETS),
            "handoff_ms": Histogram(MS_BUCKETS),
        }
        # per-model TTFT distributions, lazily keyed by public model
        # name ("" = base): the multi-model SLO plane trace-replay
        # asserts against — measured arrival -> first emitted token,
        # the engine-side component of the frontend's TTFT
        self.hist_ttft: dict[str, Histogram] = {}
        # (kind, *bucket-shape) keys whose program has dispatched at
        # least once — the complement of "about to pay a compile stall"
        self._compiled_keys: set[tuple] = set()
        #: newest-last {kind, key, ms} entries (bounded); the flight
        #: recorder's autopsies carry the tail so a compile-stalled TTFT
        #: names the program that compiled inside its window
        self.compile_ledger: list[dict] = []
        self._weight_bytes: Optional[int] = None

    def _use_pallas_for(self, mesh) -> bool:
        """Pallas decode path for ``mesh``: TPU backend + aligned tiles.
        Sharded meshes run the kernel under shard_map over tp
        (head-parallel, no collectives) when tp divides the kv heads;
        otherwise the XLA fallback lets GSPMD handle the uneven split.
        A method (not an __init__ constant) because reshard() must
        re-derive it for the new mesh — tp=4 may gate the kernel off
        where tp=1 allowed it."""
        cfg = self.cfg
        tp = mesh.shape["tp"] if mesh is not None else 1
        # EXPLICIT quantized-KV capability check (was a silent dtype
        # opt-out): the non-MLA ragged/decode/prefill kernels consume
        # int8/fp8 pages directly — the dequant cast fuses into their
        # KV page loads — so a quantized cache keeps the Pallas path.
        # The MLA latent kernels are still bf16/f32-only (the absorbed
        # latent matmuls were never validated at sub-bf16), so MLA +
        # quantized cache stays on the XLA fallback, loudly.
        kv_dt = self.k_cache.dtype
        kv_quantized = kv_dt not in (jnp.bfloat16, jnp.float32)
        kv_dtype_ok = not kv_quantized or (
            not cfg.model.is_mla
            and kv_dt in (jnp.float8_e4m3fn, jnp.int8)
        )
        if kv_quantized and not self._kvq_dispatch_logged:
            self._kvq_dispatch_logged = True
            if kv_dtype_ok:
                logger.info(
                    "quantized KV cache (%s): Pallas kernels stay engaged "
                    "— dequant fused into the ragged kernels' page loads",
                    kv_dt,
                )
            else:
                logger.info(
                    "quantized KV cache (%s) on an MLA model: falling "
                    "back to the XLA attention path (latent kernels are "
                    "bf16/f32-only)", kv_dt,
                )
        return (
            jax.default_backend() == "tpu"
            and cfg.block_size % 8 == 0
            and kv_dtype_ok
            and (
                (
                    not cfg.model.is_mla
                    # 64 covers gpt-oss (head_dim=64): Mosaic pads
                    # sub-128 lane tiles; if this chip/toolchain
                    # rejects that, _pallas_guard flips the engine to
                    # XLA at first dispatch instead of failing the
                    # request (validate_tpu_kernels checks D=64
                    # on-chip). Sinks fold into the kernels' merge
                    # denominators and per-layer windows are static
                    # per unrolled layer call, so gpt-oss is NOT
                    # gated off.
                    and cfg.model.head_dim % 64 == 0
                    # gemma-2 score softcapping lives in the XLA paths
                    and not cfg.model.attn_softcap
                    and (
                        mesh is None
                        or cfg.model.num_kv_heads % tp == 0
                    )
                )
                or (
                    # MLA: the latent decode kernel + merged one-write
                    # append (ops/mla_attention_pallas). Query heads are
                    # the tp axis; the latent cache replicates — but pp
                    # shards the cache's LAYER axis, which the per-layer
                    # shard_map would have to all-gather back, so pp
                    # meshes keep the XLA absorbed path.
                    cfg.model.is_mla
                    and cfg.model.kv_lora_rank % 128 == 0
                    and (
                        mesh is None
                        or (
                            mesh.shape.get("pp", 1) == 1
                            # the sharded latent kernels shard_map the
                            # QUERY-head axis over tp (advisor r3): an
                            # uneven split must fall back to XLA, not
                            # crash at first decode
                            and cfg.model.num_heads % tp == 0
                        )
                    )
                )
            )
        )

    # ---------------- public api ----------------

    def start(self) -> None:
        if self._loop_task is None:
            self._loop_task = asyncio.get_running_loop().create_task(self._loop())

    async def close(self) -> None:
        self._closed = True
        self._wake.set()
        if self._loop_task:
            self._loop_task.cancel()
            self._loop_task = None
        if self.offload is not None:
            self.offload.close()
        if self.mirror is not None:
            # release follower ranks blocked on the next broadcast; take the
            # device lock so the halt can't interleave with a decode/prefill
            # broadcast still running in an executor thread
            async with self._device_lock:
                await asyncio.get_running_loop().run_in_executor(
                    None, self.mirror.lead_halt
                )

    async def warmup(self, decode: bool = True) -> list[int]:
        """Compile the serving paths BEFORE real traffic: one dummy
        request per reachable prefill bucket (chunked prefill buckets
        every chunk, so larger prompts only ever see these shapes) plus
        the full decode-window ladder. Without this, the first real
        request at each new shape pays a 20-40s XLA compile on its TTFT
        — the TPU analog of the reference engines' startup
        profile/warmup pass.

        Details that make the coverage real:
          * each bucket gets its own pseudo-random prompt — a repeated
            prompt would prefix-hit the previous request's committed
            blocks and prefill only the (smaller-bucket) tail;
          * a prompt of min(prefill_chunk, max_context-1) tokens warms
            the TOP bucket real chunks round up to, which the
            power-of-two list alone misses when that limit isn't a
            bucket boundary;
          * the first request's max_tokens is 2*decode_window: one token
            comes from the prefill sample, so decode has a 2W-1 budget
            and _pick_window walks the whole power-of-two ladder
            W, W/2, ..., 1 — the smaller windows (especially 1) are
            exactly what concurrent admission traffic dispatches, so
            leaving them cold would inject the compile stall mid-stream
            under real load;
          * speculation is held off for the duration: repeated-token
            dummy prompts are the canonical prompt-lookup trigger, and
            an engaged verify would swallow the very window dispatches
            being warmed (the verify itself still compiles on its first
            organic proposal).

        ``decode=False`` skips the window ladder entirely (every request
        stops at its prefill-sampled token) — for prefill-only disagg
        workers, which never dispatch decode windows.

        Dummy blocks enter the prefix cache content-addressed and age
        out LRU like any other. Returns the warmed bucket sizes.
        """
        lim = min(self.cfg.prefill_chunk, self.cfg.max_context - 1)
        lengths = [b for b in PREFILL_BUCKETS if b <= lim]
        sizes = list(lengths)
        top = _bucket(lim)
        if top not in sizes:
            lengths.append(lim)
            sizes.append(top)
        W = self.cfg.decode_window
        V = self.cfg.model.vocab_size
        gamma, self.cfg.spec_gamma = self.cfg.spec_gamma, 0
        try:
            for i, n_toks in enumerate(lengths):
                # per-bucket pseudo-random prompts: distinct across
                # buckets (no prefix-cache hit shrinking the prefilled
                # shape) and non-repeating within one (no n-gram bait)
                toks = np.random.RandomState(1000 + i).randint(
                    0, V, n_toks
                ).tolist()
                req = PreprocessedRequest(
                    token_ids=toks,
                    stop_conditions=StopConditions(
                        # the first (shortest) prompt has the context
                        # headroom to walk the decode-window ladder; the
                        # rest stop at their prefill-sampled token
                        max_tokens=2 * W if (i == 0 and decode) else 1,
                        ignore_eos=True,
                    ),
                    sampling_options=SamplingOptions(temperature=0.0),
                    eos_token_ids=[],
                )
                async for _ in self.generate(Context(req)):
                    pass
        finally:
            self.cfg.spec_gamma = gamma
        # compile-warmup coverage report (docs/observability.md): how
        # many serving-path program buckets this warmup actually
        # compiled vs what production traffic can reach through it —
        # the gap is the cold-bucket compile-stall exposure the ledger
        # will attribute later (xla_warm_buckets/xla_reachable_buckets
        # gauges through load_metrics)
        warm = sum(
            1 for k in self._compiled_keys
            if k[0] in ("prefill", "decode", "mixed")
        )
        reachable = len(sizes)
        if decode:
            w = W
            while w >= 1:  # the _pick_window power-of-two ladder
                reachable += 1
                w //= 2
        self.stats["xla_warm_buckets"] = warm
        self.stats["xla_reachable_buckets"] = reachable
        logger.info(
            "warmup coverage: %d/%d reachable program buckets compiled "
            "(%d total compiles, %.0f ms compile wall)",
            warm, reachable, self.stats["xla_compiles_total"],
            self.stats["xla_compile_ms_total"],
        )
        return sizes

    async def generate(self, request: Context) -> AsyncIterator[LLMEngineOutput]:
        if self._draining or self._dead is not None:
            # draining/dead worker: bounce immediately with a worker-lost
            # signature so a migration-aware frontend re-dispatches —
            # never park work on a queue this scheduler won't drain
            yield LLMEngineOutput(
                finish_reason=FinishReason.ERROR,
                text=self._dead or MIGRATION_SIGNAL,
            )
            return
        self.start()
        faultpoints.hit_sync("admission", request_id=request.id)
        req: PreprocessedRequest = request.data
        if isinstance(req, dict):
            req = PreprocessedRequest.from_dict(req)
        if not req.token_ids:
            yield LLMEngineOutput(finish_reason=FinishReason.ERROR, text="empty prompt")
            return
        if len(req.token_ids) >= self.cfg.max_context:
            yield LLMEngineOutput(finish_reason=FinishReason.ERROR)
            return
        if not self._tokens_in_vocab(req.token_ids):
            # out-of-vocab ids make the embedding gather IMPLEMENTATION-
            # DEFINED (XLA clamps on one device; a multi-process sharded
            # mesh lands OOB rows differently), so the same request can
            # legally produce different streams on different meshes —
            # found as the test_multihost_compose cancel-after-restore
            # "token mismatch", which was OOB prompt ids all along.
            # Reject loudly instead of serving garbage.
            yield LLMEngineOutput(
                finish_reason=FinishReason.ERROR,
                text=f"prompt token id out of range [0, "
                     f"{self.cfg.model.vocab_size})",
            )
            return
        # multi-LoRA lane: resolve the request's model name to base
        # (adapter_id -1) or a registered adapter. Fleets without
        # --adapters skip all of this — any model name passes through
        # untouched (legacy single-model behavior, the frontend already
        # checked registration).
        adapter_id, model_name = -1, ""
        if self.adapters is not None and req.model:
            base = self.cfg.served_model_name
            if self.adapters.is_known(req.model):
                try:
                    adapter_id = self._claim_adapter(req.model)
                except RuntimeError as e:
                    yield LLMEngineOutput(
                        finish_reason=FinishReason.ERROR, text=str(e)
                    )
                    return
                model_name = req.model
            elif base and req.model != base:
                # same clean signature the frontend's 404 carries —
                # worker-side requests (bench, direct dispatch) get the
                # identical body instead of serving base-model tokens
                # under an unknown name
                yield LLMEngineOutput(
                    finish_reason=FinishReason.ERROR,
                    text=f"unknown model {req.model!r}",
                )
                return
        seq = _Sequence(
            request=req,
            context=request.context,
            out_queue=asyncio.Queue(),
            tokens=list(req.token_ids),
            prompt_len=len(req.token_ids),
            adapter_id=adapter_id,
            model=model_name,
            trace=tracing.current_trace() if tracing.enabled() else None,
        )
        # the chain's root is the model's salted namespace from the very
        # first committed block (None for base = pre-multi-model bytes)
        seq.parent_hash = model_hash_salt(model_name)
        resume = (
            req.annotations.get("resume")
            if isinstance(req.annotations, dict) else None
        )
        if isinstance(resume, dict):
            # migration resume (resilience/migration.py): token_ids =
            # original prompt + tokens already delivered. Restoring the
            # prompt/generated split makes the continuation exact: the
            # per-step sampling keys fold_in(seed, generated) pick up at
            # the seam, penalty state rebuilds from the TRUE output list,
            # and max/min_tokens + usage count from the original prompt.
            try:
                plen = int(resume.get("prompt_len", 0))
            except (TypeError, ValueError):
                plen = 0
            if 0 < plen <= len(req.token_ids):
                seq.prompt_len = plen
                seq.generated = len(req.token_ids) - plen
                self.stats["migration_resumes"] += 1
        self.stats["requests_total"] += 1
        self.stats["prompt_tokens_total"] += seq.prompt_len
        await self._waiting.put(seq)
        self._wake.set()
        while True:
            out = await seq.out_queue.get()
            if out is None:
                return
            yield out
            if out.is_final():
                return

    def _claim_adapter(self, name: str) -> int:
        """Resolve an adapter request to its device-stack slot, staging
        on demand (the cold-load stall ``pre_stage_weights`` exists to
        hide) and pinning the adapter against LRU eviction for the
        request's lifetime (released in ``_finish``)."""
        reg = self.adapters
        if reg.is_staged(name):
            # the prestage (or a previous request) already paid the
            # host->device copy — this is the hit the PRESERVE lane
            # measures
            self.stats["weight_prestage_hits"] += 1
            slot = reg.slot_of(name)
        else:
            in_use = {n for n, c in self._adapter_refs.items() if c > 0}
            slot, nbytes = reg.stage(name, in_use=in_use)
            self.stats["weight_prestage_bytes"] += nbytes
        self._adapter_refs[name] = self._adapter_refs.get(name, 0) + 1
        return slot

    def served_models(self) -> list[str]:
        """Every public name this worker answers to: the base model
        first ("" = any name, the legacy wildcard), then each configured
        adapter. Advertised through load_metrics so ``select_worker``
        filters on model identity before scoring."""
        out = [self.cfg.served_model_name or ""]
        if self.adapters is not None:
            out.extend(self.adapters.names())
        return out

    def _hbm_stats(self) -> dict:
        """TPU device-memory telemetry (docs/observability.md): real
        allocator numbers from ``device.memory_stats()`` where the
        backend exposes them (TPU does; CPU returns nothing), with the
        engine's own attribution — KV pool and weight bytes are computed
        from the arrays themselves, so they are exact on every backend.
        When the allocator view is unavailable, ``in_use`` falls back to
        the attributed sum (flagged by ``limit == 0``) so the gauge
        exists fleet-wide instead of silently disappearing on CPU."""
        kv = int(getattr(self.k_cache, "nbytes", 0) or 0) + int(
            getattr(self.v_cache, "nbytes", 0) or 0
        )
        if self.k_scales is not None:
            # the int8 cache's per-page scale planes are KV-pool bytes
            kv += int(self.k_scales.nbytes) + int(self.v_scales.nbytes)
        if self._weight_bytes is None:
            try:
                self._weight_bytes = sum(
                    int(getattr(x, "nbytes", 0) or 0)
                    for x in jax.tree.leaves(self.params)
                )
            except Exception:  # noqa: BLE001 — attribution is best-effort
                self._weight_bytes = 0
        in_use = limit = 0
        try:
            dev = (
                self.mesh.devices.flat[0] if self.mesh is not None
                else jax.local_devices()[0]
            )
            ms = dev.memory_stats() or {}
            in_use = int(ms.get("bytes_in_use", 0) or 0)
            limit = int(ms.get("bytes_limit", 0) or 0)
        except Exception:  # noqa: BLE001 — backend without memory_stats
            logger.debug("device memory_stats unavailable", exc_info=True)
        if not in_use:
            in_use = kv + self._weight_bytes
        return {"in_use": in_use, "limit": limit, "kv_pool": kv,
                "weights": self._weight_bytes}

    async def profile(self, seconds: float) -> str:
        """On-demand ``jax.profiler`` capture (the frontend's
        ``POST /profile?seconds=N``): trace every device for N seconds
        into a fresh directory and return its path (TensorBoard /
        Perfetto-loadable). Runs in an executor thread so serving, lease
        keepalives and scrapes continue underneath the capture."""
        import tempfile

        out_dir = tempfile.mkdtemp(prefix="dynamo-profile-")

        def _capture() -> str:
            jax.profiler.start_trace(out_dir)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            return out_dir

        return await asyncio.get_running_loop().run_in_executor(
            None, _capture
        )

    def load_metrics(self) -> dict:
        """Worker stats for the KV router plane (ref ForwardPassMetrics)."""
        self._register_device_executor()
        self._fold_quant_counters()
        out = {}
        # SLO observatory: worker latency distributions as serialized
        # bucket vectors (merged loss-free downstream), the XLA compile
        # ledger counters + warmup coverage, and HBM telemetry
        out["hist_queue_wait_ms"] = self.hist["queue_wait_ms"].to_vec()
        out["hist_prefill_ms"] = self.hist["prefill_ms"].to_vec()
        out["hist_restore_ms"] = self.hist["restore_ms"].to_vec()
        out["hist_handoff_ms"] = self.hist["handoff_ms"].to_vec()
        # per-model TTFT families keyed by public model name ("" = base)
        out["hist_ttft_ms"] = {
            m: h.to_vec() for m, h in self.hist_ttft.items()
        }
        out["xla_compiles_total"] = self.stats["xla_compiles_total"]
        out["xla_compile_ms_total"] = round(
            self.stats["xla_compile_ms_total"], 3
        )
        out["xla_warm_buckets"] = self.stats["xla_warm_buckets"]
        out["xla_reachable_buckets"] = self.stats["xla_reachable_buckets"]
        # autopilot actuation mirrors (warmup/quarantine listeners)
        out["autopilot_warmups_applied"] = self.stats[
            "autopilot_warmups_applied"]
        out["autopilot_warmup_ms_total"] = self.stats[
            "autopilot_warmup_ms_total"]
        out["autopilot_quarantined"] = self.stats["autopilot_quarantined"]
        out["autopilot_quarantines_total"] = self.stats[
            "autopilot_quarantines_total"]
        hbm = self._hbm_stats()
        out["hbm_bytes_in_use"] = hbm["in_use"]
        out["hbm_bytes_limit"] = hbm["limit"]
        out["hbm_kv_pool_bytes"] = hbm["kv_pool"]
        out["hbm_weights_bytes"] = hbm["weights"]
        if self.offload is not None:
            # piggyback the (loop-side) stats scrape to publish queued
            # tier-drop removals: blocks that left the LAST local tier
            # must stop counting as this worker's radix residency
            self.offload.flush_dropped()
            out.update(self.offload.stats())
        # runtime-sanitizer counters (analysis/sanitizer.py): zeros when
        # no sanitizer has ever been active in this process; under
        # --sanitize (or the test suite) they surface loop stalls and
        # worst lock holds through the scrape -> metrics-gauge plane
        out.update(sanitizer.counters())
        return out | {
            # mixed-batch fusion activity (prefill chunks riding decode
            # steps, and how many prompt segments packed into them) —
            # lets the router/metrics plane see whether decode ITL is
            # being shielded from concurrent prefill and whether queued
            # prompts are advancing together or head-of-line blocking
            "mixed_steps": self.stats["mixed_steps"],
            "mixed_prefill_segments": self.stats["mixed_prefill_segments"],
            "kv_active_blocks": self.allocator.used_count,
            "kv_total_blocks": self.allocator.num_blocks - 1,
            "gpu_cache_usage_perc": self.allocator.usage(),  # dynlint: disable=unscraped-stat -- reference-schema compat key (vLLM ForwardPassMetrics); consumers derive usage from kv_active/kv_total
            "request_active_slots": self._n_active,
            "request_total_slots": self.cfg.max_batch_size,
            "num_requests_waiting": self._waiting_size(),
            # cumulative serving counters: the planner's telemetry
            # aggregator derives fleet arrival/throughput rates from
            # scrape-to-scrape deltas of these
            "requests_total": self.stats["requests_total"],
            "tokens_generated": self.stats["tokens_generated"],
            "prompt_tokens_total": self.stats["prompt_tokens_total"],
            # resilience surface: the router deprioritizes draining
            # workers; the metrics component tracks drain/migration volume
            "draining": int(self._draining),
            "drains_total": self.stats["drains_total"],
            "drain_handoffs": self.stats["drain_handoffs"],
            "migration_resumes": self.stats["migration_resumes"],
            # transfer-cost-aware placement surface (costmodel.py): the
            # worker's observed link bandwidths + corrected prefill
            # throughput + block geometry + slice identity — everything
            # the router needs to convert this worker's overlap depths
            # into predicted TTFT milliseconds
            "kv_block_bytes": self.kv_block_bytes,
            # tier/wire bytes per block under --kv-quant (== the full
            # width when the codec is off): what restore/pull legs
            # actually move, so the router prices them at these bytes
            "kv_wire_block_bytes": self.kv_wire_block_bytes,
            # the kv-quant quality gate's worst observed drift (set by
            # kvquant.measure_logprob_drift runs against this engine)
            "kv_quant_logprob_drift_max": self.stats[
                "kv_quant_logprob_drift_max"],
            "kv_block_size": self.cfg.block_size,
            "kv_slice_fp": self._slice_fp(),
            # the ACTUALLY-deployed TP degree: seeds the planner's
            # morph guard so a restarted planner reasons from the
            # pool's real layout instead of tp_min
            "mesh_tp": self.cfg.mesh.tp if self.cfg.mesh is not None else 1,
            # elastic-reshard surface: ``resharding`` marks the morph
            # window (the router soft-excludes this worker for it, like
            # ``draining`` but transient); the counters/gauges feed the
            # metrics component (resharded_total, reshard_hold_ms,
            # reshard_kv_moved_blocks)
            "resharding": int(self._resharding),
            "resharded_total": self.stats["resharded_total"],
            "reshard_hold_ms": self.stats["reshard_hold_ms"],
            "reshard_kv_moved_blocks": self.stats[
                "reshard_kv_moved_blocks"],
            "peer_serve_d2h_blocks_total": self.stats[
                "peer_serve_d2h_blocks"],
            "weight_prestage_requests": self.stats[
                "weight_prestage_requests"],
            "weight_prestage_bytes": self.stats["weight_prestage_bytes"],
            "weight_prestage_hits": self.stats["weight_prestage_hits"],
            # multi-model surface: every name this worker answers to
            # (base first, "" = legacy wildcard) — select_worker filters
            # on membership before scoring
            "served_models": self.served_models(),
            # int8-with-scales device-cache lane (zeros unless
            # kv_cache_dtype="int8"): resident quantized pages,
            # cumulative scale-growth requantizations, bytes the int8
            # landings saved vs full width, exports that paid a
            # requantize, and the lane's measured decode throughput
            "kv_device_quant_pages": self.stats["kv_device_quant_pages"],
            "kv_device_requants_total": self.stats[
                "kv_device_requants_total"],
            "kv_device_bytes_saved_total": self.stats[
                "kv_device_bytes_saved_total"],
            "kv_device_export_requant_total": self.stats[
                "kv_device_export_requant_total"],
            "lowprec_tok_s": self.stats["lowprec_tok_s"],
        } | (self.cost.counters() if self.cost is not None else {})

    def _register_device_executor(self) -> None:
        """Register the loop's default executor (every device dispatch
        rides ``run_in_executor(None, ...)``) for the sanitizer's
        executor-pressure surface. Lazy + idempotent: asyncio creates
        the default executor on first use, so the first scrape after
        real work picks it up; ``register_executor`` no-ops on repeats."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # scraped off-loop (tests constructing engines raw)
        # asyncio offers no public getter for the lazily-built default
        # executor; reading the private slot is the only non-invasive way
        # to observe it without forcing our own pool onto the loop
        ex = getattr(loop, "_default_executor", None)
        if ex is not None:
            sanitizer.register_executor(ex, "device")

    # ---------------- graceful drain (resilience/drain.py) ----------------

    async def drain(self, deadline_s: float = 10.0, handoff: bool = True) -> dict:
        """Stop admitting and retire in-flight work: requests get
        ``deadline_s`` to finish naturally; with ``handoff=True`` the
        stragglers (and everything still queued) are terminated with the
        migration signal so a migration-aware frontend resumes them on a
        surviving worker as prompt + tokens-so-far. ``handoff=False``
        waits for natural completion regardless of the deadline."""
        if not self._draining:
            self._draining = True
            self.stats["drains_total"] += 1
        self._drain_handoff = handoff
        self._drain_deadline = asyncio.get_running_loop().time() + deadline_s
        self._wake.set()
        handoffs_before = self.stats["drain_handoffs"]
        while (
            self._has_pending_work()
            and self._loop_task is not None
            and not self._closed
            and self._dead is None
        ):
            await asyncio.sleep(0.01)
        return {"handed_off": self.stats["drain_handoffs"] - handoffs_before}

    def _drain_tick(self) -> None:
        """One scheduler-loop pass of drain progress (runs at an
        iteration boundary, so it never races a device dispatch)."""
        if not self._drain_handoff:
            return
        # queued-but-unstarted work first: nothing is computed yet, so
        # the re-dispatch loses nothing — hand it back immediately
        while not self._waiting_is_empty():
            self._handoff_seq(self._pop_waiting())
        if asyncio.get_running_loop().time() < self._drain_deadline:
            return
        # deadline passed: hand off the stragglers still on the device.
        # _remote_ready waits until here too — its prefill + KV transfer
        # are already paid for, and admission keeps pulling it into the
        # batch while the drain window is open, so it can finish locally
        while self._remote_ready:
            self._handoff_seq(self._remote_ready.pop())
        for st in list(self._prefill_states):
            self.stats["drain_handoffs"] += 1
            self._abort_prefill(st, FinishReason.ERROR, text=MIGRATION_SIGNAL)
        for seq in list(self._active):
            if seq is not None and not seq.finished:
                self._handoff_seq(seq)

    def _handoff_seq(self, seq: "_Sequence") -> None:
        """Terminate one stream with the migration signal (tokens already
        emitted stay valid — the frontend splices the continuation)."""
        if seq.finished:
            return
        self.stats["drain_handoffs"] += 1
        seq.out_queue.put_nowait(
            LLMEngineOutput(
                finish_reason=FinishReason.ERROR, text=MIGRATION_SIGNAL
            )
        )
        self._finish(seq, FinishReason.ERROR, emit=False)

    # ---------------- elastic live resharding ----------------
    # (docs/elastic_resharding.md — quiesce / morph / resume)

    @staticmethod
    def _mesh_shape(mc: Optional[MeshConfig]) -> tuple:
        return (mc.dp, mc.pp, mc.sp, mc.ep, mc.tp) if mc is not None else ()

    async def reshard(
        self,
        mesh: Optional[MeshConfig],
        hold: bool = True,
        force: bool = False,
    ) -> dict:
        """Morph this engine's parallelism degree LIVE: re-lay weights
        and the paged KV pool onto ``mesh`` without dropping a token.

        Protocol: (1) the new layout's weights are PRE-STAGED off the
        hold window (PRESERVE-style — the move overlaps continued
        serving, since params are read-only to dispatch); (2) the
        scheduler loop quiesces at a step boundary (the pipelined
        window drains; the device lock serializes against disagg
        hooks), in-flight and queued requests are *held*, not handed
        off; (3) KV + penalty planes re-lay through the same compiled
        cross-mesh permutation programs (parallel/morph.MeshMorpher);
        (4) one assignment-only commit swaps every piece of device
        state plus ``self.mesh`` — a crash lands wholly before or
        wholly after it (the ``mid_reshard`` faultpoint phases walk
        exactly this matrix); (5) the loop resumes: RNG streams
        continue token-exactly because sampling keys fold_in(seed,
        generated) from host-side state the morph never touches, and
        penalty counts/masks moved bit-identically.

        ``hold=False`` hands off in-flight streams via the PR 4
        migration path instead of holding them (deadline-pressured
        requests; queued work is always held — it costs nothing).
        ``force=True`` re-lays even when the mesh shape is unchanged
        (absorbing a lost host: same logical shape, new device set).
        Multi-host mirrors raise :class:`ReshardUnsupported` — their
        callers drain-with-handoff instead.  Returns the morph stats
        dict ({"changed", "kv_moved_blocks", "hold_ms", ...})."""
        if self.mirror is not None:
            raise ReshardUnsupported(
                "multi-host mirrored engines cannot morph live; drain "
                "with handoff and restart on the new mesh instead"
            )
        if self._dead is not None:
            raise RuntimeError(self._dead)
        if self._closed:
            raise RuntimeError("engine closed")
        if self._reshard_busy:
            raise RuntimeError("a reshard is already in flight")
        same = self._mesh_shape(mesh) == self._mesh_shape(self.cfg.mesh)
        if same and not force:
            return {"changed": False, "kv_moved_blocks": 0, "hold_ms": 0.0}
        self.start()
        # claim the morph slot BEFORE the staging await: a second
        # reshard() racing through the checks above would otherwise
        # overwrite this one's posted request and park its caller on a
        # future nothing ever resolves
        self._reshard_busy = True
        t0 = time.perf_counter()
        self._resharding = True  # advertised: router soft-excludes now
        loop = asyncio.get_running_loop()
        try:
            # PRESERVE-style pre-stage: build the new mesh and move the
            # weights onto its layout while the engine keeps serving —
            # only the KV re-lay and the commit need the hold window
            new_mesh, staged = await loop.run_in_executor(
                None, self._stage_reshard, mesh
            )
            # the staging await dropped the loop: an engine closed (or
            # loop-crashed) meanwhile would never run _reshard_step, so
            # posting now would hang this caller forever
            if self._closed or self._dead is not None:
                raise RuntimeError(self._dead or "engine closed")
        except BaseException:
            self._resharding = False
            self._reshard_busy = False
            raise
        fut = loop.create_future()
        self._reshard_req = {
            "mesh_cfg": mesh,
            "new_mesh": new_mesh,
            "staged": staged,
            "hold": hold,
            "fut": fut,
            "t0": t0,
        }
        self._wake.set()
        return await fut

    def _stage_reshard(self, mesh_cfg: Optional[MeshConfig]):
        """Executor thread, NO device lock: resolve the logical weight
        layout against the target mesh and move the params there.
        Dispatch only ever reads params (the KV caches are the donated
        arrays), so staging overlaps live decode — the new layout's
        weight load never sits on the hold window."""
        from ..parallel.morph import MeshMorpher

        faultpoints.hit_sync("mid_reshard", phase="pre_stage")
        new_mesh = make_mesh(mesh_cfg) if mesh_cfg is not None else None
        if self.morpher is None:
            self.morpher = MeshMorpher()
        staged = self.morpher.apply_tree(
            self.params, self.layout.param_shardings(self.params, new_mesh)
        )
        jax.block_until_ready(staged)
        return new_mesh, staged

    async def _reshard_step(self) -> None:
        """One posted morph, run by the scheduler loop at an iteration
        boundary (so no dispatch is in flight) — quiesce, commit,
        resume. A failed morph leaves the engine wholly on the old
        layout and surfaces the error to the caller without killing the
        serving loop; a FaultInjected kill propagates (that IS the
        crash-mid-morph experiment)."""
        req = self._reshard_req
        fut = req["fut"]
        try:
            if not req["hold"]:
                # requests that cannot be held through the morph take
                # the PR 4 migration path NOW: tokens already delivered
                # stay valid, the frontend splices the continuation on
                # a worker that isn't morphing (the router is already
                # soft-excluding this one via the resharding flag)
                while self._remote_ready:
                    self._handoff_seq(self._remote_ready.pop())
                for st in list(self._prefill_states):
                    self.stats["drain_handoffs"] += 1
                    self._abort_prefill(
                        st, FinishReason.ERROR, text=MIGRATION_SIGNAL
                    )
                for seq in list(self._active):
                    if seq is not None and not seq.finished:
                        self._handoff_seq(seq)
            # a pipelined decode window still in flight would chain
            # tokens across the morph's program swap — drain it first
            await self._drain_inflight()
            t_hold = time.perf_counter()
            async with self._device_lock:
                out = await asyncio.get_running_loop().run_in_executor(
                    None, self._commit_reshard_device, req
                )
            out["hold_ms"] = round((time.perf_counter() - t_hold) * 1e3, 3)
            out["total_ms"] = round((time.perf_counter() - req["t0"]) * 1e3, 3)
            self.stats["reshard_hold_ms"] = out["hold_ms"]
            logger.info(
                "resharded to %s: %d KV blocks re-laid, hold %.1fms "
                "(total %.1fms)", out["mesh"], out["kv_moved_blocks"],
                out["hold_ms"], out["total_ms"],
            )
            if not fut.done():
                fut.set_result(out)
        except asyncio.CancelledError:
            fut.cancel()
            raise
        except FaultInjected as e:
            if not fut.done():
                fut.set_exception(e)
            raise
        except Exception as e:  # noqa: BLE001 — a failed morph must not
            # kill the serving loop; the engine stays on the old layout
            logger.exception("reshard failed; engine stays on old layout")
            if not fut.done():
                fut.set_exception(e)
        finally:
            self._reshard_req = None
            self._resharding = False
            self._reshard_busy = False

    def _commit_reshard_device(self, req: dict) -> dict:
        """Executor thread, device lock held, loop quiesced: re-lay the
        paged KV pool (+ penalty planes) onto the target layout, then
        commit everything in one assignment-only block. The two staging
        faultpoint phases sit BEFORE the block and the committed phase
        AFTER it — there is deliberately nothing fallible in between,
        which is what makes a mid-morph kill leave the engine on
        exactly one layout."""
        new_mesh = req["new_mesh"]
        m = self.morpher
        # the device-side requant accumulator (_note_quant_step) lives
        # on the OLD device set; fold it to the host stat now — the
        # loop is quiesced, so the one-scalar sync is free — or the
        # first post-morph dispatch would add an old-mesh scalar to a
        # new-mesh one and trip an incompatible-devices error
        self._fold_quant_counters()
        faultpoints.hit_sync("mid_reshard", phase="quiesced")
        cache_sh = self.layout.cache_sharding(new_mesh)
        new_k = m.apply(self.k_cache, cache_sh)
        new_v = m.apply(self.v_cache, cache_sh)
        rep = self.layout.replicated_sharding(new_mesh)
        new_pc = new_pm = None
        if self._pen_counts is not None:
            new_pc = m.apply(self._pen_counts, rep)
            new_pm = m.apply(self._pen_mask, rep)
        new_ks = new_vs = None
        if self.k_scales is not None:
            # int8 device cache: the scale planes ride the same morph
            # (replicated layout, page axis unsharded) so every re-laid
            # page keeps its bit-identical dequant scale
            new_ks = m.apply(self.k_scales, rep)
            new_vs = m.apply(self.v_scales, rep)
        # the staged state must be REAL (transfers landed) before the
        # commit claims the engine is on the new layout
        jax.block_until_ready(
            (new_k, new_v) if new_ks is None
            else (new_k, new_v, new_ks, new_vs)
        )
        # every fallible computation happens BEFORE the commit: the
        # dynflow commit-block-purity rule found _use_pallas_for being
        # called inside it — had that call raised, params/caches/mesh
        # would already have swapped while use_pallas (and the caller's
        # "engine stays on old layout" recovery) stayed stale: a torn
        # engine on neither layout
        new_use_pallas = self._use_pallas_for(new_mesh)
        new_params = req["staged"]
        new_mesh_cfg = req["mesh_cfg"]
        faultpoints.hit_sync("mid_reshard", phase="kv_staged")
        # dynflow: commit-block -- reshard layout swap (crash-atomicity)
        self.params = new_params
        self.k_cache, self.v_cache = new_k, new_v
        if new_pc is not None:
            self._pen_counts, self._pen_mask = new_pc, new_pm
        if new_ks is not None:
            self.k_scales, self.v_scales = new_ks, new_vs
        self.mesh = new_mesh
        self.cfg.mesh = new_mesh_cfg
        self.use_pallas = new_use_pallas
        # dynflow: end-commit-block
        moved = self.allocator.resident_count
        self.stats["resharded_total"] += 1
        self.stats["reshard_kv_moved_blocks"] += moved
        # SLO observatory invalidation: every jit program recompiles
        # under the new shardings on its next dispatch — clearing the
        # compiled-key set keeps the compile ledger seeing (and tracing)
        # those post-morph stalls instead of treating them as warm; the
        # weight-bytes attribution re-derives from the new params
        self._compiled_keys.clear()
        self._weight_bytes = None
        # ---- committed ----
        faultpoints.hit_sync("mid_reshard", phase="committed")
        return {
            "changed": True,
            "kv_moved_blocks": moved,
            "mesh": "x".join(map(str, self._mesh_shape(req["mesh_cfg"])))
                    or "unsharded",
            "morph_programs": m.programs(),
        }

    # ---------------- scheduler loop ----------------

    async def _loop(self) -> None:
        try:
            while not self._closed:
                if self._draining:
                    self._drain_tick()
                if self._reshard_req is not None:
                    await self._reshard_step()
                    continue
                admitted = await self._admit()
                if (
                    self._n_active == 0
                    and not admitted
                    and not self._prefill_states
                ):
                    # drop a stale pipelined window before going idle (its
                    # participants all finished; tokens are discards)
                    await self._drain_inflight()
                    # the drain AWAITED (device sync): requests that
                    # arrived during it already called _wake.set() — a
                    # blind clear() here erases their wakeup and the
                    # loop sleeps on a non-empty queue forever (the
                    # pipelined-decode deadlock tests/test_engine.py
                    # pins). Re-check before AND after the clear; the
                    # after-clear check has no awaits in between, so a
                    # concurrent set() is always observed by wait().
                    if self._has_pending_work():
                        continue
                    self._wake.clear()
                    if self._has_pending_work():
                        continue
                    await self._wake.wait()
                    continue
                # a multi-prompt prefill pack with no decode batch is
                # still a fused dispatch (_mixed_fusable covers it) —
                # the queued prompts advance TOGETHER instead of
                # head-of-line blocking behind states[0]
                if self._n_active or self._mixed_fusable():
                    await self._decode_once()
                # yield to the event loop so emissions flush
                await asyncio.sleep(0)
        except asyncio.CancelledError:
            # engine close() with sequences in flight: fail them — their
            # generate() coroutines block on out_queue forever otherwise,
            # and an ingress that gets cancelled around that block would
            # hand callers silently-truncated streams
            self._fail_all_owned()
        except FaultInjected as e:
            # the harness killed this worker mid-step: mark the engine
            # dead and abort every owned stream with the worker-lost
            # signature, exactly what a real death looks like through the
            # transport — the migration layer re-dispatches them all
            logger.warning("engine killed by fault point: %s", e)
            self._dead = str(e)
            self._fail_all_owned(text=str(e))
        except Exception:  # noqa: BLE001
            logger.exception("engine loop crashed")
            # a dead scheduler must not accept (and silently park) new
            # requests: fail fast with a retryable signature
            self._dead = "engine stopped: scheduler loop crashed"
            self._fail_all_owned(text=self._dead)

    def _has_pending_work(self) -> bool:
        """Anything the idle scheduler must NOT sleep on."""
        return bool(
            self._waiting_front
            or not self._waiting.empty()
            or self._remote_ready
            or self._n_active
            or self._prefill_states
        )

    def _fail_all_owned(self, text: Optional[str] = None) -> None:
        """ERROR-terminate every request this engine owns — active,
        mid-prefill, and still-waiting. ``text`` rides the terminal chunk
        (a worker-lost signature there lets the migration layer pick the
        streams up instead of surfacing errors)."""
        if self._reshard_req is not None:
            # a morph awaiting the loop must fail WITH the loop, not
            # park its caller forever
            fut = self._reshard_req["fut"]
            if not fut.done():
                fut.set_exception(RuntimeError(text or "engine stopped"))
            self._reshard_req = None
            self._resharding = False
            self._reshard_busy = False
        in_prefill = [st.seq for st in self._prefill_states]
        for seq in self._active + self._remote_ready + in_prefill:
            if seq is not None:
                seq.out_queue.put_nowait(
                    LLMEngineOutput(finish_reason=FinishReason.ERROR, text=text)
                )
        self._remote_ready.clear()
        self._prefill_states.clear()
        while self._waiting_front or not self._waiting.empty():
            seq = self._pop_waiting()
            seq.out_queue.put_nowait(
                LLMEngineOutput(finish_reason=FinishReason.ERROR, text=text)
            )

    # ---- admission ----

    def _waiting_is_empty(self) -> bool:
        return not self._waiting_front and self._waiting.empty()

    def _waiting_size(self) -> int:
        return len(self._waiting_front) + self._waiting.qsize()

    def _pop_waiting(self) -> "_Sequence":
        if self._waiting_front:
            return self._waiting_front.popleft()
        return self._waiting.get_nowait()

    async def _admit(self) -> bool:
        admitted = False
        # re-derived every scheduler iteration; True means the head of the
        # waiting queue can't get blocks right now, so waiting requests are
        # NOT actionable admission work and decode-window fusion stays on
        self._backpressured = False
        while self._remote_ready and self._n_active < self.cfg.max_batch_size:
            seq = self._remote_ready.pop(0)
            if seq.finished:
                continue
            if seq.context.is_stopped():
                self._finish(seq, FinishReason.CANCELLED)
                continue
            self._place_in_batch(seq)
            admitted = True
        # advance an in-flight chunked prefill by exactly one chunk per
        # iteration. With mixed batching OFF (or nothing to fuse with)
        # that's a dedicated prefill dispatch here; when chunks can FUSE
        # into the running batch's decode step (or into each other —
        # multi-prompt packs dispatch even with no decode batch),
        # _decode_once dispatches one mixed step instead — decode
        # streams never stall a full chunk's device time behind a
        # separate dispatch, and queued prompts advance together
        if self._prefill_states and not self._mixed_fusable():
            admitted |= await self._prefill_step()
        while (
            len(self._prefill_states) < self._prefill_limit()
            and self._n_active + len(self._prefill_states)
            < self.cfg.max_batch_size
            and (self._waiting_front or not self._waiting.empty())
        ):
            seq = self._pop_waiting()
            # a ring-routed prompt owns its whole dispatch (sequence-
            # parallel one-shot prefill) — never pack IT behind other
            # in-flight prefills; it waits for the states list to clear
            # and runs alternating (where _mixed_fusable defers to it)
            if self._prefill_states and self._could_ring(seq):
                self._waiting_front.appendleft(seq)
                break
            if seq.context.is_stopped():
                seq.out_queue.put_nowait(LLMEngineOutput(finish_reason=FinishReason.CANCELLED))
                continue
            prompt_hashes = None
            if self.offload is not None and self.offload.async_tier and (
                self.offload.has_pending()
                or self.offload.has_inflight_flushes()
            ):
                # land any in-flight d2h holding this prompt's chain
                # off-loop, so _begin_prefill's host probe never blocks
                # the scheduler on a transfer; the chain is computed once
                # and handed down so admission doesn't re-hash the prompt
                prompt_hashes = sequence_block_hashes(
                    seq.tokens[: seq.seq_len - 1], self.cfg.block_size,
                    salt=model_hash_salt(seq.model),
                )
                await self._offload_prejoin(
                    [s for _l, s in prompt_hashes]
                )
            try:
                ok = self._begin_prefill(seq, hashes=prompt_hashes)
            except Exception:  # noqa: BLE001
                # device failure on THIS request (oom, compile error): fail
                # it alone — the loop and other requests keep going
                logger.exception("prefill failed for request %s", seq.context.id)
                self.allocator.free(seq.blocks)
                seq.blocks = []
                seq.out_queue.put_nowait(
                    LLMEngineOutput(finish_reason=FinishReason.ERROR)
                )
                continue
            if ok and self._mixed_fusable():
                # first chunk rides the next fused step; keep admitting
                # more queued prompts into the pack (up to the limit —
                # the while condition) so the budget splits across them
                continue
            if not ok:
                # A sequence whose minimum reservation exceeds the whole
                # pool can never admit (e.g. preempted late with a grown
                # token list, or an oversized prompt) — finish it rather
                # than head-of-line-block the queue forever.
                bs = self.cfg.block_size
                min_needed = min(
                    (seq.seq_len + bs) // bs + 1, self.cfg.max_blocks_per_seq
                )
                if min_needed > self.allocator.num_blocks - 1:
                    # a fresh prompt that can never fit is a capacity ERROR
                    # (like prompts >= max_context); a preempted sequence
                    # that outgrew the pool already streamed real tokens,
                    # so it ends as an honest LENGTH truncation
                    reason = (
                        FinishReason.LENGTH if seq.generated
                        else FinishReason.ERROR
                    )
                    logger.warning(
                        "request %s needs %d blocks but the pool holds %d — "
                        "finishing as %s",
                        getattr(seq.context, "id", "?"), min_needed,
                        self.allocator.num_blocks - 1, reason,
                    )
                    self._finish(seq, reason)
                    continue
                # out of KV blocks: put back and stop admitting (backpressure)
                self._waiting_front.appendleft(seq)
                self._backpressured = True
                break
            admitted |= await self._prefill_step()
        self.stats["requests_active"] = self._n_active
        self.stats["requests_waiting"] = self._waiting_size()
        return admitted

    def _tokens_in_vocab(self, ids) -> bool:
        V = self.cfg.model.vocab_size
        return all(0 <= t < V for t in ids)

    def _prefill_limit(self) -> int:
        """How many chunked prefills may be in flight at once: mixed
        batching packs up to ``mixed_max_prefills`` prompts per fused
        step; the alternating scheduler (mixed off, multi-host mirror)
        keeps the single-prefill discipline."""
        if not self.cfg.mixed_batch or self.mirror is not None:
            return 1
        return self.cfg.mixed_max_prefills

    def _could_ring(self, seq: _Sequence) -> bool:
        """Pre-reservation screen for ring routing: would this prompt
        plausibly take the sp ring-attention path at pos 0? Used only to
        keep ring prompts OUT of multi-prefill packs (the ring dispatch
        is whole-prompt, not chunk-wise). Mirrors ``_ring_chunk``'s
        model-family exclusions — a family that can never ring
        (sliding-window, gpt-oss windows/sinks, gemma-2 softcap) must
        not have its long prompts barred from packing; the authoritative
        per-chunk check stays ``_ring_chunk`` (only the cached-prefix
        pos!=0 and bucket-divisibility terms are unknowable here, and
        over-matching on those merely under-packs)."""
        cfg = self.cfg
        return (
            cfg.ring_prefill_threshold > 0
            and self.mesh is not None
            and self.mesh.shape.get("sp", 1) > 1
            and len(seq.tokens) >= cfg.ring_prefill_threshold
            and cfg.model.sliding_window == 0
            and not cfg.model.layer_windows
            and not cfg.model.attn_sinks
            and not cfg.model.attn_softcap
        )

    def _reserve_for_prompt(self, seq: _Sequence, probe_host: bool = False,
                            hashes=None):
        """The one allocation protocol shared by local prefill, remote
        prefill (worker side) and remote decode (decode side): match the
        device prefix cache on the prompt's full blocks (always recompute
        the final token so prefill yields fresh last-position logits),
        optionally probe the host offload tier for the chain's
        continuation — the reserved chain starts its h2d upload HERE, so
        by the time the prefill chunk needs the pages the transfer has
        (usually) already landed — then allocate fresh blocks for prompt
        + decode headroom. Populates seq.{blocks,committed,parent_hash,
        cached_prefix}; returns (history, upload_or_None) or None with
        every claim rolled back."""
        cfg = self.cfg
        bs = cfg.block_size
        prompt = seq.tokens
        # ``hashes`` may carry the chain the caller already computed
        # (admission's prejoin) so long prompts hash once, not twice
        # the adapter's name salts the chain root (allocator.
        # model_hash_salt): a token-identical prompt under two models
        # hashes to disjoint chains, so cross-model prefix hits are
        # structurally impossible — here, in the reuse pool, and on
        # every plane that speaks these hashes (radix index, peer pulls)
        all_hashes = hashes if hashes is not None else (
            sequence_block_hashes(
                prompt[: len(prompt) - 1], bs,
                salt=model_hash_salt(seq.model),
            )
        )
        matched = self.allocator.match_prefix(
            prompt[: len(prompt) - 1], hashes=all_hashes
        )
        if self.offload is not None and matched:
            # blocks that reached the device tier via a router prefetch
            # hint and are now claimed: the hint saved this request a
            # cold host restore (or a full recompute). The claimed
            # hashes ride along so peer-pulled blocks count toward
            # peer_pull_hidden_frac (their cross-worker transfer was
            # fully hidden from this request)
            n_pf = 0
            pf_hashes = []
            for b in matched:
                if b.prefetched:
                    b.prefetched = False
                    n_pf += 1
                    pf_hashes.append(b.seq_hash)
            if n_pf:
                self.offload.note_prefetch_hits(n_pf, hashes=pf_hashes)
        # host-tier probe: continuation of the chain past the device match
        # (ref docs/kv_cache_manager.md host offload); reserving takes the
        # blocks out of the pool so they can't be LRU'd before restore
        restore_hashes: list[int] = []
        restore_data: list = []
        if probe_host and self.offload is not None:
            tail = [s for _l, s in all_hashes[len(matched) :]]
            restore_hashes, restore_data = self.offload.reserve_chain(tail)
        total_needed = min(
            (len(prompt) + bs) // bs + 1, cfg.max_blocks_per_seq
        )
        fresh = self.allocator.allocate(max(0, total_needed - len(matched)))
        if fresh is None:
            self.allocator.free(matched)
            if self.offload is not None and restore_hashes:
                self.offload.unreserve(restore_hashes, restore_data)
            return None
        seq.blocks = matched + fresh
        seq.committed = len(matched)
        # no device match: the chain restarts from its model-salted root
        # (None for base traffic — byte-identical to pre-multi-model)
        seq.parent_hash = (
            matched[-1].seq_hash if matched
            else model_hash_salt(seq.model)
        )
        history = (len(matched) + len(restore_hashes)) * bs
        seq.cached_prefix = history
        upload = None
        if self.offload is not None and restore_hashes:
            upload = self.offload.begin_upload(
                restore_hashes, restore_data,
                [b.idx for b in fresh[: len(restore_hashes)]],
            )
        return history, upload

    def _begin_prefill(self, seq: _Sequence, hashes=None) -> bool:
        """Reserve blocks + prefix/host-tier claims and queue the sequence
        as the in-flight chunked prefill. Returns False on pool pressure."""
        reserved = self._reserve_for_prompt(seq, probe_host=True, hashes=hashes)
        if reserved is None:
            return False
        history, upload = reserved
        self.stats["prefix_cache_hits_tokens"] += history
        if seq.generated == 0:
            # admission latency: arrival -> blocks reserved, reconstructed
            # backwards so the span's start anchors at arrival time. A
            # preemption REPLAY (generated > 0) is post-first-token work:
            # re-recording would overlap the original span and break the
            # decomposition's sum-to-TTFT contract
            waited_s = time.monotonic() - seq.arrival_t
            self.hist["queue_wait_ms"].observe(waited_s * 1e3)
            if seq.trace is not None:
                tracing.RECORDER.record_span(
                    "engine.queue_wait", seq.trace,
                    ts=time.time() - waited_s, dur_ms=waited_s * 1e3,
                    request_id=seq.context.id,
                    waiting=self._waiting_size(),
                )
        self._prefill_states.append(
            _PrefillState(seq=seq, pos=history, upload=upload)
        )
        return True

    async def _prefill_step(self) -> bool:
        """Run ONE prefill chunk of the OLDEST in-flight sequence (the
        alternating path only ever holds one); on the final chunk,
        sample the first token and join the decode batch. Returns True
        when the sequence was admitted (prefill completed)."""
        assert self._prefill_states
        st = self._prefill_states[0]
        faultpoints.hit_sync("mid_prefill", request_id=st.seq.context.id)
        seq = st.seq
        if seq.context.is_stopped():
            # hand reserved host blocks back even mid-upload (the upload
            # only READS the host arrays, so re-pooling is safe) — same
            # as the error path below; dropping them would leak the
            # cached prefix
            self._abort_prefill(st, FinishReason.CANCELLED)
            return False
        # device work (jit dispatch + compile + host sync) runs in a worker
        # thread so lease keepalives / bus traffic stay live on the loop
        try:
            async with self._device_lock:
                first_token = await asyncio.get_running_loop().run_in_executor(
                    None, self._prefill_chunk_device, st
                )
        except Exception:
            # device failure: hand reserved host blocks back so the prefix
            # isn't silently lost from the offload tier (host arrays are
            # never mutated, so re-pooling is safe even mid-upload)
            logger.exception("prefill failed for request %s", seq.context.id)
            self._abort_prefill(st, FinishReason.ERROR)
            return False
        if first_token is None:
            return False  # more chunks to go
        first_token, first_lp = first_token
        if seq.generated == 0:
            # first prefill only — a preemption replay's prefill is
            # post-first-token and must not re-enter the decomposition
            self.hist["prefill_ms"].observe(st.dev_ms)
            if seq.trace is not None:
                tracing.RECORDER.record_span(
                    "engine.prefill", seq.trace, ts=st.t0_wall,
                    dur_ms=st.dev_ms,
                    request_id=seq.context.id,
                    prompt_tokens=seq.prompt_len,
                    cached_prefix=seq.cached_prefix,
                )
        self._drop_prefill_state(st)
        self._commit_full_blocks(seq)
        self._emit_token(seq, first_token, first_lp)
        if not seq.finished:
            if self._n_active < self.cfg.max_batch_size:
                self._place_in_batch(seq)
            else:
                # slots filled mid-prefill (a remote-ready admission took
                # the last one): the KV is landed, so queue for the next
                # free slot exactly like a remotely-prefilled sequence —
                # an unconditional placement would index(None) on a full
                # batch and crash the scheduler loop
                self._remote_ready.append(seq)
        return True

    def _drop_prefill_state(self, st: "_PrefillState") -> None:
        if st in self._prefill_states:
            self._prefill_states.remove(st)

    def _abort_prefill(
        self, st: "_PrefillState", reason: FinishReason,
        text: Optional[str] = None,
    ) -> None:
        """The one teardown for an in-flight prefill — cancellation AND
        device failure, alternating AND mixed paths: drop the state,
        free the sequence's blocks, hand the reserved host chain back
        (_rollback_upload), and terminate the stream. The call sites
        share it so the rollback protocol cannot drift between them;
        ``text`` lets the drain handoff stamp the migration signal."""
        seq = st.seq
        self._drop_prefill_state(st)
        self.allocator.free(seq.blocks)
        seq.blocks = []
        self._rollback_upload(st)
        seq.out_queue.put_nowait(
            LLMEngineOutput(finish_reason=reason, text=text)
        )

    def _rollback_upload(self, st: _PrefillState) -> None:
        """Shared cancel/error rollback for a prefill's reserved host
        chain: record the abandoned upload (if it never landed) and
        return the entries to the pool — the one protocol both paths
        must not drift apart on."""
        if self.offload is None or st.upload is None:
            return
        if not st.restored:
            self.offload.cancel_upload(st.upload)
        self.offload.unreserve(
            st.upload.hashes, st.upload.data, restored=st.restored
        )

    def _prefill_chunk_device(self, st: _PrefillState) -> Optional[int]:
        """Runs in an executor thread: one bucketed prefill chunk. Returns
        the sampled first token on the final chunk, else None."""
        t0 = time.perf_counter()
        try:
            self._offload_preamble(
                st.upload if not st.restored else None, seq=st.seq
            )
            st.restored = True
            p0, t_c = st.pos, time.perf_counter()
            logits, st.pos = self._run_one_chunk(st.seq, st.pos)
            if self.cost is not None and st.pos > p0:
                # measured chunk timing = the observation that corrects
                # the cost model's modeled prefill throughput
                self.cost.observe_prefill(
                    st.pos - p0, max(time.perf_counter() - t_c, 1e-9)
                )
            if st.pos < len(st.seq.tokens):
                return None
            return self._sample_prefill(st.seq, logits)  # (token, lp_entry)
        finally:
            # accumulate DEVICE time only: chunks of a long prompt
            # interleave with other requests' decode steps, so the
            # traced prefill component must not absorb that wall time
            st.dev_ms += (time.perf_counter() - t0) * 1e3

    def _offload_preamble(self, upload=None, seq: Optional[_Sequence] = None) -> None:
        """Dispatch d2h gathers for every pending eviction before this
        prefill overwrites their pages (the fetch lands in background —
        budget=None takes all pending because a prefill may write any
        freshly allocated page), then land the reserved chain's h2d
        upload: a cheap on-device scatter that waits only if the upload
        begun at reservation hasn't arrived yet. With a traced ``seq``,
        the restore's hidden-vs-exposed split (PR 1's accounting) is
        recorded as this request's ``engine.kv_restore`` span."""
        if self.offload is None:
            return
        self.offload.flush_evictions_async(self.k_cache, self.v_cache)
        if upload is not None:
            t0 = time.perf_counter()
            self.k_cache, self.v_cache = self.offload.finish_upload(
                self.k_cache, self.v_cache, upload
            )
            self.hist["restore_ms"].observe((time.perf_counter() - t0) * 1e3)
            if seq is not None and seq.trace is not None and seq.generated == 0:
                waited_ms = (time.perf_counter() - t0) * 1e3
                t_landed = getattr(upload, "t_landed", None)
                total_ms = (
                    max((t_landed - upload.t_start) * 1e3, 0.0)
                    if t_landed is not None else waited_ms
                )
                exposed_ms = min(waited_ms, total_ms)
                tracing.RECORDER.record_span(
                    "engine.kv_restore", seq.trace,
                    ts=time.time() - waited_ms / 1e3, dur_ms=waited_ms,
                    request_id=seq.context.id,
                    blocks=len(upload.hashes),
                    # restore volume: lets ttft.cost_observations replay
                    # this span into a TransferCostModel ("host" class);
                    # wire bytes — what the h2d actually moved under
                    # --kv-quant
                    nbytes=len(upload.hashes) * self.kv_wire_block_bytes,
                    exposed_ms=round(exposed_ms, 3),
                    hidden_ms=round(max(total_ms - exposed_ms, 0.0), 3),
                )

    def _flush_scale_resets(self) -> None:
        """int8 device cache: reset the scale-plane entries of every
        page the allocator recycled since the last dispatch (queued by
        its ``on_allocated`` hook), as ONE scatter riding the next
        write dispatch's preamble. Idx count pads to the power-of-two
        bucket with the trash page 0 so the scatter's program count
        stays bucket-bounded."""
        if self.k_scales is None or not self._pending_scale_resets:
            return
        idxs = np.unique(
            np.asarray(self._pending_scale_resets, np.int32)
        )
        self._pending_scale_resets.clear()
        padded = np.zeros(_bucket(len(idxs)), np.int32)
        padded[: len(idxs)] = idxs
        self.k_scales, self.v_scales = _reset_scale_entries(
            self.k_scales, self.v_scales, jnp.asarray(padded)
        )

    def _note_quant_step(
        self, n_requants, tokens_written: int, gen_tokens: int = 0
    ) -> None:
        """Fold one quantized dispatch's outcome into the lane gauges.
        ``n_requants`` (the device-computed count of (layer, page) scale
        entries that grew) stays a DEVICE scalar — it accumulates
        asynchronously and only converts at scrape time
        (_fold_quant_counters), so pipelined decode never syncs on it.
        ``gen_tokens`` > 0 (decode dispatches) feeds the measured
        lane-throughput EMA behind ``lowprec_tok_s``."""
        self._requants_dev = (
            n_requants if self._requants_dev is None
            else self._requants_dev + n_requants
        )
        self.stats["kv_device_bytes_saved_total"] += (
            tokens_written * self._kv_saved_per_token
        )
        self.stats["kv_device_quant_pages"] = self.allocator.resident_count
        if gen_tokens > 0:
            now = time.perf_counter()
            dt = now - self._lowprec_rate_t
            if self._lowprec_rate_t and 0 < dt < 10.0:
                inst = gen_tokens / dt
                prev = self.stats["lowprec_tok_s"]
                self.stats["lowprec_tok_s"] = round(
                    inst if prev == 0.0 else 0.8 * prev + 0.2 * inst, 3
                )
            self._lowprec_rate_t = now

    def _fold_quant_counters(self) -> None:
        """Convert the accumulated device-side requant counter into the
        host stat (one scalar d2h; called from load_metrics scrapes),
        and fold the offload manager's export-bounce count (blocks that
        had to leave the device codec for a full-width/fp8 tier) into
        the export-requant gauge."""
        if self._requants_dev is not None:
            self.stats["kv_device_requants_total"] += int(self._requants_dev)
            self._requants_dev = None
        if self.offload is not None and self.k_scales is not None:
            cur = self.offload.device_requants_total
            self.stats["kv_device_export_requant_total"] += (
                cur - self._offload_requants_seen
            )
            self._offload_requants_seen = cur

    def _ring_chunk(self, seq: _Sequence, pos: int) -> bool:
        """Route THIS chunk through sp ring attention? History-free
        first chunk of a long-enough prompt on an sp>1 mesh, full
        attention (the whole prompt becomes one ring chunk). MLA models
        ride a latent ring — the rotated chunk is the compressed
        (c_kv, k_pe) stream."""
        cfg = self.cfg
        if (
            cfg.ring_prefill_threshold <= 0
            or pos != 0
            or self.mesh is None
            or self.mesh.shape.get("sp", 1) <= 1
            # int8 device cache: ring writes land full-width (no scale
            # stream through the rotated chunks) — paged path only
            or self.k_scales is not None
            or len(seq.tokens) < cfg.ring_prefill_threshold
            or cfg.model.sliding_window != 0
            or cfg.model.layer_windows  # per-layer windows (gpt-oss)
            or cfg.model.attn_sinks  # sinks live in the paged XLA paths
            or cfg.model.attn_softcap  # gemma-2 caps: paged XLA paths
        ):
            return False
        # bucket sizes are powers of two >= sp, so T % sp == 0 holds
        return _bucket(len(seq.tokens)) % self.mesh.shape["sp"] == 0

    # ---- multi-LoRA dispatch plumbing ----
    # With a registry configured, EVERY dispatch carries the full device
    # stack + per-row adapter ids — base rows get exact +0.0 deltas
    # (ops/lora.py) — so mixed-adapter and solo-adapter traffic run the
    # SAME compiled programs and program counts key on the registry's
    # (count, rank) buckets, never the live request mixture. Fleets
    # without --adapters return {} and the programs are byte-identical
    # to pre-multi-model builds.

    def _lora_prefill_kw(self, adapter_id: int) -> dict:
        if self.adapters is None:
            return {}
        return {
            "lora": self.adapters.device_stack(),
            "adapter_id": jnp.int32(adapter_id),
        }

    def _lora_decode_kw(self) -> dict:
        if self.adapters is None:
            return {}
        return {
            "lora": self.adapters.device_stack(),
            "adapter_ids": jnp.asarray(self._adapter_ids),
        }

    def _lora_key(self) -> tuple:
        """Compile-key suffix: the registry's static bucket pair (or
        empty — base fleets keep their exact historical key tuples)."""
        if self.adapters is None:
            return ()
        return (("lora", self.adapters.count_bucket,
                 self.adapters.rank_bucket),)

    def _run_one_chunk(self, seq: _Sequence, pos: int):
        """One bucketed prefill chunk at ``pos``; returns (logits, new_pos)."""
        cfg = self.cfg
        ring = self._ring_chunk(seq, pos)
        # ring: the WHOLE prompt is one sequence-parallel chunk
        chunk = seq.tokens[pos:] if ring else (
            seq.tokens[pos : pos + cfg.prefill_chunk]
        )
        T = _bucket(len(chunk))
        toks = np.zeros(T, np.int32)
        toks[: len(chunk)] = chunk
        if self.mirror is not None:
            logits, self.k_cache, self.v_cache = self._pallas_guard(
                lambda: self.mirror.lead_prefill(
                    self.params, toks, self._table_for(seq), pos,
                    len(chunk), self.k_cache, self.v_cache,
                    use_pallas=self.use_pallas, use_ring=ring,
                ),
                key=("prefill", T, ring), trace=seq.trace,
            )
            return logits, pos + len(chunk)
        # table must cover padded chunk; _table_for pads with trash 0
        if self.k_scales is not None:
            self._flush_scale_resets()
            out = self._pallas_guard(
                lambda: llama.prefill(
                    self.params,
                    cfg.model,
                    jnp.asarray(toks),
                    jnp.asarray(self._table_for(seq)),
                    jnp.int32(pos),
                    jnp.int32(len(chunk)),
                    self.k_cache,
                    self.v_cache,
                    use_pallas=self.use_pallas,
                    mesh=self.mesh,
                    use_ring=ring,
                    k_scales=self.k_scales,
                    v_scales=self.v_scales,
                    **self._lora_prefill_kw(seq.adapter_id),
                ),
                key=("prefill", T, ring) + self._lora_key(),
                trace=seq.trace,
            )
            (logits, self.k_cache, self.v_cache,
             self.k_scales, self.v_scales) = out
            self._note_quant_step(0, len(chunk))
            return logits, pos + len(chunk)
        logits, self.k_cache, self.v_cache = self._pallas_guard(
            lambda: llama.prefill(
                self.params,
                cfg.model,
                jnp.asarray(toks),
                jnp.asarray(self._table_for(seq)),
                jnp.int32(pos),
                jnp.int32(len(chunk)),
                self.k_cache,
                self.v_cache,
                use_pallas=self.use_pallas,
                mesh=self.mesh,
                use_ring=ring,
                **self._lora_prefill_kw(seq.adapter_id),
            ),
            key=("prefill", T, ring) + self._lora_key(),
            trace=seq.trace,
        )
        return logits, pos + len(chunk)

    def _prefill_device(
        self,
        seq: _Sequence,
        history: int,
        upload=None,
    ) -> tuple[int, Optional[dict]]:
        """Runs in an executor thread: whole-prompt chunked prefill +
        first-token sample (the disagg prefill-worker path, which owns the
        device for the whole prompt — the serving loop uses the chunk-at-a-
        time _prefill_chunk_device instead). Returns (token, logprob
        entry or None) — the entry rides the KV transfer so a logprobs
        request served via remote prefill doesn't lose its first token's
        logprobs (advisor r2)."""
        self._offload_preamble(upload, seq=seq)
        logits = None
        pos = history
        while pos < len(seq.tokens):
            p0, t_c = pos, time.perf_counter()
            logits, pos = self._run_one_chunk(seq, pos)
            if self.cost is not None and pos > p0:
                self.cost.observe_prefill(
                    pos - p0, max(time.perf_counter() - t_c, 1e-9)
                )
        return self._sample_prefill(seq, logits)

    def _table_for(self, seq: _Sequence) -> np.ndarray:
        t = np.zeros(self.cfg.max_blocks_per_seq, np.int32)
        for i, b in enumerate(seq.blocks[: self.cfg.max_blocks_per_seq]):
            t[i] = b.idx
        return t

    def _sample_prefill(self, seq: _Sequence, logits):
        """Sample the first token from the prefill logits; returns
        (token, logprob_entry_or_None). Full penalty semantics: the
        prompt mask AND output counts rebuild from the sequence's token
        lists, so the replay-after-preemption first token draws from the
        same distribution a decode window would use."""
        so = seq.request.sampling_options
        temp = so.temperature if so.temperature is not None else 1.0
        if getattr(seq.request, "greedy", False):
            temp = 0.0
        V = self.cfg.model.vocab_size

        def pad(ids):
            out = np.full(_bucket(max(len(ids), 1)), V, np.int32)
            out[: len(ids)] = ids
            return out

        prompt_p = pad(seq.tokens[: seq.prompt_len])
        gen_p = pad(seq.tokens[seq.prompt_len :])
        if self.mirror is not None:
            token = self.mirror.lead_sample1(
                logits, (so.seed or 0) & 0x7FFFFFFF, seq.generated, temp,
                so.top_k or 0, so.top_p if so.top_p is not None else 1.0,
                freq=so.frequency_penalty or 0.0,
                pres=so.presence_penalty or 0.0,
                rep=so.repetition_penalty or 1.0,
                prompt_ids=prompt_p, gen_ids=gen_p,
            )
            entry = None
            k = min(so.logprobs, 20) if so.logprobs is not None else -1
            if k >= 0:
                # read the leader's LOCAL shard (replicated => complete);
                # jax.device_get on a multiprocess array would wait on a
                # collective the followers never join
                row = np.asarray(logits.addressable_data(0), np.float64)
                row = row - row.max()
                row = row - np.log(np.exp(row).sum())
                top = np.argsort(row)[::-1][:k]
                entry = {
                    "logprob": float(row[token]),
                    "top": [[int(i), float(row[i])] for i in top],
                }
            return token, entry
        keys = make_keys(
            jnp.asarray([(so.seed or 0) & 0x7FFFFFFF]),
            jnp.asarray([seq.generated]),
        )
        tok = _sample_first_jit(
            logits[None, :],
            keys,
            jnp.asarray([temp], jnp.float32),
            jnp.asarray([so.top_k or 0], jnp.int32),
            jnp.asarray([so.top_p if so.top_p is not None else 1.0], jnp.float32),
            jnp.asarray([so.frequency_penalty or 0.0], jnp.float32),
            jnp.asarray([so.presence_penalty or 0.0], jnp.float32),
            jnp.asarray([so.repetition_penalty or 1.0], jnp.float32),
            jnp.asarray(prompt_p),
            jnp.asarray(gen_p),
        )
        token = int(jax.device_get(tok)[0])
        entry = None
        k = min(so.logprobs, 20) if so.logprobs is not None else -1
        if k >= 0:
            from ..ops.sampling import token_logprobs

            chosen, top_ids, top_lps = token_logprobs(
                jnp.asarray(logits)[None].astype(jnp.float32),
                jnp.asarray([token], jnp.int32),
            )
            ids = np.asarray(jax.device_get(top_ids))[0]
            lps = np.asarray(jax.device_get(top_lps))[0]
            entry = {
                "logprob": float(jax.device_get(chosen)[0]),
                "top": [[int(ids[j]), float(lps[j])] for j in range(k)],
            }
        return token, entry

    def _place_in_batch(self, seq: _Sequence) -> None:
        slot = self._active.index(None)
        seq.slot = slot
        self._active[slot] = seq
        self._n_active += 1
        so = seq.request.sampling_options
        self._block_tables[slot] = self._table_for(seq)
        self._seq_lens[slot] = seq.seq_len
        self._last_tokens[slot] = seq.tokens[-1]
        # mask into int32 range: PRNG seeds only need entropy, not magnitude
        self._seeds[slot] = (so.seed or 0) & 0x7FFFFFFF
        self._temps[slot] = so.temperature if so.temperature is not None else 1.0
        self._top_ks[slot] = so.top_k or 0
        self._top_ps[slot] = so.top_p if so.top_p is not None else 1.0
        self._freq_pens[slot] = so.frequency_penalty or 0.0
        self._pres_pens[slot] = so.presence_penalty or 0.0
        self._rep_pens[slot] = so.repetition_penalty or 1.0
        self._logprob_ks[slot] = (
            min(so.logprobs, 20) if so.logprobs is not None else -1
        )
        self._adapter_ids[slot] = seq.adapter_id
        if self._slot_has_penalty(slot):
            self._reset_penalty_slot(slot, seq)

    def _slot_has_penalty(self, i: int) -> bool:
        return (
            self._freq_pens[i] != 0.0
            or self._pres_pens[i] != 0.0
            or self._rep_pens[i] != 1.0
        )

    def _penalties_active(self) -> bool:
        return self._pen_counts is not None and any(
            self._slot_has_penalty(i)
            for i, s in enumerate(self._active) if s is not None
        )

    def _logprobs_active(self) -> bool:
        return any(
            self._logprob_ks[i] >= 0
            for i, s in enumerate(self._active) if s is not None
        )

    def _reset_penalty_slot(self, slot: int, seq: _Sequence) -> None:
        """Zero the slot's output counts and rebuild its prompt mask
        (repetition penalty covers prompt + output tokens)."""
        V = self.cfg.model.vocab_size
        B = self.cfg.max_batch_size

        def pad(ids):
            out = np.full(_bucket(max(len(ids), 1)), V, np.int32)  # V = drop
            out[: len(ids)] = ids
            return out

        prompt_p = pad(seq.tokens[: seq.prompt_len])
        gen_p = pad(seq.tokens[seq.prompt_len :])
        if self.mirror is not None:
            # broadcast FIRST: multi-process array creation below expects
            # every rank to participate, and the followers only start on
            # receiving the pen_reset op (leader-only device_put of a
            # process-spanning array blocks awaiting peers)
            self.mirror.lead_pen_reset(slot, prompt_p, gen_p)
        if self._pen_counts is None:
            if self.mirror is not None:
                self._pen_counts = self.mirror.to_global(
                    np.zeros((B, V), np.int32)
                )
                self._pen_mask = self.mirror.to_global(np.zeros((B, V), bool))
            else:
                self._pen_counts = jnp.zeros((B, V), jnp.int32)
                self._pen_mask = jnp.zeros((B, V), jnp.bool_)
        if self.mirror is not None:
            prompt_j = self.mirror.to_global(prompt_p)
            gen_j = self.mirror.to_global(gen_p)
        else:
            prompt_j, gen_j = jnp.asarray(prompt_p), jnp.asarray(gen_p)
        self._pen_counts, self._pen_mask = _reset_pen_slot(
            self._pen_counts, self._pen_mask, slot, prompt_j, gen_j
        )

    # ---- offload tier helpers ----

    def _flush_evictions_budgeted(self) -> None:
        """Budgeted background d2h for decode-path dispatches: at most
        ``offload_flush_budget`` optional blocks per window so offload
        traffic can't starve decode, but every pending eviction whose
        page appears in the live block tables (a page this dispatch may
        write) flushes unconditionally — deferring those would snapshot
        overwritten KV."""
        if self.offload is None or not self.offload.has_pending():
            return
        must = set(np.unique(self._block_tables).tolist())
        must.discard(0)
        self.offload.flush_evictions_async(
            self.k_cache, self.v_cache,
            budget=self.offload.flush_budget, must_idxs=must,
        )

    async def _offload_prejoin(self, hashes: list[int]) -> None:
        """Before an event-loop host-tier probe: dispatch any pending
        eviction gathers (budget-deferred entries are otherwise invisible
        to admission — neither in the pool nor in flight), wait
        OFF-LOOP for in-flight flushes holding ``hashes``, and promote
        any disk-tier continuation into the host pool — so the probe
        sees every landed block without the event loop ever blocking on
        a d2h fetch or a file read."""
        off = self.offload
        if off is None or not off.async_tier or not hashes:
            return
        off.flush_dropped()
        loop = asyncio.get_running_loop()
        if off.has_pending():
            # under the device lock: dispatch order across threads stays
            # serialized, so the gathers remain stream-ordered before any
            # later compute that overwrites the evicted pages
            async with self._device_lock:
                await loop.run_in_executor(
                    None, off.flush_evictions_async,
                    self.k_cache, self.v_cache,
                )
        if off.has_inflight_flushes():
            await loop.run_in_executor(None, off._join_flushes_for, hashes)
        if off.disk is not None:
            # disk -> host promotion off-loop; cheap when the disk index
            # has no continuation for this chain (index-only probe first)
            await loop.run_in_executor(None, off.promote_chain, hashes)

    def _slice_fp(self) -> str:
        """Accelerator-slice fingerprint (parallel/mesh.py, memoized
        there per process) — advertised in load_metrics so the router
        can tell which workers can hand KV device→device over ICI."""
        from ..parallel.mesh import slice_fingerprint

        return slice_fingerprint()

    async def export_device_chain(
        self, seq_hashes: list[int], max_blocks: int = 128
    ) -> tuple:
        """Serve side of the fleet prefix cache, DEVICE tier: the
        longest consecutive run of ``seq_hashes`` resident in the device
        prefix cache, gathered d2h as one bounded export — so chains
        living only in HBM (the hottest tier) stop being invisible to
        peers. Non-destructive: the blocks are ref-claimed for the
        gather's duration (a concurrent eviction can't recycle the
        pages mid-copy) and released untouched. The d2h runs on the
        device executor under the device lock, bounded by
        ``max_blocks`` so a serve can never become an unbounded HBM
        drain. Mirrored engines return empty (their gather is a
        lockstep broadcast no peer fetch should trigger).

        Returns (hashes, k, v, k_scales, v_scales). With an int8 device
        cache the export is the DEVICE CODEC verbatim — int8 payloads +
        [L, n] per-block scales, no full-width bounce through HBM or
        PCIe (the scales are non-None exactly then); the serving side
        adopts them when the wire codec matches and re-encodes (counted
        in ``kv_device_export_requant_total``) when it doesn't."""
        if self.mirror is not None or not seq_hashes or self._closed:
            return [], None, None, None, None
        # claim refs via the allocator's own chain matcher (hashes are
        # chained, so the local-hash slot is unused by the lookup) —
        # claiming pins the pages against eviction during the gather
        claimed = self.allocator.match_prefix(
            (), hashes=[(0, h) for h in seq_hashes[:max_blocks]]
        )
        if not claimed:
            return [], None, None, None, None
        ks = vs = None
        try:
            idxs = [b.idx for b in claimed]
            async with self._device_lock:
                if self.k_scales is not None:
                    k, v, ks, vs = await (
                        asyncio.get_running_loop().run_in_executor(
                            None, self._gather_device, idxs, False, True
                        )
                    )
                else:
                    k, v = await asyncio.get_running_loop().run_in_executor(
                        None, self._gather_device, idxs, False
                    )
        finally:
            self.allocator.free(claimed)
        served = list(seq_hashes[: len(claimed)])
        self.stats["peer_serve_d2h_blocks"] += len(served)
        return served, k, v, ks, vs

    def note_export_requant(self, n: int) -> None:
        """A peer serve re-encoded ``n`` device-codec blocks away from
        int8 (the puller's wire codec didn't match) — the visible form
        of what used to be a silent full-width bounce."""
        self.stats["kv_device_export_requant_total"] += n

    async def pre_stage_weights(self, model: str) -> bool:
        """PRESERVE-style weight pre-stage hook, driven by the router's
        prefetch hint naming the model/adapter the routed request will
        run. With an adapter registry configured this stages the named
        adapter's A/B stacks host->device BEFORE the request lands, so
        its admission finds the weights resident (a prestage *hit*,
        ``weight_prestage_hits``) instead of paying the cold-load copy
        on its TTFT. Base-model names (and fleets without --adapters)
        only count the request — the base weights are always resident.
        Returns True when staging work actually ran."""
        self.stats["weight_prestage_requests"] += 1
        reg = self.adapters
        if reg is None or not model or not reg.is_known(model):
            return False
        if reg.is_staged(model):
            # LRU-touch so the hinted adapter survives until its request
            reg.slot_of(model)
            return False
        faultpoints.hit_sync("weight_prestage", model=model)
        in_use = {n for n, c in self._adapter_refs.items() if c > 0}
        try:
            _slot, nbytes = await asyncio.get_running_loop().run_in_executor(
                None, lambda: reg.stage(model, in_use=in_use)
            )
        except RuntimeError:
            # every slot pinned by live requests — the request itself
            # will retry (and likely hit the same wall, loudly)
            return False
        self.stats["weight_prestage_bytes"] += nbytes
        return True

    def chain_coverage(self, chain: list[int]) -> int:
        """Longest prefix of chained hashes resident in ANY local tier
        (device radix, host pool, or disk index) — index-only probes, no
        data reads. The peer-pull path sizes its remote fetch from this:
        only the continuation PAST local coverage is worth wire time."""
        n = 0
        for h in chain:
            if self.allocator.has_hash(h):
                n += 1
                continue
            if self.offload is not None and self.offload.tier_contains(h):
                n += 1
                continue
            break
        return n

    async def prefetch_hint(self, blocks: list) -> int:
        """Router-hinted host-tier prefetch (PRESERVE-style): ``blocks``
        is the request's prompt chain as (local_hash, chained_hash)
        pairs, shipped by the KV router the moment it picked this worker
        (kv_router/scheduler.py emit_prefetch). Probes the device tiers
        for the longest resident prefix, restores the host-tier
        continuation into freshly allocated pages, and commits them to
        the content-addressed reuse pool — so when the request itself
        arrives, admission claims them as ordinary device prefix hits
        and TTFT never sees the h2d latency.

        Best-effort by design: bails without side effects under pool
        pressure, on mirrored engines (restores there are lockstep
        broadcasts), or when the tier is cold. The host chain is read
        NON-destructively (peek, not take): a request racing its own
        hint still finds the chain in the pool and restores normally —
        a hint can never make the hinted request slower. The host copies
        are only discarded after the device commit. Returns blocks
        restored."""
        if (
            self.offload is None
            or self.mirror is not None
            or not self.cfg.offload_async
            or not blocks
            or self._closed
        ):
            return 0
        chain = [s for _l, s in blocks]
        await self._offload_prejoin(chain)
        n_dev = 0
        for h in chain:
            if not self.allocator.has_hash(h):
                break
            n_dev += 1
        tail = blocks[n_dev:]
        if not tail:
            return 0
        hashes, data = self.offload.peek_chain([s for _l, s in tail])
        if not hashes:
            return 0
        fresh = self.allocator.allocate(len(hashes))
        if fresh is None:
            return 0
        upload = self.offload.begin_upload(
            hashes, data, [b.idx for b in fresh]
        )
        try:
            # wait out the h2d BEFORE taking the device lock — holding it
            # across the transfer would stall every decode window for the
            # upload duration, re-exposing the very latency this hides.
            # Bounded: a wedged executor must degrade this hint to a cold
            # restore, not wedge the (serial) prefetch listener with it
            if upload.future is not None:
                await asyncio.get_running_loop().run_in_executor(
                    None, upload.future.result, 30.0
                )
            async with self._device_lock:
                await asyncio.get_running_loop().run_in_executor(
                    None, self._prefetch_land_device, upload
                )
        except Exception:  # noqa: BLE001 — prefetch is advisory
            logger.exception("hinted prefetch restore failed")
            self.allocator.free(fresh)
            self.offload.cancel_upload(upload)
            return 0
        # commit the restored pages into the reuse pool under their
        # chained hashes (parent linkage from the hint), then drop our
        # ref — they become LRU-claimable device prefix blocks, exactly
        # like blocks a finished sequence left behind. A hash that went
        # device-resident DURING the upload (the request raced its own
        # hint) is not adopted — that block returns to the free list.
        # Only now do the host copies go (entries a racing admission
        # already took are fine — content is hash-addressed, identical).
        parent = chain[n_dev - 1] if n_dev else None
        adopted = 0
        for b, (local, seq_hash) in zip(fresh, tail):
            if self.allocator.adopt_restored(b, seq_hash, local, parent):
                b.prefetched = True
                adopted += 1
            parent = seq_hash
        self.allocator.free(fresh)
        self.offload.discard_chain(hashes)
        self.offload.note_prefetch_landed(upload)
        return adopted

    def _prefetch_land_device(self, upload) -> None:
        """Executor thread: flush pending evictions that may reference
        the prefetch's pages, then scatter the landed upload."""
        self.offload.flush_evictions_async(self.k_cache, self.v_cache)
        self.k_cache, self.v_cache = self.offload.finish_upload(
            self.k_cache, self.v_cache, upload, account=False
        )

    # ---- decode ----

    def _mixed_fusable(self) -> bool:
        """Can the in-flight prefills' next chunks fuse into one mixed
        step? Needs the mixed-batch path on, no multi-host mirror (the
        fused step is not a broadcast op — mirrored engines keep the
        alternating scheduler), a head-of-line chunk that isn't routed
        through sp ring attention (admission never packs a ring prompt
        behind others, so only states[0] can ring), and something to
        fuse WITH: a live decode batch, or at least two prompts packing
        into each other (a lone prefill with nothing decoding gains
        nothing from the fused dispatch — the dedicated prefill program
        is cheaper)."""
        sts = self._prefill_states
        return (
            self.cfg.mixed_batch
            and bool(sts)
            and self.mirror is None
            and not self._ring_chunk(sts[0].seq, sts[0].pos)
            and (self._n_active > 0 or len(sts) > 1)
        )

    def _pick_window(self) -> int:
        """Fused steps for the next dispatch: 1 while *actionable* admission
        work is pending (a long window would delay waiting requests), else
        the largest power of two within every active sequence's remaining
        stop/context headroom. Waiting requests that CANNOT admit right now
        (pool backpressure, batch full) don't disable fusion — that would
        reintroduce the per-token host sync exactly under load. An
        in-flight prefill whose chunks fuse into mixed steps is not
        actionable admission work either (it advances WITH the decode
        steps), so it no longer collapses the window — though mixed
        dispatch itself never consults this (a fused step is inherently
        one decode step per chunk)."""
        batch_full = self._n_active >= self.cfg.max_batch_size
        actionable = (
            (bool(self._prefill_states) and not self._mixed_fusable())
            or (not self._waiting_is_empty() and not batch_full
                and not self._backpressured)
            or (bool(self._remote_ready) and not batch_full)
        )
        if actionable or self.cfg.decode_window <= 1:
            return 1
        headroom = self.cfg.decode_window
        for seq in self._active:
            if seq is None:
                continue
            headroom = min(headroom, self.cfg.max_context - seq.seq_len)
            sc = seq.request.stop_conditions
            if sc.max_tokens is not None:
                headroom = min(headroom, sc.max_tokens - seq.generated)
        n = 1
        while n * 2 <= headroom and n * 2 <= self.cfg.decode_window:
            n *= 2
        return n

    def _preempt(self, seq: _Sequence) -> None:
        """Evict a running sequence under pool pressure (ref vllm patch
        scheduler edits, patch:249-742: swap/recompute preemption). The
        recompute flavor composes with the content-addressed reuse pool:
        freed full blocks stay claimable by hash (and park in the host
        offload tier on eviction), so re-admission re-claims the prefix
        and only recomputes the uncommitted tail — never silent
        truncation."""
        self._release_slot(seq)
        self.allocator.free(seq.blocks)
        seq.blocks = []
        seq.committed = 0
        seq.parent_hash = model_hash_salt(seq.model)
        seq.cached_prefix = 0
        # resume at the FRONT of the waiting queue: the whole token list
        # (prompt + generated so far) re-admits as a prefill whose final
        # sampled token simply continues the stream (PRNG steps continue
        # from seq.generated, so sampling is replay-exact)
        self._waiting_front.appendleft(seq)
        self.stats["preemptions"] += 1
        logger.info(
            "preempted request %s at %d tokens (pool pressure)",
            getattr(seq.context, "id", "?"), seq.seq_len,
        )

    def _youngest_active(self) -> Optional[_Sequence]:
        cand = [s for s in self._active if s is not None and not s.finished]
        return max(cand, key=lambda s: s.arrival_t) if cand else None

    def _evict_for_headroom(self, seq: _Sequence) -> bool:
        """Pool exhausted while growing ``seq``'s blocks: preempt the
        youngest active sequence — possibly ``seq`` itself — or, when
        nothing else is left to evict, LENGTH-finish ``seq`` (the pool
        cannot hold even one sequence at this length). ONE policy shared
        by the window and mixed dispatch paths so victim selection can't
        drift between them. Returns True when ``seq`` itself was removed
        (caller stops growing it)."""
        victim = self._youngest_active()
        if victim is seq or victim is None:
            if self._n_active <= 1:
                logger.warning(
                    "KV pool too small for request %s at %d tokens",
                    getattr(seq.context, "id", "?"), seq.seq_len,
                )
                self._finish(seq, FinishReason.LENGTH)
            else:
                self._preempt(seq)
            return True
        self._preempt(victim)
        return False

    async def _decode_once(self) -> None:
        cfg = self.cfg
        faultpoints.hit_sync("mid_decode")
        if self._mixed_fusable():
            # chunked prefills fuse into this iteration's decode step: a
            # pipelined window can't chain across the membership change a
            # completing prefill brings, so drain first (cheap — mixed
            # phases force 1-step windows anyway)
            await self._drain_inflight()
            if self._mixed_fusable():
                await self._mixed_step_once()
                return
            if self._n_active == 0:
                return
        n = self._pick_window()
        # tokens already written/writing on device for an undrained window
        pending = self._inflight["n"] if self._inflight else 0
        # ensure every active sequence has blocks for the window's tokens
        for seq in list(self._active):
            if seq is None or seq.finished or seq.slot < 0:
                continue  # may have been preempted earlier this pass
            if seq.context.is_stopped():
                self._finish(seq, FinishReason.CANCELLED)
                continue
            while (
                seq.seq_len + pending + n > len(seq.blocks) * cfg.block_size
                and seq.slot >= 0
                and not seq.finished
            ):
                if len(seq.blocks) >= cfg.max_blocks_per_seq:
                    if self._inflight is not None:
                        # the requirement is inflated by the speculative
                        # pending window — drain (emits its tokens,
                        # advances seq_len, pending -> 0), re-pick the
                        # window from fresh lengths, and re-evaluate
                        # before declaring a context-limit finish, or the
                        # in-flight tokens would be discarded and the
                        # stream truncated up to a window early.
                        # min(): CLAMP to the previously validated n.
                        # Sequences already provisioned earlier in this
                        # pass hold seq_len_old + pending + n <= allocated;
                        # the drain turns that into seq_len_new + n' <=
                        # allocated only for n' <= n — a larger re-pick
                        # (the drain can finish a headroom-constraining
                        # sequence) would write past their blocks through
                        # zero table entries into reserved page 0
                        await self._drain_inflight()
                        pending, n = 0, min(n, self._pick_window())
                        continue
                    self._finish(seq, FinishReason.LENGTH)  # true ctx limit
                    break
                extra = self.allocator.allocate(1)
                if extra is not None:
                    seq.blocks.extend(extra)
                    self._block_tables[seq.slot] = self._table_for(seq)
                    continue
                if self._inflight is not None:
                    # pipelining must never CAUSE a preemption: the
                    # speculative pending-window blocks are the first thing
                    # to give back under pressure. Draining emits the
                    # window (advancing seq_len by `pending`) and frees the
                    # speculation headroom requirement. min(): same
                    # already-validated-sequences clamp as above.
                    await self._drain_inflight()
                    pending, n = 0, min(n, self._pick_window())
                    continue
                # pool exhausted: preempt the youngest running sequence
                # (possibly this one) instead of truncating output
                if self._evict_for_headroom(seq):
                    break
        if self._n_active == 0:
            await self._drain_inflight()
            return

        # The in-flight window froze a batch membership; if it changed
        # (finish, cancellation, preemption, admission), the chained
        # device tokens and the `pending` offset no longer describe the
        # current batch — drain first (survivors' tokens still emit; a
        # vacated slot's are discarded) and start an unchained window.
        if self._inflight is not None:
            infl = self._inflight["slots"]
            cur = {i: s for i, s in enumerate(self._active) if s is not None}
            if cur.keys() != infl.keys() or any(
                cur[i] is not infl[i] for i in cur
            ):
                await self._drain_inflight()
                pending = 0
                if self._n_active == 0:  # drain may finish survivors
                    return

        # Speculative decoding: batches with an n-gram match verify gamma
        # proposals in one fused forward instead of a decode window.
        # Unchained (drains any pipeline first); bails to the normal path
        # when blocks are short or nothing matched. Composes with
        # penalties (sequential semantics modeled in the joint verify),
        # logprobs (emitted from the verify forward's own logits),
        # sliding-window models (the verify kernel computes exact
        # per-row window floors via its ``group`` row mapping), MLA
        # models (multi-token absorbed attention, write-before-attend),
        # gpt-oss models (per-layer windows and sinks thread through
        # the unrolled XLA verify), and the multi-host mirror (the
        # verify is a broadcast op). NO model family is gated off.
        if (
            cfg.spec_gamma > 0
            and n > 1
            and not self._prefill_states
            # int8-with-scales cache: the verify forward has no scale
            # stream (gated loudly at init) — plain windows only
            and self.k_scales is None
        ):
            # Proposals must come from the FRESH tail (an undrained
            # window's tokens are part of it), but draining kills the
            # pipeline overlap — so with a window in flight, first probe
            # the stale tail cheaply: only a hit pays the drain, then
            # re-proposes on the advanced tail. No stale hit -> stay
            # pipelined (a fresh-only match is possible but rare, and the
            # next iteration's stale probe would see it anyway).
            proposals = self._propose_ngram()
            if proposals is not None:
                if self._inflight is not None:
                    await self._drain_inflight()
                    pending = 0
                    if self._n_active == 0:
                        return
                    proposals = self._propose_ngram()
                if proposals is not None and await self._spec_verify_once(
                    proposals
                ):
                    return
                # a stale hit whose fresh re-probe (or verify) missed:
                # the tail is HOT — a match existed ``pending`` tokens
                # ago. Re-entering pipelined mode here would keep every
                # future probe one window behind the repetition, so
                # speculation could NEVER engage on a pipelined engine
                # (found via test_multihost_compose phase 4, which this
                # starved to 0 accepted tokens). Dispatch this one
                # window unchained so the next iteration probes fresh.
                spec_hot = True
            else:
                spec_hot = False
        else:
            spec_hot = False

        # Pipelined mode: dispatch window k+1 BEFORE draining window k.
        # Its token inputs are window k's last sampled tokens — a device
        # array, no host round trip — and positions/lengths/steps advance
        # by the pending step count host-side. Safe without draining on
        # finish/preempt because (a) in-flight writes land only ABOVE the
        # commit horizon (never into hash-claimable blocks) and (b) any
        # re-used block is re-prefilled by a dispatch device-ordered after
        # the in-flight window. Admission pressure forces n == 1
        # (_pick_window), which drains first — new sequences never join a
        # frozen in-flight batch.
        pipe = (
            cfg.decode_pipeline
            and n > 1
            and not self._prefill_states
            and not spec_hot
        )
        if not pipe:
            await self._drain_inflight()
            pending = 0
            if self._n_active == 0:
                return
            # min(): the provisioning pass above validated blocks for at
            # most n tokens per sequence; a fresh pick may shrink (e.g.
            # admission became actionable after a preemption) but must
            # never grow past what was provisioned
            n = min(n, self._pick_window())
        prev = self._inflight
        # chain token inputs on device when a window is in flight;
        # otherwise feed the host-mirrored last tokens. Under the mirror
        # the previous output is a multi-process array — eager indexing
        # is illegal, so the whole [n, B] array is handed over and
        # lead_decode slices on device (followers slice their own copy).
        if prev is None:
            tokens_in = None
        elif self.mirror is not None:
            tokens_in = prev["toks"]
        else:
            tokens_in = prev["toks"][-1]
        # dynlint: disable=async-blocking-call -- [B]-sized host int list, no device copy
        steps = np.asarray(
            [(self._active[i].generated if self._active[i] else 0) + pending
             for i in range(cfg.max_batch_size)],
            np.int32,
        )
        async with self._device_lock:
            toks = await asyncio.get_running_loop().run_in_executor(
                None, self._dispatch_window, steps, n, pending, tokens_in
            )
        self._inflight = {
            "toks": toks, "n": n,
            "lps": self._window_logprobs,
            "slots": {i: s for i, s in enumerate(self._active)
                      if s is not None},
        }
        if prev is not None:
            await self._emit_window(prev)
        if not pipe:
            await self._drain_inflight()

    def _propose_ngram(self) -> Optional[np.ndarray]:
        """Prompt-lookup drafts: match each sequence's trailing n-gram
        against its own earlier tokens and propose the continuation that
        followed last time (the draft-model-free speculation vLLM ships
        as prompt lookup / assisted generation). Returns [B, gamma] with
        -1 padding (never matches a real token id), or None when no slot
        produced a proposal."""
        g, ng = self.cfg.spec_gamma, self.cfg.spec_ngram
        out = np.full((self.cfg.max_batch_size, g), -1, np.int64)
        found = False
        for i, seq in enumerate(self._active):
            if seq is None or seq.finished:
                continue
            toks = seq.tokens
            if len(toks) < ng + 2:
                continue
            # vectorized sliding match over a bounded tail window (one
            # array conversion + ng compares, not a python tuple scan)
            arr = np.asarray(toks[-4097:], np.int64)
            key = arr[-ng:]
            hay = arr[:-1]  # a match ending at the tail itself is useless
            hits = hay[: len(hay) - ng + 1] == key[0]
            for k in range(1, ng):
                hits &= hay[k : len(hay) - ng + 1 + k] == key[k]
            idx = np.flatnonzero(hits)
            # the most recent occurrence BEFORE the trailing one
            idx = idx[idx < len(arr) - ng]
            if idx.size == 0:
                continue
            j = int(idx[-1])
            cont = arr[j + ng : j + ng + g]
            if cont.size:
                out[i, : cont.size] = cont
                found = True
        return out if found else None

    async def _spec_verify_once(self, proposals: np.ndarray) -> bool:
        """One fused verify of gamma proposals + bonus token per slot.
        Returns False (caller falls back to a plain window) when block
        headroom for the in-flight rows isn't available without
        preempting — speculation must never cause a preemption."""
        cfg = self.cfg
        g = cfg.spec_gamma
        T = g + 1
        if T > cfg.block_size:
            return False  # in-flight rows must fit a page (append kernel)
        for seq in list(self._active):
            if seq is None or seq.finished or seq.slot < 0:
                continue
            while seq.seq_len + g > len(seq.blocks) * cfg.block_size:
                if len(seq.blocks) >= cfg.max_blocks_per_seq:
                    return False  # near context limit: plain windows clamp
                extra = self.allocator.allocate(1)
                if extra is None:
                    return False
                seq.blocks.extend(extra)
                self._block_tables[seq.slot] = self._table_for(seq)

        # window tokens: last accepted token + proposals (-1 -> 0 for a
        # safe embed; acceptance on device uses the ORIGINAL -1s, which
        # never accept)
        window = np.zeros((cfg.max_batch_size, T), np.int32)
        window[:, 0] = self._last_tokens
        window[:, 1:] = np.maximum(proposals, 0)
        # dynlint: disable=async-blocking-call -- [B]-sized host int list, no device copy
        steps = np.asarray(
            [self._active[i].generated if self._active[i] else 0
             for i in range(cfg.max_batch_size)],
            np.int32,
        )
        async with self._device_lock:
            out_toks, n_accs, lps = await asyncio.get_running_loop().run_in_executor(
                None, self._dispatch_verify, window,
                proposals.astype(np.int32), steps,
            )
        self.stats["decode_steps"] += 1
        for i, seq in list(enumerate(self._active)):
            if seq is None or seq.finished:
                continue
            n_acc = int(n_accs[i])
            self.stats["spec_proposed"] += int((proposals[i] >= 0).sum())
            self.stats["spec_accepted"] += n_acc
            k = int(self._logprob_ks[i])
            for t in range(n_acc + 1):
                if seq.finished:
                    break
                entry = None
                if lps is not None and k >= 0:
                    chosen, top_ids, top_lps = lps
                    entry = {
                        "logprob": float(chosen[i, t]),
                        "top": [
                            [int(top_ids[i, t, j]), float(top_lps[i, t, j])]
                            for j in range(k)
                        ],
                    }
                self._emit_token(seq, int(out_toks[i, t]), entry)
            if seq.finished or self._active[i] is not seq:
                continue
            self._seq_lens[i] = seq.seq_len
            self._last_tokens[i] = seq.tokens[-1]
            self._commit_full_blocks(seq, written_len=seq.seq_len - 1)
        return True

    async def _mixed_step_once(self) -> None:
        """ONE fused mixed step: the ``mixed_step_budget`` token budget
        packed across EVERY in-flight prefill (admission order, each
        prompt guaranteed a minimum chunk so none starves) AND one
        decode token for every active sequence, in a single device
        dispatch (llama.mixed_step). The decode side behaves exactly
        like a 1-step window (same commit horizon / emission /
        preemption rules); each prefill side advances like a
        `_prefill_step` chunk (same per-prompt cancel/error rollback,
        same per-segment ``engine.prefill`` span accounting — the fused
        dispatch's device time splits across the advancing prompts in
        proportion to their token take, so decode ITL stops absorbing
        chunk time and each prompt's traced prefill stays honest)."""
        cfg = self.cfg
        # per-prompt cancel: drop ONE cancelled prompt from the pack,
        # the others keep advancing in the same step
        for st in list(self._prefill_states):
            if st.seq.context.is_stopped():
                self._abort_prefill(st, FinishReason.CANCELLED)
        if not self._prefill_states:
            return
        # provision one decode token per active sequence (no window is in
        # flight here — _decode_once drained before calling)
        for seq in list(self._active):
            if seq is None or seq.finished or seq.slot < 0:
                continue
            if seq.context.is_stopped():
                self._finish(seq, FinishReason.CANCELLED)
                continue
            while (
                seq.seq_len + 1 > len(seq.blocks) * cfg.block_size
                and seq.slot >= 0
                and not seq.finished
            ):
                if len(seq.blocks) >= cfg.max_blocks_per_seq:
                    self._finish(seq, FinishReason.LENGTH)
                    break
                extra = self.allocator.allocate(1)
                if extra is not None:
                    seq.blocks.extend(extra)
                    self._block_tables[seq.slot] = self._table_for(seq)
                    continue
                if self._evict_for_headroom(seq):
                    break
        if self._n_active == 0 and len(self._prefill_states) < 2:
            return  # a lone prefill alone: the alternating step is cheaper
        packed = self._split_mixed_budget()
        # dynlint: disable=async-blocking-call -- [B]-sized host int list, no device copy
        steps = np.asarray(
            [self._active[i].generated if self._active[i] else 0
             for i in range(cfg.max_batch_size)],
            np.int32,
        )
        try:
            async with self._device_lock:
                toks, lps, completed = await (
                    asyncio.get_running_loop().run_in_executor(
                        None, self._dispatch_mixed, packed, steps
                    )
                )
        except Exception:  # noqa: BLE001
            # a fused-dispatch failure (lowering/compile) is not
            # attributable to one prompt: fail every in-flight prefill,
            # each with its OWN upload rollback (the donated caches are
            # intact; the decode rows simply didn't advance and retry
            # next iteration on the plain path)
            logger.exception(
                "mixed prefill step failed for requests %s",
                [st.seq.context.id for st, _ in packed],
            )
            for st in list(self._prefill_states):
                self._abort_prefill(st, FinishReason.ERROR)
            return
        self.stats["decode_steps"] += 1
        self.stats["mixed_steps"] += 1
        self.stats["mixed_prefill_segments"] += len(packed)
        # decode emission: exactly a drained 1-step window
        if self._n_active:
            for i, seq in list(enumerate(self._active)):
                if seq is None or seq.finished:
                    continue
                entry = None
                k = int(self._logprob_ks[i])
                if lps is not None and k >= 0:
                    chosen, top_ids, top_lps = lps
                    entry = {
                        "logprob": float(chosen[i]),
                        "top": [
                            [int(top_ids[i, j]), float(top_lps[i, j])]
                            for j in range(k)
                        ],
                    }
                self._emit_token(seq, int(toks[i]), entry)
                if seq.finished or self._active[i] is not seq:
                    continue
                self._seq_lens[i] = seq.seq_len
                self._last_tokens[i] = seq.tokens[-1]
                self._commit_full_blocks(seq, written_len=seq.seq_len - 1)
        # prompts whose FINAL chunk just ran: first token sampled on
        # device in _dispatch_mixed — emit + join the batch, in
        # admission order (multiple prompts may complete in one step)
        for st, first in completed:
            seq_p = st.seq
            first_token, first_lp = first
            if seq_p.generated == 0:
                self.hist["prefill_ms"].observe(st.dev_ms)
                if seq_p.trace is not None:
                    tracing.RECORDER.record_span(
                        "engine.prefill", seq_p.trace, ts=st.t0_wall,
                        dur_ms=st.dev_ms,
                        request_id=seq_p.context.id,
                        prompt_tokens=seq_p.prompt_len,
                        cached_prefix=seq_p.cached_prefix,
                    )
            self._drop_prefill_state(st)
            self._commit_full_blocks(seq_p)
            self._emit_token(seq_p, first_token, first_lp)
            if not seq_p.finished:
                if self._n_active < cfg.max_batch_size:
                    self._place_in_batch(seq_p)
                else:
                    # slots filled mid-prefill (remote-ready admissions):
                    # the KV is landed, so queue for the next free slot
                    # exactly like a remotely-prefilled sequence
                    self._remote_ready.append(seq_p)

    def _split_mixed_budget(self) -> list[tuple["_PrefillState", int]]:
        """Pack the Sarathi token budget across the in-flight prefills:
        every prompt gets a minimum chunk of budget // n_prompts (at
        least 1 token — no prompt starves, the stall-free guarantee),
        and the leftover goes to the EARLIEST-admitted prompts first
        (admission order keeps TTFT ordering fair). Returns
        [(state, take)] with every take >= 1."""
        sts = self._prefill_states
        budget = self.cfg.mixed_step_budget
        rem = [len(st.seq.tokens) - st.pos for st in sts]
        floor = max(budget // len(sts), 1)
        takes = [min(r, floor) for r in rem]
        left = budget - sum(takes)
        for i in range(len(sts)):
            if left <= 0:
                break
            extra = min(left, rem[i] - takes[i])
            takes[i] += extra
            left -= extra
        return list(zip(sts, takes))

    def _dispatch_mixed(
        self, packed: list[tuple["_PrefillState", int]], steps: np.ndarray
    ):
        """Executor thread: the fused mixed dispatch over M prefill
        segments + the decode batch. Returns (decode_tokens [B] np,
        logprob arrays or None, completed: [(state, (first_token,
        lp_entry))] for every prompt whose final chunk just ran).

        Shape discipline: the segment count pads to a power-of-two
        bucket (dead segments: valid 0, zero tables — their rows land
        in reserved trash page 0 and their logits are never read) and
        every segment shares ONE bucketed length T = bucket(max take),
        so the compiled program count is bounded by segment-count
        buckets x prefill buckets, never by the live mixture."""
        cfg = self.cfg
        # provisioning invariant (loud, not silent — the same check the
        # window dispatch makes): every active sequence must have a block
        # for this step's token, or its write would scatter through zero
        # table entries into reserved page 0 as silent garbage
        for seq in self._active:
            if seq is None or seq.finished or seq.slot < 0:
                continue
            if seq.seq_len + 1 > len(seq.blocks) * cfg.block_size:
                raise RuntimeError(
                    f"mixed step exceeds provisioned blocks for request "
                    f"{getattr(seq.context, 'id', '?')} "
                    f"(seq_len={seq.seq_len}, blocks={len(seq.blocks)})"
                )
        t0 = time.perf_counter()
        total_take = sum(take for _st, take in packed) or 1
        try:
            # land each prompt's reserved host chain (first step only);
            # eviction flushes are shared across the pack
            for st, _take in packed:
                self._offload_preamble(
                    st.upload if not st.restored else None, seq=st.seq
                )
                st.restored = True
            MP = _seg_bucket(len(packed))
            T = _bucket(max(take for _st, take in packed))
            toks_p = np.zeros((MP, T), np.int32)
            tables_p = np.zeros((MP, cfg.max_blocks_per_seq), np.int32)
            hists_p = np.zeros(MP, np.int32)
            valids_p = np.zeros(MP, np.int32)
            for i, (st, take) in enumerate(packed):
                chunk = st.seq.tokens[st.pos : st.pos + take]
                toks_p[i, : len(chunk)] = chunk
                tables_p[i] = self._table_for(st.seq)
                hists_p[i] = st.pos
                valids_p[i] = len(chunk)
            positions = np.maximum(self._seq_lens - 1, 0).astype(np.int32)
            penalized = self._penalties_active()
            want_lp = self._logprobs_active()
            kwargs = {}
            if penalized:
                kwargs.update(
                    freq_pens=jnp.asarray(self._freq_pens),
                    pres_pens=jnp.asarray(self._pres_pens),
                    rep_pens=jnp.asarray(self._rep_pens),
                    counts=self._pen_counts,
                    prompt_mask=self._pen_mask,
                )
            quantized = self.k_scales is not None
            if quantized:
                self._flush_scale_resets()
                kwargs.update(
                    k_scales=self.k_scales, v_scales=self.v_scales
                )
            if self.adapters is not None:
                # decode rows use the slot-mirrored ids; prefill
                # segments carry their sequence's id (padded rows -1 =
                # base = exact zero delta)
                p_ids = np.full(MP, -1, np.int32)
                for i, (st, _take) in enumerate(packed):
                    p_ids[i] = st.seq.adapter_id
                kwargs.update(
                    lora=self.adapters.device_stack(),
                    d_adapter_ids=jnp.asarray(self._adapter_ids),
                    p_adapter_ids=jnp.asarray(p_ids),
                )
            out = self._pallas_guard(lambda: llama.mixed_step(
                self.params,
                cfg.model,
                jnp.asarray(self._last_tokens),
                jnp.asarray(positions),
                jnp.asarray(self._block_tables),
                jnp.asarray(self._seq_lens),
                jnp.asarray(self._seeds),
                jnp.asarray(steps),
                jnp.asarray(self._temps),
                jnp.asarray(self._top_ks),
                jnp.asarray(self._top_ps),
                jnp.asarray(toks_p),
                jnp.asarray(tables_p),
                jnp.asarray(hists_p),
                jnp.asarray(valids_p),
                self.k_cache,
                self.v_cache,
                use_pallas=self.use_pallas,
                mesh=self.mesh,
                # the decode part must mirror this engine's own
                # decode_window structure or the XLA branch's bit-exact
                # contract breaks
                unroll=not cfg.decode_layer_scan,
                merged=cfg.decode_merged,
                with_logprobs=want_lp,
                **kwargs,
            ), key=("mixed", MP, T, penalized, want_lp)
                + self._lora_key())
            toks, p_logits, self.k_cache, self.v_cache = out[:4]
            rest = list(out[4:])
            if quantized:
                self.k_scales = rest.pop(0)
                self.v_scales = rest.pop(0)
                self._note_quant_step(
                    rest.pop(0), self._n_active + total_take,
                    gen_tokens=self._n_active,
                )
            if penalized:
                self._pen_counts = rest.pop(0)
            lps_dev = rest.pop(0) if want_lp else None
            completed = []
            for i, (st, take) in enumerate(packed):
                st.pos += take
                if st.pos >= len(st.seq.tokens):
                    completed.append(
                        (st, self._sample_prefill(st.seq, p_logits[i]))
                    )
            toks_host = np.asarray(jax.device_get(toks))
            lps = (
                tuple(np.asarray(jax.device_get(a)) for a in lps_dev)
                if lps_dev is not None else None
            )
            return toks_host, lps, completed
        finally:
            # the fused dispatch's device time lands on the traced
            # prefill components, split across the advancing prompts in
            # proportion to their token take (the chunks dominate the
            # step; attributing the decode row share too slightly
            # overcounts prefill but keeps decode ITL honest — the span
            # decode streams no longer wait on)
            dt_ms = (time.perf_counter() - t0) * 1e3
            for st, take in packed:
                st.dev_ms += dt_ms * (take / total_take)

    def _note_compile(self, key: tuple, wall_ms: float, trace=None) -> None:
        """First dispatch of a program bucket: ledger it. The wall time
        of a first dispatch is dominated by trace+compile (steady-state
        dispatch of a compiled program returns in microseconds), so the
        entry's ``ms`` is the compile stall a cold request would have
        paid. With a request trace in scope the compile is also stamped
        into that request's timeline — the autopsy names it."""
        self._compiled_keys.add(key)
        entry = {"kind": key[0], "key": list(key[1:]),
                 "ms": round(wall_ms, 3)}
        self.compile_ledger.append(entry)
        if len(self.compile_ledger) > 512:
            del self.compile_ledger[:-256]
        self.stats["xla_compiles_total"] += 1
        self.stats["xla_compile_ms_total"] += wall_ms
        if trace is not None:
            tracing.RECORDER.event(
                "engine.xla_compile", trace=trace,
                kind=key[0], key=str(key[1:]), ms=round(wall_ms, 3),
            )
        if wall_ms > 1000.0:
            logger.info("XLA compile: %s %s took %.0f ms",
                        key[0], key[1:], wall_ms)

    def _pallas_guard(self, thunk, key: Optional[tuple] = None, trace=None):
        """Run a device dispatch; if Mosaic rejects a kernel at its
        FIRST compile (a constraint the CPU tests can't prove — e.g. the
        sub-128 pe-stream lane tiles, advisor r3), flip ``use_pallas``
        off and retry once on the XLA path instead of failing the
        request. The thunk must read ``self.use_pallas`` at call time.

        ``key`` names the dispatch's program bucket (kind + the shape
        coordinates the jit cache keys on): the first dispatch of each
        bucket is timed into the XLA compile ledger (_note_compile).

        Two hard gates on the retry:
          * mirror mode never retries — the step descriptor (with
            ``pallas=True``) was already broadcast before the leader's
            compile failed, so a lone leader retry would re-enter the
            collective against followers that crashed on the same
            kernel; SPMD fallback would need coordination BEFORE the
            broadcast, so mirrored engines surface the error instead;
          * the caches must still be live — donation frees buffers at
            execution, so a lowering/compile rejection leaves them
            intact, but an EXECUTION-stage Mosaic error arrives after
            donation and a retry would dispatch on deleted arrays.
        """
        cold = key is not None and key not in self._compiled_keys
        t0 = time.perf_counter() if cold else 0.0
        try:
            out = thunk()
        except Exception as e:  # noqa: BLE001 — inspected, re-raised
            msg = str(e).lower()
            if (
                self.mirror is not None
                or not self.use_pallas
                or not ("mosaic" in msg or "pallas" in msg)
                or self.k_cache.is_deleted()
                or self.v_cache.is_deleted()
            ):
                raise
            logger.warning(
                "Mosaic rejected a kernel at first dispatch; "
                "falling back to XLA attention for this engine: %s", e
            )
            self.use_pallas = False
            out = thunk()
        if cold:
            self._note_compile(key, (time.perf_counter() - t0) * 1e3, trace)
        return out

    def _dispatch_verify(
        self, window: np.ndarray, proposals: np.ndarray, steps: np.ndarray
    ):
        """Executor thread: fused verify forward + on-device acceptance.
        Returns (out_tokens [B, T], n_acc [B], lp arrays or None)."""
        cfg = self.cfg
        self._flush_evictions_budgeted()
        positions = np.maximum(self._seq_lens - 1, 0).astype(np.int32)
        penalized = self._penalties_active()
        want_lp = self._logprobs_active()
        if self.mirror is not None:
            out = self._pallas_guard(lambda: self.mirror.lead_verify(
                self.params, window, proposals, positions,
                self._block_tables, self._seq_lens, self._seeds, steps,
                self._temps, self._top_ks, self._top_ps,
                self.k_cache, self.v_cache,
                n_spec=cfg.spec_gamma, use_pallas=self.use_pallas,
                penalties=(self._freq_pens, self._pres_pens, self._rep_pens)
                if penalized else None,
                pen_state=(self._pen_counts, self._pen_mask)
                if penalized else None,
                with_logprobs=want_lp,
            ), key=("verify", cfg.spec_gamma, penalized, want_lp))
            toks, n_acc, self.k_cache, self.v_cache = out[:4]
            rest = list(out[4:])
            if penalized:
                self._pen_counts = rest.pop(0)
            lps = rest.pop(0) if want_lp else None
            return toks, n_acc, lps
        kwargs = {}
        if penalized:
            kwargs.update(
                freq_pens=jnp.asarray(self._freq_pens),
                pres_pens=jnp.asarray(self._pres_pens),
                rep_pens=jnp.asarray(self._rep_pens),
                counts=self._pen_counts,
                prompt_mask=self._pen_mask,
            )
        out = self._pallas_guard(lambda: llama.verify_window(
            self.params,
            cfg.model,
            jnp.asarray(window),
            jnp.asarray(proposals),
            jnp.asarray(positions),
            jnp.asarray(self._block_tables),
            jnp.asarray(self._seq_lens),
            jnp.asarray(self._seeds),
            jnp.asarray(steps),
            jnp.asarray(self._temps),
            jnp.asarray(self._top_ks),
            jnp.asarray(self._top_ps),
            self.k_cache,
            self.v_cache,
            n_spec=cfg.spec_gamma,
            use_pallas=self.use_pallas,
            mesh=self.mesh,
            with_logprobs=want_lp,
            **kwargs,
        ), key=("verify", cfg.spec_gamma, penalized, want_lp))
        toks, n_acc, self.k_cache, self.v_cache = out[:4]
        rest = list(out[4:])
        if penalized:
            self._pen_counts = rest.pop(0)
        lps_dev = rest.pop(0) if want_lp else None
        lps = (
            tuple(np.asarray(jax.device_get(a)) for a in lps_dev)
            if lps_dev is not None else None
        )
        return (
            np.asarray(jax.device_get(toks)),
            np.asarray(jax.device_get(n_acc)),
            lps,
        )

    async def _drain_inflight(self) -> None:
        """Sync + emit the pending pipelined window, if any."""
        inflight, self._inflight = self._inflight, None
        if inflight is not None:
            await self._emit_window(inflight)

    async def _emit_window(self, window: dict) -> None:
        def materialize():
            t = window["toks"]
            if hasattr(t, "addressable_data") and not getattr(
                t, "is_fully_addressable", True
            ):
                # multi-process replicated array: read the local shard
                # (device_get would wait on a collective followers never
                # join)
                toks = np.asarray(t.addressable_data(0))
            else:
                toks = np.asarray(jax.device_get(t))
            lp = window.get("lps")
            if lp is not None:
                # local shards: complete for replicated outputs, and the
                # only safe fetch on multi-process arrays (device_get
                # would wait on a cross-process collective the followers
                # never join)
                lp = tuple(np.asarray(a.addressable_data(0)) for a in lp)
            return toks, lp

        toks_host, lps = await asyncio.get_running_loop().run_in_executor(
            None, materialize
        )
        n = window["n"]
        self.stats["decode_steps"] += n
        # emit window tokens in step order; a sequence that hits a stop
        # condition mid-window has its tail tokens discarded, and a slot
        # that changed hands since dispatch (finish -> re-admission) must
        # not receive the old occupant's tokens
        live = [
            (i, seq) for i, seq in window["slots"].items()
            if self._active[i] is seq and not seq.finished
        ]
        for step_i in range(n):
            for i, seq in live:
                if seq.finished:
                    continue
                entry = None
                k = int(self._logprob_ks[i])
                if lps is not None and k >= 0:
                    chosen, top_ids, top_lps = lps
                    entry = {
                        "logprob": float(chosen[step_i, i]),
                        "top": [
                            [int(top_ids[step_i, i, j]),
                             float(top_lps[step_i, i, j])]
                            for j in range(k)
                        ],
                    }
                self._emit_token(seq, int(toks_host[step_i, i]), entry)
        for i, seq in live:
            if seq.finished:
                continue
            self._seq_lens[i] = seq.seq_len
            self._last_tokens[i] = seq.tokens[-1]
            self._commit_full_blocks(seq, written_len=seq.seq_len - 1)

    def _dispatch_window(
        self, steps: np.ndarray, n: int, pending: int, tokens_in=None
    ):
        """Runs in an executor thread: dispatch one fused n-step
        decode+sample window WITHOUT syncing its result. Returns the
        sampled-token device array [n, B] (host np array on the mirror
        path, which syncs internally).

        ``pending`` > 0 means an undrained window is in flight: this
        window's token inputs are that window's last sampled tokens
        (``tokens_in``, a device array — the chain stays on device) and
        the host-mirrored positions/lengths advance by ``pending``
        steps."""
        cfg = self.cfg
        if pending and tokens_in is None:
            raise RuntimeError(
                "pending window without a chained token source"
            )
        # Provisioning invariant (loud, not silent): every active sequence
        # must have blocks covering this window's writes. A violation
        # would scatter through zero block-table entries into reserved
        # page 0 — garbage K/V that later reads silently consume.
        for seq in self._active:
            if seq is None or seq.finished or seq.slot < 0:
                continue
            if seq.seq_len + pending + n > len(seq.blocks) * cfg.block_size:
                raise RuntimeError(
                    f"window n={n} pending={pending} exceeds provisioned "
                    f"blocks for request "
                    f"{getattr(seq.context, 'id', '?')} "
                    f"(seq_len={seq.seq_len}, blocks={len(seq.blocks)})"
                )
        self._flush_evictions_budgeted()
        positions = (
            np.maximum(self._seq_lens - 1, 0) + pending
        ).astype(np.int32)
        seq_lens = (self._seq_lens + pending).astype(np.int32)
        if self.mirror is not None:
            penalized = self._penalties_active()
            want_lp = self._logprobs_active()
            out = self._pallas_guard(lambda: self.mirror.lead_decode(
                self.params, self._last_tokens, positions,
                self._block_tables, seq_lens, self._seeds, steps,
                self._temps, self._top_ks, self._top_ps,
                self.k_cache, self.v_cache,
                n_steps=n, use_pallas=self.use_pallas,
                unroll=not cfg.decode_layer_scan,
                merged=cfg.decode_merged,
                penalties=(self._freq_pens, self._pres_pens, self._rep_pens)
                if penalized else None,
                pen_state=(self._pen_counts, self._pen_mask)
                if penalized else None,
                with_logprobs=want_lp,
                tokens_dev=tokens_in,
                sync=False,  # device handle; materialized at emission so
                # a pipelined next window dispatches without waiting
            ), key=("decode", n, penalized, want_lp))
            toks, self.k_cache, self.v_cache = out[0], out[1], out[2]
            rest = list(out[3:])
            if penalized:
                self._pen_counts = rest.pop(0)
            # device handles; materialized at emission
            self._window_logprobs = rest.pop(0) if want_lp else None
            return toks
        if tokens_in is None:
            tokens_in = jnp.asarray(self._last_tokens)
        args = (
            self.params,
            cfg.model,
            tokens_in,
            jnp.asarray(positions),
            jnp.asarray(self._block_tables),
            jnp.asarray(seq_lens),
            jnp.asarray(self._seeds),
            jnp.asarray(steps),
            jnp.asarray(self._temps),
            jnp.asarray(self._top_ks),
            jnp.asarray(self._top_ps),
            self.k_cache,
            self.v_cache,
        )
        want_lp = self._logprobs_active()
        # use_pallas stays OUT of kw: the guard's retry thunk must read
        # the freshly-flipped value, not a stale snapshot
        kw = dict(
            n_steps=n,
            mesh=self.mesh,
            unroll=not cfg.decode_layer_scan,
            merged=cfg.decode_merged,
            with_logprobs=want_lp,
        )
        kw.update(self._lora_decode_kw())
        quantized = self.k_scales is not None
        if quantized:
            self._flush_scale_resets()
            kw.update(k_scales=self.k_scales, v_scales=self.v_scales)
        if self._penalties_active():
            out = self._pallas_guard(lambda: llama.decode_window(
                *args, **kw, use_pallas=self.use_pallas,
                freq_pens=jnp.asarray(self._freq_pens),
                pres_pens=jnp.asarray(self._pres_pens),
                rep_pens=jnp.asarray(self._rep_pens),
                counts=self._pen_counts,
                prompt_mask=self._pen_mask,
            ), key=("decode", n, True, want_lp) + self._lora_key())
            penalized = True
        else:
            out = self._pallas_guard(lambda: llama.decode_window(
                *args, **kw, use_pallas=self.use_pallas
            ), key=("decode", n, False, want_lp) + self._lora_key())
            penalized = False
        toks, self.k_cache, self.v_cache = out[:3]
        rest = list(out[3:])
        if quantized:
            self.k_scales = rest.pop(0)
            self.v_scales = rest.pop(0)
            self._note_quant_step(
                rest.pop(0), self._n_active * n,
                gen_tokens=self._n_active * n,
            )
        if penalized:
            self._pen_counts = rest.pop(0)
        lps = rest.pop(0) if want_lp else None
        # device handles; materialized at emission (fetching here would
        # block the pipelined dispatch on the window's full execution)
        self._window_logprobs = lps
        return toks

    # ---- token emission + finish logic ----

    def _emit_token(self, seq: _Sequence, token: int, lp_entry=None) -> None:
        req = seq.request
        sc = req.stop_conditions
        seq.tokens.append(token)
        seq.generated += 1
        self.stats["tokens_generated"] += 1
        if seq.generated == 1:
            # per-model TTFT family (the trace-replay assertion plane)
            h = self.hist_ttft.get(seq.model)
            if h is None:
                h = self.hist_ttft[seq.model] = Histogram(MS_BUCKETS)
            h.observe((time.monotonic() - seq.arrival_t) * 1000.0)
        if seq.trace is not None and seq.generated == 1:
            # first-token anchor for the TTFT decomposition; later tokens
            # pay only the seq.trace None-check above
            tracing.RECORDER.event(
                "engine.first_token", trace=seq.trace,
                request_id=seq.context.id,
            )

        finish: Optional[FinishReason] = None
        eos_ids = set(req.eos_token_ids or [])
        min_ok = seq.generated >= (sc.min_tokens or 0)
        if token in (sc.stop_token_ids or []):
            finish = FinishReason.STOP
        elif not sc.ignore_eos and token in eos_ids and min_ok:
            finish = FinishReason.EOS
        elif sc.max_tokens is not None and seq.generated >= sc.max_tokens:
            finish = FinishReason.LENGTH
        elif seq.seq_len >= self.cfg.max_context:
            finish = FinishReason.LENGTH
        elif seq.context.is_stopped():
            finish = FinishReason.CANCELLED

        out = LLMEngineOutput(token_ids=[token])
        if lp_entry is not None:
            out.logprobs = [lp_entry]
        if finish is not None:
            out.finish_reason = finish
            out.prompt_tokens = seq.prompt_len
            out.completion_tokens = seq.generated
            out.kv_overlap_blocks = seq.cached_prefix // self.cfg.block_size
        seq.out_queue.put_nowait(out)
        if finish is not None:
            self._finish(seq, finish, emit=False)

    def _finish(self, seq: _Sequence, reason: FinishReason, emit: bool = True) -> None:
        if seq.finished:
            return
        seq.finished = True
        if seq.model:
            # release the adapter's eviction pin (idempotent via the
            # finished flag above)
            held = self._adapter_refs.get(seq.model, 0)
            if held > 0:
                self._adapter_refs[seq.model] = held - 1
        if emit:
            seq.out_queue.put_nowait(
                LLMEngineOutput(
                    finish_reason=reason,
                    prompt_tokens=seq.prompt_len,
                    completion_tokens=seq.generated,
                )
            )
        self._release_slot(seq)
        self.allocator.free(seq.blocks)
        seq.blocks = []
        self._wake.set()

    def _release_slot(self, seq: _Sequence) -> None:
        """Vacate a sequence's continuous-batching slot (shared by finish
        and preemption so the teardown can't drift between them)."""
        if seq.slot >= 0:
            self._active[seq.slot] = None
            self._seq_lens[seq.slot] = 0
            self._block_tables[seq.slot] = 0
            self._adapter_ids[seq.slot] = -1
            self._n_active -= 1
            seq.slot = -1

    def _commit_full_blocks(self, seq: _Sequence, written_len: int = -1) -> None:
        """Content-address blocks that just became full AND fully written.

        ``written_len`` is the number of positions whose KV is actually in
        the device cache. After a decode window (and after complete_remote's
        first-token emit) the final sampled token is in ``seq.tokens`` but
        its KV is only written at the start of the NEXT dispatch — callers
        there pass ``seq.seq_len - 1`` so a block whose last row is pending
        is never exposed to match_prefix (a concurrent prefix hit would
        attend garbage). Prefill-side callers commit at ``seq.seq_len``
        (tokens list holds only written positions there)."""
        bs = self.cfg.block_size
        if written_len < 0:
            written_len = seq.seq_len
        full = written_len // bs
        while seq.committed < full and seq.committed < len(seq.blocks):
            i = seq.committed
            tokens = seq.tokens[i * bs : (i + 1) * bs]
            seq.parent_hash = self.allocator.commit_full_block(
                seq.blocks[i], tokens, seq.parent_hash
            )
            seq.committed += 1

    # ---------------- disaggregation hooks ----------------
    # (ref docs/disagg_serving.md:58-91; vllm patch remote-prefill states)

    def n_prompt_blocks(self, prompt_len: int) -> int:
        bs = self.cfg.block_size
        return (prompt_len + bs - 1) // bs

    def _guard_remote_adapter(self, req: PreprocessedRequest) -> None:
        """The disagg remote-prefill/decode paths have no adapter lane
        yet (the KV wire carries no adapter identity, and a prefill
        worker would silently compute BASE KV for an adapter prompt —
        wrong tokens with no error). Reject loudly; the monolithic path
        serves adapter traffic. Declared as a leftover in
        docs/multi_model.md."""
        if (
            self.adapters is not None
            and req.model
            and self.adapters.is_known(req.model)
        ):
            raise RuntimeError(
                f"adapter model {req.model!r} is not supported on the "
                "remote prefill/decode paths yet — route adapter "
                "traffic to monolithic workers"
            )

    async def prefill_extract(
        self, req: PreprocessedRequest, context, skip_blocks: int = 0,
        keep_on_device: bool = False, timings: Optional[dict] = None,
    ) -> tuple[int, Optional[dict], Optional[np.ndarray], Optional[np.ndarray]]:
        """Prefill-worker side: compute the prompt's KV (with this worker's
        own prefix cache), sample the first token (max_tokens=1 semantics,
        ref prefill_worker.py:109-137), and return the prompt's KV blocks
        after ``skip_blocks`` (the decode side's prefix hit). Blocks are
        committed to the reuse pool before being freed, so repeated
        prefixes stay warm on the prefill worker.

        ``keep_on_device`` returns jax.Arrays (the gather's own device
        buffers — safe after the source blocks are freed) instead of host
        copies: the in-process LocalKvPipe path hands them straight to the
        decode engine's scatter, so same-slice disagg never pays the
        d2h + h2d round-trip (VERDICT round-1 missing #3; the reference's
        same-node NIXL path is GPU-direct for the same reason).

        Under the multi-host mirror the gather is a mirrored op with
        replicated output (compiled all-gather over ICI/DCN) and the
        LEADER ships full host blocks over the transfer plane;
        ``keep_on_device`` is ignored there (a multi-process array cannot
        hand over in-process to a differently-meshed engine)."""
        if self.mirror is not None:
            keep_on_device = False
        self._guard_remote_adapter(req)
        prompt = list(req.token_ids)
        seq = _Sequence(
            request=req,
            context=context,
            out_queue=asyncio.Queue(),
            tokens=prompt,
            prompt_len=len(prompt),
            trace=tracing.current_trace() if tracing.enabled() else None,
        )
        reserved = self._reserve_for_prompt(seq)
        if reserved is None:
            raise OutOfBlocks(f"cannot cover {len(prompt)}-token prompt")
        history = reserved[0]
        self.stats["prefix_cache_hits_tokens"] += history
        try:
            async with self._device_lock:
                first_token, first_lp = await (
                    asyncio.get_running_loop().run_in_executor(
                        None, self._prefill_device, seq, history
                    )
                )
                n_prompt = self.n_prompt_blocks(len(prompt))
                idxs = [b.idx for b in seq.blocks[skip_blocks:n_prompt]]
                if idxs:
                    t_g = time.perf_counter()
                    k_np, v_np = await asyncio.get_running_loop().run_in_executor(
                        None, self._gather_device, idxs, keep_on_device
                    )
                    if timings is not None:
                        # the d2h extraction is HANDOFF work, not prompt
                        # compute — the caller folds it into the
                        # kv_transfer decomposition (ttft.py)
                        timings["gather_ms"] = (
                            timings.get("gather_ms", 0.0)
                            + (time.perf_counter() - t_g) * 1e3
                        )
                else:
                    k_np = v_np = None
            self._commit_full_blocks(seq)
        finally:
            self.allocator.free(seq.blocks)
            seq.blocks = []
        return first_token, first_lp, k_np, v_np

    async def prefill_extract_stream(
        self, req: PreprocessedRequest, context, skip_blocks: int = 0,
        keep_on_device: bool = False, segment_blocks: int = 0,
        on_segment=None, timings: Optional[dict] = None,
    ) -> tuple[int, Optional[dict], int]:
        """Streamed twin of :meth:`prefill_extract` (ROADMAP item 1 /
        FlowKV): the prompt prefills chunk by chunk and every chunk's
        freshly completed blocks are gathered and handed to
        ``on_segment(b0, k_seg, v_seg)`` the moment the chunk's compute
        finishes — the caller ships them while the NEXT chunk computes,
        hiding the transfer behind prefill. ``b0`` is the block offset
        relative to ``skip_blocks``; segments arrive in order and cover
        [skip_blocks, n_prompt_blocks) exactly once. ``segment_blocks``
        caps a segment's block count (0 = one segment per prefill chunk).

        The FINAL segment (including the prompt's partial last block) is
        emitted BEFORE first-token sampling, so even the tail transfer
        overlaps the sampling dispatch instead of sitting on TTFT.

        Gathers go through the same bucketed ``_gather_device`` as the
        bulk path, so the compiled-program count is bounded by segment
        GEOMETRY buckets, not per-request shapes (test_compiled_perf).
        Returns (first_token, first_lp, blocks_emitted)."""
        if self.mirror is not None:
            keep_on_device = False
        self._guard_remote_adapter(req)
        prompt = list(req.token_ids)
        seq = _Sequence(
            request=req,
            context=context,
            out_queue=asyncio.Queue(),
            tokens=prompt,
            prompt_len=len(prompt),
            trace=tracing.current_trace() if tracing.enabled() else None,
        )
        reserved = self._reserve_for_prompt(seq)
        if reserved is None:
            raise OutOfBlocks(f"cannot cover {len(prompt)}-token prompt")
        history = reserved[0]
        self.stats["prefix_cache_hits_tokens"] += history
        bs = self.cfg.block_size
        n_prompt = self.n_prompt_blocks(len(prompt))
        sent = skip_blocks
        loop = asyncio.get_running_loop()

        async def emit_upto(full: int) -> None:
            nonlocal sent
            while sent < full:
                hi = (
                    min(full, sent + segment_blocks)
                    if segment_blocks > 0 else full
                )
                idxs = [b.idx for b in seq.blocks[sent:hi]]
                t_g = time.perf_counter()
                async with self._device_lock:
                    k_seg, v_seg = await loop.run_in_executor(
                        None, self._gather_device, idxs, keep_on_device
                    )
                if timings is not None:
                    # per-segment d2h time is handoff work too (same
                    # accounting as the bulk twin's single gather)
                    timings["gather_ms"] = (
                        timings.get("gather_ms", 0.0)
                        + (time.perf_counter() - t_g) * 1e3
                    )
                await on_segment(sent - skip_blocks, k_seg, v_seg)
                sent = hi

        try:
            # the device lock is taken PER CHUNK (and per gather), not
            # across the whole prompt: M concurrent streamed extracts —
            # and a co-resident serving loop's decode steps — interleave
            # chunk-wise instead of serializing whole prompts, so every
            # advancing prompt streams its segments as its own chunks
            # land (the multi-prompt twin of the mixed-batch packer;
            # PrefillWorker ``concurrency`` drives it). Safe because the
            # sequence's blocks are reserved (no interleaved dispatch
            # can touch them) and every cache-donating dispatch still
            # serializes under the lock. on_segment backpressure is paid
            # OUTSIDE the lock, so a slow peer throttles only its own
            # prompt, never the whole engine.
            async with self._device_lock:
                await loop.run_in_executor(None, self._offload_preamble)
            pos = history
            logits = None
            while pos < len(prompt):
                p0, t_c = pos, time.perf_counter()
                async with self._device_lock:
                    logits, pos = await loop.run_in_executor(
                        None, self._run_one_chunk, seq, pos
                    )
                if self.cost is not None and pos > p0:
                    self.cost.observe_prefill(
                        pos - p0, max(time.perf_counter() - t_c, 1e-9)
                    )
                # blocks whose every position is now written; the
                # final chunk also releases the partial last block
                full = n_prompt if pos >= len(prompt) else min(
                    pos // bs, n_prompt
                )
                if on_segment is not None and full > sent:
                    await emit_upto(full)
            async with self._device_lock:
                first_token, first_lp = await loop.run_in_executor(
                    None, self._sample_prefill, seq, logits
                )
            self._commit_full_blocks(seq)
        finally:
            self.allocator.free(seq.blocks)
            seq.blocks = []
        return first_token, first_lp, max(n_prompt - skip_blocks, 0)

    def _gather_device(self, idxs: list[int], keep_on_device: bool = False,
                       with_scales: bool = False):
        """Bucketed d2h page gather. With an int8 device cache the pages
        are quantized codes: ``with_scales=True`` returns the device
        codec verbatim — (k, v, k_scales, v_scales) with [L, n] scale
        stacks matching the tier/wire entry form, zero re-encode — while
        ``with_scales=False`` (callers that need full width: disagg
        extract, legacy peers) dequantizes on device before the d2h and
        counts the bounce in ``kv_device_export_requant_total``."""
        from .offload import _gather_blocks, _gather_blocks_s, _pad_idxs

        padded = _pad_idxs(idxs)
        if self.mirror is not None:
            k, v = self.mirror.lead_kv_gather_full(
                self.k_cache, self.v_cache, padded
            )
            return k[:, :, : len(idxs)], v[:, :, : len(idxs)]
        if self.k_scales is not None:
            k, v, ks, vs = _gather_blocks_s(
                self.k_cache, self.v_cache, self.k_scales, self.v_scales,
                jnp.asarray(padded),
            )
            n = len(idxs)
            if with_scales:
                k, v = k[:, :, :n], v[:, :, :n]
                ks, vs = ks[:, :n], vs[:, :n]
                if keep_on_device:
                    return k, v, ks, vs
                return tuple(
                    np.asarray(jax.device_get(a)) for a in (k, v, ks, vs)
                )
            # full-width bounce (visible, not silent): dequantize with
            # the plane scales before the d2h
            self.stats["kv_device_export_requant_total"] += n
            k = _dequant_gathered(k, ks, self.cfg.model.dtype)
            v = _dequant_gathered(v, vs, self.cfg.model.dtype)
            k, v = k[:, :, :n], v[:, :, :n]
            if keep_on_device:
                return k, v
            return np.asarray(jax.device_get(k)), np.asarray(jax.device_get(v))
        k, v = _gather_blocks(self.k_cache, self.v_cache, jnp.asarray(padded))
        k, v = k[:, :, : len(idxs)], v[:, :, : len(idxs)]
        if keep_on_device:
            return k, v
        return np.asarray(jax.device_get(k)), np.asarray(jax.device_get(v))

    def begin_remote(self, request: Context) -> Optional["RemoteHandle"]:
        """Decode side, before enqueueing a remote prefill: match the local
        prefix cache and pre-allocate the sequence's blocks (the reference
        allocates decode blocks up front and ships their ids in
        RemotePrefillRequest). Returns None when the pool can't cover the
        request — caller falls back to local serving's backpressure.

        Composes with the multi-host mirror: the reservation is pure
        host-side allocator work, and the eventual remote-KV landing
        (complete_remote -> _scatter_device) broadcasts the blocks so
        every process scatters its shards in lockstep."""
        req: PreprocessedRequest = request.data
        if isinstance(req, dict):
            req = PreprocessedRequest.from_dict(req)
        prompt = list(req.token_ids)
        if (
            not prompt
            or len(prompt) >= self.cfg.max_context
            # OOB ids: fall back to local serving, whose generate()
            # rejects them with the clean vocab-range error
            or not self._tokens_in_vocab(prompt)
            # adapter traffic: fall back to local serving (the remote
            # paths have no adapter lane — _guard_remote_adapter)
            or (
                self.adapters is not None
                and req.model
                and self.adapters.is_known(req.model)
            )
        ):
            return None
        seq = _Sequence(
            request=req,
            context=request.context,
            out_queue=asyncio.Queue(),
            tokens=prompt,
            prompt_len=len(prompt),
            trace=tracing.current_trace() if tracing.enabled() else None,
        )
        if self._reserve_for_prompt(seq) is None:
            return None
        self.stats["requests_total"] += 1
        self.stats["prompt_tokens_total"] += seq.prompt_len
        return RemoteHandle(
            seq=seq,
            skip_blocks=seq.committed,
            n_prompt_blocks=self.n_prompt_blocks(len(prompt)),
        )

    def release_remote(self, handle: "RemoteHandle") -> None:
        """Local-prefill fallback chosen after begin_remote: return the
        blocks untouched (no output emitted; caller re-submits locally)."""
        self.stats["requests_total"] -= 1
        self.stats["prompt_tokens_total"] -= handle.seq.prompt_len
        self.allocator.free(handle.seq.blocks)
        handle.seq.blocks = []

    async def complete_remote(
        self,
        handle: "RemoteHandle",
        first_token: int,
        k_data: Optional[np.ndarray],
        v_data: Optional[np.ndarray],
        first_lp: Optional[dict] = None,
        k_scales: Optional[np.ndarray] = None,
        v_scales: Optional[np.ndarray] = None,
    ) -> asyncio.Queue:
        """KV landed from the prefill worker: scatter it into the
        pre-allocated pages, register the sequence for continuous-batching
        decode, emit the (already sampled) first token with the logprob
        entry the prefill worker computed for it (if requested).
        ``k_scales``/``v_scales`` ([L, n] f32) mark a quantized wire
        delivery — the dequant fuses into the device-side scatter."""
        seq = handle.seq
        if k_data is not None and k_data.shape[2]:
            n = int(k_data.shape[2])
            idxs = [
                b.idx
                for b in seq.blocks[handle.skip_blocks : handle.skip_blocks + n]
            ]
            async with self._device_lock:
                await asyncio.get_running_loop().run_in_executor(
                    None, self._scatter_device, idxs, k_data, v_data,
                    k_scales, v_scales,
                )
        self.stats["prefix_cache_hits_tokens"] += seq.cached_prefix
        self._emit_token(seq, first_token, first_lp)
        if not seq.finished:
            self._commit_full_blocks(seq, written_len=seq.seq_len - 1)
            self._remote_ready.append(seq)
            self._wake.set()
        return seq.out_queue

    async def scatter_remote_segment(
        self, handle: "RemoteHandle", b0: int, k_data, v_data,
        k_scales=None, v_scales=None,
    ) -> None:
        """Streamed disagg landing (decode side): scatter ONE segment's
        blocks into the pre-allocated reservation the moment it arrives,
        instead of buffering the full [L, Hkv, n, bs, D] stack until
        prefill completes. ``b0`` is the block offset relative to the
        handle's skip_blocks. Replay-safe: a redelivered stream
        re-scatters the same still-uncommitted pages, so exactly-once
        queue semantics need no extra bookkeeping here.

        The data stack is padded HOST-side to the bucketed index count
        (pad rows target trash block 0), so the donated scatter compiles
        one program per segment-size bucket — not one per distinct
        segment geometry (test_compiled_perf guard)."""
        seq = handle.seq
        n = int(k_data.shape[2])
        if n == 0:
            return
        blocks = seq.blocks[handle.skip_blocks + b0 : handle.skip_blocks + b0 + n]
        if seq.finished or len(blocks) != n:
            raise RuntimeError(
                f"remote segment [{b0}, {b0 + n}) outside the live "
                f"reservation of {getattr(seq.context, 'id', '?')}"
            )
        idxs = [b.idx for b in blocks]
        async with self._device_lock:
            await asyncio.get_running_loop().run_in_executor(
                None, self._scatter_segment_device, idxs, k_data, v_data,
                k_scales, v_scales,
            )

    def _scatter_segment_device(self, idxs: list[int], k_data, v_data,
                                k_scales=None, v_scales=None) -> None:
        from .offload import _pad_idxs

        bucket = len(_pad_idxs(idxs))
        if int(k_data.shape[2]) < bucket:
            pad = [(0, 0)] * k_data.ndim
            pad[2] = (0, bucket - int(k_data.shape[2]))
            spad = ((0, 0), (0, bucket - int(k_data.shape[2])))
            if isinstance(k_data, np.ndarray):
                k_data = np.pad(k_data, pad)
                v_data = np.pad(v_data, pad)
                if k_scales is not None:
                    k_scales = np.pad(np.asarray(k_scales, np.float32), spad)
                    v_scales = np.pad(np.asarray(v_scales, np.float32), spad)
            else:  # device-resident segment (LocalKvPipe)
                k_data = jnp.pad(k_data, pad)
                v_data = jnp.pad(v_data, pad)
        self._scatter_device(idxs, k_data, v_data, k_scales, v_scales)

    def abort_remote(self, handle: "RemoteHandle", message: str = "") -> None:
        seq = handle.seq
        self.allocator.free(seq.blocks)
        seq.blocks = []
        seq.finished = True
        seq.out_queue.put_nowait(
            LLMEngineOutput(finish_reason=FinishReason.ERROR, text=message or None)
        )

    def _scatter_device(
        self, idxs: list[int], k_data: np.ndarray, v_data: np.ndarray,
        k_scales: Optional[np.ndarray] = None,
        v_scales: Optional[np.ndarray] = None,
    ) -> None:
        from .offload import (
            _pad_idxs,
            _scatter_blocks,
            _scatter_blocks_adopt,
            _scatter_blocks_q,
            _scatter_blocks_requant,
        )

        if self.offload is not None:
            # pending evictions may reference the very pages we're about to
            # overwrite — dispatch their gathers first (budget=None: the
            # landing KV may target any freshly allocated page)
            self.offload.flush_evictions_async(self.k_cache, self.v_cache)
        padded = _pad_idxs(idxs)
        if self.mirror is not None:
            # mirrored landing: broadcast the UNPADDED host blocks (the
            # scatter core pads on device), every process scatters its
            # cache shards in lockstep. Quantized wire deliveries never
            # reach mirrors (the negotiation requires the capability,
            # which mirror-backed engines do not advertise).
            assert k_scales is None, "mirror landings are full-width"
            self.k_cache, self.v_cache = self.mirror.lead_kv_scatter(
                self.k_cache, self.v_cache, padded,
                np.asarray(k_data), np.asarray(v_data),
            )
            return
        # only real blocks ship over PCIe — the scatter cores pad the
        # stack to the bucketed index count on device
        if self.k_scales is not None:
            # int8 device cache: the plain cores' astype would truncate
            # real values into int8 codes. A matching int8 wire payload
            # adopts verbatim (payload + scales, same codec); anything
            # else (full-width, fp8 wire) re-quantizes on landing.
            k_j, v_j = jnp.asarray(k_data), jnp.asarray(v_data)
            if k_scales is not None and k_j.dtype == self.k_cache.dtype:
                core = _scatter_blocks_adopt
            else:
                core = _scatter_blocks_requant
            if k_scales is None:
                shape = (self.k_scales.shape[0], int(k_j.shape[2]))
                ks_j = vs_j = jnp.ones(shape, jnp.float32)
            else:
                ks_j = jnp.asarray(np.asarray(k_scales, np.float32))
                vs_j = jnp.asarray(np.asarray(v_scales, np.float32))
            (
                self.k_cache, self.v_cache, self.k_scales, self.v_scales,
            ) = core(
                self.k_cache, self.v_cache, self.k_scales, self.v_scales,
                jnp.asarray(padded), k_j, v_j, ks_j, vs_j,
            )
            return
        if k_scales is not None:
            # quantized delivery: dequant fuses into the donated scatter
            self.k_cache, self.v_cache = _scatter_blocks_q(
                self.k_cache, self.v_cache, jnp.asarray(padded),
                jnp.asarray(k_data), jnp.asarray(v_data),
                jnp.asarray(np.asarray(k_scales, np.float32)),
                jnp.asarray(np.asarray(v_scales, np.float32)),
            )
            return
        self.k_cache, self.v_cache = _scatter_blocks(
            self.k_cache,
            self.v_cache,
            jnp.asarray(padded),
            jnp.asarray(k_data),
            jnp.asarray(v_data),
        )


@dataclass
class RemoteHandle:
    """A decode-side reservation for a remotely-prefilled sequence."""

    seq: _Sequence
    skip_blocks: int
    n_prompt_blocks: int


@dataclass
class _PrefillState:
    """An in-flight chunked prefill: one chunk runs per scheduler
    iteration so decode steps interleave with long prompts (the
    reference gets this from its patched engine scheduler's chunked
    prefill; here it's native to the loop)."""

    seq: _Sequence
    pos: int  # next prompt index to prefill
    # reserved host chain's in-flight h2d stage (offload.RestoreUpload,
    # begun at reservation), or None when the host tier missed
    upload: Optional[object] = None
    restored: bool = False  # host-tier restore landed (first chunk)
    # span anchors for the traced "engine.prefill" component: wall start
    # + accumulated per-chunk DEVICE milliseconds (the span duration —
    # wall time would absorb decode steps interleaved between chunks)
    t0_wall: float = field(default_factory=time.time)
    dev_ms: float = 0.0

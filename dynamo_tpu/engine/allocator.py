"""Paged KV block allocator with prefix reuse.

Host-side bookkeeping for the device-resident paged KV cache (the
reference's equivalent machinery is vLLM's block manager plus the Rust
reuse pool, lib/llm/src/kv/{manager,reuse}.rs). Responsibilities:

  * free-list allocation of fixed-size token blocks (block 0 is reserved
    as the trash block — padded-position writes land there harmlessly),
  * content addressing: full blocks carry a chained sequence hash
    (ref lib/llm/src/tokens.rs SequenceHash) so identical prefixes map to
    identical block chains,
  * prefix-cache reuse: freed blocks go to an LRU reuse pool indexed by
    sequence hash; new requests claim matching chains (radix-style match),
  * refcounting: shared prefix blocks are copy-free (multiple sequences
    reference the same immutable full block — ref kv/reserved.rs).

Events (block stored/removed) feed the KV router's global index via
dynamo_tpu.kv_router.publisher.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence


def block_token_hash(tokens: Sequence[int]) -> int:
    """Content hash of one block's tokens (local hash, ref
    kv_router/indexer.rs:87 LocalBlockHash over token bytes)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(b"tok:" + b",".join(str(t).encode() for t in tokens))
    return int.from_bytes(h.digest(), "big")


def chain_hash(parent: Optional[int], local: int) -> int:
    """Chained sequence hash (ref tokens.rs:166-202 SequenceHash)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(b"seq:" + (parent or 0).to_bytes(8, "big") + local.to_bytes(8, "big"))
    return int.from_bytes(h.digest(), "big")


def model_hash_salt(model: Optional[str]) -> Optional[int]:
    """Per-model root of the chain-hash namespace (multi-model serving).

    The chained sequence hash is the cross-process address of a KV block
    — radix index entries, reuse-pool keys, wire pulls all speak it. Two
    models sharing a token-identical prompt must NEVER share that
    address (an adapter's KV is a different function of the same
    tokens), so the ADAPTER's name hashes into the chain as a synthetic
    root parent. ``None``/empty (the base model) returns None — the
    chain starts unsalted, byte-identical to every pre-multi-model
    fleet: no hash drift for existing deployments, and base-model
    traffic on an adapter-serving fleet still prefix-shares with
    base-only peers."""
    if not model:
        return None
    h = hashlib.blake2b(digest_size=8)
    h.update(b"model:" + model.encode())
    return int.from_bytes(h.digest(), "big")


def sequence_block_hashes(
    tokens: Sequence[int], block_size: int, salt: Optional[int] = None
) -> list[tuple[int, int]]:
    """[(local_hash, chained_hash)] for each *full* block of the sequence.

    Uses the native C++ batch hasher when built (bit-identical output —
    hashes address KV blocks across processes, so both layers must agree).
    ``salt`` (``model_hash_salt``) roots the chain in a per-model
    namespace; the native hasher takes it too (seeding the chain's root
    parent), so adapter prompts keep the fast path — unless the loaded
    .so predates the salted entry point, in which case salted chains
    fall back to the pure-python walk below.
    """
    from .. import native

    if native.available() and (salt is None or native.salted_available()):
        return native.sequence_block_hashes(tokens, block_size, salt=salt)
    out: list[tuple[int, int]] = []
    parent: Optional[int] = salt
    for i in range(0, len(tokens) - len(tokens) % block_size, block_size):
        local = block_token_hash(tokens[i : i + block_size])
        parent = chain_hash(parent, local)
        out.append((local, parent))
    return out


@dataclass
class Block:
    idx: int  # device block index
    ref_count: int = 0
    seq_hash: Optional[int] = None  # chained hash when full+immutable
    local_hash: Optional[int] = None
    # restored via a router prefetch hint and not yet claimed — cleared
    # (and counted as h2d_prefetch_hits) on the first match_prefix claim
    prefetched: bool = False


class BlockAllocator:
    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        on_stored: Optional[Callable[[Block, Optional[int]], None]] = None,
        on_removed: Optional[Callable[[list[int]], None]] = None,
        on_evict: Optional[Callable[[int, Block], None]] = None,
    ):
        """``num_blocks`` includes the reserved trash block 0."""
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._blocks = [Block(i) for i in range(num_blocks)]
        self._free: list[int] = list(range(num_blocks - 1, 0, -1))  # stack; 0 reserved
        # full immutable blocks by chained hash (active, refcounted)
        self._by_hash: dict[int, int] = {}
        # reuse pool: freed-but-still-resident blocks, LRU ordered
        self._reuse: OrderedDict[int, int] = OrderedDict()  # seq_hash -> idx
        self.on_stored = on_stored
        self.on_removed = on_removed
        # fired when a reuse-pool block is about to be repurposed — the
        # offload tier's chance to copy it down (engine/offload.py)
        self.on_evict = on_evict
        # fired INSTEAD of on_removed when an offload tier takes the
        # evicted block (set alongside on_evict by the KV-event
        # publisher): the worker still holds the KV, one tier down, so
        # the router's radix index must keep counting it as residency —
        # the true removal arrives later via OffloadManager.on_dropped
        # when the block leaves the last local tier
        self.on_demoted: Optional[Callable[[list[int]], None]] = None
        # fired with the device block index every time a block becomes
        # fresh-mutable (free-list pop OR reuse-pool eviction) — the
        # engine's int8 device cache resets the block's scale-plane
        # entries here so stale absmax scales never survive recycling.
        # match_prefix claims deliberately do NOT fire it: a claimed
        # prefix block keeps its content AND its scales.
        self.on_allocated: Optional[Callable[[int], None]] = None

    # ---- stats ----
    @property
    def free_count(self) -> int:
        return len(self._free) + len(self._reuse)

    @property
    def used_count(self) -> int:
        return self.num_blocks - 1 - self.free_count

    @property
    def resident_count(self) -> int:
        """Blocks holding LIVE KV content: ref'd by sequences or parked
        in the content-addressed reuse pool (claimable prefix cache).
        This is what a live reshard actually re-lays — the reuse pool's
        prefix blocks survive a morph exactly like active ones."""
        return self.num_blocks - 1 - len(self._free)

    def usage(self) -> float:
        cap = self.num_blocks - 1
        return self.used_count / cap if cap else 0.0

    # ---- allocation ----
    def _pop_free(self) -> Optional[Block]:
        if self._free:
            b = self._blocks[self._free.pop()]
        elif self._reuse:
            # evict LRU from the reuse pool
            seq_hash, idx = self._reuse.popitem(last=False)
            b = self._blocks[idx]
            if self.on_evict:
                self.on_evict(seq_hash, b)
            if self.on_evict and self.on_demoted:
                # device -> offload tier: a demotion, not a removal
                self.on_demoted([seq_hash])
            elif self.on_removed:
                self.on_removed([seq_hash])
            b.seq_hash = None
            b.local_hash = None
            b.prefetched = False
        else:
            return None
        b.ref_count = 1
        if self.on_allocated:
            self.on_allocated(b.idx)
        return b

    def match_prefix(
        self,
        tokens: Sequence[int],
        hashes: Optional[list[tuple[int, int]]] = None,
    ) -> list[Block]:
        """Longest chain of cached full blocks matching the token prefix.
        Claims refs on the matched blocks (caller owns them). ``hashes``
        may carry precomputed ``sequence_block_hashes(tokens, block_size)``
        to avoid re-hashing."""
        matched: list[Block] = []
        if hashes is None:
            hashes = sequence_block_hashes(tokens, self.block_size)
        for _local, seq_hash in hashes:
            idx = self._by_hash.get(seq_hash)
            if idx is None and seq_hash in self._reuse:
                idx = self._reuse.pop(seq_hash)
                self._by_hash[seq_hash] = idx
            if idx is None:
                break
            b = self._blocks[idx]
            b.ref_count += 1
            matched.append(b)
        return matched

    def allocate(self, n: int) -> Optional[list[Block]]:
        """n fresh (mutable) blocks, or None if insufficient."""
        if self.free_count < n:
            return None
        out = []
        for _ in range(n):
            b = self._pop_free()
            assert b is not None
            out.append(b)
        return out

    def has_hash(self, seq_hash: int) -> bool:
        """Non-claiming device-residency probe (active OR reuse pool) —
        the prefetch path's radix check before it touches the host tier."""
        return seq_hash in self._by_hash or seq_hash in self._reuse

    def adopt_restored(
        self,
        block: Block,
        seq_hash: int,
        local_hash: Optional[int],
        parent_hash: Optional[int],
    ) -> bool:
        """Content-address a block whose KV was just restored from a
        lower tier (router-hinted prefetch): like
        :meth:`commit_full_block` but the hashes arrive precomputed from
        the hint instead of from tokens. The caller still holds the
        allocation ref; its :meth:`free` parks the block in the reuse
        pool where match_prefix claims it.

        Returns False without adopting when the hash is ALREADY device
        resident (a request raced its own hint and committed first):
        registering a second block under the hash would let free() park
        it over the existing reuse entry and orphan that block. The
        un-adopted block stays plain and free() returns it to the free
        list."""
        if self.has_hash(seq_hash):
            return False
        block.seq_hash = seq_hash
        block.local_hash = local_hash
        self._by_hash[seq_hash] = block.idx
        if self.on_stored:
            self.on_stored(block, parent_hash)
        return True

    def commit_full_block(self, block: Block, tokens: Sequence[int], parent_hash: Optional[int]) -> int:
        """Mark a now-full block immutable + content-addressed; returns its
        chained hash. Fires the stored event (feeds the KV router)."""
        local = block_token_hash(tokens)
        seq_hash = chain_hash(parent_hash, local)
        block.local_hash = local
        existing = self._by_hash.get(seq_hash)
        if existing is not None and existing != block.idx:
            # another sequence committed identical content first; keep ours
            # as a duplicate (device copy dedup is a later optimization)
            pass
        else:
            self._by_hash[seq_hash] = block.idx
        block.seq_hash = seq_hash
        if self.on_stored:
            self.on_stored(block, parent_hash)
        return seq_hash

    def free(self, blocks: list[Block]) -> None:
        """Release refs; full content-addressed blocks go to the reuse pool,
        partial blocks go straight to the free list."""
        removed_hashes: list[int] = []
        for b in blocks:
            if b.idx == 0:
                continue
            b.ref_count -= 1
            if b.ref_count > 0:
                continue
            if b.seq_hash is not None and self._by_hash.get(b.seq_hash) == b.idx:
                del self._by_hash[b.seq_hash]
                if b.seq_hash not in self._reuse:
                    self._reuse[b.seq_hash] = b.idx
                    self._reuse.move_to_end(b.seq_hash)
                else:
                    # belt-and-braces vs adopt_restored's residency
                    # check: parking over an existing reuse entry would
                    # orphan that block (ref 0, in neither _free nor
                    # _reuse) — the duplicate goes to the free list
                    b.seq_hash = None
                    b.local_hash = None
                    b.prefetched = False
                    self._free.append(b.idx)
            else:
                b.seq_hash = None
                b.local_hash = None
                b.prefetched = False
                self._free.append(b.idx)
        if removed_hashes and self.on_removed:
            self.on_removed(removed_hashes)

    def reset(self) -> None:
        for b in self._blocks:
            b.ref_count = 0
            b.seq_hash = None
            b.local_hash = None
            b.prefetched = False
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._by_hash.clear()
        self._reuse.clear()

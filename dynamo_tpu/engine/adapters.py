"""LoRA adapter registry: the engine's multi-model lane.

One worker process serves its base model plus N low-rank adapters
(ROADMAP item 1; the reference Dynamo reaches the same surface through
vLLM's LoRARequest plumbing). The registry owns:

  * **specs** — every adapter this worker MAY serve, from
    ``EngineConfig.adapters`` strings (``name:rank[:seed]`` for seeded
    synthetic adapters, or ``name=/path/to/adapter.npz`` for weights on
    disk). Spec'd-but-unstaged adapters are advertised, routable, and
    cold-loadable; they just aren't resident yet.
  * **host weights** — per-adapter A/B stacks as numpy arrays
    (materialized lazily: seeded init or npz load), the staging source.
  * **the device stack** — ONE stacked pytree
    ``{qa,qb,ka,kb,va,vb,oa,ob}`` of ``[L, NA, ...]`` jnp arrays,
    where NA is the adapter-count BUCKET (next power of two over the
    live capacity) and every adapter is zero-padded to the rank bucket.
    Zero padding is bitwise exact (``x @ 0 == 0``), so the compiled
    program count keys on the (NA, rank) bucket pair, never on the live
    adapter census (test_compiled_perf pins this).

Staging (``stage()``) copies one adapter host -> device into a free
slot, evicting the least-recently-used IDLE adapter when the slots are
full — evicting an adapter with in-flight sequences would corrupt their
streams, so that raises instead (the engine passes the in-use id set).
``pre_stage_weights`` hints (kv_router/publisher.py, PRESERVE-style)
land here ahead of the request so the request path finds the adapter
already resident: zero cold-load stall (bench_multi_model measures it).

Deltas attach to the attention projections (wq/wk/wv/wo). A rank-r
adapter on hidden size E costs 2*r*(E + O) parameters per projection
per layer — kilobytes at tiny ranks, which is the entire point: dozens
of fine-tunes share one resident base model.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional

import numpy as np

__all__ = [
    "AdapterSpec",
    "AdapterRegistry",
    "parse_adapter_specs",
    "LORA_KEYS",
]

#: device-stack leaves: (down, up) pairs for each attention projection
LORA_KEYS = ("qa", "qb", "ka", "kb", "va", "vb", "oa", "ob")

#: rank bucket quantum — ranks pad up to a multiple of this so two
#: adapters of rank 3 and 5 share one compiled program (both bucket 8)
_RANK_STEP = 8


def _rank_bucket(r: int) -> int:
    return max(_RANK_STEP, ((r + _RANK_STEP - 1) // _RANK_STEP) * _RANK_STEP)


def _count_bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


@dataclass(frozen=True)
class AdapterSpec:
    """One served adapter. ``path`` set -> weights come from an npz
    (leaves ``{qa,qb,...}.{layer}``); otherwise a seeded synthetic
    adapter (deterministic across processes — bench/test fixtures)."""

    name: str
    rank: int
    seed: int = 0
    path: Optional[str] = None

    def __post_init__(self):
        if not self.name:
            raise ValueError("adapter spec needs a name")
        if self.rank <= 0:
            raise ValueError(f"adapter {self.name!r}: rank must be > 0")


def parse_adapter_specs(specs) -> tuple[AdapterSpec, ...]:
    """``EngineConfig.adapters`` strings -> AdapterSpec tuple.

    Forms: ``name:rank``, ``name:rank:seed``, ``name=/path.npz``.
    Duplicate names refuse loudly (two adapters answering one model
    name would route nondeterministically)."""
    out: list[AdapterSpec] = []
    seen: set[str] = set()
    for s in specs or ():
        if isinstance(s, AdapterSpec):
            spec = s
        elif "=" in s:
            name, path = s.split("=", 1)
            spec = AdapterSpec(name=name.strip(), rank=1, path=path.strip())
        else:
            parts = s.split(":")
            if len(parts) not in (2, 3):
                raise ValueError(
                    f"bad adapter spec {s!r} (want name:rank[:seed] or "
                    "name=/path.npz)"
                )
            spec = AdapterSpec(
                name=parts[0].strip(),
                rank=int(parts[1]),
                seed=int(parts[2]) if len(parts) == 3 else 0,
            )
        if spec.name in seen:
            raise ValueError(f"duplicate adapter name {spec.name!r}")
        seen.add(spec.name)
        out.append(spec)
    return tuple(out)


class AdapterRegistry:
    """Thread-safe adapter store + device stack. The engine's scheduler
    thread reads ``device_stack()`` / ``slot_of()`` per dispatch; the
    event-loop thread stages/evicts via ``stage()`` — a single lock
    covers the mutations, and the stack swap is an atomic rebind."""

    def __init__(self, specs, model_cfg, max_live: int = 0,
                 dtype=None):
        self.specs: "OrderedDict[str, AdapterSpec]" = OrderedDict(
            (s.name, s) for s in parse_adapter_specs(specs)
        )
        if not self.specs:
            raise ValueError("AdapterRegistry needs at least one adapter")
        self.model_cfg = model_cfg
        self.max_live = max_live if max_live > 0 else len(self.specs)
        #: adapter-count bucket (static NA shape of the device stack)
        self.count_bucket = _count_bucket(self.max_live)
        #: rank bucket shared by every slot
        self.rank_bucket = _rank_bucket(
            max(s.rank for s in self.specs.values())
        )
        self._dtype = dtype
        self._lock = threading.Lock()
        self._host: dict[str, dict[str, np.ndarray]] = {}
        # staged name -> slot, LRU-ordered (move_to_end on every use)
        self._slots: "OrderedDict[str, int]" = OrderedDict()
        # only max_live slots hand out (the LIVE capacity); the stack's
        # NA axis is the count BUCKET, so any zero slots past max_live
        # are pure shape padding
        self._free_slots = list(range(self.max_live - 1, -1, -1))
        self._stack = None  # built lazily on first device need
        self.stats = {
            "adapters_staged_total": 0,
            "adapters_evicted_total": 0,
            "adapter_bytes_staged_total": 0,
        }

    # ---- introspection ----

    def names(self) -> list[str]:
        return list(self.specs)

    def is_known(self, name: str) -> bool:
        return name in self.specs

    def is_staged(self, name: str) -> bool:
        return name in self._slots

    def slot_of(self, name: str) -> Optional[int]:
        """Staged slot id (touches LRU), or None when not resident."""
        with self._lock:
            if name not in self._slots:
                return None
            self._slots.move_to_end(name)
            return self._slots[name]

    def staged_names(self) -> list[str]:
        return list(self._slots)

    # ---- host weights ----

    def host_weights(self, name: str) -> dict[str, np.ndarray]:
        """Materialize (and memoize) one adapter's host A/B stacks:
        ``{qa: [L, E, r], qb: [L, r, Oq], ...}`` at the shared rank
        bucket. Synthetic adapters draw from a seeded generator — A
        gets a small gaussian, B a smaller one (non-zero so adapter
        outputs genuinely differ from base: a zero-B adapter would make
        every bit-exactness test vacuous); npz adapters load + pad."""
        spec = self.specs[name]
        cached = self._host.get(name)
        if cached is not None:
            return cached
        cfg = self.model_cfg
        L, E, D = cfg.num_layers, cfg.hidden_size, cfg.head_dim
        Oq = cfg.num_heads * D
        Okv = cfg.num_kv_heads * D
        r, rb = spec.rank, self.rank_bucket
        dt = np.dtype(self._dtype or "float32")
        if spec.path:
            import numpy.lib.npyio  # noqa: F401 — explicit: plain npz

            data = np.load(spec.path)
            w = {}
            for key, odim in (("qa", Oq), ("qb", Oq), ("ka", Okv),
                              ("kb", Okv), ("va", Okv), ("vb", Okv),
                              ("oa", E), ("ob", E)):
                arr = np.asarray(data[key], dt)
                w[key] = arr
        else:
            rng = np.random.default_rng(
                abs(hash(("lora", name, spec.seed))) % (2**32)
            )
            scale_a = 1.0 / np.sqrt(E)
            # large enough that a synthetic adapter's greedy stream
            # visibly diverges from base on tiny test models (a delta
            # below argmax resolution would make every mixed-vs-solo
            # bit-exactness assertion vacuously pass)
            scale_b = 0.5 / r
            w = {
                "qa": rng.normal(0, scale_a, (L, E, r)),
                "qb": rng.normal(0, scale_b, (L, r, Oq)),
                "ka": rng.normal(0, scale_a, (L, E, r)),
                "kb": rng.normal(0, scale_b, (L, r, Okv)),
                "va": rng.normal(0, scale_a, (L, E, r)),
                "vb": rng.normal(0, scale_b, (L, r, Okv)),
                "oa": rng.normal(0, scale_a, (L, Oq, r)),
                "ob": rng.normal(0, scale_b, (L, r, E)),
            }
            w = {k: np.asarray(v, dt) for k, v in w.items()}
        # zero-pad the rank axis to the bucket (bitwise exact)
        for k in list(w):
            arr = w[k]
            ax = arr.ndim - 1 if k.endswith("a") else arr.ndim - 2
            if arr.shape[ax] < rb:
                pad = [(0, 0)] * arr.ndim
                pad[ax] = (0, rb - arr.shape[ax])
                w[k] = np.pad(arr, pad)
            elif arr.shape[ax] > rb:
                raise ValueError(
                    f"adapter {name!r} rank {arr.shape[ax]} exceeds the "
                    f"registry rank bucket {rb}"
                )
        self._host[name] = w
        return w

    def host_nbytes(self, name: str) -> int:
        return sum(a.nbytes for a in self.host_weights(name).values())

    # ---- device stack ----

    def _empty_stack(self):
        import jax.numpy as jnp

        cfg = self.model_cfg
        L, E, D = cfg.num_layers, cfg.hidden_size, cfg.head_dim
        Oq, Okv = cfg.num_heads * D, cfg.num_kv_heads * D
        NA, rb = self.count_bucket, self.rank_bucket
        dt = self._dtype or "float32"
        shapes = {
            "qa": (L, NA, E, rb), "qb": (L, NA, rb, Oq),
            "ka": (L, NA, E, rb), "kb": (L, NA, rb, Okv),
            "va": (L, NA, E, rb), "vb": (L, NA, rb, Okv),
            "oa": (L, NA, Oq, rb), "ob": (L, NA, rb, E),
        }
        return {k: jnp.zeros(s, dt) for k, s in shapes.items()}

    def device_stack(self):
        """The stacked ``[L, NA, ...]`` pytree every dispatch threads.
        Unstaged slots hold zeros (exact base behavior for stray ids)."""
        with self._lock:
            if self._stack is None:
                self._stack = self._empty_stack()
            return self._stack

    # ---- staging / eviction ----

    def stage(self, name: str, in_use: Optional[set] = None
              ) -> tuple[int, int]:
        """Make ``name`` device-resident; returns (slot, bytes_staged
        — 0 when it was already resident). Evicts the LRU idle adapter
        when slots are full; every staged adapter in-flight -> loud
        RuntimeError (the caller's backpressure, never silent
        corruption of a live stream's weights)."""
        if name not in self.specs:
            raise KeyError(f"unknown adapter {name!r}")
        import jax.numpy as jnp

        with self._lock:
            if name in self._slots:
                self._slots.move_to_end(name)
                return self._slots[name], 0
            if self._stack is None:
                self._stack = self._empty_stack()
            if self._free_slots:
                slot = self._free_slots.pop()
            else:
                victim = next(
                    (n for n in self._slots if n not in (in_use or ())),
                    None,
                )
                if victim is None:
                    raise RuntimeError(
                        "no evictable adapter slot: all "
                        f"{len(self._slots)} staged adapters are in use"
                    )
                slot = self._slots.pop(victim)
                self.stats["adapters_evicted_total"] += 1
            w = self.host_weights(name)
            stack = dict(self._stack)
            for k in LORA_KEYS:
                stack[k] = stack[k].at[:, slot].set(jnp.asarray(w[k]))
            self._stack = stack
            self._slots[name] = slot
            nbytes = sum(a.nbytes for a in w.values())
            self.stats["adapters_staged_total"] += 1
            self.stats["adapter_bytes_staged_total"] += nbytes
            return slot, nbytes

    def evict(self, name: str) -> bool:
        """Drop a staged adapter's slot back to the free list (weights
        stay in the stack until the slot is re-staged — ids never point
        at it, so the stale planes are unreachable)."""
        with self._lock:
            slot = self._slots.pop(name, None)
            if slot is None:
                return False
            self._free_slots.append(slot)
            self.stats["adapters_evicted_total"] += 1
            return True

"""User-supplied Python engines (``pystr:``/``pytok:``).

Re-design of the reference's generic Python engine (lib/llm/src/engines/
python.rs:43-70): ``out=pystr:file.py`` / ``out=pytok:file.py`` load a user
file and bridge its async generator into the AsyncEngine pipeline — the
escape hatch for serving any model/runtime behind the full frontend stack
(HTTP, routing, disagg) without touching framework code.

User-file contract — define an async generator::

    async def generate(request: dict):
        ...yield items...

  * ``pytok:`` — token-level engine, sits where the JAX core engine does
    (behind preprocessor + detokenizer). ``request`` is a
    PreprocessedRequest dict (token_ids, stop_conditions, sampling_options,
    …). Yield ``int`` token ids, ``list[int]``, LLMEngineOutput, or its
    dict form.
  * ``pystr:`` — text-level engine (reference "full" surface): ``request``
    additionally carries the rendered prompt at
    ``request["annotations"]["formatted_prompt"]``. Yield ``str`` text
    deltas, LLMEngineOutput, or its dict form. The detokenizer stage is
    skipped.

Optionally define ``async def init() -> None`` (called once before the
first request) and ``ENGINE_NAME`` (reported model name).
"""

from __future__ import annotations

import asyncio
import importlib.util
import os
import sys
from typing import AsyncIterator, Optional

from ..protocols.common import FinishReason, LLMEngineOutput
from ..runtime.engine import AsyncEngine, Context


def load_user_module(path: str):
    """Import a user engine file as an anonymous module (runpy-equivalent,
    ref engines/python.rs:43 loading via runpy)."""
    path = os.path.abspath(path)
    if not os.path.exists(path):
        raise FileNotFoundError(f"engine file not found: {path}")
    name = f"_dyn_user_engine_{abs(hash(path)) & 0xFFFFFF:x}"
    spec = importlib.util.spec_from_file_location(name, path)
    assert spec and spec.loader
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    if not hasattr(mod, "generate"):
        raise TypeError(f"{path} must define `async def generate(request)`")
    return mod


def _normalize(item, text_mode: bool) -> LLMEngineOutput:
    if isinstance(item, LLMEngineOutput):
        return item
    if isinstance(item, dict):
        return LLMEngineOutput.from_dict(item)
    if text_mode:
        if isinstance(item, str):
            return LLMEngineOutput(text=item)
    else:
        if isinstance(item, int):
            return LLMEngineOutput(token_ids=[item])
        if isinstance(item, (list, tuple)) and all(isinstance(t, int) for t in item):
            return LLMEngineOutput(token_ids=list(item))
    raise TypeError(
        f"user engine yielded {type(item).__name__}; expected "
        + ("str/dict/LLMEngineOutput" if text_mode else "int/list[int]/dict/LLMEngineOutput")
    )


class PythonEngine(AsyncEngine):
    """Bridges a user module's ``generate`` into the engine protocol.

    ``text_mode=False`` -> pytok (token-level core engine);
    ``text_mode=True``  -> pystr (text-level engine, detokenizer skipped).
    """

    def __init__(self, module, text_mode: bool):
        self._mod = module
        self.text_mode = text_mode
        self._initialized = not hasattr(module, "init")
        self._init_lock = asyncio.Lock()
        self.name = getattr(module, "ENGINE_NAME", None)

    @classmethod
    def from_spec(cls, spec: str) -> "PythonEngine":
        """``pystr:path`` or ``pytok:path`` (ref dynamo-run out= grammar)."""
        kind, _, path = spec.partition(":")
        if kind not in ("pystr", "pytok") or not path:
            raise ValueError(f"bad python engine spec {spec!r}")
        return cls(load_user_module(path), text_mode=(kind == "pystr"))

    async def generate(self, request: Context) -> AsyncIterator[LLMEngineOutput]:
        if not self._initialized:
            async with self._init_lock:
                if not self._initialized:
                    await self._mod.init()
                    self._initialized = True
        req = request.data
        req_dict = req if isinstance(req, dict) else req.to_dict()
        n_tokens = 0
        agen = self._mod.generate(req_dict).__aiter__()
        # Race each __anext__ against context.stopped() (same pattern as
        # SubprocessEngine.generate) so cancellation interrupts a user
        # generator that blocks between yields, instead of being observed
        # only after the next item arrives.
        try:
            while True:
                nxt = asyncio.ensure_future(agen.__anext__())
                stopped = asyncio.ensure_future(request.context.stopped())
                done, _ = await asyncio.wait(
                    [nxt, stopped], return_when=asyncio.FIRST_COMPLETED
                )
                if nxt not in done:
                    nxt.cancel()
                    try:
                        await nxt
                    except (asyncio.CancelledError, StopAsyncIteration):
                        pass
                    yield LLMEngineOutput(
                        finish_reason=FinishReason.CANCELLED,
                        prompt_tokens=len(req_dict.get("token_ids", [])),
                        completion_tokens=n_tokens,
                    )
                    return
                stopped.cancel()
                try:
                    item = nxt.result()
                except StopAsyncIteration:
                    break
                out = _normalize(item, self.text_mode)
                n_tokens += len(out.token_ids) or (1 if out.text else 0)
                if out.is_final():
                    out.prompt_tokens = out.prompt_tokens or len(
                        req_dict.get("token_ids", [])
                    )
                    out.completion_tokens = out.completion_tokens or n_tokens
                    yield out
                    return
                yield out
                # a generator whose __anext__ resolves immediately would
                # otherwise starve the race above — honor stop between yields
                if request.context.is_stopped():
                    yield LLMEngineOutput(
                        finish_reason=FinishReason.CANCELLED,
                        prompt_tokens=len(req_dict.get("token_ids", [])),
                        completion_tokens=n_tokens,
                    )
                    return
        finally:
            aclose = getattr(agen, "aclose", None)
            if aclose is not None:
                await aclose()
        # generator ended without a finish marker
        yield LLMEngineOutput(
            finish_reason=FinishReason.STOP if self.text_mode else FinishReason.LENGTH,
            prompt_tokens=len(req_dict.get("token_ids", [])),
            completion_tokens=n_tokens,
        )


def build_python_engine(
    spec: str, subprocess_mode: bool = False
) -> tuple[AsyncEngine, bool]:
    """Resolve an ``out=pystr:…|pytok:…`` spec. Returns (engine, text_mode).

    ``subprocess_mode=True`` isolates the user engine in a child process
    (ref: the vLLM/SGLang subprocess pattern, engines/vllm/worker.rs) —
    crashes or GIL-hogging user code can't take down the worker's control
    plane."""
    text_mode = spec.startswith("pystr:")
    if subprocess_mode:
        from .subproc import SubprocessEngine

        return SubprocessEngine(spec), text_mode
    return PythonEngine.from_spec(spec), text_mode

"""Subprocess-isolated engine: run a user Python engine in a child process.

Re-design of the reference's engine-subprocess pattern (engines/vllm/
worker.rs:65-115, engines/sglang/subprocess.rs): the worker process keeps
its control plane (leases, bus, HTTP) responsive by pushing the user
engine — arbitrary Python that may crash, block the GIL, or leak — into a
child process. The reference multiplexes zmq sockets (data/input/output/
heartbeat); here one unix-domain socket carries two-part-codec frames with
per-request ids:

  parent -> child:  {op:"generate", id} + data=json(request dict)
                    {op:"stop", id}          (client disconnected)
  child -> parent:  {op:"ready", name}       (engine loaded)
                    {op:"item", id} + data=json(LLMEngineOutput dict)
                    {op:"done", id}          (stream complete)
                    {op:"err",  id, error}   (request failed)

Child death fails all in-flight requests with FinishReason.ERROR — the
component stays up and later requests return errors rather than hanging.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import os
import sys
import tempfile
from typing import AsyncIterator, Optional

from ..protocols.common import FinishReason, LLMEngineOutput
from ..runtime.codec import TwoPartMessage, read_frame, write_frame
from ..runtime.engine import AsyncEngine, Context

logger = logging.getLogger(__name__)

_DONE = object()


class SubprocessEngine(AsyncEngine):
    """AsyncEngine facade whose generate() streams from a child process."""

    def __init__(self, spec: str, ready_timeout: float = 60.0):
        self.spec = spec
        self.ready_timeout = ready_timeout
        self.name: Optional[str] = None
        self._proc: Optional[asyncio.subprocess.Process] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._streams: dict[int, asyncio.Queue] = {}
        self._ids = itertools.count(1)
        self._lock = asyncio.Lock()
        self._started = False
        self._closing = False
        self._connected = asyncio.Event()
        self._server: Optional[asyncio.AbstractServer] = None
        self._sock_dir: Optional[tempfile.TemporaryDirectory] = None

    async def start(self) -> None:
        # late joiners wait for the child's connect instead of racing past
        # a _started flag into a writer that isn't there yet
        if not self._started:
            self._started = True
            self._sock_dir = tempfile.TemporaryDirectory(prefix="dyn-subproc-")
            sock_path = os.path.join(self._sock_dir.name, "engine.sock")

            async def on_connect(reader, writer):
                self._writer = writer
                self._connected.set()
                try:
                    await self._read_loop(reader)
                finally:
                    # close the transport so the server's connection count
                    # drops — wait_closed() blocks on lingering transports
                    writer.close()  # dynlint: disable=writer-wait-closed -- deliberate: wait_closed() wedges on the lingering child transport

            self._server = await asyncio.start_unix_server(on_connect, path=sock_path)
            self._proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "dynamo_tpu.engine.subproc",
                "--spec", self.spec, "--connect", sock_path,
            )
            asyncio.get_running_loop().create_task(self._reap())
        await asyncio.wait_for(self._connected.wait(), self.ready_timeout)

    async def _reap(self) -> None:
        assert self._proc is not None
        rc = await self._proc.wait()
        logger.warning("engine subprocess exited rc=%s", rc)
        for q in list(self._streams.values()):
            q.put_nowait(
                LLMEngineOutput(
                    finish_reason=FinishReason.ERROR,
                    text=f"engine subprocess died (rc={rc})",
                )
            )
            q.put_nowait(_DONE)
        self._streams.clear()
        self._writer = None
        if not self._closing:
            # reset startup state so the next generate() respawns a fresh
            # child instead of erroring forever while the worker keeps its
            # lease and continues to attract routed traffic
            self._started = False
            self._connected = asyncio.Event()
            if self._server is not None:
                # respawn path: close() alone releases the listener fd;
                # wait_closed() can wedge on the dead child's transport
                self._server.close()  # dynlint: disable=writer-wait-closed -- respawn path, see comment
                self._server = None
            if self._sock_dir is not None:
                self._sock_dir.cleanup()
                self._sock_dir = None

    async def _read_loop(self, reader) -> None:
        while True:
            frame = await read_frame(reader)
            if frame is None:
                return
            head = frame.header_json() or {}
            op, rid = head.get("op"), head.get("id")
            if op == "ready":
                self.name = head.get("name")
            elif op == "item" and rid in self._streams:
                self._streams[rid].put_nowait(
                    LLMEngineOutput.from_dict(json.loads(frame.data))
                )
            elif op == "done" and rid in self._streams:
                self._streams[rid].put_nowait(_DONE)
            elif op == "err" and rid in self._streams:
                self._streams[rid].put_nowait(
                    LLMEngineOutput(
                        finish_reason=FinishReason.ERROR, text=head.get("error")
                    )
                )
                self._streams[rid].put_nowait(_DONE)

    async def _send(self, head: dict, data: bytes = b"") -> None:
        async with self._lock:
            if self._writer is None:
                raise RuntimeError("engine subprocess not running")
            # frame-serialization lock: held across the write by design
            # so frames never interleave on the pipe
            await write_frame(self._writer, TwoPartMessage.from_json(head, data))  # dynlint: disable=await-in-lock -- frame-serialization lock, guards only this stream

    async def close(self) -> None:
        self._closing = True
        if self._proc and self._proc.returncode is None:
            try:
                await self._send({"op": "shutdown"})
                await asyncio.wait_for(self._proc.wait(), 5.0)
            except Exception:  # noqa: BLE001
                self._proc.kill()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        if self._sock_dir is not None:
            self._sock_dir.cleanup()

    async def generate(self, request: Context) -> AsyncIterator[LLMEngineOutput]:
        await self.start()
        req = request.data
        req_dict = req if isinstance(req, dict) else req.to_dict()
        rid = next(self._ids)
        q: asyncio.Queue = asyncio.Queue()
        self._streams[rid] = q
        try:
            await self._send({"op": "generate", "id": rid},
                             json.dumps(req_dict).encode())
            while True:
                get = asyncio.ensure_future(q.get())
                stopped = asyncio.ensure_future(request.context.stopped())
                done, _ = await asyncio.wait(
                    [get, stopped], return_when=asyncio.FIRST_COMPLETED
                )
                if get in done:
                    stopped.cancel()
                    item = get.result()
                    if item is _DONE:
                        return
                    yield item
                    if item.is_final():
                        return
                else:
                    get.cancel()
                    try:
                        await self._send({"op": "stop", "id": rid})
                    except RuntimeError:
                        pass
                    yield LLMEngineOutput(finish_reason=FinishReason.CANCELLED)
                    return
        finally:
            self._streams.pop(rid, None)


# ---------------- child-process side ----------------


async def _child_main(spec: str, sock_path: str) -> None:
    from .python_engine import PythonEngine

    engine = PythonEngine.from_spec(spec)
    reader, writer = await asyncio.open_unix_connection(sock_path)
    wlock = asyncio.Lock()
    tasks: dict[int, asyncio.Task] = {}

    async def send(head: dict, data: bytes = b"") -> None:
        async with wlock:
            # frame-serialization lock: held across the write by design
            await write_frame(writer, TwoPartMessage.from_json(head, data))  # dynlint: disable=await-in-lock -- frame-serialization lock, guards only this stream

    class _ChildContext:
        """Minimal AsyncEngineContext for the child side."""

        def __init__(self):
            self._stop = asyncio.Event()

        def id(self) -> str:
            return "subproc"

        def is_stopped(self) -> bool:
            return self._stop.is_set()

        async def stopped(self) -> None:
            await self._stop.wait()

        def stop_generating(self) -> None:
            self._stop.set()

    async def run_request(rid: int, req_dict: dict, ctx: "_ChildContext") -> None:
        try:
            async for out in engine.generate(Context(req_dict, context=ctx)):
                await send({"op": "item", "id": rid},
                           json.dumps(out.to_dict()).encode())
            await send({"op": "done", "id": rid})
        except Exception as e:  # noqa: BLE001
            await send({"op": "err", "id": rid, "error": f"{type(e).__name__}: {e}"})
        finally:
            tasks.pop(rid, None)
            tasks_ctx.pop(rid, None)

    tasks_ctx: dict[int, _ChildContext] = {}
    await send({"op": "ready", "name": engine.name})
    while True:
        frame = await read_frame(reader)
        if frame is None:
            return
        head = frame.header_json() or {}
        op, rid = head.get("op"), head.get("id")
        if op == "generate":
            # register the context synchronously so a 'stop' frame arriving
            # before the task's first tick still lands
            ctx = _ChildContext()
            tasks_ctx[rid] = ctx
            tasks[rid] = asyncio.get_running_loop().create_task(
                run_request(rid, json.loads(frame.data), ctx)
            )
        elif op == "stop" and rid in tasks_ctx:
            tasks_ctx[rid].stop_generating()
        elif op == "shutdown":
            return


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--spec", required=True)
    p.add_argument("--connect", required=True)
    args = p.parse_args(argv)
    asyncio.run(_child_main(args.spec, args.connect))


if __name__ == "__main__":
    main()

"""The native JAX/TPU inference engine: paged KV allocator, continuous
batching scheduler, and the AsyncEngine facade the serving stack links to.

This replaces the reference's wrapped engines (vLLM/SGLang/TRT-LLM,
lib/llm/src/engines/*) with a first-party TPU engine.
"""

from .allocator import BlockAllocator
from .engine import EngineConfig, JaxEngine

__all__ = ["BlockAllocator", "EngineConfig", "JaxEngine"]

"""Host-DRAM KV offload tier: the TPU equivalent of the reference's
multi-tier block manager (lib/llm/src/kv/{manager,reuse}.rs + the pinned
host tier and CUDA scatter/gather CopyStream, kv/layer.rs:619-1132,
kernels/block_copy.cu).

On TPU-VM the "pinned host" tier is plain host RAM: evicted device blocks
are gathered on device ([L, Hkv, n, bs, D] slices of the paged cache),
fetched with one d2h transfer, and parked in an LRU pool keyed by the
block's *chained* sequence hash. A later prefill whose prefix misses the
device pool probes this pool and restores hits with one h2d upload plus a
jitted scatter back into freshly allocated pages (docs/architecture.md:91
— host offload buys ~40% TTFT on multi-turn workloads).

Transfer shapes are bucketed (pad block-index vectors with the trash
block 0 — scatters to it are harmless by design) so the jitted
gather/scatter pair compiles O(log max_batch) programs, not one per
transfer size.
"""

from __future__ import annotations

from collections import OrderedDict
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_BUCKETS = [1, 2, 4, 8, 16, 32, 64, 128]


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return -(-n // 128) * 128


def _pad_idxs(idxs: list[int]) -> np.ndarray:
    out = np.zeros(_bucket(len(idxs)), np.int32)  # pad with trash block 0
    out[: len(idxs)] = idxs
    return out


@jax.jit
def _gather_blocks(k_cache, v_cache, idxs):
    """[L, Hkv, N, bs, D] x [n] -> two [L, Hkv, n, bs, D] stacks."""
    return jnp.take(k_cache, idxs, axis=2), jnp.take(v_cache, idxs, axis=2)


@partial(jax.jit, donate_argnames=("k_cache", "v_cache"))
def _scatter_blocks(k_cache, v_cache, idxs, k_data, v_data):
    return (
        k_cache.at[:, :, idxs].set(k_data),
        v_cache.at[:, :, idxs].set(v_data),
    )


class HostKvPool:
    """LRU pool of offloaded blocks: seq_hash -> (k, v) host arrays of
    shape [L, Hkv, bs, D] (ref kv/reuse.rs AvailableBlocks, one tier up)."""

    def __init__(self, capacity_blocks: int):
        self.capacity = capacity_blocks
        self._data: OrderedDict[int, tuple[np.ndarray, np.ndarray]] = OrderedDict()
        self.stored_total = 0
        self.hit_blocks_total = 0

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._data

    def put(self, seq_hash: int, k: np.ndarray, v: np.ndarray) -> None:
        if self.capacity <= 0:
            return
        if seq_hash in self._data:
            self._data.move_to_end(seq_hash)
            return
        while len(self._data) >= self.capacity:
            self._data.popitem(last=False)
        self._data[seq_hash] = (k, v)

    def take(self, seq_hash: int) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """Remove and return (the block is moving back to the device tier,
        which re-registers it in the device reuse pool on release)."""
        return self._data.pop(seq_hash, None)

    def match_chain(self, seq_hashes: list[int]) -> int:
        """Longest consecutive run of hashes resident in the pool."""
        n = 0
        for h in seq_hashes:
            if h not in self._data:
                break
            n += 1
        return n


class OffloadManager:
    """Orchestrates device<->host block movement for one engine.

    Runs entirely on the engine's device-executor thread (the same thread
    that issues prefill/decode), so gathers of evicted blocks are always
    dispatched before the compute that overwrites those pages — ordering
    by construction, the role CUDA stream events play in the reference's
    CopyStream (kv/layer.rs:619).
    """

    def __init__(self, host_blocks: int):
        self.pool = HostKvPool(host_blocks)
        # (seq_hash, device_block_idx) evictions awaiting d2h
        self._pending: list[tuple[int, int]] = []

    # -- allocator callback (event-loop thread) --
    def on_evict(self, seq_hash: int, block_idx: int) -> None:
        self._pending.append((seq_hash, block_idx))

    # -- admission-time reservation (event-loop thread) --
    def reserve_chain(
        self, seq_hashes: list[int]
    ) -> tuple[list[int], list[tuple[np.ndarray, np.ndarray]]]:
        """Take the longest resident prefix OUT of the pool (so a later
        flush_evictions can't LRU it away before restore runs)."""
        n = self.pool.match_chain(seq_hashes)
        hashes = seq_hashes[:n]
        return hashes, [self.pool.take(h) for h in hashes]

    def unreserve(self, hashes: list[int], data) -> None:
        """Admission failed after reservation — return blocks to the pool."""
        for h, (k, v) in zip(hashes, data):
            self.pool.put(h, k, v)

    # -- device-thread operations --
    def flush_evictions(self, k_cache, v_cache) -> None:
        """Gather + d2h all pending evicted blocks into the host pool."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        idxs = _pad_idxs([idx for _h, idx in pending])
        kg, vg = _gather_blocks(k_cache, v_cache, jnp.asarray(idxs))
        kg, vg = np.asarray(jax.device_get(kg)), np.asarray(jax.device_get(vg))
        for i, (seq_hash, _idx) in enumerate(pending):
            # copy: a view would pin the whole padded gather batch in RAM
            # for as long as any one block stays resident
            self.pool.put(seq_hash, kg[:, :, i].copy(), vg[:, :, i].copy())
        self.pool.stored_total += len(pending)

    def restore(self, k_cache, v_cache, data, block_idxs: list[int]):
        """Upload reserved host blocks (from :meth:`reserve_chain`) into
        device pages ``block_idxs``; returns updated caches."""
        assert len(data) == len(block_idxs)
        if not data:
            return k_cache, v_cache
        ks = [k for k, _v in data]
        vs = [v for _k, v in data]
        self.pool.hit_blocks_total += len(data)
        n = _bucket(len(block_idxs))
        k_host = np.stack(ks, axis=2)  # [L, Hkv, n, bs, D]
        v_host = np.stack(vs, axis=2)
        if n != len(block_idxs):
            pad = ((0, 0), (0, 0), (0, n - len(block_idxs)), (0, 0), (0, 0))
            k_host = np.pad(k_host, pad)
            v_host = np.pad(v_host, pad)
        return _scatter_blocks(
            k_cache,
            v_cache,
            jnp.asarray(_pad_idxs(block_idxs)),
            jnp.asarray(k_host),
            jnp.asarray(v_host),
        )

    def stats(self) -> dict:
        return {
            "offload_blocks_resident": len(self.pool),
            "offload_blocks_stored_total": self.pool.stored_total,
            "offload_hit_blocks_total": self.pool.hit_blocks_total,
        }

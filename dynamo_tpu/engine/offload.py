"""Host-DRAM KV offload tier: the TPU equivalent of the reference's
multi-tier block manager (lib/llm/src/kv/{manager,reuse}.rs + the pinned
host tier and CUDA scatter/gather CopyStream, kv/layer.rs:619-1132,
kernels/block_copy.cu).

On TPU-VM the "pinned host" tier is plain host RAM: evicted device blocks
are gathered on device ([L, Hkv, n, bs, D] slices of the paged cache),
fetched with one d2h transfer, and parked in an LRU pool keyed by the
block's *chained* sequence hash. A later prefill whose prefix misses the
device pool probes this pool and restores hits with one h2d upload plus a
jitted scatter back into freshly allocated pages (docs/architecture.md:91
— host offload buys ~40% TTFT on multi-turn workloads).

Transfer shapes are bucketed (pad block-index vectors with the trash
block 0 — scatters to it are harmless by design) so the jitted
gather/scatter pair compiles O(log max_batch) programs, not one per
transfer size.

**Async tier (PRESERVE-style overlap).** Both transfer directions are
pipelined so the single scheduler loop never blocks on PCIe:

  * **d2h**: :meth:`OffloadManager.flush_evictions_async` dispatches the
    bucketed device gather in the calling (device-executor) thread — so
    it is device-stream-ordered BEFORE the compute that overwrites the
    evicted pages, the invariant the sync path also relied on — but the
    blocking d2h fetch + host-pool insertion run on a small offload
    executor, double-buffered (at most ``_MAX_INFLIGHT_FLUSHES`` gathers
    in flight) with a per-iteration block budget so decode windows are
    never starved by offload traffic. Evictions whose pages the caller
    is about to overwrite are flushed unconditionally (``must_idxs``).
  * **h2d**: restore splits into :meth:`begin_upload` — stacks the
    reserved host chain and starts the device upload on the offload
    executor the moment admission claims it — and :meth:`finish_upload`,
    a cheap on-device scatter that only waits if the upload hasn't
    landed. The wait actually paid is tracked as *exposed* restore
    latency vs. the *hidden* remainder (``restore_latency_hidden_frac``).

**Disk tier (third tier).** With ``disk_blocks > 0`` the host pool's LRU
overflow *demotes* to an on-disk block store (:class:`DiskKvStore`, one
content-addressed file per block: small validated header + raw ``k``/``v``
bytes) instead of dropping, and restores *promote* back through host DRAM
— :meth:`OffloadManager.promote_chain` reads disk hits into a host-DRAM
staging area (``_staged``, exempt from the pool's LRU capacity so chains
longer than the host budget restore whole) on the offload executor, after
which ``reserve_chain``/``begin_upload``/``finish_upload`` (and their
hidden-vs-exposed accounting) work unchanged.
Eviction story per tier: device LRU → host, host LRU → disk, disk
LRU/TTL → dropped. All disk I/O runs on the offload executor (or a
sync backstop off the event loop) — the ``blocking-disk-io`` dynlint
rule keeps the loop itself filesystem-free.

**Fleet tier (peer prefix pulls).** Dropping a block from the *last*
local tier is the only true removal: the manager queues the hash
(:meth:`flush_dropped` → ``on_dropped``) so the KV-event publisher can
tell the router, which otherwise keeps counting demoted blocks as this
worker's radix residency — that residency is what lets a *peer* worker
pull the chain from here (:meth:`export_chain` serves host∪disk blocks
non-destructively; :meth:`land_peer_chain` parks a pulled chain in the
host staging area, where the normal prefetch restore promotes it to
device).

Under the multi-host mirror every transfer stays a synchronous mirrored
op (leader/follower lockstep leaves no room for background landing) and
the disk/fleet tiers are disabled.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import struct
import tempfile
import threading
import time
import zlib
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

# shared with the wire codec (disagg/transfer.py) so the two
# serialization planes can't drift on which dtypes round-trip
from ..models.quant import KV_INT8_QMAX, KV_SCALE_EPS
from ..utils.dtypes import np_dtype as _resolve_dtype
from . import kvquant
from .kvquant import entry_nbytes

logger = logging.getLogger(__name__)

_BUCKETS = [1, 2, 4, 8, 16, 32, 64, 128]

#: double-buffer depth for async d2h flushes: one gather landing while
#: the next is being filled; more would just queue PCIe traffic
_MAX_INFLIGHT_FLUSHES = 2


def _device_fetch(arr) -> np.ndarray:
    """The one d2h sync point (module-level so tests can inject latency)."""
    return np.asarray(jax.device_get(arr))


def _device_put(arr: np.ndarray):
    """The one h2d entry point (module-level so tests can inject latency)."""
    return jnp.asarray(arr)


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return -(-n // 128) * 128


def _pad_idxs(idxs: list[int]) -> np.ndarray:
    out = np.zeros(_bucket(len(idxs)), np.int32)  # pad with trash block 0
    out[: len(idxs)] = idxs
    return out


def gather_blocks_core(k_cache, v_cache, idxs):
    """[L, Hkv, N, bs, D] x [n] -> two [L, Hkv, n, bs, D] stacks.
    Unjitted core — StepMirror re-jits it with mesh out_shardings for the
    mirrored multi-host paths."""
    return jnp.take(k_cache, idxs, axis=2), jnp.take(v_cache, idxs, axis=2)


def scatter_blocks_core(k_cache, v_cache, idxs, k_data, v_data):
    """Pads the data stack to the (bucketed) index count ON DEVICE — host
    callers ship only real blocks over PCIe/DCN; pad rows target trash
    block 0 and never leave HBM."""
    n, m = idxs.shape[0], k_data.shape[2]
    if m < n:  # static at trace time
        pad = [(0, 0)] * k_data.ndim
        pad[2] = (0, n - m)
        k_data = jnp.pad(k_data, pad)
        v_data = jnp.pad(v_data, pad)
    return (
        k_cache.at[:, :, idxs].set(k_data.astype(k_cache.dtype)),
        v_cache.at[:, :, idxs].set(v_data.astype(v_cache.dtype)),
    )


def stack_pieces(entries: list, which: int) -> list[np.ndarray]:
    """Stack per-piece host blocks ([L, Hl, bs, D] each) into per-piece
    [L, Hl, m, bs, D] stacks (m = len(entries), UNPADDED — the scatter
    core pads to the bucketed index count on device). ``entries`` are
    host-tier values (k_pieces, v_pieces); ``which`` selects k (0) or
    v (1). ONE implementation shared by the leader's
    OffloadManager.restore and the follower's offload_restore replay —
    both sides must build identically-shaped global arrays."""
    n_pieces = len(entries[0][which])
    return [
        np.stack([e[which][j] for e in entries], axis=2)
        for j in range(n_pieces)
    ]


def scatter_blocks_q_core(k_cache, v_cache, idxs, k_data, v_data, ks, vs):
    """Quantized-restore twin of :func:`scatter_blocks_core`: the host
    ships int8/fp8 payloads + per-(layer, block) f32 scales (HALF the
    PCIe bytes of a full-width restore), and the dequantize fuses into
    the device-side scatter. Pad rows (scale 0) land zeros in trash
    block 0 and never leave HBM."""
    n, m = idxs.shape[0], k_data.shape[2]
    if m < n:  # static at trace time
        pad = [(0, 0)] * k_data.ndim
        pad[2] = (0, n - m)
        k_data = jnp.pad(k_data, pad)
        v_data = jnp.pad(v_data, pad)
        ks = jnp.pad(ks, ((0, 0), (0, n - m)))
        vs = jnp.pad(vs, ((0, 0), (0, n - m)))
    kd = k_data.astype(jnp.float32) * ks[:, None, :, None, None]
    vd = v_data.astype(jnp.float32) * vs[:, None, :, None, None]
    return (
        k_cache.at[:, :, idxs].set(kd.astype(k_cache.dtype)),
        v_cache.at[:, :, idxs].set(vd.astype(v_cache.dtype)),
    )


def gather_blocks_s_core(k_cache, v_cache, k_scales, v_scales, idxs):
    """Scale-plane twin of :func:`gather_blocks_core` for the int8
    DEVICE cache (models/quant.py KV_CACHE_DTYPES): the gathered pages
    are quantized codes, so their per-(layer, page) scales ride along —
    [L, N] planes -> [L, n] stacks matching the tier entry form."""
    kg, vg = gather_blocks_core(k_cache, v_cache, idxs)
    return (
        kg, vg,
        jnp.take(k_scales, idxs, axis=1),
        jnp.take(v_scales, idxs, axis=1),
    )


def _pad_block_stack(idxs, k_data, v_data, ks, vs):
    n, m = idxs.shape[0], k_data.shape[2]
    if m < n:  # static at trace time
        pad = [(0, 0)] * k_data.ndim
        pad[2] = (0, n - m)
        k_data, v_data = jnp.pad(k_data, pad), jnp.pad(v_data, pad)
        ks = jnp.pad(ks, ((0, 0), (0, n - m)))
        vs = jnp.pad(vs, ((0, 0), (0, n - m)))
    return k_data, v_data, ks, vs


def scatter_blocks_adopt_core(k_cache, v_cache, k_scales, v_scales, idxs,
                              k_data, v_data, ks, vs):
    """int8 payload -> int8-with-scales DEVICE cache: the tier/wire
    codec (engine/kvquant.py) and the device planes share the same
    symmetric-absmax per-(layer, block) scheme at qmax 127, so the
    payload scatters VERBATIM and the carried scales are adopted into
    the engine's scale planes — no dequantize bounce in either
    direction. Pad rows target trash block 0; their zero scales clamp
    to the epsilon floor (block 0's scale is never read meaningfully)."""
    k_data, v_data, ks, vs = _pad_block_stack(idxs, k_data, v_data, ks, vs)
    return (
        k_cache.at[:, :, idxs].set(k_data.astype(k_cache.dtype)),
        v_cache.at[:, :, idxs].set(v_data.astype(v_cache.dtype)),
        k_scales.at[:, idxs].set(
            jnp.maximum(ks.astype(jnp.float32), KV_SCALE_EPS)
        ),
        v_scales.at[:, idxs].set(
            jnp.maximum(vs.astype(jnp.float32), KV_SCALE_EPS)
        ),
    )


def scatter_blocks_requant_core(k_cache, v_cache, k_scales, v_scales, idxs,
                                k_data, v_data, ks, vs):
    """Full-width or foreign-codec (fp8-wire) landing into the int8
    DEVICE cache: dequantize with the carried scales (callers pass ones
    for a full-width payload), re-quantize each block against fresh
    per-(layer, block) absmax, and land payload + plane scales in one
    donated dispatch. The cast in :func:`scatter_blocks_core` /
    :func:`scatter_blocks_q_core` would silently truncate reals to int
    codes here — this core is the only correct landing."""
    k_data, v_data, ks, vs = _pad_block_stack(idxs, k_data, v_data, ks, vs)
    kd = k_data.astype(jnp.float32) * ks[:, None, :, None, None]
    vd = v_data.astype(jnp.float32) * vs[:, None, :, None, None]
    new_ks = jnp.maximum(
        jnp.max(jnp.abs(kd), axis=(1, 3, 4)) / KV_INT8_QMAX, KV_SCALE_EPS
    )
    new_vs = jnp.maximum(
        jnp.max(jnp.abs(vd), axis=(1, 3, 4)) / KV_INT8_QMAX, KV_SCALE_EPS
    )
    qk = jnp.clip(jnp.round(kd / new_ks[:, None, :, None, None]),
                  -KV_INT8_QMAX, KV_INT8_QMAX)
    qv = jnp.clip(jnp.round(vd / new_vs[:, None, :, None, None]),
                  -KV_INT8_QMAX, KV_INT8_QMAX)
    return (
        k_cache.at[:, :, idxs].set(qk.astype(k_cache.dtype)),
        v_cache.at[:, :, idxs].set(qv.astype(v_cache.dtype)),
        k_scales.at[:, idxs].set(new_ks),
        v_scales.at[:, idxs].set(new_vs),
    )


_gather_blocks = jax.jit(gather_blocks_core)
_gather_blocks_s = jax.jit(gather_blocks_s_core)
_scatter_blocks = jax.jit(
    scatter_blocks_core, donate_argnames=("k_cache", "v_cache")
)
_scatter_blocks_q = jax.jit(
    scatter_blocks_q_core, donate_argnames=("k_cache", "v_cache")
)
_scatter_blocks_adopt = jax.jit(
    scatter_blocks_adopt_core,
    donate_argnames=("k_cache", "v_cache", "k_scales", "v_scales"),
)
_scatter_blocks_requant = jax.jit(
    scatter_blocks_requant_core,
    donate_argnames=("k_cache", "v_cache", "k_scales", "v_scales"),
)


class DiskKvStore:
    """Third KV tier: content-addressed on-disk block store.

    One file per block (``<seq_hash:016x>.kvb``): a small validated
    header (magic, format version, shapes, dtype, payload CRC) followed
    by the raw ``k`` then ``v`` bytes. Crash safety by construction:
    writes land in a temp file and ``os.replace`` into place (a crash
    mid-write leaves no entry), and every read re-validates magic /
    version / declared sizes / CRC — a truncated, corrupt or
    version-mismatched entry is a clean cache miss (discarded, counted
    in ``corrupt_discards``), never an exception on the restore path.

    Capacity is LRU over an in-memory index rebuilt from the directory
    at construction (so a restarted worker keeps its disk tier);
    ``ttl_s > 0`` additionally expires entries by residency age. Every
    hash that leaves the store (LRU, TTL, corruption) is queued in
    ``drain_dropped`` so the owner can publish the residency loss.

    Quantized tier (format v2): an entry may carry int8/fp8 payloads
    plus the per-layer f32 scale vectors (engine/kvquant.py), declared
    in the header (``quant``/``ks_bytes``/``vs_bytes``) and covered by
    the same CRC — a truncated or corrupted scale section reads as a
    clean miss exactly like a torn payload. v1 (pre-scale) entries are
    clean misses by the existing version check. With ``block_bytes``
    set, capacity becomes a BYTE budget (``capacity_blocks`` full-width
    blocks' worth), so quantized entries pack ~2x the blocks into the
    same disk footprint — that is the capacity win, made real.

    All methods do blocking filesystem I/O — callers must be on the
    offload executor (or an explicitly-off-loop backstop), never the
    serving event loop (the ``blocking-disk-io`` dynlint rule).
    """

    MAGIC = b"DKV1"
    VERSION = 2

    def __init__(self, path: str, capacity_blocks: int, ttl_s: float = 0.0,
                 block_bytes: int = 0):
        self.path = path
        self.capacity = capacity_blocks
        self.ttl_s = ttl_s
        #: full-width per-block bytes; > 0 switches the LRU from entry
        #: COUNT to a byte budget of capacity_blocks * block_bytes
        self.block_bytes = int(block_bytes)
        self._lock = threading.Lock()
        # seq_hash -> (stored_at monotonic, file bytes); OrderedDict = LRU
        self._index: OrderedDict[int, tuple[float, int]] = OrderedDict()
        self._used_bytes = 0
        self._dropped: list[int] = []
        self.stored_total = 0
        self.hit_blocks_total = 0
        self.corrupt_discards = 0
        self.evictions_total = 0
        os.makedirs(path, exist_ok=True)
        now = time.monotonic()
        for name in sorted(os.listdir(path)):
            if not name.endswith(".kvb"):
                continue  # temp files from a crashed writer, etc.
            try:
                h = int(name[:-4], 16)
            except ValueError:
                continue
            # budget accounting counts PAYLOAD bytes (like the host
            # pool's entry_nbytes): filesize minus magic + header, read
            # back from the length prefix — charging the ~250B header
            # would silently shave one full-width block off every
            # byte-budgeted tier
            f = os.path.join(path, name)
            try:
                sz = os.path.getsize(f)
                with open(f, "rb") as fh:
                    pre = fh.read(8)
                hlen = (
                    struct.unpack("<I", pre[4:8])[0] if len(pre) == 8 else 0
                )
                sz = max(sz - 8 - hlen, 0)
            except OSError:
                continue
            self._index[h] = (now, sz)
            self._used_bytes += sz

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    def contains(self, seq_hash: int) -> bool:
        with self._lock:
            return seq_hash in self._index

    def _file(self, seq_hash: int) -> str:
        return os.path.join(self.path, f"{seq_hash:016x}.kvb")

    def _discard_locked(self, seq_hash: int, corrupt: bool = False) -> None:
        _t, sz = self._index.pop(seq_hash, (0.0, 0))
        self._used_bytes -= sz
        self._dropped.append(seq_hash)
        if corrupt:
            self.corrupt_discards += 1
        else:
            self.evictions_total += 1
        try:
            os.remove(self._file(seq_hash))
        except OSError:
            pass

    def _sweep_ttl_locked(self) -> None:
        if self.ttl_s <= 0:
            return
        cutoff = time.monotonic() - self.ttl_s
        expired = [h for h, (t, _sz) in self._index.items() if t < cutoff]
        for h in expired:
            self._discard_locked(h)

    def _over_budget_locked(self, extra: int = 0) -> bool:
        if self.block_bytes > 0:
            return (
                self._used_bytes + extra > self.capacity * self.block_bytes
                and len(self._index) > 0
            )
        return len(self._index) > self.capacity

    def put(self, seq_hash: int, k: np.ndarray, v: np.ndarray,
            scales: Optional[tuple] = None) -> bool:
        """Demote one block to disk; returns whether it is resident
        afterwards (False = capacity 0 or the write failed). ``scales``
        = (ks, vs) per-layer f32 vectors for a quantized payload
        (engine/kvquant.py) — written as the v2 scale section."""
        if self.capacity <= 0:
            return False
        with self._lock:
            self._sweep_ttl_locked()
            if seq_hash in self._index:
                self._index.move_to_end(seq_hash)
                return True
        k_bytes = np.ascontiguousarray(k).tobytes()
        v_bytes = np.ascontiguousarray(v).tobytes()
        ks_bytes = vs_bytes = b""
        if scales is not None:
            ks_bytes = np.ascontiguousarray(
                scales[0], dtype=np.float32).tobytes()
            vs_bytes = np.ascontiguousarray(
                scales[1], dtype=np.float32).tobytes()
        crc = zlib.crc32(k_bytes)
        for part in (v_bytes, ks_bytes, vs_bytes):
            crc = zlib.crc32(part, crc)
        header = json.dumps({
            "v": self.VERSION,
            "hash": seq_hash,
            "k_shape": list(k.shape),
            "v_shape": list(v.shape),
            "dtype": str(k.dtype),
            "k_bytes": len(k_bytes),
            "v_bytes": len(v_bytes),
            # quantized-entry scale section (0/absent = full-width):
            # per-layer f32 absmax scales, one vector per K/V
            "ks_bytes": len(ks_bytes),
            "vs_bytes": len(vs_bytes),
            "crc": crc,
        }).encode()
        final = self._file(seq_hash)
        try:
            fd, tmp = tempfile.mkstemp(dir=self.path, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(self.MAGIC)
                    f.write(struct.pack("<I", len(header)))
                    f.write(header)
                    f.write(k_bytes)
                    f.write(v_bytes)
                    f.write(ks_bytes)
                    f.write(vs_bytes)
                os.replace(tmp, final)  # atomic: no half-written entries
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
        except OSError:
            logger.warning("disk tier write failed for %x (block dropped)",
                           seq_hash, exc_info=True)
            return False
        # payload bytes only (header excluded — see the rescan comment)
        nbytes = (len(k_bytes) + len(v_bytes)
                  + len(ks_bytes) + len(vs_bytes))
        with self._lock:
            self._index[seq_hash] = (time.monotonic(), nbytes)
            self._index.move_to_end(seq_hash)
            self._used_bytes += nbytes
            self.stored_total += 1
            while self._over_budget_locked() and len(self._index) > 1:
                old = next(iter(self._index))
                self._discard_locked(old)
            if self._over_budget_locked():
                # one entry bigger than the whole byte budget: it can
                # never be resident — discard it as an eviction
                self._discard_locked(seq_hash)
                return False
        return True

    def get(self, seq_hash: int) -> Optional[tuple]:
        """Read + validate one block; any validation failure discards
        the entry and reads as a miss (None). Returns an ENTRY tuple:
        (k, v) full-width, or (k, v, ks, vs) when the entry carries a
        quantized payload + scale section."""
        with self._lock:
            self._sweep_ttl_locked()
            if seq_hash not in self._index:
                return None
            self._index.move_to_end(seq_hash)
        try:
            with open(self._file(seq_hash), "rb") as f:
                raw = f.read()
        except OSError:
            with self._lock:
                self._discard_locked(seq_hash, corrupt=True)
            return None
        got = self._decode(seq_hash, raw)
        if got is None:
            with self._lock:
                self._discard_locked(seq_hash, corrupt=True)
            return None
        with self._lock:
            self.hit_blocks_total += 1
        return got

    def _decode(self, seq_hash: int, raw: bytes) -> Optional[tuple]:
        try:
            if raw[:4] != self.MAGIC:
                return None
            (hlen,) = struct.unpack("<I", raw[4:8])
            head = json.loads(raw[8 : 8 + hlen])
            if head.get("v") != self.VERSION or head.get("hash") != seq_hash:
                # includes v1 (pre-scale-section) entries: old-format
                # files are clean misses, never misread payloads
                return None
            nk, nv = int(head["k_bytes"]), int(head["v_bytes"])
            # tolerant reads: absent scale keys = full-width entry
            nks = int(head.get("ks_bytes") or 0)
            nvs = int(head.get("vs_bytes") or 0)
            payload = raw[8 + hlen :]
            if len(payload) != nk + nv + nks + nvs:
                return None  # truncated/padded payload OR scale section
            if zlib.crc32(payload) != head.get("crc"):
                return None
            dt = _resolve_dtype(head["dtype"])
            k = np.frombuffer(payload, dt, nk // dt.itemsize).reshape(
                head["k_shape"]
            )
            v = np.frombuffer(
                payload, dt, nv // dt.itemsize, offset=nk
            ).reshape(head["v_shape"])
            if not nks:
                return k, v
            ks = np.frombuffer(payload, np.float32, nks // 4, offset=nk + nv)
            vs = np.frombuffer(
                payload, np.float32, nvs // 4, offset=nk + nv + nks
            )
            if ks.shape[0] != k.shape[0] or vs.shape[0] != v.shape[0]:
                return None  # scale vectors must be per-layer
            return k, v, ks, vs
        except Exception:  # noqa: BLE001 — any malformed entry = miss
            logger.debug("disk tier entry %x malformed", seq_hash,
                         exc_info=True)
            return None

    def match_chain(self, seq_hashes: list[int]) -> int:
        """Longest consecutive run resident in the index (index-only —
        cheap enough for the event loop; the data reads stay on the
        executor)."""
        with self._lock:
            self._sweep_ttl_locked()
            n = 0
            for h in seq_hashes:
                if h not in self._index:
                    break
                n += 1
            return n

    def drain_dropped(self) -> list[int]:
        with self._lock:
            dropped, self._dropped = self._dropped, []
            return dropped


class HostKvPool:
    """LRU pool of offloaded blocks: seq_hash -> ENTRY host tuples —
    ``(k, v)`` full-width [L, Hkv, bs, D] pairs, or ``(k, v, ks, vs)``
    quantized payloads with per-layer f32 scales (engine/kvquant.py).
    (ref kv/reuse.rs AvailableBlocks, one tier up.)

    With ``block_bytes`` set, capacity is a BYTE budget
    (``capacity_blocks`` full-width blocks' worth) charged at each
    entry's actual bytes — full-width entries charge exactly one
    block, quantized entries ~half, so the same budget holds ~2x the
    quantized blocks. ``block_bytes == 0`` keeps the legacy entry-count
    LRU (mirror pools, standalone tests).

    ``on_overflow(hash, entry) -> bool`` (when set) is offered every LRU
    overflow victim — True means a lower tier kept it (demotion, not a
    drop); ``on_drop(hash)`` fires for entries that truly left this
    worker's tiers. :meth:`apply_plan` bypasses both (the mirror path
    accounts for its plan's drops explicitly)."""

    def __init__(self, capacity_blocks: int, block_bytes: int = 0):
        self.capacity = capacity_blocks
        self.block_bytes = int(block_bytes)
        self._data: OrderedDict[int, tuple] = OrderedDict()
        self._used_bytes = 0
        self.stored_total = 0
        self.hit_blocks_total = 0
        self.on_overflow: Optional[Callable] = None
        self.on_drop: Optional[Callable] = None

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._data

    def _over_budget(self, extra: int = 0) -> bool:
        if self.block_bytes > 0:
            return (
                self._used_bytes + extra > self.capacity * self.block_bytes
                and len(self._data) > 0
            )
        return len(self._data) >= self.capacity

    def put(self, seq_hash: int, k: np.ndarray, v: np.ndarray,
            scales: Optional[tuple] = None) -> None:
        """Insert one entry; ``scales`` = (ks, vs) for a quantized
        payload."""
        if self.capacity <= 0:
            return
        if seq_hash in self._data:
            self._data.move_to_end(seq_hash)
            return
        entry = (k, v) if scales is None else (k, v, scales[0], scales[1])
        incoming = entry_nbytes(entry) if self.block_bytes > 0 else 0
        while self._over_budget(incoming):
            old_h, old_e = self._data.popitem(last=False)
            self._used_bytes -= (
                entry_nbytes(old_e) if self.block_bytes > 0 else 0
            )
            kept = bool(self.on_overflow and self.on_overflow(old_h, old_e))
            if not kept and self.on_drop:
                self.on_drop(old_h)
        self._data[seq_hash] = entry
        self._used_bytes += incoming

    def take(self, seq_hash: int) -> Optional[tuple]:
        """Remove and return (the block is moving back to the device tier,
        which re-registers it in the device reuse pool on release)."""
        got = self._data.pop(seq_hash, None)
        if got is not None and self.block_bytes > 0:
            self._used_bytes -= entry_nbytes(got)
        return got

    def peek(self, seq_hash: int) -> Optional[tuple]:
        """Return WITHOUT removing (router-hinted prefetch reads the
        chain non-destructively: the entry stays claimable by a racing
        admission until the prefetched copy is committed on device —
        content is hash-addressed and immutable, so concurrent readers
        are safe)."""
        got = self._data.get(seq_hash)
        if got is not None:
            self._data.move_to_end(seq_hash)
        return got

    def match_chain(self, seq_hashes: list[int]) -> int:
        """Longest consecutive run of hashes resident in the pool."""
        n = 0
        for h in seq_hashes:
            if h not in self._data:
                break
            n += 1
        return n

    def plan_puts(
        self, hashes: list[int]
    ) -> tuple[list[int], list[bool], list[int]]:
        """Simulate :meth:`put` over ``hashes`` without data: returns
        (drops, keep, final_order) — drops = currently-resident hashes the
        LRU will evict, keep[i] = whether hashes[i] is resident AFTER all
        puts (False when a later insert evicts it or capacity is 0), and
        the final recency order. The multi-host mirror broadcasts the plan
        so follower tiers apply the leader's policy verbatim instead of
        running their own."""
        sim = OrderedDict((k, None) for k in self._data)
        for h in hashes:
            if self.capacity <= 0:
                break
            if h in sim:
                sim.move_to_end(h)
                continue
            while len(sim) >= self.capacity:
                sim.popitem(last=False)
            sim[h] = None
        drops = [k for k in self._data if k not in sim]
        seen: set = set()
        keep = []
        for h in hashes:
            keep.append(h in sim and h not in seen)
            seen.add(h)
        return drops, keep, list(sim.keys())

    def apply_plan(self, drops, keep, final_order, hashes, data_for) -> None:
        """Apply a :meth:`plan_puts` result: drop evictions, insert kept
        entries (``data_for(i)`` supplies hashes[i]'s value), and restore
        the simulated recency order. (Mirror-only path — plan
        simulation is entry-count based, which coincides with the byte
        budget exactly while every entry is full-width.)"""
        for h in drops:
            old = self._data.pop(h, None)
            if old is not None and self.block_bytes > 0:
                self._used_bytes -= entry_nbytes(old)
        for i, h in enumerate(hashes):
            if keep[i] and h not in self._data:
                e = data_for(i)
                self._data[h] = e
                if self.block_bytes > 0:
                    self._used_bytes += entry_nbytes(e)
        for h in final_order:
            if h in self._data:
                self._data.move_to_end(h)


class _FlushTask:
    """One in-flight async d2h flush: the gather was dispatched on the
    device thread; ``future`` lands the host copies into the pool."""

    __slots__ = ("hashes", "future")

    def __init__(self, hashes: list[int], future):
        self.hashes = hashes
        self.future = future


class RestoreUpload:
    """One reserved host chain's h2d stage: stacking + device upload run
    on the offload executor from the moment admission reserves the chain;
    :meth:`OffloadManager.finish_upload` scatters (and only then waits,
    if the upload hasn't landed). ``future`` is None on the synchronous
    paths (mirror, async tier disabled, empty chain)."""

    __slots__ = ("hashes", "data", "idxs", "future", "t_start", "t_landed",
                 "cancelled")

    def __init__(self, hashes: list, data: list, idxs: list[int]):
        self.hashes = hashes
        self.data = data
        self.idxs = idxs
        self.future = None
        self.t_start = time.monotonic()
        self.t_landed: Optional[float] = None
        self.cancelled = False


class OffloadManager:
    """Orchestrates device<->host block movement for one engine.

    Device dispatch (gathers, scatters) happens on the engine's
    device-executor thread, so transfers are always stream-ordered before
    the compute that overwrites those pages — ordering by construction,
    the role CUDA stream events play in the reference's CopyStream
    (kv/layer.rs:619). The blocking host side of each transfer (d2h
    fetch, host stacking, h2d upload) runs on ``_exec``, a 2-thread
    offload executor, so the scheduler loop and the device thread never
    wait on PCIe unless a restore is needed *right now* (module
    docstring). ``_lock`` guards the pool + pending/in-flight structures
    across the event-loop, device-executor and offload-executor threads.
    """

    def __init__(self, host_blocks: int, mirror=None,
                 flush_budget: int = 64, async_tier: bool = True,
                 disk_blocks: int = 0, disk_path: Optional[str] = None,
                 tier_ttl_s: float = 0.0, kv_quant: str = "none",
                 block_bytes: int = 0, full_dtype: str = "float32"):
        if kv_quant not in kvquant.KV_QUANT_MODES:
            raise ValueError(
                f"kv_quant must be one of {kvquant.KV_QUANT_MODES}"
            )
        # per-block tier/wire codec (engine/kvquant.py): every block
        # entering the host pool (and everything demoted past it) is
        # stored int8/fp8 + per-layer scales; restores dequantize in
        # the device-side scatter. The mirror path stays full-width —
        # its lockstep broadcasts ship per-shard piece lists the block
        # codec doesn't describe.
        self.kv_quant = kv_quant if mirror is None else "none"
        #: full-width per-block bytes (engine.kv_block_bytes): > 0 turns
        #: the host/disk capacities into byte budgets so quantized
        #: entries actually pack ~2x the blocks into the same budget
        self.block_bytes = int(block_bytes) if mirror is None else 0
        #: dtype quantized entries dequantize back to when a consumer
        #: needs full-width bytes (legacy peers, mode-none restarts)
        self.full_dtype = full_dtype
        self.kv_quant_blocks_total = 0
        self.kv_quant_bytes_saved_total = 0
        self.pool = HostKvPool(host_blocks, block_bytes=self.block_bytes)
        # (seq_hash, device_block_idx) evictions awaiting d2h
        self._pending: list[tuple[int, int]] = []
        # async tier state: in-flight d2h flush tasks + transfer knobs.
        # The mirror path is always synchronous (lockstep broadcasts).
        self.async_tier = async_tier and mirror is None
        self.flush_budget = max(1, flush_budget)
        self._lock = threading.RLock()
        self._exec: Optional[ThreadPoolExecutor] = None
        self._closed = False
        self._inflight_flushes: list[_FlushTask] = []
        # stats (ISSUE: d2h_flush_async / h2d_prefetch_hits /
        # restore_latency_hidden_frac)
        self.d2h_flush_async_total = 0
        self.d2h_flush_failures = 0
        self.h2d_prefetch_blocks_total = 0
        self.h2d_prefetch_hits = 0
        self.h2d_uploads_started = 0
        self.h2d_uploads_cancelled = 0
        self.restore_hidden_s = 0.0
        self.restore_exposed_s = 0.0
        # third tier (local disk/SSD): host-pool LRU overflow demotes
        # here via the offload executor; restores promote back through
        # host DRAM (promote_chain). Mirror engines keep two tiers —
        # lockstep broadcasts have no background thread to write on.
        self.disk: Optional[DiskKvStore] = None
        self.disk_demotions_total = 0
        # auto-created tempdirs are OURS to remove at close(); an
        # explicit disk_path persists across restarts by design
        self._own_disk_path: Optional[str] = None
        if disk_blocks > 0 and mirror is None:
            if disk_path is None:
                disk_path = tempfile.mkdtemp(prefix="dynkv-")
                self._own_disk_path = disk_path
            self.disk = DiskKvStore(disk_path, disk_blocks, ttl_s=tier_ttl_s,
                                    block_bytes=self.block_bytes)
            self.pool.on_overflow = self._demote_to_disk
        self.pool.on_drop = self._note_dropped_one
        # fleet tier: hashes that left the LAST local tier, queued for
        # the KV-event publisher (flush_dropped runs on the event loop —
        # the callback publishes on the bus, which is not thread-safe
        # from the executor threads most drops originate on)
        self.on_dropped: Optional[Callable[[list[int]], None]] = None
        self._dropped_pending: list[int] = []
        # transfer-cost calibration (kv_router/costmodel.py, wired by
        # the engine): restore landings observe the "host" link class,
        # disk promotions the "disk" class. None = no calibration.
        self.cost_model = None
        # device-tier residency probe (engine wires allocator.has_hash):
        # a queued drop is only PUBLISHED as a removal if the hash is
        # resident in NO tier at publish time — a stale disk copy aging
        # out while the block sits hot on device (or re-staged in the
        # host tier) must not remove live residency from the router,
        # where the tree's chain-cascade would take the worker's whole
        # downstream subtree with it
        self.device_has: Optional[Callable[[int], bool]] = None
        # int8-with-scales DEVICE cache (kv_cache_dtype="int8"): the
        # engine publishes its per-(layer, page) scale planes so tier
        # traffic speaks the device codec directly. device_planes()
        # -> (k_scales, v_scales) [L, N] f32 (or None when the cache is
        # full-width / scale-free fp8); device_planes_set re-homes
        # updated planes on the engine after a donated scatter. Flushes
        # then gather int8 pages + their scales and ADOPT them as tier
        # entries when the tier codec is int8 too (zero re-encode, the
        # d2h already moved 1-byte elements); restores scatter payload +
        # scales back into cache + planes. device_requants_total counts
        # blocks forced OFF the device codec on the way out (full-width
        # or fp8-tier bounce) — folded into the engine's
        # kv_device_export_requant_total gauge at scrape time.
        self.device_planes: Optional[Callable[[], Optional[tuple]]] = None
        self.device_planes_set: Optional[Callable[[tuple], None]] = None
        self.device_requants_total = 0
        # staging area for INCOMING chains (disk promotions, peer
        # pulls): a reserve-side overlay the host pool's LRU capacity
        # does not apply to. Promoting a chain longer than the host
        # budget through pool.put would thrash — each put demotes the
        # chain's own earlier blocks back out before match_chain ever
        # sees a consecutive run. Entries are transient: popped by
        # reserve/discard, LRU-capped at a small multiple of the host
        # budget (disk-backed entries re-read for free; a capped-out
        # peer block just shortens that pull's restore).
        self._staged: OrderedDict[int, tuple] = OrderedDict()
        # peer-pulled hashes resident in the staging/host tier but not
        # yet claimed by a request — claiming one means its transfer
        # latency was fully hidden (peer_pull_hidden_frac). Insertion-
        # ordered + capped: a pull whose request never arrives would
        # otherwise track its hashes forever (evicting the oldest only
        # undercounts hidden_frac for ancient unclaimed pulls)
        self._peer_hashes: OrderedDict[int, None] = OrderedDict()
        self._peer_track_cap = 8192
        self.peer_pull_blocks_total = 0
        self.peer_pull_blocks_claimed = 0
        self.peer_serve_blocks_total = 0
        # multi-host: flushes/restores become mirrored ops — every process
        # gathers/scatters in lockstep and parks its OWN cache shards in
        # host DRAM (pool values are per-unique-shard piece lists instead
        # of full arrays). The leader's LRU plan is broadcast so follower
        # tiers stay content-identical (parallel/multihost.py).
        self.mirror = mirror
        # leader-side pool mutations that happen OUTSIDE a mirrored op
        # (unreserve's re-pool evictions, discards of already-restored
        # reservations) queue their follower-side drops here; the next
        # flush/restore broadcast carries them. Invariant: the follower
        # tier must remain a superset of {leader pool + reservations}.
        self._deferred_drops: list[int] = []

    # -- allocator callback (event-loop thread) --
    def on_evict(self, seq_hash: int, block_idx: int) -> None:
        with self._lock:
            self._pending.append((seq_hash, block_idx))

    def _executor(self) -> ThreadPoolExecutor:
        if self._closed:
            # a late hint/flush after engine close must not resurrect
            # threads on a torn-down engine
            raise RuntimeError("offload manager is closed")
        if self._exec is None:
            self._exec = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="kv-offload"
            )
            # executor-pressure surface: deepest d2h/disk backlog this
            # pool reaches exports as executor_pending_max (sanitizer
            # counter -> load_metrics -> WorkerLoad -> gauge)
            from ..analysis.sanitizer import register_executor

            register_executor(self._exec, "offload")
        return self._exec

    #: admission-side cap on waiting for a relevant in-flight flush to
    #: land: normally the d2h was dispatched a scheduler iteration ago
    #: and the wait is ~zero, but a wedged executor must degrade to a
    #: cache miss (shorter reserved chain), not a stalled event loop
    _JOIN_TIMEOUT_S = 1.0

    def _join_flushes_for(self, seq_hashes: list[int]) -> None:
        """Wait (bounded) for in-flight flushes holding any of
        ``seq_hashes`` to land; paying the usually-zero wait only when a
        probe could actually hit keeps admission from trading a whole
        prefix recompute for a near-landed copy. On timeout the unlanded
        entries simply don't match — they land later and serve the next
        request."""
        need = set(seq_hashes)
        with self._lock:
            tasks = [
                t for t in self._inflight_flushes
                if not need.isdisjoint(t.hashes)
            ]
        deadline = time.monotonic() + self._JOIN_TIMEOUT_S
        for t in tasks:
            try:
                t.future.result(max(0.0, deadline - time.monotonic()))
            except Exception:  # noqa: BLE001 — timeout/failure = cache miss
                logger.debug("flush join missed (treated as cache miss)",
                             exc_info=True)
        with self._lock:
            self._reap_flushes_locked()

    def _reap_flushes_locked(self) -> None:
        alive = []
        for t in self._inflight_flushes:
            if not t.future.done():
                alive.append(t)
                continue
            exc = t.future.exception()
            if exc is not None:
                # a failed landing silently drops those blocks from the
                # host tier (multi-turn TTFT regresses to recompute) —
                # that must be visible to operators, not just absent,
                # and the router must stop counting them as residency
                self.d2h_flush_failures += 1
                self._dropped_pending.extend(t.hashes)
                logger.warning(
                    "async d2h flush of %d blocks failed (KV dropped "
                    "from the host tier): %s", len(t.hashes), exc,
                )
        self._inflight_flushes = alive

    def has_pending(self) -> bool:
        return bool(self._pending)

    def has_inflight_flushes(self) -> bool:
        return bool(self._inflight_flushes)

    # -- disk tier (third tier) --

    def _note_dropped_one(self, seq_hash: int) -> None:
        # callers hold self._lock (pool.put paths) or don't need it for
        # a list append under the GIL; re-entrant lock keeps this cheap
        with self._lock:
            self._dropped_pending.append(seq_hash)

    # -- tier codec (engine/kvquant.py) --

    def _encode_entry(self, k: np.ndarray, v: np.ndarray) -> tuple:
        """Full-width block -> this manager's tier entry form. Executor
        threads only (the quantize is real CPU work per block)."""
        if self.kv_quant == "none":
            return (k, v)
        full = k.nbytes + v.nbytes
        qk, qv, ks, vs = kvquant.quantize_entry(k, v, self.kv_quant)
        with self._lock:
            self.kv_quant_blocks_total += 1
            self.kv_quant_bytes_saved_total += max(
                full - entry_nbytes((qk, qv, ks, vs)), 0
            )
        return (qk, qv, ks, vs)

    def _encode_device_entry(self, qk: np.ndarray, qv: np.ndarray,
                             ks: np.ndarray, vs: np.ndarray) -> tuple:
        """Device-codec block (int8 payload + per-layer scales gathered
        straight off the int8 device cache) -> this manager's tier entry.
        An int8 tier adopts it verbatim — no CPU quantize, and the d2h
        already moved 1-byte elements. Any other tier codec forces the
        bounce back through full width (counted: device_requants_total).
        Executor threads only."""
        full = (qk.size + qv.size) * np.dtype(self.full_dtype).itemsize
        if self.kv_quant == "int8":
            entry = (qk, qv, ks, vs)
            with self._lock:
                self.kv_quant_blocks_total += 1
                self.kv_quant_bytes_saved_total += max(
                    full - entry_nbytes(entry), 0
                )
            return entry
        with self._lock:
            self.device_requants_total += 1
        k, v = kvquant.dequantize_entry(qk, qv, ks, vs, self.full_dtype)
        return self._encode_entry(k, v)

    def _normalize_entry(self, entry: tuple) -> tuple:
        """Coerce an incoming entry (disk read after a --kv-quant flip,
        peer-pulled wire payload) to THIS manager's mode, so every
        pool/staged entry is uniform and restore/export stacks never
        mix dtypes. Executor threads only."""
        quantized = len(entry) > 2 and entry[2] is not None
        if self.kv_quant == "none":
            if not quantized:
                return entry
            k, v = kvquant.dequantize_entry(
                entry[0], entry[1], entry[2], entry[3], self.full_dtype
            )
            return (k, v)
        want = kvquant.quant_dtype(self.kv_quant)
        if quantized and entry[0].dtype == want:
            return entry
        if quantized:  # a different quant mode (pre-restart flag flip)
            k, v = kvquant.dequantize_entry(
                entry[0], entry[1], entry[2], entry[3], self.full_dtype
            )
            entry = (k, v)
        return self._encode_entry(entry[0], entry[1])

    @staticmethod
    def _entry_scales(entry: tuple) -> Optional[tuple]:
        return (entry[2], entry[3]) if len(entry) > 2 else None

    def _demote_to_disk(self, seq_hash: int, entry: tuple) -> bool:
        """Host-pool overflow victim -> disk, via the offload executor
        (pool.put callers hold ``_lock`` on whatever thread they're on;
        the file write itself must never run on the event loop). True =
        the block stays resident (a failed write later re-queues the
        hash as a drop)."""
        if self.disk is None or self._closed:
            return False
        if self.disk.contains(seq_hash):
            return True  # already demoted once; content is immutable
        try:
            self._executor().submit(self._disk_demote_worker, seq_hash, entry)
        except RuntimeError:
            return False
        return True

    def _disk_demote_worker(self, seq_hash: int, entry: tuple) -> None:
        kept = False
        try:
            kept = self.disk.put(
                seq_hash, entry[0], entry[1],
                scales=self._entry_scales(entry),
            )
        except Exception:  # noqa: BLE001 — a failed demotion is a drop
            logger.warning("disk demotion of %x failed", seq_hash,
                           exc_info=True)
        with self._lock:
            if kept:
                self.disk_demotions_total += 1
            else:
                self._dropped_pending.append(seq_hash)
            self._dropped_pending.extend(self.disk.drain_dropped())

    def _staged_cap(self) -> int:
        return max(4 * self.pool.capacity, 64)

    def _stage_locked(self, seq_hash: int, entry: tuple, peer: bool = False,
                      fresh: Optional[set] = None) -> None:
        self._staged[seq_hash] = entry
        self._staged.move_to_end(seq_hash)
        if fresh is not None:
            fresh.add(seq_hash)
        while len(self._staged) > self._staged_cap():
            old = next(iter(self._staged))
            if fresh is not None and old in fresh:
                # NEVER evict the chain being staged right now: reserve
                # matches a CONSECUTIVE prefix from the chain head, so
                # popping its own head would zero the whole restore.
                # Per-call staging is capped at _staged_cap() blocks
                # (callers truncate the TAIL), so the transient
                # over-cap here is bounded at ~2x while a previous
                # call's stale entries drain
                break
            self._staged.popitem(last=False)
            self._peer_hashes.pop(old, None)
            if self.disk is None or not self.disk.contains(old):
                # left the last tier (a capped-out peer block; disk-
                # backed stagings re-read for free and stay resident)
                self._dropped_pending.append(old)
        if peer:
            self._peer_hashes[seq_hash] = None
            while len(self._peer_hashes) > self._peer_track_cap:
                self._peer_hashes.popitem(last=False)

    def _match_chain_locked(self, seq_hashes: list[int]) -> int:
        """Longest consecutive run claimable by a reserve: host pool ∪
        staging area."""
        n = 0
        for h in seq_hashes:
            if h in self.pool or h in self._staged:
                n += 1
            else:
                break
        return n

    def promote_chain(self, seq_hashes: list[int]) -> int:
        """Disk -> host-DRAM promotion of the chain's continuation past
        the already-claimable prefix, into the staging area (NOT the
        LRU pool — a chain longer than the host budget must still
        restore whole; see ``_staged``), so the unchanged
        reserve/upload/scatter restore path serves it. Blocking disk
        reads — executor thread (engine._offload_prejoin) or an
        explicitly off-loop backstop only. Returns blocks promoted."""
        if self.disk is None or not seq_hashes:
            return 0
        with self._lock:
            n = self._match_chain_locked(seq_hashes)
        tail = seq_hashes[n:]
        # truncate at the staging cap: a chain longer than the staging
        # area restores its PREFIX (reads stop before the cap would
        # start evicting this chain's own head out from under the
        # consecutive match)
        run = min(
            self.disk.match_chain(tail) if tail else 0, self._staged_cap()
        )
        promoted = 0
        fresh: set = set()
        read_bytes, read_s = 0, 0.0
        for h in tail[:run]:
            t_r = time.monotonic()
            got = self.disk.get(h)  # validates; corrupt -> clean miss
            if got is None:
                break
            read_s += time.monotonic() - t_r
            read_bytes += entry_nbytes(got)
            # normalize to this manager's codec mode (a --kv-quant flip
            # across a restart leaves the other format on disk)
            got = self._normalize_entry(got)
            with self._lock:
                self._stage_locked(h, got, fresh=fresh)
            promoted += 1
        if self.cost_model is not None and read_bytes and read_s > 0:
            # measured disk-read wall -> the "disk" link class (the
            # h2d leg on top of it is observed separately as "host")
            self.cost_model.observe("disk", read_bytes, read_s)
        with self._lock:
            self._dropped_pending.extend(self.disk.drain_dropped())
        return promoted

    def tier_contains(self, seq_hash: int) -> bool:
        """Index-only host∪staged∪disk residency probe (no data reads)."""
        with self._lock:
            if seq_hash in self.pool or seq_hash in self._staged:
                return True
        return self.disk is not None and self.disk.contains(seq_hash)

    def flush_dropped(self) -> None:
        """Deliver queued tier-drop notifications to ``on_dropped``.
        Event-loop callers only: the callback publishes KV removal
        events on the bus (kv_router.publisher), and the drops
        themselves accrue from executor threads.

        Drops are re-checked against EVERY tier (device via the
        engine-wired ``device_has``, host pool, staging, disk) before
        publishing: tiers hold independent copies of a content-addressed
        block, so one tier evicting its copy is only a removal if no
        other copy survives — publishing otherwise would erase live
        residency (and, via the index's chain cascade, the worker's
        whole downstream chain) from the router."""
        cb = self.on_dropped
        with self._lock:
            if self.disk is not None:
                self._dropped_pending.extend(self.disk.drain_dropped())
            dropped, self._dropped_pending = self._dropped_pending, []
        if cb is None or not dropped:
            return
        gone = []
        seen: set = set()
        for h in dropped:
            if h in seen:
                continue
            seen.add(h)
            if self.tier_contains(h):
                continue
            if self.device_has is not None and self.device_has(h):
                continue
            gone.append(h)
        if gone:
            try:
                cb(gone)
            except Exception:  # noqa: BLE001 — residency events are advisory
                logger.debug("tier-drop notification failed", exc_info=True)

    # -- fleet tier (peer prefix pulls) --

    def _collect_export(self, seq_hashes: list[int], max_blocks: int):
        """Longest consecutive resident run of ``seq_hashes`` as entry
        tuples, uniform in this manager's codec mode (disk reads are
        normalized). Non-destructive. Executor thread."""
        served: list[int] = []
        entries: list[tuple] = []
        for h in seq_hashes[:max_blocks]:
            with self._lock:
                got = self.pool.peek(h)
                if got is None:
                    got = self._staged.get(h)
            if got is None and self.disk is not None:
                got = self.disk.get(h)
                if got is not None:
                    got = self._normalize_entry(got)
            if got is None:
                break
            served.append(h)
            entries.append(got)
        return served, entries

    def export_chain(
        self, seq_hashes: list[int], max_blocks: int = 512
    ) -> tuple[list[int], Optional[np.ndarray], Optional[np.ndarray]]:
        """Serve side of a peer prefix pull, FULL-WIDTH form: the
        longest consecutive run of ``seq_hashes`` resident in the
        host∪disk tiers, stacked [L, Hkv, n, bs, D] for the transfer
        plane — quantized entries are dequantized first (the legacy-
        peer shape of the negotiation matrix; :meth:`export_chain_q`
        serves quant-capable pullers at wire width). Non-destructive
        (peek + disk read, no promotion churn) so a requester dying
        mid-pull leaves this worker's tiers untouched. Executor thread
        (disk reads + multi-MB stacking)."""
        served, k, v, _ks, _vs = self.export_chain_q(
            seq_hashes, max_blocks=max_blocks, quant_ok=False
        )
        return served, k, v

    def export_chain_q(
        self, seq_hashes: list[int], max_blocks: int = 512,
        quant_ok: bool = True,
    ) -> tuple:
        """Quant-aware export: (hashes, k, v, ks, vs). With the codec
        active and ``quant_ok`` (the puller advertised the capability),
        the stacks are the stored int8/fp8 payloads plus [L, n] scale
        arrays — half the wire bytes; otherwise scales are None and
        the stacks are full-width."""
        if self.mirror is not None:
            return [], None, None, None, None  # mirror pools hold pieces
        served, entries = self._collect_export(seq_hashes, max_blocks)
        if not served:
            return [], None, None, None, None
        quantized = self.kv_quant != "none"
        if quantized and not quant_ok:
            entries = [
                kvquant.dequantize_entry(
                    e[0], e[1], e[2], e[3], self.full_dtype
                )
                for e in entries
            ]
            quantized = False
        k = np.stack([e[0] for e in entries], axis=2)
        v = np.stack([e[1] for e in entries], axis=2)
        ks = vs = None
        if quantized:
            ks = np.stack([e[2] for e in entries], axis=1)  # [L, n]
            vs = np.stack([e[3] for e in entries], axis=1)
        with self._lock:
            self.peer_serve_blocks_total += len(served)
        return served, k, v, ks, vs

    def land_peer_chain(
        self, seq_hashes: list[int], k_data: np.ndarray, v_data: np.ndarray,
        k_scales: Optional[np.ndarray] = None,
        v_scales: Optional[np.ndarray] = None,
    ) -> int:
        """Puller side: park a peer-served chain in the host-DRAM
        STAGING area — not the LRU pool, whose capacity would thrash a
        chain longer than the host budget out of existence before the
        restore runs — where the hinted-prefetch restore promotes it to
        device exactly like a locally-offloaded chain. ``k_scales``/
        ``v_scales`` ([L, n] f32) mark a quantized delivery; either
        way each block is normalized to THIS manager's codec mode
        (quantized puller vs unquantized peer and vice versa both
        land clean). Executor thread — the per-block splits are
        multi-MB copies (a view would pin the whole stack for as long
        as any one block stays resident)."""
        landed = 0
        fresh: set = set()
        # truncate at the staging cap (keep the chain's PREFIX): staging
        # past it would evict this chain's own head and zero the
        # consecutive match the restore needs
        for i, h in enumerate(seq_hashes[: self._staged_cap()]):
            entry = (k_data[:, :, i].copy(), v_data[:, :, i].copy())
            if k_scales is not None:
                entry = entry + (
                    np.ascontiguousarray(k_scales[:, i], dtype=np.float32),
                    np.ascontiguousarray(v_scales[:, i], dtype=np.float32),
                )
            entry = self._normalize_entry(entry)
            with self._lock:
                if (
                    h in self.pool
                    or h in self._staged
                    or (self.disk is not None and self.disk.contains(h))
                ):
                    continue  # raced a local landing; content-identical
                self._stage_locked(h, entry, peer=True, fresh=fresh)
                self.peer_pull_blocks_total += 1
            landed += 1
        return landed

    # -- admission-time reservation (event-loop thread) --
    def reserve_chain(
        self, seq_hashes: list[int]
    ) -> tuple[list[int], list[tuple[np.ndarray, np.ndarray]]]:
        """Take the longest resident prefix OUT of the pool (so a later
        flush_evictions can't LRU it away before restore runs).

        Callers on the event loop should have pre-joined relevant
        in-flight flushes AND pre-promoted disk hits off-loop
        (engine._offload_prejoin); the inline bounded join / promotion
        here is the correctness backstop for direct callers."""
        if seq_hashes and self._inflight_flushes:
            self._join_flushes_for(seq_hashes)
        if self.disk is not None and seq_hashes:
            self.promote_chain(seq_hashes)
        with self._lock:
            n = self._match_chain_locked(seq_hashes)
            hashes = seq_hashes[:n]
            out = []
            for h in hashes:
                if h in self.pool:
                    out.append(self.pool.take(h))
                else:
                    out.append(self._staged.pop(h))
                # a request racing its own hint can reserve a
                # peer-pulled block before the prefetch restore marks
                # it: reserving IS the claim (restore instead of
                # recompute — the transfer was hidden either way)
                if h in self._peer_hashes:
                    self._peer_hashes.pop(h)
                    self.peer_pull_blocks_claimed += 1
            return hashes, out

    def peek_chain(
        self, seq_hashes: list[int]
    ) -> tuple[list[int], list[tuple[np.ndarray, np.ndarray]]]:
        """Non-destructive :meth:`reserve_chain` for the prefetch path:
        the entries STAY in the pool, claimable by a racing admission,
        until :meth:`discard_chain` drops them after the device commit.
        (A hint must never make the hinted request slower: popping here
        would hide the chain from the request while the upload is in
        flight.)"""
        if seq_hashes and self._inflight_flushes:
            self._join_flushes_for(seq_hashes)
        if self.disk is not None and seq_hashes:
            self.promote_chain(seq_hashes)
        with self._lock:
            n = self._match_chain_locked(seq_hashes)
            hashes = seq_hashes[:n]
            out = []
            for h in hashes:
                got = self.pool.peek(h)
                if got is None:
                    got = self._staged[h]
                    self._staged.move_to_end(h)
                out.append(got)
            return hashes, out

    def discard_chain(self, hashes: list[int]) -> None:
        """Drop host copies whose content is now device-resident (the
        prefetch landed + committed). Entries a racing admission already
        took are simply gone — nothing to do."""
        with self._lock:
            for h in hashes:
                self.pool.take(h)
                self._staged.pop(h, None)

    def unreserve(self, hashes: list[int], data, restored: bool = False) -> None:
        """Admission failed (or the prefill was cancelled/errored) after
        reservation — return blocks to the pool.

        Under the mirror, ``restored`` says the entries already landed via
        a mirrored restore, i.e. follower tiers POPPED them: re-pooling on
        the leader would let a later restore take a hash the followers no
        longer hold (KeyError -> dead follower). Those entries are
        discarded instead (their content usually survives in the device
        reuse pool anyway). Re-pools of never-restored entries go through
        the LRU plan and queue any evictions as deferred follower drops."""
        if self.mirror is not None:
            with self._lock:
                if restored:
                    # followers popped at restore; leader forgets too. The
                    # drop is deferred only to cover the (idempotent) case
                    # of follower tiers that never saw the restore.
                    self._deferred_drops.extend(hashes)
                    return
                drops, keep, order = self.pool.plan_puts(hashes)
                by_hash = dict(zip(hashes, data))
                self.pool.apply_plan(
                    drops, keep, order, hashes, lambda i: by_hash[hashes[i]]
                )
                # follower tiers hold every hash from the original flush:
                # drop both the plan's evictions AND any re-pooled hash the
                # plan itself discarded (keep=False, not resident
                # afterwards) — or follower host DRAM grows past the
                # leader's budget
                final = set(order)
                self._deferred_drops.extend(drops)
                self._deferred_drops.extend(
                    h for h in hashes if h not in final
                )
                self._dropped_pending.extend(drops)
                self._dropped_pending.extend(
                    h for h in hashes if h not in final
                )
            return
        with self._lock:
            for h, e in zip(hashes, data):
                # entries re-pool in whatever form they were reserved
                # (already this manager's codec mode)
                self.pool.put(h, e[0], e[1], scales=self._entry_scales(e))

    # -- device-thread operations --
    def flush_evictions(self, k_cache, v_cache) -> None:
        """Gather + d2h all pending evicted blocks into the host pool,
        synchronously (the mirror path and the ``async_tier=False``
        escape hatch)."""
        with self._lock:
            if not self._pending:
                return
            pending, self._pending = self._pending, []
        idxs = _pad_idxs([idx for _h, idx in pending])
        if self.mirror is not None:
            hashes = [h for h, _idx in pending]
            with self._lock:
                drops, keep, order = self.pool.plan_puts(hashes)
                bcast_drops = drops + self._deferred_drops
                self._deferred_drops = []
                # plan drops leave the leader's last tier (mirror
                # engines have no disk tier): residency ends here
                self._dropped_pending.extend(drops)
                self._dropped_pending.extend(
                    h for i, h in enumerate(hashes) if not keep[i]
                )
            kg, vg = self.mirror.lead_offload_flush(
                k_cache, v_cache, idxs, hashes,
                np.asarray(keep, np.uint8), bcast_drops,
            )
            k_pc = self.mirror.local_pieces(kg)
            v_pc = self.mirror.local_pieces(vg)
            with self._lock:
                self.pool.apply_plan(
                    drops, keep, order, hashes,
                    lambda i: (
                        [p[:, :, i].copy() for p in k_pc],
                        [p[:, :, i].copy() for p in v_pc],
                    ),
                )
                self.pool.stored_total += len(pending)
            return
        planes = self.device_planes() if self.device_planes else None
        if planes is not None:
            kg, vg, ksg, vsg = _gather_blocks_s(
                k_cache, v_cache, planes[0], planes[1], jnp.asarray(idxs)
            )
            return self._land_flush(pending, kg, vg, ksg, vsg)
        kg, vg = _gather_blocks(k_cache, v_cache, jnp.asarray(idxs))
        self._land_flush(pending, kg, vg)

    def _land_flush(self, pending, kg, vg, ksg=None, vsg=None) -> None:
        """Blocking half of a flush: d2h fetch + host-pool insertion
        (quantized to the tier codec when --kv-quant is on — the
        quantize runs here, off the loop, before the entry is priced
        against the pool's byte budget). Runs inline on the sync path,
        on the offload executor otherwise."""
        kg, vg = _device_fetch(kg), _device_fetch(vg)
        if ksg is not None:
            ksg, vsg = _device_fetch(ksg), _device_fetch(vsg)
        entries = []
        for i, (seq_hash, _idx) in enumerate(pending):
            # copy: a view would pin the whole padded gather batch in
            # RAM for as long as any one block stays resident
            if ksg is not None:
                e = self._encode_device_entry(
                    kg[:, :, i].copy(), vg[:, :, i].copy(),
                    ksg[:, i].copy(), vsg[:, i].copy(),
                )
            else:
                e = self._encode_entry(kg[:, :, i].copy(), vg[:, :, i].copy())
            entries.append((seq_hash, e))
        with self._lock:
            for seq_hash, e in entries:
                self.pool.put(seq_hash, e[0], e[1],
                              scales=self._entry_scales(e))
            self.pool.stored_total += len(pending)

    def flush_evictions_async(
        self, k_cache, v_cache,
        budget: Optional[int] = None,
        must_idxs: Optional[set] = None,
    ) -> None:
        """Dispatch d2h for pending evictions WITHOUT blocking on the
        copy (device thread). The bucketed gather is dispatched here so
        it stays stream-ordered before the caller's page-overwriting
        compute; the fetch + pool insertion land on the offload executor.

        ``budget`` caps how many optional blocks one call gathers and the
        double buffer caps concurrent in-flight flushes — but evictions
        whose page index is in ``must_idxs`` (pages the caller's imminent
        dispatch writes) are ALWAYS taken: deferring those would snapshot
        a page after its new owner overwrote it. Callers that overwrite
        arbitrary pages (prefill preamble, remote-KV landing) pass
        ``budget=None`` = flush everything now.
        """
        if not self.async_tier:
            return self.flush_evictions(k_cache, v_cache)
        with self._lock:
            self._reap_flushes_locked()
            if not self._pending:
                return
            if budget is None:
                pending, self._pending = self._pending, []
            else:
                room = max(0, budget)
                if len(self._inflight_flushes) >= _MAX_INFLIGHT_FLUSHES:
                    room = 0  # double buffer full: must-flush only
                pending, deferred = [], []
                for h, idx in self._pending:
                    if must_idxs is not None and idx in must_idxs:
                        pending.append((h, idx))
                    elif room > 0:
                        pending.append((h, idx))
                        room -= 1
                    else:
                        deferred.append((h, idx))
                self._pending = deferred
            if not pending:
                return
        idxs = _pad_idxs([idx for _h, idx in pending])
        planes = self.device_planes() if self.device_planes else None
        if planes is not None:
            kg, vg, ksg, vsg = _gather_blocks_s(
                k_cache, v_cache, planes[0], planes[1], jnp.asarray(idxs)
            )
        else:
            kg, vg = _gather_blocks(k_cache, v_cache, jnp.asarray(idxs))
            ksg = vsg = None
        fut = self._executor().submit(
            self._land_flush, pending, kg, vg, ksg, vsg
        )
        with self._lock:
            self._inflight_flushes.append(
                _FlushTask([h for h, _idx in pending], fut)
            )
            self.d2h_flush_async_total += 1

    # -- async h2d restore stage --
    def begin_upload(
        self, hashes: list[int], data: list, block_idxs: list[int]
    ) -> RestoreUpload:
        """Start the h2d half of a restore the moment the chain is
        reserved: stack the host blocks and upload them on the offload
        executor. The returned handle goes to :meth:`finish_upload` (or
        :meth:`cancel_upload` on rollback). Synchronous paths (mirror,
        async tier off, empty chain) return a handle with no future —
        finish_upload falls back to the one-shot :meth:`restore`."""
        up = RestoreUpload(hashes, data, block_idxs)
        if not hashes or not self.async_tier:
            return up
        up.future = self._executor().submit(self._upload_worker, up)
        with self._lock:
            self.h2d_uploads_started += 1
        return up

    def _upload_worker(self, up: RestoreUpload):
        k_host = np.stack([e[0] for e in up.data], axis=2)
        v_host = np.stack([e[1] for e in up.data], axis=2)
        k_dev, v_dev = _device_put(k_host), _device_put(v_host)
        if len(up.data[0]) > 2:
            # quantized chain: the h2d moves int8/fp8 payloads (half
            # the PCIe bytes) + the tiny per-block scale stacks; the
            # dequantize fuses into the device-side scatter
            ks = np.stack([e[2] for e in up.data], axis=1)  # [L, m]
            vs = np.stack([e[3] for e in up.data], axis=1)
            ks_dev, vs_dev = _device_put(ks), _device_put(vs)
            jax.block_until_ready((k_dev, v_dev, ks_dev, vs_dev))
            up.t_landed = time.monotonic()
            return k_dev, v_dev, ks_dev, vs_dev
        jax.block_until_ready((k_dev, v_dev))
        up.t_landed = time.monotonic()
        return k_dev, v_dev

    def cancel_upload(self, up: Optional[RestoreUpload]) -> None:
        """Admission failed / request cancelled with the upload still in
        flight. The upload only READS the host arrays, so the caller's
        :meth:`unreserve` re-pool is safe concurrently; this just records
        the abandonment (the device arrays are dropped on landing)."""
        if up is None or up.future is None or up.cancelled:
            return
        up.cancelled = True
        with self._lock:
            self.h2d_uploads_cancelled += 1

    def finish_upload(self, k_cache, v_cache, up: RestoreUpload,
                      account: bool = True):
        """Land a begun upload: wait for the device copies (only if they
        haven't arrived — the wait actually paid is the EXPOSED restore
        latency; the rest was hidden behind scheduling/compute) and
        scatter them into the reserved pages. ``account=False`` skips the
        hidden/exposed bookkeeping (prefetch landings never block
        admission; their whole latency counts as hidden at claim time)."""
        if not up.hashes:
            return k_cache, v_cache
        if up.future is None:
            return self.restore(
                k_cache, v_cache, up.data, up.idxs, hashes=up.hashes
            )
        t0 = time.monotonic()
        landed = up.future.result()
        k_dev, v_dev = landed[0], landed[1]
        if account and self.cost_model is not None and up.t_landed is not None:
            # the upload worker's measured stack+h2d wall is the "host"
            # link observation routing prices this worker's restores at.
            # Request-driven restores only: hinted-prefetch landings
            # (account=False) observe once in note_prefetch_landed —
            # observing here too would double-weight every prefetch
            # sample and open the cold-start gate at half the evidence
            self.cost_model.observe(
                "host", k_dev.nbytes + v_dev.nbytes,
                max(up.t_landed - up.t_start, 1e-9),
            )
        if account:
            waited = time.monotonic() - t0
            total = max(up.t_landed - up.t_start, 1e-9)
            exposed = min(waited, total)
            with self._lock:
                self.restore_exposed_s += exposed
                self.restore_hidden_s += max(total - exposed, 0.0)
                # request-driven restores only: speculative prefetch
                # landings (account=False) count as hits at CLAIM time
                # (h2d_prefetch_hits), not at landing — a hint for a
                # request that never arrives is not a hit
                self.pool.hit_blocks_total += len(up.data)
        idxs = jnp.asarray(_pad_idxs(up.idxs))
        planes = self.device_planes() if self.device_planes else None
        if planes is not None:
            return self._scatter_into_device_q(
                k_cache, v_cache, planes, idxs, landed
            )
        if len(landed) > 2:  # quantized chain: dequant fused into scatter
            return _scatter_blocks_q(
                k_cache, v_cache, idxs, k_dev, v_dev, landed[2], landed[3]
            )
        return _scatter_blocks(k_cache, v_cache, idxs, k_dev, v_dev)

    def _scatter_into_device_q(self, k_cache, v_cache, planes, idxs, parts):
        """Land a restore into the int8-with-scales DEVICE cache: a
        matching int8 tier entry adopts payload + scales verbatim
        (:func:`scatter_blocks_adopt_core`); a full-width or fp8 entry
        re-quantizes on device against fresh per-(layer, block) absmax
        (:func:`scatter_blocks_requant_core`). The updated planes are
        re-homed on the engine via ``device_planes_set``; returns the
        updated caches (same shape as the plain scatter paths)."""
        ks_p, vs_p = planes
        k_dev, v_dev = jnp.asarray(parts[0]), jnp.asarray(parts[1])
        if len(parts) > 2 and parts[2] is not None:
            ks, vs = jnp.asarray(parts[2]), jnp.asarray(parts[3])
            core = (
                _scatter_blocks_adopt
                if k_dev.dtype == k_cache.dtype
                else _scatter_blocks_requant
            )
        else:
            shape = (ks_p.shape[0], k_dev.shape[2])
            ks = vs = jnp.ones(shape, jnp.float32)
            core = _scatter_blocks_requant
        k_cache, v_cache, nk, nv = core(
            k_cache, v_cache, ks_p, vs_p, idxs, k_dev, v_dev, ks, vs
        )
        self.device_planes_set((nk, nv))
        return k_cache, v_cache

    # -- prefetch accounting (router-hinted restores, engine-side) --
    def note_prefetch_landed(self, up: RestoreUpload) -> None:
        """A hinted restore landed off the admission path: its entire
        transfer latency was hidden from every future request."""
        with self._lock:
            self.h2d_prefetch_blocks_total += len(up.hashes)
            if up.t_landed is not None:
                self.restore_hidden_s += max(up.t_landed - up.t_start, 0.0)
        if self.cost_model is not None and up.t_landed is not None and up.data:
            nbytes = sum(entry_nbytes(e) for e in up.data)
            self.cost_model.observe(
                "host", nbytes, max(up.t_landed - up.t_start, 1e-9)
            )

    def note_prefetch_hits(self, n: int, hashes: Optional[list] = None) -> None:
        with self._lock:
            self.h2d_prefetch_hits += n
            # a claimed block that arrived via a peer pull: its whole
            # cross-worker transfer was hidden from the request
            # (peer_pull_hidden_frac numerator)
            for h in hashes or ():
                if h in self._peer_hashes:
                    self._peer_hashes.pop(h)
                    self.peer_pull_blocks_claimed += 1

    def restore(self, k_cache, v_cache, data, block_idxs: list[int],
                hashes: Optional[list[int]] = None):
        """Upload reserved host blocks (from :meth:`reserve_chain`) into
        device pages ``block_idxs``; returns updated caches. Under the
        multi-host mirror ``hashes`` names the entries so follower tiers
        pop the same blocks (their data is their own local shards)."""
        assert len(data) == len(block_idxs)
        if not data:
            return k_cache, v_cache
        with self._lock:
            self.pool.hit_blocks_total += len(data)
        if self.mirror is not None:
            assert hashes is not None and len(hashes) == len(data)
            k_pieces = stack_pieces(data, 0)
            v_pieces = stack_pieces(data, 1)

            def gs(cache):  # MLA caches have DIFFERENT trailing dims
                return (cache.shape[0], cache.shape[1], len(data),
                        cache.shape[3], cache.shape[4])

            with self._lock:
                drops = self._deferred_drops
                self._deferred_drops = []
            return self.mirror.lead_offload_restore(
                k_cache, v_cache, _pad_idxs(block_idxs), hashes,
                k_pieces, v_pieces, gs(k_cache), gs(v_cache),
                drop_hashes=drops,
            )
        k_host = np.stack([e[0] for e in data], axis=2)  # [L, Hkv, m, bs, D]
        v_host = np.stack([e[1] for e in data], axis=2)  # unpadded — the
        idxs = jnp.asarray(_pad_idxs(block_idxs))  # scatter core pads on device
        planes = self.device_planes() if self.device_planes else None
        if planes is not None:
            parts = [k_host, v_host]
            if len(data[0]) > 2:
                parts += [
                    np.stack([e[2] for e in data], axis=1),
                    np.stack([e[3] for e in data], axis=1),
                ]
            return self._scatter_into_device_q(
                k_cache, v_cache, planes, idxs, parts
            )
        if len(data[0]) > 2:  # quantized chain (sync path)
            return _scatter_blocks_q(
                k_cache, v_cache, idxs,
                jnp.asarray(k_host), jnp.asarray(v_host),
                jnp.asarray(np.stack([e[2] for e in data], axis=1)),
                jnp.asarray(np.stack([e[3] for e in data], axis=1)),
            )
        return _scatter_blocks(
            k_cache, v_cache, idxs,
            jnp.asarray(k_host), jnp.asarray(v_host),
        )

    def close(self) -> None:
        """Release the offload executor (in-flight landings still run to
        completion; nothing new is accepted). A disk tier on an
        AUTO-created tempdir is deleted with the engine — leaving every
        short-lived engine's multi-MB block files in /tmp would fill the
        host; explicit ``disk_path`` directories persist by design."""
        self._closed = True
        if self._exec is not None:
            self._exec.shutdown(wait=False)
            self._exec = None
        if self._own_disk_path is not None:
            shutil.rmtree(self._own_disk_path, ignore_errors=True)

    def stats(self) -> dict:
        with self._lock:
            hid, exp = self.restore_hidden_s, self.restore_exposed_s
            denom = hid + exp
            pulled = self.peer_pull_blocks_total
            return {
                "offload_blocks_resident": len(self.pool),
                "offload_blocks_stored_total": self.pool.stored_total,  # dynlint: disable=unscraped-stat -- cumulative churn diagnostic; residency is the gauge
                "offload_hit_blocks_total": self.pool.hit_blocks_total,  # dynlint: disable=unscraped-stat -- h2d_prefetch_hits is the gauge-side hit counter
                # third-tier surface (ISSUE 10): disk residency/traffic,
                # and the fleet tier's pull volume + the fraction of
                # pulled blocks whose cross-worker transfer was fully
                # hidden (landed + promoted before a request claimed it)
                "disk_blocks_resident": (
                    len(self.disk) if self.disk is not None else 0
                ),
                "disk_blocks_stored_total": (  # dynlint: disable=unscraped-stat -- cumulative churn diagnostic; residency + demotions are the gauges
                    self.disk.stored_total if self.disk is not None else 0
                ),
                "disk_hit_blocks_total": (
                    self.disk.hit_blocks_total if self.disk is not None else 0
                ),
                "disk_corrupt_discards": (
                    self.disk.corrupt_discards if self.disk is not None else 0
                ),
                "disk_evictions_total": (  # dynlint: disable=unscraped-stat -- tier-eviction diagnostic asserted by the prefix-fleet tests
                    self.disk.evictions_total if self.disk is not None else 0
                ),
                "disk_demotions_total": self.disk_demotions_total,
                "peer_pull_blocks_total": pulled,
                "peer_pull_blocks_claimed": self.peer_pull_blocks_claimed,  # dynlint: disable=unscraped-stat -- numerator of peer_pull_hidden_frac, which IS the gauge
                "peer_pull_hidden_frac": (
                    round(self.peer_pull_blocks_claimed / pulled, 6)
                    if pulled else 0.0
                ),
                "peer_serve_blocks_total": self.peer_serve_blocks_total,
                # per-block tier/wire quantization (engine/kvquant.py):
                # blocks encoded to the int8/fp8 codec on their way into
                # the tiers/wire, and the bytes that saved vs full width
                "kv_quant_blocks_total": self.kv_quant_blocks_total,
                "kv_quant_bytes_saved_total": self.kv_quant_bytes_saved_total,
                # async-tier surface (ISSUE 1): background d2h flushes
                # dispatched, hinted blocks restored + later claimed, and
                # the fraction of total restore (h2d) latency hidden
                # behind scheduling/compute instead of exposed on TTFT
                "d2h_flush_async": self.d2h_flush_async_total,
                "d2h_flush_failures": self.d2h_flush_failures,  # dynlint: disable=unscraped-stat -- pipeline diagnostic asserted by the offload tests; executor_pending_max is the pressure gauge
                "d2h_flush_pending": len(self._pending),  # dynlint: disable=unscraped-stat -- instantaneous depth diagnostic; executor_pending_max is the pressure gauge
                "h2d_prefetch_blocks_total": self.h2d_prefetch_blocks_total,  # dynlint: disable=unscraped-stat -- restore-volume diagnostic; h2d_prefetch_hits (claimed) is the gauge
                "h2d_prefetch_hits": self.h2d_prefetch_hits,
                "h2d_uploads_started": self.h2d_uploads_started,  # dynlint: disable=unscraped-stat -- upload-lifecycle diagnostic asserted by the offload-pipeline tests
                "h2d_uploads_cancelled": self.h2d_uploads_cancelled,  # dynlint: disable=unscraped-stat -- upload-lifecycle diagnostic asserted by the offload-pipeline tests
                "restore_latency_hidden_frac": (
                    round(hid / denom, 6) if denom > 0 else 0.0
                ),
            }

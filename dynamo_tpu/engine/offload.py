"""Host-DRAM KV offload tier: the TPU equivalent of the reference's
multi-tier block manager (lib/llm/src/kv/{manager,reuse}.rs + the pinned
host tier and CUDA scatter/gather CopyStream, kv/layer.rs:619-1132,
kernels/block_copy.cu).

On TPU-VM the "pinned host" tier is plain host RAM: evicted device blocks
are gathered on device ([L, Hkv, n, bs, D] slices of the paged cache),
fetched with one d2h transfer, and parked in an LRU pool keyed by the
block's *chained* sequence hash. A later prefill whose prefix misses the
device pool probes this pool and restores hits with one h2d upload plus a
jitted scatter back into freshly allocated pages (docs/architecture.md:91
— host offload buys ~40% TTFT on multi-turn workloads).

Transfer shapes are bucketed (pad block-index vectors with the trash
block 0 — scatters to it are harmless by design) so the jitted
gather/scatter pair compiles O(log max_batch) programs, not one per
transfer size.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_BUCKETS = [1, 2, 4, 8, 16, 32, 64, 128]


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return -(-n // 128) * 128


def _pad_idxs(idxs: list[int]) -> np.ndarray:
    out = np.zeros(_bucket(len(idxs)), np.int32)  # pad with trash block 0
    out[: len(idxs)] = idxs
    return out


def gather_blocks_core(k_cache, v_cache, idxs):
    """[L, Hkv, N, bs, D] x [n] -> two [L, Hkv, n, bs, D] stacks.
    Unjitted core — StepMirror re-jits it with mesh out_shardings for the
    mirrored multi-host paths."""
    return jnp.take(k_cache, idxs, axis=2), jnp.take(v_cache, idxs, axis=2)


def scatter_blocks_core(k_cache, v_cache, idxs, k_data, v_data):
    """Pads the data stack to the (bucketed) index count ON DEVICE — host
    callers ship only real blocks over PCIe/DCN; pad rows target trash
    block 0 and never leave HBM."""
    n, m = idxs.shape[0], k_data.shape[2]
    if m < n:  # static at trace time
        pad = [(0, 0)] * k_data.ndim
        pad[2] = (0, n - m)
        k_data = jnp.pad(k_data, pad)
        v_data = jnp.pad(v_data, pad)
    return (
        k_cache.at[:, :, idxs].set(k_data.astype(k_cache.dtype)),
        v_cache.at[:, :, idxs].set(v_data.astype(v_cache.dtype)),
    )


def stack_pieces(entries: list, which: int) -> list[np.ndarray]:
    """Stack per-piece host blocks ([L, Hl, bs, D] each) into per-piece
    [L, Hl, m, bs, D] stacks (m = len(entries), UNPADDED — the scatter
    core pads to the bucketed index count on device). ``entries`` are
    host-tier values (k_pieces, v_pieces); ``which`` selects k (0) or
    v (1). ONE implementation shared by the leader's
    OffloadManager.restore and the follower's offload_restore replay —
    both sides must build identically-shaped global arrays."""
    n_pieces = len(entries[0][which])
    return [
        np.stack([e[which][j] for e in entries], axis=2)
        for j in range(n_pieces)
    ]


_gather_blocks = jax.jit(gather_blocks_core)
_scatter_blocks = jax.jit(
    scatter_blocks_core, donate_argnames=("k_cache", "v_cache")
)


class HostKvPool:
    """LRU pool of offloaded blocks: seq_hash -> (k, v) host arrays of
    shape [L, Hkv, bs, D] (ref kv/reuse.rs AvailableBlocks, one tier up)."""

    def __init__(self, capacity_blocks: int):
        self.capacity = capacity_blocks
        self._data: OrderedDict[int, tuple[np.ndarray, np.ndarray]] = OrderedDict()
        self.stored_total = 0
        self.hit_blocks_total = 0

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._data

    def put(self, seq_hash: int, k: np.ndarray, v: np.ndarray) -> None:
        if self.capacity <= 0:
            return
        if seq_hash in self._data:
            self._data.move_to_end(seq_hash)
            return
        while len(self._data) >= self.capacity:
            self._data.popitem(last=False)
        self._data[seq_hash] = (k, v)

    def take(self, seq_hash: int) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """Remove and return (the block is moving back to the device tier,
        which re-registers it in the device reuse pool on release)."""
        return self._data.pop(seq_hash, None)

    def match_chain(self, seq_hashes: list[int]) -> int:
        """Longest consecutive run of hashes resident in the pool."""
        n = 0
        for h in seq_hashes:
            if h not in self._data:
                break
            n += 1
        return n

    def plan_puts(
        self, hashes: list[int]
    ) -> tuple[list[int], list[bool], list[int]]:
        """Simulate :meth:`put` over ``hashes`` without data: returns
        (drops, keep, final_order) — drops = currently-resident hashes the
        LRU will evict, keep[i] = whether hashes[i] is resident AFTER all
        puts (False when a later insert evicts it or capacity is 0), and
        the final recency order. The multi-host mirror broadcasts the plan
        so follower tiers apply the leader's policy verbatim instead of
        running their own."""
        sim = OrderedDict((k, None) for k in self._data)
        for h in hashes:
            if self.capacity <= 0:
                break
            if h in sim:
                sim.move_to_end(h)
                continue
            while len(sim) >= self.capacity:
                sim.popitem(last=False)
            sim[h] = None
        drops = [k for k in self._data if k not in sim]
        seen: set = set()
        keep = []
        for h in hashes:
            keep.append(h in sim and h not in seen)
            seen.add(h)
        return drops, keep, list(sim.keys())

    def apply_plan(self, drops, keep, final_order, hashes, data_for) -> None:
        """Apply a :meth:`plan_puts` result: drop evictions, insert kept
        entries (``data_for(i)`` supplies hashes[i]'s value), and restore
        the simulated recency order."""
        for h in drops:
            self._data.pop(h, None)
        for i, h in enumerate(hashes):
            if keep[i] and h not in self._data:
                self._data[h] = data_for(i)
        for h in final_order:
            if h in self._data:
                self._data.move_to_end(h)


class OffloadManager:
    """Orchestrates device<->host block movement for one engine.

    Runs entirely on the engine's device-executor thread (the same thread
    that issues prefill/decode), so gathers of evicted blocks are always
    dispatched before the compute that overwrites those pages — ordering
    by construction, the role CUDA stream events play in the reference's
    CopyStream (kv/layer.rs:619).
    """

    def __init__(self, host_blocks: int, mirror=None):
        self.pool = HostKvPool(host_blocks)
        # (seq_hash, device_block_idx) evictions awaiting d2h
        self._pending: list[tuple[int, int]] = []
        # multi-host: flushes/restores become mirrored ops — every process
        # gathers/scatters in lockstep and parks its OWN cache shards in
        # host DRAM (pool values are per-unique-shard piece lists instead
        # of full arrays). The leader's LRU plan is broadcast so follower
        # tiers stay content-identical (parallel/multihost.py).
        self.mirror = mirror
        # leader-side pool mutations that happen OUTSIDE a mirrored op
        # (unreserve's re-pool evictions, discards of already-restored
        # reservations) queue their follower-side drops here; the next
        # flush/restore broadcast carries them. Invariant: the follower
        # tier must remain a superset of {leader pool + reservations}.
        self._deferred_drops: list[int] = []

    # -- allocator callback (event-loop thread) --
    def on_evict(self, seq_hash: int, block_idx: int) -> None:
        self._pending.append((seq_hash, block_idx))

    # -- admission-time reservation (event-loop thread) --
    def reserve_chain(
        self, seq_hashes: list[int]
    ) -> tuple[list[int], list[tuple[np.ndarray, np.ndarray]]]:
        """Take the longest resident prefix OUT of the pool (so a later
        flush_evictions can't LRU it away before restore runs)."""
        n = self.pool.match_chain(seq_hashes)
        hashes = seq_hashes[:n]
        return hashes, [self.pool.take(h) for h in hashes]

    def unreserve(self, hashes: list[int], data, restored: bool = False) -> None:
        """Admission failed (or the prefill was cancelled/errored) after
        reservation — return blocks to the pool.

        Under the mirror, ``restored`` says the entries already landed via
        a mirrored restore, i.e. follower tiers POPPED them: re-pooling on
        the leader would let a later restore take a hash the followers no
        longer hold (KeyError -> dead follower). Those entries are
        discarded instead (their content usually survives in the device
        reuse pool anyway). Re-pools of never-restored entries go through
        the LRU plan and queue any evictions as deferred follower drops."""
        if self.mirror is not None:
            if restored:
                # followers popped at restore; leader forgets too. The
                # drop is deferred only to cover the (idempotent) case of
                # follower tiers that never saw the restore.
                self._deferred_drops.extend(hashes)
                return
            drops, keep, order = self.pool.plan_puts(hashes)
            by_hash = dict(zip(hashes, data))
            self.pool.apply_plan(
                drops, keep, order, hashes, lambda i: by_hash[hashes[i]]
            )
            # follower tiers hold every hash from the original flush: drop
            # both the plan's evictions AND any re-pooled hash the plan
            # itself discarded (keep=False, not resident afterwards) — or
            # follower host DRAM grows past the leader's budget
            final = set(order)
            self._deferred_drops.extend(drops)
            self._deferred_drops.extend(h for h in hashes if h not in final)
            return
        for h, (k, v) in zip(hashes, data):
            self.pool.put(h, k, v)

    # -- device-thread operations --
    def flush_evictions(self, k_cache, v_cache) -> None:
        """Gather + d2h all pending evicted blocks into the host pool."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        idxs = _pad_idxs([idx for _h, idx in pending])
        if self.mirror is not None:
            hashes = [h for h, _idx in pending]
            drops, keep, order = self.pool.plan_puts(hashes)
            bcast_drops = drops + self._deferred_drops
            self._deferred_drops = []
            kg, vg = self.mirror.lead_offload_flush(
                k_cache, v_cache, idxs, hashes,
                np.asarray(keep, np.uint8), bcast_drops,
            )
            k_pc = self.mirror.local_pieces(kg)
            v_pc = self.mirror.local_pieces(vg)
            self.pool.apply_plan(
                drops, keep, order, hashes,
                lambda i: (
                    [p[:, :, i].copy() for p in k_pc],
                    [p[:, :, i].copy() for p in v_pc],
                ),
            )
            self.pool.stored_total += len(pending)
            return
        kg, vg = _gather_blocks(k_cache, v_cache, jnp.asarray(idxs))
        kg, vg = np.asarray(jax.device_get(kg)), np.asarray(jax.device_get(vg))
        for i, (seq_hash, _idx) in enumerate(pending):
            # copy: a view would pin the whole padded gather batch in RAM
            # for as long as any one block stays resident
            self.pool.put(seq_hash, kg[:, :, i].copy(), vg[:, :, i].copy())
        self.pool.stored_total += len(pending)

    def restore(self, k_cache, v_cache, data, block_idxs: list[int],
                hashes: Optional[list[int]] = None):
        """Upload reserved host blocks (from :meth:`reserve_chain`) into
        device pages ``block_idxs``; returns updated caches. Under the
        multi-host mirror ``hashes`` names the entries so follower tiers
        pop the same blocks (their data is their own local shards)."""
        assert len(data) == len(block_idxs)
        if not data:
            return k_cache, v_cache
        self.pool.hit_blocks_total += len(data)
        if self.mirror is not None:
            assert hashes is not None and len(hashes) == len(data)
            k_pieces = stack_pieces(data, 0)
            v_pieces = stack_pieces(data, 1)

            def gs(cache):  # MLA caches have DIFFERENT trailing dims
                return (cache.shape[0], cache.shape[1], len(data),
                        cache.shape[3], cache.shape[4])

            drops = self._deferred_drops
            self._deferred_drops = []
            return self.mirror.lead_offload_restore(
                k_cache, v_cache, _pad_idxs(block_idxs), hashes,
                k_pieces, v_pieces, gs(k_cache), gs(v_cache),
                drop_hashes=drops,
            )
        ks = [k for k, _v in data]
        vs = [v for _k, v in data]
        k_host = np.stack(ks, axis=2)  # [L, Hkv, m, bs, D] unpadded —
        v_host = np.stack(vs, axis=2)  # the scatter core pads on device
        return _scatter_blocks(
            k_cache,
            v_cache,
            jnp.asarray(_pad_idxs(block_idxs)),
            jnp.asarray(k_host),
            jnp.asarray(v_host),
        )

    def stats(self) -> dict:
        return {
            "offload_blocks_resident": len(self.pool),
            "offload_blocks_stored_total": self.pool.stored_total,
            "offload_hit_blocks_total": self.pool.hit_blocks_total,
        }

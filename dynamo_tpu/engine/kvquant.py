"""Per-block KV quantization codec for the offload tiers and the wire.

Every KV boundary PR 9/11 built is bandwidth-bound — d2h flush, disk
write, peer pull over TCP, h2d restore, streamed disagg handoff
("Understanding Bottlenecks for Efficiently Serving LLM Inference With
KV Offloading", PAPERS.md) — so storing and shipping KV blocks at
int8/fp8 instead of bf16 roughly doubles the effective capacity of the
host pool, the disk tier and the wire *at once*, compounding the fleet
prefix cache (ROADMAP item 3).

Scheme — symmetric absmax, ONE scale per (layer, block) per K/V:

    scale[l, b] = max(|x[l, :, b, :, :]|) / qmax        (f32)
    q[l, h, b, :, :] = round(x / scale[l, b])           (int8 | fp8_e4m3)

Coarser than per-channel (the weight path in models/quant.py) because
a *block* is the unit every tier and wire plane already moves — the
scale rides the block through demotion, disk headers, peer pulls and
stream frames without any re-grouping, and the kv-head axis stays
scale-free so the ``kv_rearrange`` head permutation and tp regrouping
apply to quantized payloads unchanged. ``fp8`` keeps the scale too
(scaled e4m3, not the device cache's scale-free direct cast): the
scale recenters each block's dynamic range onto the format's ±448
span, which measurably tightens logprob drift on small-magnitude V
blocks.

The DEVICE cache's quantization is ``EngineConfig.kv_cache_dtype``:
scale-free fp8 cast (per-element, no block rescale on append) or the
int8-with-scales mode (models/quant.py), whose per-(layer, page) scale
planes use EXACTLY this codec's granularity and qmax — so an int8
device cache and an int8 tier exchange blocks verbatim (payload +
scale adoption, zero re-encode), while fp8/full-width tiers re-encode
from the device scales (counted: ``kv_device_export_requant_total``).
This codec covers every plane that moves KV *bytes* off the device.

Quality is gated honestly: the tier round-trip is NOT bit-exact, so
:func:`measure_logprob_drift` ships alongside the codec — greedy-token
agreement plus max/mean chosen-token logprob delta against a bf16
reference on fixed prompts — and the ``--kv-quant`` opt-in defaults to
``"none"``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

#: tier/wire KV codec modes (EngineConfig.kv_quant / --kv-quant):
#: "int8" = symmetric absmax int8 + f32 block scales, "fp8" = scaled
#: float8_e4m3fn + f32 block scales, "none" = full-width passthrough
KV_QUANT_MODES = ("none", "int8", "fp8")

_EPS = 1e-12


def quant_dtype(mode: str) -> np.dtype:
    if mode == "int8":
        return np.dtype(np.int8)
    if mode == "fp8":
        import ml_dtypes  # ships with jax

        return np.dtype(ml_dtypes.float8_e4m3fn)
    raise ValueError(f"kv_quant must be one of {KV_QUANT_MODES[1:]}, got {mode!r}")


def _qmax(mode: str) -> float:
    return 127.0 if mode == "int8" else 448.0


def _quantize(x: np.ndarray, axes: tuple, mode: str):
    """Core: absmax over ``axes`` (everything but layer + block), scale
    per remaining (layer[, block]) coordinate."""
    dt, qmax = quant_dtype(mode), _qmax(mode)
    xf = np.asarray(x, np.float32)
    scale = np.maximum(
        np.max(np.abs(xf), axis=axes) / qmax, _EPS
    ).astype(np.float32)
    q = xf / np.expand_dims(scale, axes)
    if mode == "int8":
        q = np.clip(np.rint(q), -127, 127)
    return np.ascontiguousarray(q.astype(dt)), scale


def quantize_stack(k: np.ndarray, v: np.ndarray, mode: str):
    """Quantize a block stack pair ([L, H, n, bs, D] each; k and v may
    have different H/D — MLA latents). Returns (qk, qv, ks, vs) with
    scales [L, n] f32 — one scale per block per layer per K/V."""
    qk, ks = _quantize(k, (1, 3, 4), mode)
    qv, vs = _quantize(v, (1, 3, 4), mode)
    return qk, qv, ks, vs


def dequantize_stack(qk, qv, ks, vs, dtype):
    """Invert :func:`quantize_stack` back to full-width ``dtype``."""
    dt = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    k = np.asarray(qk, np.float32) * np.asarray(ks, np.float32)[:, None, :, None, None]
    v = np.asarray(qv, np.float32) * np.asarray(vs, np.float32)[:, None, :, None, None]
    return k.astype(dt), v.astype(dt)


def quantize_entry(k: np.ndarray, v: np.ndarray, mode: str):
    """Quantize ONE block ([L, H, bs, D] pair) — the host-pool / disk
    entry form. Scales are [L] f32 per K/V."""
    qk, ks = _quantize(k, (1, 2, 3), mode)
    qv, vs = _quantize(v, (1, 2, 3), mode)
    return qk, qv, ks, vs


def dequantize_entry(qk, qv, ks, vs, dtype):
    dt = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    k = np.asarray(qk, np.float32) * np.asarray(ks, np.float32)[:, None, None, None]
    v = np.asarray(qv, np.float32) * np.asarray(vs, np.float32)[:, None, None, None]
    return k.astype(dt), v.astype(dt)


def entry_nbytes(entry: tuple) -> int:
    """Bytes one pool/staging entry actually occupies (payload + any
    scale vectors) — the unit of the tiers' byte budgets."""
    n = entry[0].nbytes + entry[1].nbytes
    if len(entry) > 2 and entry[2] is not None:
        n += entry[2].nbytes + entry[3].nbytes
    return n


def wire_block_bytes(block_bytes: int, itemsize: int, layers: int,
                     mode: str) -> int:
    """Bytes ONE block costs on the tier/wire planes under ``mode``:
    the payload collapses to 1 byte/element, plus the per-layer f32
    scale pair. ``block_bytes`` is the full-width per-block size
    (engine.kv_block_bytes) and ``itemsize`` the cache dtype's width —
    what the routing plane advertises so restore/pull legs are priced
    at the bytes that actually move (kv_router/costmodel.py)."""
    if mode in (None, "none"):
        return int(block_bytes)
    elems = block_bytes // max(itemsize, 1)
    return int(elems * quant_dtype(mode).itemsize + 2 * layers * 4)


# ---------------- logprob-drift harness (the quality gate) ----------------


async def measure_logprob_drift(
    ref_engine,
    quant_engine,
    prompts: list,
    max_tokens: int = 16,
    park=None,
    stat_key: str = "kv_quant_logprob_drift_max",
) -> dict:
    """Greedy-token agreement + chosen-token logprob drift of a
    quantized engine against a full-width reference, on a fixed
    prompt set. Gates every quantized mode, not just the tier codec:
    pass ``stat_key`` to record int8-weight (``models/quant.py``
    WEIGHT_MODES) or int8-device-cache drift under its own stat
    (``park=None`` — those modes quantize the live compute path, no
    tier churn needed).

    Protocol per prompt: the reference engine serves it cold (greedy,
    chosen-token logprobs on). The quantized engine serves it once to
    populate the KV, then ``park(quant_engine, prompt)`` (caller-
    provided) churns the prefix out of the device pool and into the
    quantized host/disk tiers, and the prompt is served AGAIN — its
    prefix now restored through the quantize→dequantize round-trip —
    which is the stream actually compared. Without ``park`` the second
    serve still exercises whatever tier traffic the engine's pool
    pressure produces.

    Bit-exactness is off the table by construction; this measures what
    the codec actually costs where it matters: the emitted tokens and
    their logprobs. The max drift is recorded on the quantized engine
    (``stats["kv_quant_logprob_drift_max"]``) so it rides load_metrics
    → WorkerLoad → the metrics component like any other gauge.
    """
    import asyncio as _asyncio  # noqa: F401  (callers run us in a loop)

    from ..protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from ..runtime.engine import Context

    def req(toks):
        return PreprocessedRequest(
            token_ids=list(toks),
            stop_conditions=StopConditions(max_tokens=max_tokens,
                                           ignore_eos=True),
            sampling_options=SamplingOptions(temperature=0.0, seed=0,
                                             logprobs=0),
            eos_token_ids=[],
        )

    async def serve(engine, toks):
        out_toks, out_lps = [], []
        async for o in engine.generate(Context(req(toks))):
            out_toks.extend(o.token_ids)
            for lp in o.logprobs or []:
                out_lps.append(float(lp["logprob"]))
        return out_toks, out_lps

    agree = total = 0
    deltas: list[float] = []
    for toks in prompts:
        ref_toks, ref_lps = await serve(ref_engine, toks)
        await serve(quant_engine, toks)  # populate the quantized tiers
        if park is not None:
            await park(quant_engine, toks)
        q_toks, q_lps = await serve(quant_engine, toks)
        n = min(len(ref_toks), len(q_toks))
        total += n
        for i in range(n):
            if ref_toks[i] == q_toks[i]:
                agree += 1
        for a, b in zip(ref_lps, q_lps):
            deltas.append(abs(a - b))
    drift_max = max(deltas) if deltas else 0.0
    result = {
        "n_prompts": len(prompts),
        "n_tokens": total,
        "greedy_agreement": round(agree / total, 6) if total else 1.0,
        "logprob_delta_max": round(drift_max, 6),
        "logprob_delta_mean": (
            round(sum(deltas) / len(deltas), 6) if deltas else 0.0
        ),
    }
    stats = getattr(quant_engine, "stats", None)
    if stats is not None:
        stats[stat_key] = max(float(stats.get(stat_key, 0.0)), drift_max)
    return result

"""Deployment spec -> Kubernetes manifests.

What the reference's operator reconcilers materialize imperatively
(operator/internal/controller/dynamonimdeployment_controller.go: child
Deployments, Services, Ingress), the TPU build renders declaratively:

  * a hub Deployment + Service (control plane; the reference deploys
    etcd + NATS here, deploy/docker-compose.yml:16-40),
  * per graph service: a Deployment (or one per TPU slice) with TPU
    nodeSelectors (`cloud.google.com/gke-tpu-accelerator`,
    `gke-tpu-topology`) and `google.com/tpu` chip limits,
  * a Service for any http_port, an Ingress for ingress_host,
  * queue-depth HPA-equivalent rendered as an annotation block (the
    autoscaler component consumes it; k8s HPA cannot see queue depth).

Manifests are plain dicts; ``to_yaml`` serializes a multi-doc stream.
"""

from __future__ import annotations

from .crd import DynamoDeployment, ServiceDeploymentSpec, SpecError

MANAGED_BY = "dynamo-tpu"


def _meta(dep: DynamoDeployment, name: str, extra: dict | None = None) -> dict:
    labels = {
        "app.kubernetes.io/managed-by": MANAGED_BY,
        "dynamo.deployment": dep.name,
        **dep.labels,
        **(extra or {}),
    }
    return {"name": name, "namespace": dep.namespace, "labels": labels}


def _hub_manifests(dep: DynamoDeployment) -> list[dict]:
    name = f"{dep.name}-hub"
    labels = {"dynamo.component": "hub"}
    return [
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": _meta(dep, name, labels),
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"dynamo.service": name}},
                "template": {
                    "metadata": {"labels": {"dynamo.service": name, **labels}},
                    "spec": {
                        "containers": [
                            {
                                "name": "hub",
                                "image": dep.image,
                                "args": [
                                    "python", "-m", "dynamo_tpu.launch.dynamo_run",
                                    "hub", "--hub-port", str(dep.hub_port),
                                ],
                                "ports": [{"containerPort": dep.hub_port}],
                            }
                        ]
                    },
                },
            },
        },
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta(dep, name, labels),
            "spec": {
                "selector": {"dynamo.service": name},
                "ports": [{"port": dep.hub_port, "targetPort": dep.hub_port}],
            },
        },
    ]


def _container(dep: DynamoDeployment, svc: ServiceDeploymentSpec) -> dict:
    hub_addr = f"{dep.name}-hub.{dep.namespace}.svc:{dep.hub_port}"
    env = [{"name": "DYN_RUNTIME_HUB_URL", "value": hub_addr}]
    env += [{"name": k, "value": v} for k, v in sorted(svc.env.items())]
    res = svc.resources
    limits: dict = {"cpu": res.cpu, "memory": res.memory}
    if res.tpu_accelerator:
        limits["google.com/tpu"] = str(res.tpu_chips)
    c = {
        "name": svc.name,
        "image": dep.image,
        "args": list(svc.command),
        "env": env,
        "resources": {"limits": limits, "requests": {"cpu": res.cpu, "memory": res.memory}},
    }
    if svc.http_port:
        c["ports"] = [{"containerPort": svc.http_port}]
        c["readinessProbe"] = {
            "httpGet": {"path": "/health", "port": svc.http_port},
            "periodSeconds": 5,
        }
    return c


_MODEL_MOUNT = "/model-cache"


def _weight_distribution(dep: DynamoDeployment, svc: ServiceDeploymentSpec):
    """(initContainers, volumes, mounts, env) for the service's model
    weights (VERDICT r4 missing #4; ref DynamoNimRequest + PVC
    machinery, dynamodeployment_types.go:28-120).

    A repo id renders a fetch initContainer (``python -m
    dynamo_tpu.llm.hub <id>`` — the engine's own resolver, so the cache
    layout matches what ``--model-path org/name`` reads at startup)
    over an emptyDir or PVC-backed cache.  A filesystem path (starts
    with "/" or "./") renders the PVC mount when one is named — the
    weights are pre-staged ON that volume — and nothing at all
    otherwise (node-local path)."""
    if not svc.model:
        return [], [], [], []
    mounts = [{"name": "model-cache", "mountPath": _MODEL_MOUNT}]
    volumes = [
        {"name": "model-cache",
         **({"persistentVolumeClaim": {"claimName": svc.model_cache_pvc}}
            if svc.model_cache_pvc else {"emptyDir": {}})}
    ]
    if svc.model.startswith(("/", ".")):
        if svc.model_cache_pvc:
            return [], volumes, mounts, []  # pre-staged volume, no fetch
        return [], [], [], []  # node-local path: nothing to render
    hf_env = [{"name": "HF_HOME", "value": f"{_MODEL_MOUNT}/hf"}]
    init = [{
        "name": "fetch-weights",
        "image": dep.image,
        "command": ["python", "-m", "dynamo_tpu.llm.hub", svc.model],
        "env": hf_env,
        "volumeMounts": mounts,
    }]
    return init, volumes, mounts, hf_env


def _pod_spec(dep: DynamoDeployment, svc: ServiceDeploymentSpec) -> dict:
    pod_spec: dict = {"containers": [_container(dep, svc)]}
    init, volumes, mounts, env = _weight_distribution(dep, svc)
    if volumes:  # pvc-mount-only path models render no initContainer
        if init:
            pod_spec["initContainers"] = init
        pod_spec["volumes"] = volumes
        c = pod_spec["containers"][0]
        c["volumeMounts"] = mounts
        if env:
            c["env"] = c.get("env", []) + env
    res = svc.resources
    if res.tpu_accelerator:
        # TPU slice scheduling: GKE places the pod on a node of the slice
        # with the matching accelerator/topology; chips-per-host come from
        # the google.com/tpu limit (the TPU analog of nvidia.com/gpu)
        pod_spec["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator": res.tpu_accelerator,
            "cloud.google.com/gke-tpu-topology": res.tpu_topology,
        }
    return pod_spec


def _autoscale_annotations(svc: ServiceDeploymentSpec) -> dict:
    if not svc.autoscaling.enabled:
        return {}
    a = svc.autoscaling
    return {
        "dynamo.autoscale": (
            f"min={a.min_replicas},max={a.max_replicas},"
            f"target_queue_depth={a.target_queue_depth}"
        )
    }


def _multihost_service_manifests(
    dep: DynamoDeployment, svc: ServiceDeploymentSpec
) -> list[dict]:
    """A ``num_nodes > 1`` service (BASELINE config 4: one SPMD engine
    spanning hosts) renders as one StatefulSet PER REPLICA GROUP with
    ``num_nodes`` pods — the k8s shape of the reference operator's
    multinode deployments (dynamonimdeployment_controller.go renders
    LeaderWorkerSet-style groups):

      * rank = pod index (the ``apps.kubernetes.io/pod-index`` label the
        StatefulSet controller stamps — k8s >= 1.28 only, PodIndexLabel
        gate), injected as DYN_NODE_RANK via the downward API —
        dynamo_run reads it as its --node-rank default and, when the
        env resolves empty on an older cluster, falls back to the
        hostname ordinal (StatefulSet pod names end in the same index);
      * a headless Service gives pod 0 a stable DNS name, which every
        rank gets as DYN_COORDINATOR (jax.distributed coordinator);
      * podManagementPolicy Parallel: SPMD ranks must start together —
        OrderedReady would deadlock rank 0's barrier on rank 1 never
        being created;
      * a whole group restarts together on rank crash (the controller's
        crash-group semantics); separate groups = separate StatefulSets
        so one group's rolling restart can't take down another.
    """
    if svc.hosts:
        raise SpecError(
            f"service {svc.name!r} pins hosts {svc.hosts}; host-pinned "
            "multi-host services are controller-launched (HostLauncher), "
            "not k8s-rendered — drop the hosts list to let the scheduler "
            "place the ranks"
        )
    name = f"{dep.name}-{svc.name}"
    labels = {"dynamo.component": svc.name}
    # pod-matching labels must carry the DEPLOYMENT too: dynamo.component
    # alone would cross-select same-named services of another deployment
    # in the namespace
    selector = {"dynamo.component": svc.name, "dynamo.deployment": dep.name}
    headless = f"{name}-ranks"
    # group-count scaling means adding/removing whole StatefulSets (a
    # StatefulSet's replicas field is RANKS, which must equal num_nodes),
    # so the autoscale annotation lives on the service-level object
    annotations = _autoscale_annotations(svc)
    out: list[dict] = [
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta(dep, headless, labels) | (
                {"annotations": annotations} if annotations else {}
            ),
            "spec": {
                "clusterIP": "None",  # headless: per-pod DNS records
                # ranks need the coordinator's DNS record BEFORE pod 0 is
                # ready (readiness needs jax.distributed init, which
                # needs all ranks connected — a records-when-ready
                # headless service would deadlock the group)
                "publishNotReadyAddresses": True,
                "selector": dict(selector),
                "ports": [
                    {
                        "port": svc.coordinator_port,
                        "targetPort": svc.coordinator_port,
                    }
                ],
            },
        }
    ]
    for r in range(svc.replicas):
        group = f"{name}-g{r}"
        pod_spec = _pod_spec(dep, svc)
        env = pod_spec["containers"][0].setdefault("env", [])
        env.extend(
            [
                {"name": "DYN_NUM_NODES", "value": str(svc.num_nodes)},
                {
                    "name": "DYN_NODE_RANK",
                    "valueFrom": {
                        "fieldRef": {
                            "fieldPath": (
                                "metadata.labels"
                                "['apps.kubernetes.io/pod-index']"
                            )
                        }
                    },
                },
                {
                    "name": "DYN_COORDINATOR",
                    "value": (
                        f"{group}-0.{headless}.{dep.namespace}.svc:"
                        f"{svc.coordinator_port}"
                    ),
                },
            ]
        )
        out.append(
            {
                "apiVersion": "apps/v1",
                "kind": "StatefulSet",
                "metadata": _meta(dep, group, labels),
                "spec": {
                    "serviceName": headless,
                    "replicas": svc.num_nodes,
                    "podManagementPolicy": "Parallel",
                    "selector": {"matchLabels": {"dynamo.service": group}},
                    "template": {
                        "metadata": {
                            "labels": {
                                "dynamo.service": group,
                                **selector,
                            }
                        },
                        "spec": pod_spec,
                    },
                },
            }
        )
    if svc.http_port:  # front all ranks' pods (the engine serves on rank 0;
        # non-leaders fail the readiness probe and drop out of endpoints —
        # this NON-headless service only routes to ready pods)
        out.append(
            {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": _meta(dep, name, labels),
                "spec": {
                    "selector": dict(selector),
                    "ports": [
                        {"port": svc.http_port, "targetPort": svc.http_port}
                    ],
                },
            }
        )
        if svc.ingress_host:
            out.append(_ingress(dep, svc, name, labels))
    return out


def _service_manifests(dep: DynamoDeployment, svc: ServiceDeploymentSpec) -> list[dict]:
    name = f"{dep.name}-{svc.name}"
    labels = {"dynamo.component": svc.name}
    pod_spec = _pod_spec(dep, svc)
    annotations = _autoscale_annotations(svc)
    deployment = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta(dep, name, labels) | (
            {"annotations": annotations} if annotations else {}
        ),
        "spec": {
            "replicas": svc.replicas,
            "selector": {"matchLabels": {"dynamo.service": name}},
            "template": {
                "metadata": {"labels": {"dynamo.service": name, **labels}},
                "spec": pod_spec,
            },
        },
    }
    out = [deployment]
    if svc.http_port:
        out.append(
            {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": _meta(dep, name, labels),
                "spec": {
                    "selector": {"dynamo.service": name},
                    "ports": [{"port": svc.http_port, "targetPort": svc.http_port}],
                },
            }
        )
    if svc.ingress_host:
        out.append(_ingress(dep, svc, name, labels))
    return out


def _ingress(dep: DynamoDeployment, svc: ServiceDeploymentSpec,
             name: str, labels: dict) -> dict:
    return {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "Ingress",
        "metadata": _meta(dep, name, labels),
        "spec": {
            "rules": [
                {
                    "host": svc.ingress_host,
                    "http": {
                        "paths": [
                            {
                                "path": "/",
                                "pathType": "Prefix",
                                "backend": {
                                    "service": {
                                        "name": name,
                                        "port": {"number": svc.http_port},
                                    }
                                },
                            }
                        ]
                    },
                }
            ]
        },
    }


def render_manifests(dep: DynamoDeployment) -> list[dict]:
    """Validate + render the full manifest set for one deployment."""
    dep.validate()
    out = _hub_manifests(dep)
    for svc in dep.services:
        if svc.num_nodes > 1:
            out.extend(_multihost_service_manifests(dep, svc))
        else:
            out.extend(_service_manifests(dep, svc))
    return out


def to_yaml(manifests: list[dict]) -> str:
    """Multi-document YAML stream (kubectl apply -f -)."""
    import yaml

    return "---\n".join(
        yaml.safe_dump(m, sort_keys=False, default_flow_style=False)
        for m in manifests
    )

"""Deployment spec -> Kubernetes manifests.

What the reference's operator reconcilers materialize imperatively
(operator/internal/controller/dynamonimdeployment_controller.go: child
Deployments, Services, Ingress), the TPU build renders declaratively:

  * a hub Deployment + Service (control plane; the reference deploys
    etcd + NATS here, deploy/docker-compose.yml:16-40),
  * per graph service: a Deployment (or one per TPU slice) with TPU
    nodeSelectors (`cloud.google.com/gke-tpu-accelerator`,
    `gke-tpu-topology`) and `google.com/tpu` chip limits,
  * a Service for any http_port, an Ingress for ingress_host,
  * queue-depth HPA-equivalent rendered as an annotation block (the
    autoscaler component consumes it; k8s HPA cannot see queue depth).

Manifests are plain dicts; ``to_yaml`` serializes a multi-doc stream.
"""

from __future__ import annotations

from .crd import DynamoDeployment, ServiceDeploymentSpec

MANAGED_BY = "dynamo-tpu"


def _meta(dep: DynamoDeployment, name: str, extra: dict | None = None) -> dict:
    labels = {
        "app.kubernetes.io/managed-by": MANAGED_BY,
        "dynamo.deployment": dep.name,
        **dep.labels,
        **(extra or {}),
    }
    return {"name": name, "namespace": dep.namespace, "labels": labels}


def _hub_manifests(dep: DynamoDeployment) -> list[dict]:
    name = f"{dep.name}-hub"
    labels = {"dynamo.component": "hub"}
    return [
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": _meta(dep, name, labels),
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"dynamo.service": name}},
                "template": {
                    "metadata": {"labels": {"dynamo.service": name, **labels}},
                    "spec": {
                        "containers": [
                            {
                                "name": "hub",
                                "image": dep.image,
                                "args": [
                                    "python", "-m", "dynamo_tpu.launch.dynamo_run",
                                    "hub", "--hub-port", str(dep.hub_port),
                                ],
                                "ports": [{"containerPort": dep.hub_port}],
                            }
                        ]
                    },
                },
            },
        },
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta(dep, name, labels),
            "spec": {
                "selector": {"dynamo.service": name},
                "ports": [{"port": dep.hub_port, "targetPort": dep.hub_port}],
            },
        },
    ]


def _container(dep: DynamoDeployment, svc: ServiceDeploymentSpec) -> dict:
    hub_addr = f"{dep.name}-hub.{dep.namespace}.svc:{dep.hub_port}"
    env = [{"name": "DYN_RUNTIME_HUB_URL", "value": hub_addr}]
    env += [{"name": k, "value": v} for k, v in sorted(svc.env.items())]
    res = svc.resources
    limits: dict = {"cpu": res.cpu, "memory": res.memory}
    if res.tpu_accelerator:
        limits["google.com/tpu"] = str(res.tpu_chips)
    c = {
        "name": svc.name,
        "image": dep.image,
        "args": list(svc.command),
        "env": env,
        "resources": {"limits": limits, "requests": {"cpu": res.cpu, "memory": res.memory}},
    }
    if svc.http_port:
        c["ports"] = [{"containerPort": svc.http_port}]
        c["readinessProbe"] = {
            "httpGet": {"path": "/health", "port": svc.http_port},
            "periodSeconds": 5,
        }
    return c


def _service_manifests(dep: DynamoDeployment, svc: ServiceDeploymentSpec) -> list[dict]:
    name = f"{dep.name}-{svc.name}"
    labels = {"dynamo.component": svc.name}
    pod_spec: dict = {"containers": [_container(dep, svc)]}
    res = svc.resources
    if res.tpu_accelerator:
        # TPU slice scheduling: GKE places the pod on a node of the slice
        # with the matching accelerator/topology; chips-per-host come from
        # the google.com/tpu limit (the TPU analog of nvidia.com/gpu)
        pod_spec["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator": res.tpu_accelerator,
            "cloud.google.com/gke-tpu-topology": res.tpu_topology,
        }
    annotations = {}
    if svc.autoscaling.enabled:
        a = svc.autoscaling
        annotations["dynamo.autoscale"] = (
            f"min={a.min_replicas},max={a.max_replicas},"
            f"target_queue_depth={a.target_queue_depth}"
        )
    deployment = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta(dep, name, labels) | (
            {"annotations": annotations} if annotations else {}
        ),
        "spec": {
            "replicas": svc.replicas,
            "selector": {"matchLabels": {"dynamo.service": name}},
            "template": {
                "metadata": {"labels": {"dynamo.service": name, **labels}},
                "spec": pod_spec,
            },
        },
    }
    out = [deployment]
    if svc.http_port:
        out.append(
            {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": _meta(dep, name, labels),
                "spec": {
                    "selector": {"dynamo.service": name},
                    "ports": [{"port": svc.http_port, "targetPort": svc.http_port}],
                },
            }
        )
    if svc.ingress_host:
        out.append(
            {
                "apiVersion": "networking.k8s.io/v1",
                "kind": "Ingress",
                "metadata": _meta(dep, name, labels),
                "spec": {
                    "rules": [
                        {
                            "host": svc.ingress_host,
                            "http": {
                                "paths": [
                                    {
                                        "path": "/",
                                        "pathType": "Prefix",
                                        "backend": {
                                            "service": {
                                                "name": name,
                                                "port": {"number": svc.http_port},
                                            }
                                        },
                                    }
                                ]
                            },
                        }
                    ]
                },
            }
        )
    return out


def render_manifests(dep: DynamoDeployment) -> list[dict]:
    """Validate + render the full manifest set for one deployment."""
    dep.validate()
    out = _hub_manifests(dep)
    for svc in dep.services:
        out.extend(_service_manifests(dep, svc))
    return out


def to_yaml(manifests: list[dict]) -> str:
    """Multi-document YAML stream (kubectl apply -f -)."""
    import yaml

    return "---\n".join(
        yaml.safe_dump(m, sort_keys=False, default_flow_style=False)
        for m in manifests
    )

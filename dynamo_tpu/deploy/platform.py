"""One-command control-plane packaging (VERDICT r4 next #8; ref
deploy/dynamo/helm/ — the reference ships the platform as a Helm chart;
here it is a renderer emitting one applyable manifest set, consistent
with the repo-wide no-templating stance of deploy/manifests.py).

``render_platform`` produces everything a cluster needs BEFORE any
model deployment exists:

  * the hub (control-plane transport: store/bus/discovery) —
    Deployment + Service;
  * the control pair — ONE Deployment whose two containers (api-server
    with revisions/rollback, kube reconciler loop) share the durable
    DeploymentStore volume, plus the api Service;
  * the OpenAI frontend (``in=http out=dyn``) — Deployment + Service,
    optional Ingress;
  * the metrics stack — Prometheus (scrape config as a ConfigMap,
    targets pointed at the rendered Services) and Grafana with the
    repo dashboard + datasource provisioning baked into ConfigMaps.

CLI: ``python -m dynamo_tpu.deploy render-platform --name dyn | kubectl
apply -f -`` (deploy/builder.py).  Snapshot-locked by
tests/test_platform_render.py the way the Grafana dashboard is.
"""

from __future__ import annotations

import json
import os

import yaml

_METRICS_DIR = os.path.join(os.path.dirname(__file__), "metrics")


def _meta(name: str, namespace: str, component: str) -> dict:
    return {
        "name": name,
        "namespace": namespace,
        "labels": {
            "app.kubernetes.io/managed-by": "dynamo-tpu",
            "dynamo.platform": "control-plane",
            "dynamo.component": component,
        },
    }


def _deployment(name, namespace, component, pod_spec, replicas=1):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta(name, namespace, component),
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"dynamo.service": name}},
            "template": {
                "metadata": {"labels": {
                    "dynamo.service": name, "dynamo.component": component}},
                "spec": pod_spec,
            },
        },
    }


def _service(name, namespace, component, port, target=None,
             selector=None):
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta(name, namespace, component),
        "spec": {
            "selector": selector or {"dynamo.service": name},
            "ports": [{"port": port, "targetPort": target or port}],
        },
    }


def render_platform(
    name: str = "dynamo",
    namespace: str = "default",
    image: str = "dynamo-tpu:latest",
    *,
    hub_port: int = 18500,
    api_port: int = 7700,
    frontend_port: int = 8080,
    ingress_host: str = "",
    store_pvc: str = "",
    hub_pvc: str = "",
    with_metrics: bool = True,
) -> list[dict]:
    """``store_pvc`` backs the control pair's DeploymentStore,
    ``hub_pvc`` the hub's snapshot+WAL — SEPARATE claims because a
    default ReadWriteOnce volume cannot attach to two pods on
    different nodes ('' = emptyDir: survives container restarts, not
    pod rescheduling)."""
    out: list[dict] = []

    # ---- hub
    hub = f"{name}-hub"
    out.append(_deployment(hub, namespace, "hub", {
        "containers": [{
            "name": "hub",
            "image": image,
            "args": ["python", "-m", "dynamo_tpu.launch.dynamo_run", "hub",
                     "--hub-port", str(hub_port),
                     "--data-dir", "/data/hub"],
            "ports": [{"containerPort": hub_port}],
            "volumeMounts": [{"name": "store", "mountPath": "/data"}],
        }],
        "volumes": [_store_volume(hub_pvc)],
    }))
    out.append(_service(hub, namespace, "hub", hub_port))

    # ---- control pair: api-server + reconciler over one store volume
    ctrl = f"{name}-control"
    store_mount = [{"name": "store", "mountPath": "/data"}]
    out.append(_deployment(ctrl, namespace, "control", {
        "containers": [
            {
                "name": "api-server",
                "image": image,
                "args": ["python", "-m", "dynamo_tpu.deploy.api_server",
                         "--root", "/data/api", "--host", "0.0.0.0",
                         "--port", str(api_port)],
                "ports": [{"containerPort": api_port}],
                "volumeMounts": store_mount,
            },
            {
                "name": "reconciler",
                "image": image,
                "args": ["python", "-m", "dynamo_tpu.deploy.kube",
                         "--root", "/data/api",
                         "--namespace", namespace],
                "volumeMounts": store_mount,
            },
        ],
        # the reconciler applies manifests: its pod needs the operator
        # ServiceAccount rendered below
        "serviceAccountName": f"{name}-operator",
        "volumes": [_store_volume(store_pvc)],
    }))
    out.append(_service(f"{name}-api", namespace, "control", api_port,
                        selector={"dynamo.service": ctrl}))
    out.extend(_rbac(name, namespace))

    # ---- frontend
    fe = f"{name}-frontend"
    out.append(_deployment(fe, namespace, "frontend", {
        "containers": [{
            "name": "frontend",
            "image": image,
            "args": ["python", "-m", "dynamo_tpu.launch.dynamo_run",
                     "in=http", "out=dyn://",
                     "--hub", f"{hub}.{namespace}.svc:{hub_port}",
                     "--http-port", str(frontend_port)],
            "ports": [{"containerPort": frontend_port}],
            "readinessProbe": {
                "httpGet": {"path": "/health", "port": frontend_port},
                "periodSeconds": 5,
            },
        }],
    }))
    out.append(_service(fe, namespace, "frontend", frontend_port))
    if ingress_host:
        out.append({
            "apiVersion": "networking.k8s.io/v1",
            "kind": "Ingress",
            "metadata": _meta(fe, namespace, "frontend"),
            "spec": {"rules": [{
                "host": ingress_host,
                "http": {"paths": [{
                    "path": "/", "pathType": "Prefix",
                    "backend": {"service": {
                        "name": fe,
                        "port": {"number": frontend_port}}},
                }]},
            }]},
        })

    if with_metrics:
        # the worker-fleet metrics aggregation component
        # (observability/__main__.py): scrapes every backend's stats
        # endpoint through the hub and serves the fleet gauges
        mc = f"{name}-metrics"
        out.append(_deployment(mc, namespace, "metrics", {
            "containers": [{
                "name": "metrics",
                "image": image,
                "args": ["python", "-m", "dynamo_tpu.observability",
                         "dynamo.backend.generate",
                         "--hub", f"{hub}.{namespace}.svc:{hub_port}",
                         "--port", "9091"],
                "ports": [{"containerPort": 9091}],
            }],
        }))
        out.append(_service(mc, namespace, "metrics", 9091))
        out.extend(_metrics_stack(name, namespace, fe, frontend_port))
    return out


def _store_volume(store_pvc: str) -> dict:
    return {
        "name": "store",
        **({"persistentVolumeClaim": {"claimName": store_pvc}}
           if store_pvc else {"emptyDir": {}}),
    }


def _rbac(name: str, namespace: str) -> list[dict]:
    """The reconciler's ServiceAccount: exactly the kinds KubectlApi
    manages, nothing cluster-scoped."""
    sa = f"{name}-operator"
    return [
        {"apiVersion": "v1", "kind": "ServiceAccount",
         "metadata": _meta(sa, namespace, "control")},
        {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "Role",
         "metadata": _meta(sa, namespace, "control"),
         "rules": [
             {"apiGroups": ["apps"],
              "resources": ["deployments", "statefulsets"],
              "verbs": ["get", "list", "create", "patch", "delete"]},
             {"apiGroups": [""],
              "resources": ["services", "configmaps"],
              "verbs": ["get", "list", "create", "patch", "delete"]},
             {"apiGroups": ["networking.k8s.io"],
              "resources": ["ingresses"],
              "verbs": ["get", "list", "create", "patch", "delete"]},
         ]},
        {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "RoleBinding",
         "metadata": _meta(sa, namespace, "control"),
         "subjects": [{"kind": "ServiceAccount", "name": sa,
                       "namespace": namespace}],
         "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                     "kind": "Role", "name": sa}},
    ]


def _metrics_stack(name, namespace, frontend_name, frontend_port):
    prom = f"{name}-prometheus"
    graf = f"{name}-grafana"
    scrape = {
        "global": {"scrape_interval": "5s", "evaluation_interval": "5s"},
        "scrape_configs": [
            {"job_name": "dynamo-frontend", "metrics_path": "/metrics",
             "static_configs": [{
                 "targets": [f"{frontend_name}:{frontend_port}"]}]},
            {"job_name": "dynamo-metrics-component",
             "metrics_path": "/metrics",
             "static_configs": [{"targets": [f"{name}-metrics:9091"]}]},
        ],
    }
    with open(os.path.join(_METRICS_DIR, "grafana-dashboard.json")) as f:
        dashboard = f.read()
    datasource = {
        "apiVersion": 1,
        "datasources": [{
            "name": "Prometheus", "type": "prometheus", "access": "proxy",
            "url": f"http://{prom}:9090", "isDefault": True,
        }],
    }
    dash_provider = {
        "apiVersion": 1,
        "providers": [{
            "name": "dynamo", "type": "file",
            "options": {"path": "/var/lib/grafana/dashboards"},
        }],
    }
    return [
        {"apiVersion": "v1", "kind": "ConfigMap",
         "metadata": _meta(f"{prom}-config", namespace, "metrics"),
         "data": {"prometheus.yml": yaml.safe_dump(scrape, sort_keys=False)}},
        _deployment(prom, namespace, "metrics", {
            "containers": [{
                "name": "prometheus",
                "image": "prom/prometheus:latest",
                "args": ["--config.file=/etc/prometheus/prometheus.yml"],
                "ports": [{"containerPort": 9090}],
                "volumeMounts": [{"name": "config",
                                  "mountPath": "/etc/prometheus"}],
            }],
            "volumes": [{"name": "config",
                         "configMap": {"name": f"{prom}-config"}}],
        }),
        _service(prom, namespace, "metrics", 9090),
        {"apiVersion": "v1", "kind": "ConfigMap",
         "metadata": _meta(f"{graf}-provisioning", namespace, "metrics"),
         "data": {
             "datasource.yml": yaml.safe_dump(datasource, sort_keys=False),
             "dashboards.yml": yaml.safe_dump(dash_provider,
                                              sort_keys=False),
         }},
        {"apiVersion": "v1", "kind": "ConfigMap",
         "metadata": _meta(f"{graf}-dashboard", namespace, "metrics"),
         "data": {"dynamo-tpu.json": dashboard}},
        _deployment(graf, namespace, "metrics", {
            "containers": [{
                "name": "grafana",
                "image": "grafana/grafana-oss:latest",
                "env": [
                    {"name": "GF_AUTH_ANONYMOUS_ENABLED", "value": "true"},
                    {"name": "GF_AUTH_ANONYMOUS_ORG_ROLE",
                     "value": "Viewer"},
                ],
                "ports": [{"containerPort": 3000}],
                "volumeMounts": [
                    {"name": "provisioning-ds",
                     "mountPath": "/etc/grafana/provisioning/datasources"},
                    {"name": "provisioning-dash",
                     "mountPath": "/etc/grafana/provisioning/dashboards"},
                    {"name": "dashboard",
                     "mountPath": "/var/lib/grafana/dashboards"},
                ],
            }],
            "volumes": [
                {"name": "provisioning-ds", "configMap": {
                    "name": f"{graf}-provisioning",
                    "items": [{"key": "datasource.yml",
                               "path": "datasource.yml"}]}},
                {"name": "provisioning-dash", "configMap": {
                    "name": f"{graf}-provisioning",
                    "items": [{"key": "dashboards.yml",
                               "path": "dashboards.yml"}]}},
                {"name": "dashboard",
                 "configMap": {"name": f"{graf}-dashboard"}},
            ],
        }),
        _service(graf, namespace, "metrics", 3000),
    ]

"""Live reconcile loop over the deployment store.

Re-design of the reference's in-cluster operator
(deploy/dynamo/operator/internal/controller/
dynamonimdeployment_controller.go — watch CRs, create/scale the child
Deployments, write status conditions). On a TPU-VM fleet the unit of
scheduling is a host process, not a pod, so the controller here converges
*processes*: it polls the DeploymentStore (the CR store), diffs desired
replicas against the child processes it owns, and spawns/kills/restarts
to match — crash-restart with exponential backoff, queue-depth
autoscaling, and a status subresource written back next to each spec.

The manifest renderer (manifests.py) remains the GitOps path for real
k8s clusters; this controller is the single-host / dev-fleet reconciler
the api-server can host directly (``ApiServer(..., reconcile=True)``).
"""

from __future__ import annotations

import asyncio
import logging
import math
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .crd import DynamoDeployment, ServiceDeploymentSpec, SpecError

logger = logging.getLogger(__name__)


@dataclass
class _Replica:
    proc: object  # subprocess.Popen-like (poll/terminate/kill)
    started_at: float = field(default_factory=time.monotonic)


class DeploymentController:
    """Reconciles DeploymentStore specs into running child processes.

    ``spawn`` is injectable (tests use fakes): called with
    (deployment_name, service_spec, replica_index) and must return a
    Popen-like object. ``metrics_fn(deployment, service) -> queue_depth``
    enables autoscaling; None means replicas follow the spec exactly.
    """

    def __init__(
        self,
        store,
        poll_interval: float = 1.0,
        spawn: Optional[Callable] = None,
        metrics_fn: Optional[Callable] = None,
        backoff_base: float = 1.0,
        backoff_max: float = 30.0,
    ):
        self.store = store
        self.poll_interval = poll_interval
        self._spawn = spawn or self._spawn_subprocess
        self._metrics_fn = metrics_fn
        self._backoff_base = backoff_base
        self._backoff_max = backoff_max
        self._replicas: dict[tuple[str, str, int], _Replica] = {}
        # terminated children awaiting reap; SIGKILL after the grace period
        self._terminating: list[tuple[object, float]] = []
        self.kill_grace = 10.0
        # consecutive crash count + not-before time per replica slot
        self._crashes: dict[tuple[str, str, int], int] = {}
        self._not_before: dict[tuple[str, str, int], float] = {}
        self._task: Optional[asyncio.Task] = None
        self._stopping = False
        self._last_status: dict[str, dict] = {}
        self.stats = {"spawns": 0, "restarts": 0, "kills": 0, "reconciles": 0}

    # ---- lifecycle ----

    def start(self) -> None:
        if self._task is None:
            self._stopping = False
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self, kill_children: bool = True) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if kill_children:
            for key in list(self._replicas):
                self._kill(key)
            deadline = time.monotonic() + self.kill_grace
            while self._terminating and time.monotonic() < deadline:
                self._reap_terminating()
                await asyncio.sleep(0.05)
            for proc, _d in self._terminating:
                try:
                    proc.kill()
                except Exception:  # noqa: BLE001
                    pass
            self._terminating = []

    async def _loop(self) -> None:
        while not self._stopping:
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001 — controller must survive
                logger.exception("reconcile iteration failed")
            await asyncio.sleep(self.poll_interval)

    # ---- the reconcile step ----

    def reconcile_once(self) -> None:
        """One observe/diff/converge pass (sync; also called from tests)."""
        self.stats["reconciles"] += 1
        self._reap_terminating()
        desired: dict[tuple[str, str, int], ServiceDeploymentSpec] = {}
        deployments: dict[str, DynamoDeployment] = {}
        for name in self.store.list():
            try:
                dep = DynamoDeployment.from_dict(self.store.get(name))
                dep.validate()
            except (SpecError, KeyError, TypeError) as e:
                logger.warning("skipping invalid deployment %s: %s", name, e)
                continue
            deployments[name] = dep
            for svc in dep.services:
                n = self._desired_replicas(name, svc)
                for i in range(n):
                    desired[(name, svc.name, i)] = svc

        # reap crashed children; schedule their restart with backoff
        for key, rep in list(self._replicas.items()):
            if rep.proc.poll() is not None:
                del self._replicas[key]
                if key in desired:
                    crashes = self._crashes.get(key, 0) + 1
                    self._crashes[key] = crashes
                    delay = min(
                        self._backoff_base * (2 ** (crashes - 1)),
                        self._backoff_max,
                    )
                    self._not_before[key] = time.monotonic() + delay
                    self.stats["restarts"] += 1
                    logger.warning(
                        "replica %s exited rc=%s; restart in %.1fs (crash #%d)",
                        key, rep.proc.poll(), delay, crashes,
                    )

        # converge: kill what shouldn't run, spawn what should
        for key in list(self._replicas):
            if key not in desired:
                self._kill(key)
        # drop per-slot crash/backoff state for slots that no longer exist
        # (a deleted-and-recreated deployment must start fresh, not
        # inherit the old slot's backoff) and status cache for deleted
        # deployments (a recreate must rewrite its .status file)
        for key in list(self._crashes):
            if key not in desired:
                self._crashes.pop(key, None)
        for key in list(self._not_before):
            if key not in desired:
                self._not_before.pop(key, None)
        for name in list(self._last_status):
            if name not in deployments:
                self._last_status.pop(name, None)
        now = time.monotonic()
        for key, svc in desired.items():
            if key in self._replicas or self._not_before.get(key, 0) > now:
                continue
            name, _svc_name, idx = key
            try:
                proc = self._spawn(name, svc, idx)
            except Exception:  # noqa: BLE001 — bad command must not kill
                logger.exception("spawn failed for %s", key)
                self._not_before[key] = now + self._backoff_max
                continue
            self._replicas[key] = _Replica(proc)
            self.stats["spawns"] += 1
        # a replica that stayed up past the backoff window resets its count
        for key, rep in self._replicas.items():
            if self._crashes.get(key) and (
                time.monotonic() - rep.started_at > self._backoff_max
            ):
                self._crashes.pop(key, None)

        self._write_statuses(deployments, desired)

    def _desired_replicas(self, name: str, svc: ServiceDeploymentSpec) -> int:
        if not (svc.autoscaling.enabled and self._metrics_fn):
            return svc.replicas
        a = svc.autoscaling
        try:
            depth = self._metrics_fn(name, svc)
        except Exception:  # noqa: BLE001 — metrics plane down: hold steady
            logger.exception("metrics_fn failed; keeping current scale")
            current = sum(
                1 for (d, s, _i) in self._replicas if d == name and s == svc.name
            )
            return max(current, a.min_replicas)
        if depth is None:
            return svc.replicas
        want = math.ceil(depth / max(a.target_queue_depth, 1)) if depth > 0 else a.min_replicas
        return max(a.min_replicas, min(a.max_replicas, want))

    def _kill(self, key) -> None:
        rep = self._replicas.pop(key, None)
        if rep is None:
            return
        self.stats["kills"] += 1
        try:
            rep.proc.terminate()
        except Exception:  # noqa: BLE001
            pass
        self._terminating.append((rep.proc, time.monotonic() + self.kill_grace))
        self._crashes.pop(key, None)
        self._not_before.pop(key, None)

    def _reap_terminating(self) -> None:
        """Reap terminated children (no zombies); SIGKILL any that trap
        SIGTERM past the grace period."""
        still = []
        for proc, deadline in self._terminating:
            if proc.poll() is not None:
                continue  # reaped
            if time.monotonic() >= deadline:
                logger.warning("child ignored SIGTERM; killing")
                try:
                    proc.kill()
                except Exception:  # noqa: BLE001
                    pass
                # keep it one more round so the SIGKILL gets reaped too
                still.append((proc, deadline + self.kill_grace))
            else:
                still.append((proc, deadline))
        self._terminating = still

    # ---- status subresource ----

    def _write_statuses(self, deployments, desired) -> None:
        if not hasattr(self.store, "put_status"):
            return
        for name, dep in deployments.items():
            services = {}
            for svc in dep.services:
                want = sum(
                    1 for (d, s, _i) in desired if d == name and s == svc.name
                )
                ready = sum(
                    1 for (d, s, _i) in self._replicas if d == name and s == svc.name
                )
                services[svc.name] = {"desired": want, "ready": ready}
            ok = all(v["ready"] >= v["desired"] for v in services.values())
            body = {
                "services": services,
                "conditions": [{
                    "type": "Available",
                    "status": "True" if ok else "False",
                }],
            }
            # write only on change: a steady-state poll loop must not
            # churn one file-replace per deployment per second
            if self._last_status.get(name) == body:
                continue
            self._last_status[name] = body
            self.store.put_status(name, body | {"updated_at": time.time()})

    # ---- default child spawner ----

    @staticmethod
    def _spawn_subprocess(name: str, svc: ServiceDeploymentSpec, idx: int):
        env = os.environ.copy()
        env.update(svc.env)
        env["DYN_DEPLOYMENT"] = name
        env["DYN_SERVICE"] = svc.name
        env["DYN_REPLICA"] = str(idx)
        cmd = svc.command or [sys.executable, "-c", "import time; time.sleep(1e9)"]
        logger.info("spawning %s/%s[%d]: %s", name, svc.name, idx, cmd)
        return subprocess.Popen(cmd, env=env)

"""Live reconcile loop over the deployment store.

Re-design of the reference's in-cluster operator
(deploy/dynamo/operator/internal/controller/
dynamonimdeployment_controller.go — watch CRs, create/scale the child
Deployments, write status conditions). On a TPU-VM fleet the unit of
scheduling is a host process, not a pod, so the controller here converges
*processes*: it polls the DeploymentStore (the CR store), diffs desired
replicas against the child processes it owns, and spawns/kills/restarts
to match — crash-restart with exponential backoff, queue-depth
autoscaling, and a status subresource written back next to each spec.

Multi-host engines (BASELINE config 4: one logical worker spanning 2
TPU-VM hosts) are first-class: a service with ``num_nodes > 1`` expands
every replica into ``num_nodes`` rank processes placed on ``hosts[k %
len(hosts)]`` through a pluggable :class:`HostLauncher` (local
subprocess for the dev fleet, :class:`SshLauncher` for real hosts, fakes
in tests). Rank processes get ``DYN_NODE_RANK / DYN_NUM_NODES /
DYN_COORDINATOR`` env so ``dynamo_run --num-nodes`` style workers can
join the jax.distributed runtime, and a rank crash restarts the WHOLE
replica group — SPMD lockstep cannot survive a lone rank respawn.

The manifest renderer (manifests.py) remains the GitOps path for real
k8s clusters; this controller is the single-host / dev-fleet reconciler
the api-server can host directly (``ApiServer(..., reconcile=True)``).
"""

from __future__ import annotations

import asyncio
import logging
import math
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .crd import DynamoDeployment, ServiceDeploymentSpec, SpecError

logger = logging.getLogger(__name__)


@dataclass
class _Replica:
    proc: object  # subprocess.Popen-like (poll/terminate/kill)
    started_at: float = field(default_factory=time.monotonic)


class LocalLauncher:
    """Spawn rank processes as local children (the dev-fleet default);
    ``host`` is ignored."""

    def spawn(self, host: str, name: str, svc: ServiceDeploymentSpec,
              replica: int, rank: int, extra_env: dict):
        env = os.environ.copy()
        env.update(svc.env)
        env.update(extra_env)
        cmd = svc.command or [sys.executable, "-c", "import time; time.sleep(1e9)"]
        logger.info(
            "spawning %s/%s[%d.%d] on %s: %s",
            name, svc.name, replica, rank, host or "local", cmd,
        )
        return subprocess.Popen(cmd, env=env)


class SshLauncher:
    """Spawn rank processes on remote hosts over ssh (agent-less fleet
    path — a TPU-VM pool reachable by hostname). The returned Popen is
    the LOCAL ssh client: poll() tracks the remote command's exit,
    terminate() drops the connection (with ``-tt`` the remote side gets
    SIGHUP and dies with it). env rides the remote command line —
    values are shell-quoted."""

    def __init__(self, user: str = "", ssh_opts: Optional[list[str]] = None):
        self.user = user
        self.ssh_opts = ssh_opts or ["-o", "BatchMode=yes"]

    def spawn(self, host: str, name: str, svc: ServiceDeploymentSpec,
              replica: int, rank: int, extra_env: dict):
        import shlex

        if not host:
            # fail FAST: an empty hostname would become `ssh "" ...`,
            # which exits instantly and puts the group in an endless
            # crash/backoff loop. Hostless multi-node specs are for the
            # local dev fleet or the k8s renderer, not the ssh fleet.
            raise SpecError(
                f"{name}/{svc.name}: SshLauncher needs a hosts list "
                "(hostless multi-node specs are platform-scheduled — "
                "use the k8s renderer or the LocalLauncher)"
            )
        env = dict(svc.env)
        env.update(extra_env)
        assigns = " ".join(
            f"{k}={shlex.quote(str(v))}" for k, v in env.items()
        )
        cmd = svc.command or ["sleep", "infinity"]
        remote = f"env {assigns} {' '.join(shlex.quote(c) for c in cmd)}"
        target = f"{self.user}@{host}" if self.user else host
        logger.info(
            "ssh-spawning %s/%s[%d.%d] on %s", name, svc.name, replica,
            rank, target,
        )
        # stdin=DEVNULL: concurrent rank clients must not contend for the
        # controller's terminal (-tt still forces a remote pty so a
        # dropped connection SIGHUPs the remote command)
        return subprocess.Popen(
            ["ssh", "-tt", *self.ssh_opts, target, remote],
            stdin=subprocess.DEVNULL,
        )


class DeploymentController:
    """Reconciles DeploymentStore specs into running child processes.

    ``launcher`` is injectable (tests use fakes): ``spawn(host, name,
    svc, replica, rank, extra_env)`` must return a Popen-like object.
    The legacy ``spawn(name, svc, idx)`` callable is still accepted for
    single-node services. ``metrics_fn(deployment, service) ->
    queue_depth`` enables autoscaling; None means replicas follow the
    spec exactly.
    """

    def __init__(
        self,
        store,
        poll_interval: float = 1.0,
        spawn: Optional[Callable] = None,
        launcher=None,
        metrics_fn: Optional[Callable] = None,
        backoff_base: float = 1.0,
        backoff_max: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        planner=None,
    ):
        self.store = store
        self.poll_interval = poll_interval
        if launcher is None and spawn is not None:
            launcher = _LegacySpawnLauncher(spawn)
        self.launcher = launcher or LocalLauncher()
        self._metrics_fn = metrics_fn
        self._backoff_base = backoff_base
        self._backoff_max = backoff_max
        # injected clock drives the autoscaler guard rails (tests tick
        # a fake clock); process lifecycle keeps real time.monotonic
        self._clock = clock
        # embedded SLA planner (planner.Planner): ticked once per
        # reconcile pass so its scale decisions land in the same store
        # this controller converges — `dynamo_run --planner` is the
        # standalone alternative
        self.planner = planner
        # per-(deployment, service) autoscaler guard: hysteresis +
        # cooldown so a threshold-straddling queue depth can't flap
        # replicas every tick (planner/guard.py, shared with the
        # planner's prefill/decode drivers)
        self._guards: dict[tuple[str, str], tuple[object, tuple]] = {}
        # key = (deployment, service, replica, rank)
        self._replicas: dict[tuple[str, str, int, int], _Replica] = {}
        # terminated children awaiting reap; SIGKILL after the grace
        # period. Entries carry their replica key: a group must not
        # respawn while any of its old ranks still drains — the new
        # rank 0 would race the old one for the deterministic
        # coordinator port and the TPU devices.
        self._terminating: list[tuple[object, object, float]] = []
        self.kill_grace = 10.0
        # consecutive crash count + not-before time per replica GROUP
        # (deployment, service, replica) — ranks restart together
        self._crashes: dict[tuple[str, str, int], int] = {}
        self._not_before: dict[tuple[str, str, int], float] = {}
        self._task: Optional[asyncio.Task] = None
        self._stopping = False
        self._last_status: dict[str, dict] = {}
        self.stats = {"spawns": 0, "restarts": 0, "kills": 0, "reconciles": 0}

    # ---- lifecycle ----

    def start(self) -> None:
        if self._task is None:
            self._stopping = False
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self, kill_children: bool = True) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if kill_children:
            for key in list(self._replicas):
                self._kill(key)
            deadline = time.monotonic() + self.kill_grace
            while self._terminating and time.monotonic() < deadline:
                self._reap_terminating()
                await asyncio.sleep(0.05)
            for _key, proc, _d in self._terminating:
                try:
                    proc.kill()
                except Exception:  # noqa: BLE001 — already reaped/dead
                    logger.debug("kill on exit failed", exc_info=True)
            self._terminating = []

    async def _loop(self) -> None:
        while not self._stopping:
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001 — controller must survive
                logger.exception("reconcile iteration failed")
            await asyncio.sleep(self.poll_interval)

    # ---- the reconcile step ----

    def reconcile_once(self) -> None:
        """One observe/diff/converge pass (sync; also called from tests)."""
        self.stats["reconciles"] += 1
        if self.planner is not None:
            try:
                self.planner.tick()
            except Exception:  # noqa: BLE001 — a sick planner must not
                logger.exception("embedded planner tick failed")  # stop
        self._reap_terminating()
        desired: dict[tuple[str, str, int, int], tuple] = {}
        deployments: dict[str, DynamoDeployment] = {}
        for name in self.store.list():
            try:
                dep = DynamoDeployment.from_dict(self.store.get(name))
                dep.validate()
            except (SpecError, KeyError, TypeError) as e:
                logger.warning("skipping invalid deployment %s: %s", name, e)
                continue
            deployments[name] = dep
            for svc in dep.services:
                n = self._desired_replicas(name, svc)
                for r in range(n):
                    for k in range(svc.num_nodes):
                        host = (
                            svc.hosts[k % len(svc.hosts)] if svc.hosts else ""
                        )
                        desired[(name, svc.name, r, k)] = (svc, host)

        # reap crashed children; a crashed rank takes its whole GROUP
        # down (SPMD lockstep) and schedules the group's restart. A
        # group counts ONE crash per pass no matter how many of its
        # ranks died together (a host reboot must not fast-forward the
        # exponential backoff schedule).
        crashed_groups: set[tuple[str, str, int]] = set()
        for key, rep in list(self._replicas.items()):
            if rep.proc.poll() is not None:
                rc = rep.proc.poll()
                del self._replicas[key]
                if key in desired:
                    group = key[:3]
                    if group in crashed_groups:
                        continue
                    crashed_groups.add(group)
                    crashes = self._crashes.get(group, 0) + 1
                    self._crashes[group] = crashes
                    delay = min(
                        self._backoff_base * (2 ** (crashes - 1)),
                        self._backoff_max,
                    )
                    self._not_before[group] = time.monotonic() + delay
                    self.stats["restarts"] += 1
                    logger.warning(
                        "replica %s exited rc=%s; group restart in %.1fs "
                        "(crash #%d)", key, rc, delay, crashes,
                    )
        for key in list(self._replicas):
            if key[:3] in crashed_groups:
                self._kill(key, clear_group_state=False)

        # converge: kill what shouldn't run, spawn what should
        for key in list(self._replicas):
            if key not in desired:
                self._kill(key)
        # drop per-group crash/backoff state for groups that no longer
        # exist (a deleted-and-recreated deployment must start fresh, not
        # inherit the old slot's backoff) and status cache for deleted
        # deployments (a recreate must rewrite its .status file)
        desired_groups = {key[:3] for key in desired}
        for group in list(self._crashes):
            if group not in desired_groups:
                self._crashes.pop(group, None)
        for group in list(self._not_before):
            if group not in desired_groups:
                self._not_before.pop(group, None)
        for name in list(self._last_status):
            if name not in deployments:
                self._last_status.pop(name, None)
        # autoscaler guards die with their service (a recreated
        # deployment must not inherit the old cooldown clock) — keyed on
        # the SPECS, not `desired`: a service legitimately scaled to
        # zero has no desired replicas but must keep its guard, or the
        # next reconcile reseeds from spec.replicas and flaps 0 -> spec
        live_services = {
            (name, svc.name)
            for name, dep in deployments.items()
            for svc in dep.services
        }
        for key in list(self._guards):
            if key not in live_services:
                self._guards.pop(key, None)
        now = time.monotonic()
        # groups with a rank still draining must not respawn yet — the
        # old process holds the coordinator port / TPU devices until it
        # exits (single-node replicas hold the chip just the same)
        draining = {k[:3] for k, _p, _d in self._terminating
                    if k is not None}
        for key, (svc, host) in desired.items():
            if key in self._replicas or self._not_before.get(key[:3], 0) > now:
                continue
            if key[:3] in draining:
                continue
            name, _svc_name, r, k = key
            try:
                proc = self.launcher.spawn(
                    host, name, svc, r, k,
                    self._rank_env(svc, r, k, deployment=name),
                )
            except Exception:  # noqa: BLE001 — bad command must not kill
                logger.exception("spawn failed for %s", key)
                self._not_before[key[:3]] = now + self._backoff_max
                # a partial SPMD group must not run: already-spawned
                # sibling ranks would wedge in jax.distributed init
                # waiting for the peer that never arrives — kill them
                for k2 in [
                    kk for kk in self._replicas if kk[:3] == key[:3]
                ]:
                    self._kill(k2, clear_group_state=False)
                continue
            self._replicas[key] = _Replica(proc)
            self.stats["spawns"] += 1
        # a replica group that stayed up past the backoff window resets
        # its crash count
        for key, rep in self._replicas.items():
            if self._crashes.get(key[:3]) and (
                time.monotonic() - rep.started_at > self._backoff_max
            ):
                self._crashes.pop(key[:3], None)

        self._write_statuses(deployments, desired)

    @staticmethod
    def _rank_env(svc: ServiceDeploymentSpec, replica: int, rank: int,
                  deployment: str = "") -> dict:
        env = {
            "DYN_DEPLOYMENT": deployment,
            "DYN_REPLICA": str(replica),
            "DYN_SERVICE": svc.name,
        }
        if svc.num_nodes > 1:
            # coordinator = rank 0's host; one port per replica group.
            # Empty hosts = every rank local (dev fleet on one box; the
            # k8s renderer covers platform-scheduled ranks instead).
            head = svc.hosts[0] if svc.hosts else "127.0.0.1"
            env.update({
                "DYN_NODE_RANK": str(rank),
                "DYN_NUM_NODES": str(svc.num_nodes),
                "DYN_COORDINATOR": (
                    f"{head}:{svc.coordinator_port + replica}"
                ),
            })
        return env

    def _desired_replicas(self, name: str, svc: ServiceDeploymentSpec) -> int:
        if not (svc.autoscaling.enabled and self._metrics_fn):
            self._guards.pop((name, svc.name), None)
            return svc.replicas
        a = svc.autoscaling
        try:
            depth = self._metrics_fn(name, svc)
        except Exception:  # noqa: BLE001 — metrics plane down: hold steady
            logger.exception("metrics_fn failed; keeping current scale")
            current = sum(
                1 for (d, s, _r, k) in self._replicas
                if d == name and s == svc.name and k == 0
            )
            return max(current, a.min_replicas)
        if depth is None:
            # metric not yet published this tick: hold the guarded scale
            # — falling back to spec.replicas would bypass the guard and
            # kill/respawn autoscaled replicas on one missing sample
            cached = self._guards.get((name, svc.name))
            if cached is not None and cached[0].current is not None:
                return cached[0].current
            return svc.replicas
        want = math.ceil(depth / max(a.target_queue_depth, 1)) if depth > 0 else a.min_replicas
        return self._guard_for(name, svc).apply(want)

    def _guard_for(self, name: str, svc: ServiceDeploymentSpec):
        """Per-service ScaleGuard, rebuilt (keeping the current scale)
        when the spec's autoscaling rails change."""
        from ..planner.guard import GuardConfig, ScaleGuard

        a = svc.autoscaling
        key = (name, svc.name)
        cfg_sig = (a.min_replicas, a.max_replicas, a.up_cooldown_s,
                   a.down_cooldown_s, a.down_stable_s)
        cached = self._guards.get(key)
        if cached is not None and cached[1] == cfg_sig:
            return cached[0]
        guard = ScaleGuard(
            GuardConfig(
                min_replicas=a.min_replicas, max_replicas=a.max_replicas,
                up_cooldown_s=a.up_cooldown_s,
                down_cooldown_s=a.down_cooldown_s,
                down_stable_s=a.down_stable_s,
            ),
            clock=self._clock,
            # rails changed: keep the live scale; brand new: seed from
            # the spec so a fresh controller can only scale DOWN through
            # the stability window, never instantly on its first tick
            initial=cached[0].current if cached is not None else svc.replicas,
        )
        self._guards[key] = (guard, cfg_sig)
        return guard

    def _kill(self, key, clear_group_state: bool = True) -> None:
        rep = self._replicas.pop(key, None)
        if rep is None:
            return
        self.stats["kills"] += 1
        try:
            rep.proc.terminate()
        except Exception:  # noqa: BLE001 — already exited on its own
            logger.debug("terminate failed", exc_info=True)
        self._terminating.append(
            (key, rep.proc, time.monotonic() + self.kill_grace)
        )
        if clear_group_state:
            self._crashes.pop(key[:3], None)
            self._not_before.pop(key[:3], None)

    def _reap_terminating(self) -> None:
        """Reap terminated children (no zombies); SIGKILL any that trap
        SIGTERM past the grace period."""
        still = []
        for key, proc, deadline in self._terminating:
            if proc.poll() is not None:
                continue  # reaped
            if time.monotonic() >= deadline:
                logger.warning("child ignored SIGTERM; killing")
                try:
                    proc.kill()
                except Exception:  # noqa: BLE001 — already exited
                    logger.debug("sigkill failed", exc_info=True)
                # keep it one more round so the SIGKILL gets reaped too
                still.append((key, proc, deadline + self.kill_grace))
            else:
                still.append((key, proc, deadline))
        self._terminating = still

    # ---- status subresource ----

    def _write_statuses(self, deployments, desired) -> None:
        if not hasattr(self.store, "put_status"):
            return
        for name, dep in deployments.items():
            services = {}
            for svc in dep.services:
                want_groups = {
                    (d, s, r) for (d, s, r, _k) in desired
                    if d == name and s == svc.name
                }
                # a multi-host replica is ready only when ALL ranks run
                ready = sum(
                    1 for g in want_groups
                    if all(
                        (g[0], g[1], g[2], k) in self._replicas
                        for k in range(svc.num_nodes)
                    )
                )
                services[svc.name] = {
                    "desired": len(want_groups), "ready": ready,
                }
            ok = all(v["ready"] >= v["desired"] for v in services.values())
            body = {
                "services": services,
                "conditions": [{
                    "type": "Available",
                    "status": "True" if ok else "False",
                }],
            }
            # write only on change: a steady-state poll loop must not
            # churn one file-replace per deployment per second
            if self._last_status.get(name) == body:
                continue
            self._last_status[name] = body
            self.store.put_status(name, body | {"updated_at": time.time()})


class _LegacySpawnLauncher:
    """Adapter for the pre-round-3 ``spawn(name, svc, idx)`` injectable
    (single-node services only; rank env rides the svc env unused)."""

    def __init__(self, spawn: Callable):
        self._spawn = spawn

    def spawn(self, host, name, svc, replica, rank, extra_env):
        return self._spawn(name, svc, replica)

"""Deployment plane: spec types, k8s manifest generation, api-server.

TPU-native re-design of the reference's Kubernetes machinery
(deploy/dynamo/operator Go CRDs + controllers, deploy/dynamo/api-server
REST): the deployment *spec* is the same shape (a graph deployment with
per-service replicas/resources/autoscaling, operator/api/v1alpha1/
dynamodeployment_types.go:28). Two execution paths:

  * **manifests** — deterministic k8s YAML (GitOps-style) with TPU-slice
    scheduling (nodeSelectors for gke-tpu-accelerator/topology, one
    worker per slice host group) for real clusters;
  * **controller** — a live reconcile loop (the operator-controller
    equivalent, dynamonimdeployment_controller.go) for TPU-VM hosts: it
    converges specs into child processes with crash-restart backoff,
    queue-depth autoscaling, and a status subresource;
  * **kube** — the cluster-API reconciler: renders each stored spec and
    APPLIES it through a KubeApi client (create / drift-revert / prune),
    aggregating live readiness back into the status subresource — the
    operator's Reconcile() role against a real (or fake, in tests)
    Kubernetes API.
"""

from .api_server import ApiServer
from .builder import build_artifact, read_artifact
from .controller import DeploymentController
from .crd import (
    Autoscaling,
    DynamoDeployment,
    Resources,
    ServiceDeploymentSpec,
)
from .kube import FakeKubeApi, KubeReconciler
from .manifests import render_manifests, to_yaml

__all__ = [
    "ApiServer",
    "DeploymentController",
    "FakeKubeApi",
    "KubeReconciler",
    "Autoscaling",
    "DynamoDeployment",
    "Resources",
    "ServiceDeploymentSpec",
    "build_artifact",
    "read_artifact",
    "render_manifests",
    "to_yaml",
]

"""Deployment plane: spec types, k8s manifest generation, api-server.

TPU-native re-design of the reference's Kubernetes machinery
(deploy/dynamo/operator Go CRDs + controllers, deploy/dynamo/api-server
REST): the deployment *spec* is the same shape (a graph deployment with
per-service replicas/resources/autoscaling, operator/api/v1alpha1/
dynamodeployment_types.go:28), but instead of an in-cluster reconciler
the TPU build renders deterministic manifests (GitOps-style) with
TPU-slice scheduling (nodeSelectors for gke-tpu-accelerator/topology,
one worker per slice host group) — a controller has nothing TPU-specific
to reconcile that the manifest cannot declare.
"""

from .api_server import ApiServer
from .builder import build_artifact, read_artifact
from .crd import (
    Autoscaling,
    DynamoDeployment,
    Resources,
    ServiceDeploymentSpec,
)
from .manifests import render_manifests, to_yaml

__all__ = [
    "ApiServer",
    "Autoscaling",
    "DynamoDeployment",
    "Resources",
    "ServiceDeploymentSpec",
    "build_artifact",
    "read_artifact",
    "render_manifests",
    "to_yaml",
]

"""Deployment api-server.

Re-design of the reference's Go api-server (deploy/dynamo/api-server/api/
routes/routes.go:339: REST for clusters/deployments/revisions backed by
Postgres): a REST service over the shared asyncio HTTP base with a
file-backed store (one JSON per deployment, atomic replace; artifacts as
content-addressed tarballs) — the control plane a TPU-VM fleet actually
needs, with no database dependency.

  GET    /health
  GET    /api/v1/deployments
  POST   /api/v1/deployments                   (409 on duplicate)
  GET    /api/v1/deployments/{name}
  PUT    /api/v1/deployments/{name}
  DELETE /api/v1/deployments/{name}
  GET    /api/v1/deployments/{name}/manifests  (YAML stream, text/yaml)
  GET    /api/v1/deployments/{name}/revisions  (append-only spec history)
  POST   /api/v1/deployments/{name}/rollback   ({"revision": N})
  GET    /api/v1/artifacts
  POST   /api/v1/artifacts                     (raw tar.gz body -> digest)
  GET    /api/v1/artifacts/{digest}
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Optional

from ..http.base import HttpError, HttpServerBase
from .crd import DynamoDeployment, SpecError
from .manifests import render_manifests, to_yaml


class DeploymentStore:
    """Durable deployment specs: one JSON file per deployment, written
    atomically (tmp + rename) so a crashed write never corrupts a spec."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(os.path.join(root, "deployments"), exist_ok=True)
        os.makedirs(os.path.join(root, "artifacts"), exist_ok=True)

    def _path(self, name: str) -> str:
        if not name or "/" in name or name.startswith("."):
            raise HttpError(400, f"bad deployment name {name!r}")
        return os.path.join(self.root, "deployments", name + ".json")

    def list(self) -> list[str]:
        d = os.path.join(self.root, "deployments")
        return sorted(f[:-5] for f in os.listdir(d) if f.endswith(".json"))

    def get(self, name: str) -> dict:
        try:
            with open(self._path(name)) as f:
                return json.load(f)
        except FileNotFoundError:
            raise HttpError(404, f"deployment {name!r} not found", "not_found") from None

    @staticmethod
    def _atomic_write(path: str, obj: dict) -> None:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, indent=2)
        os.replace(tmp, path)

    def put(self, name: str, spec: dict, create: bool) -> None:
        path = self._path(name)
        if create and os.path.exists(path):
            raise HttpError(409, f"deployment {name!r} exists", "conflict")
        if not create and not os.path.exists(path):
            raise HttpError(404, f"deployment {name!r} not found", "not_found")
        self._atomic_write(path, spec)
        self._append_revision(name, spec)

    # ---- revisions (ref api-server routes.go:339 revision model) ----

    def _rev_path(self, name: str) -> str:
        return self._path(name) + ".revisions.jsonl"

    def _last_revision(self, name: str) -> Optional[dict]:
        """Parse only the FINAL line (the append path must not re-parse
        the whole history per PUT)."""
        try:
            with open(self._rev_path(name), "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - (1 << 20)))
                tail = f.read().splitlines()
        except FileNotFoundError:
            return None
        for ln in reversed(tail):
            if ln.strip():
                return json.loads(ln)
        return None

    def _append_revision(self, name: str, spec: dict) -> int:
        """Every accepted spec CHANGE appends an immutable revision —
        the rollback target set. A rollback itself appends a NEW
        revision (history is linear and append-only, like the
        reference's deployment revisions). Idempotent re-PUTs of the
        same spec (the standard reconciler pattern) append nothing."""
        import time

        last = self._last_revision(name)
        if last is not None and last["spec"] == spec:
            return last["revision"]
        n = (last["revision"] + 1) if last else 1
        with open(self._rev_path(name), "a") as f:
            json.dump(
                {"revision": n, "spec": spec,
                 "created_at": time.strftime(
                     "%Y-%m-%dT%H:%M:%SZ", time.gmtime())},
                f,
            )
            f.write("\n")
        return n

    def list_revisions(self, name: str) -> list[dict]:
        try:
            with open(self._rev_path(name)) as f:
                return [json.loads(ln) for ln in f if ln.strip()]
        except FileNotFoundError:
            return []

    def rollback(self, name: str, revision: int) -> dict:
        """Reinstate an earlier revision's spec as the current one."""
        current = self.get(name)  # 404 on unknown deployment
        for rev in self.list_revisions(name):
            if rev["revision"] == revision:
                spec = rev["spec"]
                if spec == current:
                    return spec  # no-op rollback: don't append noise
                self._atomic_write(self._path(name), spec)
                self._append_revision(name, spec)
                return spec
        raise HttpError(
            404, f"deployment {name!r} has no revision {revision}", "not_found"
        )

    def delete(self, name: str) -> None:
        try:
            os.unlink(self._path(name))
        except FileNotFoundError:
            raise HttpError(404, f"deployment {name!r} not found", "not_found") from None
        for suffix in (".status", ".revisions.jsonl"):
            try:
                os.unlink(self._path(name) + suffix)
            except FileNotFoundError:
                pass

    # ---- status subresource (written by the reconcile controller) ----

    def put_status(self, name: str, status: dict) -> None:
        self._atomic_write(self._path(name) + ".status", status)

    def get_status(self, name: str) -> Optional[dict]:
        try:
            with open(self._path(name) + ".status") as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    # ---- artifacts ("bentos", ref api-server revisions) ----

    def put_artifact(self, data: bytes) -> str:
        digest = hashlib.sha256(data).hexdigest()[:16]
        path = os.path.join(self.root, "artifacts", digest + ".tar.gz")
        if not os.path.exists(path):
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        return digest

    def list_artifacts(self) -> list[str]:
        d = os.path.join(self.root, "artifacts")
        return sorted(f[: -len(".tar.gz")] for f in os.listdir(d) if f.endswith(".tar.gz"))

    def get_artifact(self, digest: str) -> bytes:
        if not digest or "/" in digest or digest.startswith("."):
            raise HttpError(400, f"bad digest {digest!r}")
        try:
            with open(os.path.join(self.root, "artifacts", digest + ".tar.gz"), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise HttpError(404, f"artifact {digest!r} not found", "not_found") from None


class ApiServer(HttpServerBase):
    """Unauthenticated control-plane API: binds loopback by default; put an
    authenticating proxy in front before exposing it beyond the host."""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 7700):
        super().__init__(host=host, port=port)
        self.store = DeploymentStore(root)

    def _parse_spec(self, body: bytes) -> DynamoDeployment:
        try:
            spec = DynamoDeployment.from_dict(json.loads(body))
            spec.validate()
            return spec
        except (json.JSONDecodeError, KeyError, TypeError, SpecError) as e:
            raise HttpError(422, f"invalid deployment spec: {e}") from None

    async def _route(self, method, path, headers, body, writer) -> None:
        parts = [p for p in path.split("?")[0].split("/") if p]
        if method == "GET" and parts == ["health"]:
            await self._send_json(writer, 200, {"status": "ok"})
            return
        if len(parts) < 2 or parts[0] != "api" or parts[1] != "v1":
            raise HttpError(404, f"no route for {method} {path}", "not_found")
        rest = parts[2:]

        if rest and rest[0] == "deployments":
            if method == "GET" and len(rest) == 1:
                await self._send_json(
                    writer, 200, {"deployments": self.store.list()}
                )
            elif method == "POST" and len(rest) == 1:
                spec = self._parse_spec(body)
                self.store.put(spec.name, spec.to_dict(), create=True)
                await self._send_json(writer, 201, spec.to_dict())
            elif method == "GET" and len(rest) == 2:
                await self._send_json(writer, 200, self.store.get(rest[1]))
            elif method == "PUT" and len(rest) == 2:
                spec = self._parse_spec(body)
                if spec.name != rest[1]:
                    raise HttpError(422, "spec name does not match URL")
                self.store.put(rest[1], spec.to_dict(), create=False)
                await self._send_json(writer, 200, spec.to_dict())
            elif method == "DELETE" and len(rest) == 2:
                self.store.delete(rest[1])
                await self._send_json(writer, 200, {"deleted": rest[1]})
            elif method == "GET" and len(rest) == 3 and rest[2] == "status":
                self.store.get(rest[1])  # 404 on unknown deployment
                await self._send_json(
                    writer, 200, self.store.get_status(rest[1]) or {}
                )
            elif method == "GET" and len(rest) == 3 and rest[2] == "revisions":
                self.store.get(rest[1])  # 404 on unknown deployment
                await self._send_json(
                    writer, 200,
                    {"revisions": self.store.list_revisions(rest[1])},
                )
            elif method == "POST" and len(rest) == 3 and rest[2] == "rollback":
                try:
                    revision = int(json.loads(body)["revision"])
                except (json.JSONDecodeError, KeyError, TypeError, ValueError):
                    raise HttpError(
                        422, 'rollback body must be {"revision": N}'
                    ) from None
                spec = self.store.rollback(rest[1], revision)
                await self._send_json(writer, 200, spec)
            elif method == "GET" and len(rest) == 3 and rest[2] == "manifests":
                dep = DynamoDeployment.from_dict(self.store.get(rest[1]))
                yaml_text = to_yaml(render_manifests(dep))
                await self._send_response(
                    writer, 200, yaml_text.encode(), content_type="text/yaml"
                )
            else:
                raise HttpError(405, f"{method} not allowed on {path}")
            return

        if rest and rest[0] == "artifacts":
            if method == "GET" and len(rest) == 1:
                await self._send_json(
                    writer, 200, {"artifacts": self.store.list_artifacts()}
                )
            elif method == "POST" and len(rest) == 1:
                digest = self.store.put_artifact(body)
                await self._send_json(writer, 201, {"digest": digest})
            elif method == "GET" and len(rest) == 2:
                await self._send_response(
                    writer, 200, self.store.get_artifact(rest[1]),
                    content_type="application/gzip",
                )
            else:
                raise HttpError(405, f"{method} not allowed on {path}")
            return

        raise HttpError(404, f"no route for {method} {path}", "not_found")


def main(argv=None) -> None:
    import argparse
    import asyncio

    p = argparse.ArgumentParser("dynamo-api-server", description=__doc__)
    p.add_argument("--root", default="./dynamo-deployments")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (no auth — keep loopback unless proxied)")
    p.add_argument("--port", type=int, default=7700)
    p.add_argument("--reconcile", action="store_true",
                   help="run the live controller: converge specs into child "
                        "processes on this host (deploy/controller.py)")
    args = p.parse_args(argv)

    async def run():
        srv = ApiServer(args.root, host=args.host, port=args.port)
        await srv.start()
        ctl = None
        if args.reconcile:
            from .controller import DeploymentController

            ctl = DeploymentController(srv.store)
            ctl.start()
        print(f"api-server on http://{args.host}:{srv.port} (root {args.root}"
              f"{', reconciling' if ctl else ''})", flush=True)
        try:
            await srv.run()
        finally:
            if ctl is not None:
                await ctl.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()

"""Kubernetes reconciler: converge rendered manifests against a cluster.

Closes the loop the reference's operator closes (deploy/dynamo/operator/
internal/controller/dynamonimdeployment_controller.go:136: a 3,157-LoC
Reconcile() that renders a DynamoNimDeployment into Deployments/Services
/Ingresses and applies them against the live API, requeueing on drift).
Round 3 rendered manifests (`manifests.py`) and supervised processes
(`controller.py`) but nothing ever APPLIED the rendered objects
(VERDICT r3 missing #2).

The controller speaks to the cluster through the small :class:`KubeApi`
interface — the subset of the API machinery reconciliation needs (get /
list-by-label / apply / delete). Deployments run it against a real
client adapter; tests (and this zero-egress dev box) run it against
:class:`FakeKubeApi`, an in-memory API server with the same observable
semantics (resourceVersion bumps, label selection, namespacing) — the
same technique as controller-runtime's fake client that the reference's
operator tests use.

Reconciliation semantics (one pass = ``reconcile_once``):

  * every deployment spec in the :class:`~.api_server.DeploymentStore`
    renders to its manifest set; each object is applied when ABSENT or
    when its spec drifted from the rendered truth (field-owner
    comparison on ``spec``/data fields, not resourceVersion equality —
    status written by kubelets must not thrash the diff);
  * objects labeled ``app.kubernetes.io/managed-by: dynamo-tpu`` whose
    ``dynamo.deployment`` no longer exists in the store are PRUNED —
    deleting a deployment converges to deleting its objects;
  * live state aggregates back into the store's status subresource
    (per-service ready/desired counts), mirroring the operator's
    status writes.
"""

from __future__ import annotations

import copy
import itertools
import json
import logging
from typing import Optional, Protocol

from .api_server import DeploymentStore
from .crd import DynamoDeployment
from .manifests import MANAGED_BY, render_manifests

logger = logging.getLogger(__name__)


class KubeApi(Protocol):
    """The slice of the Kubernetes API the reconciler consumes."""

    def get(self, kind: str, namespace: str, name: str) -> Optional[dict]:
        ...

    def list(self, namespace: Optional[str] = None,
             labels: Optional[dict] = None) -> list[dict]:
        ...

    def apply(self, obj: dict) -> dict:
        ...

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        ...


class FakeKubeApi:
    """In-memory stand-in for the API server (tests / dry runs): objects
    keyed by (kind, namespace, name), resourceVersion bumped per write,
    creations/updates/deletions recorded for assertions."""

    def __init__(self):
        self._objs: dict[tuple, dict] = {}
        self._rv = itertools.count(1)
        self.actions: list[tuple] = []  # ("apply"|"delete", kind, ns, name)

    @staticmethod
    def _key(obj_or_kind, namespace=None, name=None) -> tuple:
        if isinstance(obj_or_kind, dict):
            meta = obj_or_kind.get("metadata", {})
            return (obj_or_kind.get("kind"), meta.get("namespace"),
                    meta.get("name"))
        return (obj_or_kind, namespace, name)

    def get(self, kind, namespace, name):
        obj = self._objs.get((kind, namespace, name))
        return copy.deepcopy(obj) if obj is not None else None

    def list(self, namespace=None, labels=None):
        out = []
        for obj in self._objs.values():
            meta = obj.get("metadata", {})
            if namespace is not None and meta.get("namespace") != namespace:
                continue
            obj_labels = meta.get("labels", {})
            if labels and any(obj_labels.get(k) != v for k, v in labels.items()):
                continue
            out.append(copy.deepcopy(obj))
        return out

    def apply(self, obj):
        key = self._key(obj)
        stored = copy.deepcopy(obj)
        prev = self._objs.get(key)
        meta = stored.setdefault("metadata", {})
        meta["resourceVersion"] = str(next(self._rv))
        if prev is not None and "status" in prev and "status" not in stored:
            stored["status"] = prev["status"]  # apply never clears status
        self._objs[key] = stored
        self.actions.append(("apply", *key))
        return copy.deepcopy(stored)

    def delete(self, kind, namespace, name):
        existed = self._objs.pop((kind, namespace, name), None) is not None
        if existed:
            self.actions.append(("delete", kind, namespace, name))
        return existed

    # test helpers ----------------------------------------------------
    def set_status(self, kind, namespace, name, status: dict) -> None:
        self._objs[(kind, namespace, name)]["status"] = status

    def mutate(self, kind, namespace, name, fn) -> None:
        """Simulate out-of-band drift (a human kubectl edit)."""
        fn(self._objs[(kind, namespace, name)])


class KubectlApi:
    """KubeApi against a real cluster through ``kubectl`` (the portable
    client this zero-dependency image has a path to; a python-client
    adapter drops in behind the same four methods). Maps: get -> kubectl
    get -o json, list -> get -l selector, apply -> apply -f -, delete ->
    kubectl delete."""

    _KINDS = ("Deployment", "StatefulSet", "Service", "Ingress", "ConfigMap")

    def __init__(self, kubectl: str = "kubectl", context: str = "",
                 namespace: str = ""):
        # ``namespace`` scopes list() when the caller passes none — the
        # rendered platform runs under a NAMESPACED Role, which cannot
        # authorize --all-namespaces (deploy/platform.py RBAC)
        self._base = [kubectl] + (["--context", context] if context else [])
        self._namespace = namespace

    def _run(self, args: list[str], stdin: str = ""):
        import subprocess

        return subprocess.run(
            self._base + args, input=stdin, capture_output=True, text=True,
            timeout=60,
        )

    def get(self, kind, namespace, name):
        r = self._run(["get", kind, name, "-n", namespace, "-o", "json"])
        return json.loads(r.stdout) if r.returncode == 0 else None

    def list(self, namespace=None, labels=None):
        sel = ",".join(f"{k}={v}" for k, v in (labels or {}).items())
        namespace = namespace or self._namespace
        ns = ["-n", namespace] if namespace else ["--all-namespaces"]
        out = []
        for kind in self._KINDS:
            r = self._run(
                ["get", kind, *ns, "-o", "json"]
                + (["-l", sel] if sel else [])
            )
            if r.returncode == 0:
                out.extend(json.loads(r.stdout).get("items", []))
            else:
                # a swallowed read error makes "forbidden/cluster down"
                # look like "nothing to prune" — say so loudly (but keep
                # going: other kinds may still be readable)
                logger.warning("kubectl get %s failed (rc=%s): %s",
                               kind, r.returncode, r.stderr.strip()[:200])
        return out

    def apply(self, obj):
        # server-side apply under the reconciler's field manager: the
        # API server tracks field ownership, so drift-repair re-applies
        # only contested fields and other controllers' fields survive
        # (mirrors the FakeKubeApi-tested field-owner diff semantics;
        # --force-conflicts because the reconciler IS the owner of the
        # rendered spec — a fight over those fields must resolve to it)
        r = self._run(
            ["apply", "--server-side", "--field-manager", "dynamo-operator",
             "--force-conflicts", "-f", "-"],
            stdin=json.dumps(obj),
        )
        if r.returncode != 0:
            raise RuntimeError(f"kubectl apply failed: {r.stderr.strip()}")
        return obj

    def delete(self, kind, namespace, name):
        r = self._run(
            ["delete", kind, name, "-n", namespace, "--ignore-not-found"]
        )
        return r.returncode == 0 and "deleted" in r.stdout


def _spec_fields(obj: dict) -> dict:
    """The fields the reconciler OWNS and diffs: everything except
    status and server-managed metadata."""
    out = {k: v for k, v in obj.items() if k not in ("status", "metadata")}
    meta = obj.get("metadata", {})
    out["metadata"] = {
        k: v for k, v in meta.items()
        if k in ("name", "namespace", "labels", "annotations")
    }
    return out


def _covered(rendered, live) -> bool:
    """Field-OWNER drift check: every field the rendered manifest sets
    must hold in the live object; fields the API server defaulted
    (spec.strategy, protocol: TCP, ...) are nobody's drift. Plain
    equality would read those server-side defaults as perpetual drift
    and re-apply every object every pass against a real cluster."""
    if isinstance(rendered, dict):
        return isinstance(live, dict) and all(
            k in live and _covered(v, live[k]) for k, v in rendered.items()
        )
    if isinstance(rendered, list):
        return (
            isinstance(live, list)
            and len(live) == len(rendered)
            and all(_covered(r, l) for r, l in zip(rendered, live))
        )
    return rendered == live


class KubeReconciler:
    """Converge DeploymentStore specs into KubeApi objects.

    ``reconcile_once`` is level-triggered and idempotent — the async
    loop just reruns it on an interval (the operator's requeue), and a
    test can single-step it deterministically."""

    def __init__(self, store: DeploymentStore, api: KubeApi,
                 interval: float = 2.0):
        self.store = store
        self.api = api
        self.interval = interval
        self._task = None

    # ---- loop plumbing ----
    def start(self) -> None:
        import asyncio

        async def _loop():
            while True:
                try:
                    self.reconcile_once()
                except Exception as e:  # noqa: BLE001 — reconcile must not die
                    logger.warning("kube reconcile error: %s", e)
                await asyncio.sleep(self.interval)

        self._task = asyncio.get_running_loop().create_task(_loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            self._task = None

    # ---- one level-triggered pass ----
    def reconcile_once(self) -> None:
        live_by_dep: dict[str, list[dict]] = {}
        for obj in self.api.list(labels={"app.kubernetes.io/managed-by": MANAGED_BY}):
            dep = obj.get("metadata", {}).get("labels", {}).get("dynamo.deployment")
            if dep is None:
                # managed-by alone is NOT ownership: the rendered control
                # plane itself (deploy/platform.py) carries the managed-by
                # label with no dynamo.deployment — grouping it under None
                # would make the prune pass delete the hub, frontend,
                # metrics stack and the reconciler's own Deployment on
                # its first tick
                continue
            live_by_dep.setdefault(dep, []).append(obj)

        names = set(self.store.list())
        for name in sorted(names):
            try:
                dep = DynamoDeployment.from_dict(self.store.get(name))
                desired = render_manifests(dep)
            except Exception as e:  # noqa: BLE001 — bad spec, skip + report
                self.store.put_status(name, {"error": str(e)})
                continue
            self._converge(name, desired,
                           live_by_dep.get(name, []))

        # prune: managed objects whose deployment vanished from the store
        for dep_name, objs in live_by_dep.items():
            if dep_name in names:
                continue
            for obj in objs:
                kind, ns, obj_name = FakeKubeApi._key(obj)
                self.api.delete(kind, ns, obj_name)
                logger.info("pruned %s/%s of deleted deployment %s",
                            kind, obj_name, dep_name)

    def _converge(self, dep_name: str, desired: list[dict],
                  live: list[dict]) -> None:
        wanted = {}
        for obj in desired:
            key = FakeKubeApi._key(obj)
            wanted[key] = obj
            cur = self.api.get(*key)
            if cur is None or not _covered(_spec_fields(obj), _spec_fields(cur)):
                self.api.apply(obj)
        # delete managed objects of this deployment no longer rendered
        # (a service removed from the graph, a replica-group shrunk)
        for obj in live:
            key = FakeKubeApi._key(obj)
            if key not in wanted:
                self.api.delete(*key)
        self._write_status(dep_name, wanted)

    def _write_status(self, dep_name: str, wanted: dict) -> None:
        services = {}
        ready_all = True
        for (kind, ns, name), obj in wanted.items():
            if kind not in ("Deployment", "StatefulSet"):
                continue
            cur = self.api.get(kind, ns, name) or {}
            desired_n = (cur.get("spec") or {}).get("replicas", 0)
            ready_n = (cur.get("status") or {}).get("readyReplicas", 0)
            services[name] = {
                "kind": kind, "desired": desired_n, "ready": ready_n,
            }
            ready_all &= ready_n >= desired_n
        self.store.put_status(dep_name, {
            "phase": "Ready" if ready_all else "Progressing",
            "services": services,
        })


def main(argv=None) -> None:  # pragma: no cover - in-cluster entry
    """``python -m dynamo_tpu.deploy.kube --root /data/api`` — the
    reconciler container of the rendered platform (deploy/platform.py):
    converge every spec in the shared DeploymentStore into cluster
    objects through kubectl, forever."""
    import argparse
    import asyncio

    p = argparse.ArgumentParser("dynamo-kube-reconciler", description=__doc__)
    p.add_argument("--root", default="./dynamo-deployments",
                   help="DeploymentStore root (shared with the api-server)")
    p.add_argument("--interval", type=float, default=5.0)
    p.add_argument("--kubectl", default="kubectl")
    p.add_argument("--context", default="")
    p.add_argument("--namespace", default="",
                   help="scope list/prune to one namespace (required "
                        "under the rendered platform's namespaced Role)")
    args = p.parse_args(argv)

    store = DeploymentStore(args.root)
    rec = KubeReconciler(
        store, KubectlApi(kubectl=args.kubectl, context=args.context,
                          namespace=args.namespace),
        interval=args.interval,
    )

    async def run():
        rec.start()
        print(f"kube reconciler over {args.root} every {args.interval}s",
              flush=True)
        try:
            await asyncio.Event().wait()
        finally:
            await rec.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()

"""Deployment spec types (CRD-equivalents).

Re-design of the reference's CRDs (operator/api/v1alpha1/
dynamodeployment_types.go:28 `DynamoDeployment`,
dynamonimdeployment_types.go `DynamoNimDeployment`): a deployment is a
named graph of services; each service declares replicas, resources
(with first-class TPU topology instead of nvidia.com/gpu counts),
autoscaling, env, and optional ingress. Specs are plain dataclasses with
dict/JSON round-trip and validation — consumed by the manifest renderer
and the api-server.
"""

from __future__ import annotations

import re
from dataclasses import asdict, dataclass, field
from typing import Optional

# GKE TPU accelerator names (cloud.google.com/gke-tpu-accelerator values)
TPU_ACCELERATORS = {
    "tpu-v4-podslice",
    "tpu-v5-lite-podslice",   # v5e
    "tpu-v5p-slice",
    "tpu-v6e-slice",
}


class SpecError(ValueError):
    pass


@dataclass
class Resources:
    """Per-replica resources (ref dynamonimdeployment_types.go Resources,
    TPU-flavored: an accelerator + topology instead of a GPU count)."""

    cpu: str = "1"
    memory: str = "2Gi"
    tpu_accelerator: str = ""     # "" = CPU-only service (frontend, router)
    tpu_topology: str = ""        # e.g. "2x4" — chips per replica's slice
    tpu_chips: int = 0            # chips requested per host (google.com/tpu)

    def validate(self) -> None:
        if self.tpu_accelerator and self.tpu_accelerator not in TPU_ACCELERATORS:
            raise SpecError(
                f"unknown tpu accelerator {self.tpu_accelerator!r}; "
                f"expected one of {sorted(TPU_ACCELERATORS)}"
            )
        if self.tpu_accelerator and not self.tpu_topology:
            raise SpecError("tpu_topology required when tpu_accelerator is set")
        if self.tpu_accelerator and self.tpu_chips <= 0:
            raise SpecError("tpu_chips must be > 0 for TPU services")


@dataclass
class Autoscaling:
    """ref dynamonimdeployment_types.go Autoscaling."""

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 1
    # scale on the worker's queue depth (num_requests_waiting from the
    # metrics plane) — the TPU-meaningful signal; CPU% is meaningless for
    # a device-bound worker
    target_queue_depth: int = 8
    # guard rails (planner/guard.py ScaleGuard — shared with the SLA
    # planner): scale-up paced by up_cooldown_s; scale-down only after
    # the desire has sat below current for down_stable_s continuously
    # AND down_cooldown_s since the last action — a queue depth
    # oscillating around the threshold can no longer flap replicas
    # every reconcile tick. All three at 0 = the legacy instant path.
    up_cooldown_s: float = 0.0
    down_cooldown_s: float = 60.0
    down_stable_s: float = 30.0

    def validate(self) -> None:
        if self.enabled and self.min_replicas > self.max_replicas:
            raise SpecError("min_replicas > max_replicas")
        if min(self.up_cooldown_s, self.down_cooldown_s,
               self.down_stable_s) < 0:
            raise SpecError("autoscaling cooldown/stability windows "
                            "must be >= 0")


@dataclass
class ServiceDeploymentSpec:
    """One service of the graph (ref DynamoNimDeployment spec)."""

    name: str
    command: list[str] = field(default_factory=list)  # container args
    replicas: int = 1
    resources: Resources = field(default_factory=Resources)
    autoscaling: Autoscaling = field(default_factory=Autoscaling)
    env: dict[str, str] = field(default_factory=dict)
    # expose an HTTP ingress for this service (the OpenAI frontend)
    http_port: int = 0
    ingress_host: str = ""
    # multi-host SPMD engines (BASELINE config 4: 2 hosts x tp=8): each
    # REPLICA expands to num_nodes rank processes. With a ``hosts``
    # list, rank k is placed on hosts[k % len(hosts)] via the
    # controller's host launcher; with hosts EMPTY the ranks are
    # platform-scheduled — the k8s renderer emits one StatefulSet per
    # replica group (rank = pod index, coordinator = pod 0's stable
    # DNS name). Ranks get DYN_NODE_RANK / DYN_NUM_NODES /
    # DYN_COORDINATOR env, and a rank crash restarts the WHOLE replica
    # group — SPMD lockstep can't survive a lone rank respawn.
    num_nodes: int = 1
    hosts: list[str] = field(default_factory=list)  # empty = platform-placed
    coordinator_port: int = 9900
    # weight distribution (ref DynamoNimRequest / PVC machinery,
    # dynamodeployment_types.go:28-120): an org/name HF repo id renders
    # an initContainer that pre-fetches weights into a model-cache
    # volume before the engine starts, so pods come up on BARE nodes; a
    # local path renders only the mount + env (weights pre-staged).
    model: str = ""  # "" = service carries no model weights
    # "" = per-pod emptyDir cache; a PVC name = shared cluster cache
    # (ReadOnlyMany volumes let every replica reuse one download)
    model_cache_pvc: str = ""

    def validate(self) -> None:
        if not self.name or "/" in self.name:
            raise SpecError(f"bad service name {self.name!r}")
        if self.replicas < 0:
            raise SpecError("replicas must be >= 0")
        if self.num_nodes < 1:
            raise SpecError("num_nodes must be >= 1")
        if self.ingress_host and not self.http_port:
            # an Ingress backend needs a Service port; accepting the
            # host and rendering nothing would silently drop it
            raise SpecError("ingress_host requires http_port")
        if self.model_cache_pvc and not self.model:
            raise SpecError("model_cache_pvc without a model to cache")
        if self.model and not self.model.startswith(("/", ".")):
            # the renderer classifies by prefix: "/..." or "./..." is a
            # pre-staged path; everything else must be a strict org/name
            # HF repo id (^[\w.-]+/[\w.-]+$ — one slash, no spaces or
            # empty components, ASCII only). A bare relative dir like
            # "models/llama" has valid repo-id SHAPE, but "models" /
            # "datasets" / "spaces" are reserved hub ROUTES that can
            # never be org names — exactly the classic weights-dir
            # spellings, rejected deterministically (no filesystem
            # probing: validation must give one answer on every
            # machine). Both mistakes would render a crash-looping
            # hub-fetch initContainer; the fix is "./models/llama".
            org = self.model.split("/", 1)[0].lower()
            if not re.fullmatch(
                r"[\w.-]+/[\w.-]+", self.model, re.ASCII
            ) or org in ("models", "datasets", "spaces"):
                raise SpecError(
                    f"model {self.model!r} must be an org/name HF repo id "
                    r"(^[\w.-]+/[\w.-]+$, org not a reserved dir name), "
                    "or a path starting with '/' or './'"
                )
        self.resources.validate()
        self.autoscaling.validate()


@dataclass
class DynamoDeployment:
    """The graph deployment (ref dynamodeployment_types.go:28)."""

    name: str
    namespace: str = "default"
    image: str = "dynamo-tpu:latest"
    hub_port: int = 18500
    services: list[ServiceDeploymentSpec] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)

    def validate(self) -> None:
        if not self.name:
            raise SpecError("deployment needs a name")
        seen = set()
        for svc in self.services:
            svc.validate()
            if svc.name in seen:
                raise SpecError(f"duplicate service {svc.name!r}")
            seen.add(svc.name)
        if not self.services:
            raise SpecError("deployment has no services")

    # ---- dict/JSON round-trip (api-server wire format) ----

    def to_dict(self) -> dict:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "DynamoDeployment":
        services = [
            ServiceDeploymentSpec(
                name=s["name"],
                command=list(s.get("command", [])),
                replicas=s.get("replicas", 1),
                resources=Resources(**s.get("resources", {})),
                autoscaling=Autoscaling(**s.get("autoscaling", {})),
                env=dict(s.get("env", {})),
                http_port=s.get("http_port", 0),
                ingress_host=s.get("ingress_host", ""),
                num_nodes=s.get("num_nodes", 1),
                hosts=list(s.get("hosts", [])),
                coordinator_port=s.get("coordinator_port", 9900),
                model=s.get("model", ""),
                model_cache_pvc=s.get("model_cache_pvc", ""),
            )
            for s in d.get("services", [])
        ]
        return DynamoDeployment(
            name=d["name"],
            namespace=d.get("namespace", "default"),
            image=d.get("image", "dynamo-tpu:latest"),
            hub_port=d.get("hub_port", 18500),
            services=services,
            labels=dict(d.get("labels", {})),
        )

from .builder import main

main()

"""Graph artifact builder + deploy CLI ("bentos" equivalent).

Re-design of the reference's ``dynamo build`` packaging (deploy/dynamo/sdk
cli/{bentos,deploy}.py, BentoML-derived): resolve the service graph,
package source + config + a build manifest into a tar.gz artifact, and
push specs/artifacts to the api-server.

  python -m dynamo_tpu.deploy build  examples.sdk_pipeline:Frontend -o graph.tar.gz
  python -m dynamo_tpu.deploy deploy spec.json   --api http://host:7700
  python -m dynamo_tpu.deploy manifests spec.json > k8s.yaml
"""

from __future__ import annotations

import importlib
import io
import json
import os
import tarfile
import time
from typing import Optional

MANIFEST_NAME = "dynamo_manifest.json"


def _resolve(graph: str):
    mod_name, _, leaf_name = graph.partition(":")
    mod = importlib.import_module(mod_name)
    leaf = getattr(mod, leaf_name)
    from ..sdk.service import resolve_graph

    return mod, resolve_graph(leaf)


def build_artifact(
    graph: str,
    out_path: str,
    config: Optional[dict] = None,
    created_ts: Optional[float] = None,
) -> dict:
    """Package the graph's source module + manifest into ``out_path``.

    The manifest records the graph entry, its resolved services (name,
    namespace, endpoints), and the per-service config — everything the
    serving CLI needs to run the artifact on a fresh host."""
    mod, specs = _resolve(graph)
    manifest = {
        "graph": graph,
        "created": created_ts if created_ts is not None else time.time(),
        "services": [
            {
                "name": s.name,
                "namespace": s.namespace,
                "endpoints": sorted(s.endpoints),
            }
            for s in specs
        ],
        "config": config or {},
    }
    src_file = getattr(mod, "__file__", None)
    with tarfile.open(out_path, "w:gz") as tar:
        data = json.dumps(manifest, indent=2).encode()
        info = tarfile.TarInfo(MANIFEST_NAME)
        info.size = len(data)
        tar.addfile(info, io.BytesIO(data))
        if src_file and os.path.exists(src_file):
            tar.add(src_file, arcname=f"src/{os.path.basename(src_file)}")
    return manifest


def read_artifact(path: str) -> dict:
    """Load the build manifest from an artifact."""
    with tarfile.open(path, "r:gz") as tar:
        f = tar.extractfile(MANIFEST_NAME)
        if f is None:
            raise ValueError(f"{path} has no {MANIFEST_NAME}")
        return json.load(f)


# ---------------- CLI ----------------


def _http_json(method: str, url: str, body: Optional[bytes] = None) -> dict:
    import urllib.request

    req = urllib.request.Request(url, data=body, method=method)
    req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser("dynamo-deploy", description=__doc__)
    sub = p.add_subparsers(dest="verb", required=True)

    b = sub.add_parser("build", help="package a graph into an artifact")
    b.add_argument("graph", help="pkg.module:LeafService")
    b.add_argument("-o", "--out", default="graph.tar.gz")
    b.add_argument("-f", "--file", default=None, help="config yaml/json")

    d = sub.add_parser("deploy", help="push a deployment spec to the api-server")
    d.add_argument("spec", help="deployment spec json file")
    d.add_argument("--api", default="http://127.0.0.1:7700")

    m = sub.add_parser("manifests", help="render k8s manifests for a spec")
    m.add_argument("spec", help="deployment spec json file")

    pl = sub.add_parser(
        "render-platform",
        help="render the whole control plane (hub + api-server + "
             "reconciler + frontend + metrics) as one applyable set",
    )
    pl.add_argument("--name", default="dynamo")
    pl.add_argument("--namespace", default="default")
    pl.add_argument("--image", default="dynamo-tpu:latest")
    pl.add_argument("--ingress-host", default="")
    pl.add_argument("--store-pvc", default="",
                    help="PVC for the durable control store ('' = emptyDir)")
    pl.add_argument("--hub-pvc", default="",
                    help="PVC for the hub's snapshot+WAL (separate claim: "
                         "RWO volumes cannot attach to two pods)")
    pl.add_argument("--no-metrics", action="store_true")

    args = p.parse_args(argv)
    if args.verb == "build":
        config = None
        if args.file:
            from ..sdk.cli import _load_config

            config = _load_config(args.file)
        manifest = build_artifact(args.graph, args.out, config=config)
        print(f"built {args.out}: {len(manifest['services'])} services "
              f"({', '.join(s['name'] for s in manifest['services'])})")
    elif args.verb == "deploy":
        with open(args.spec, "rb") as f:
            body = f.read()
        name = json.loads(body)["name"]
        try:
            out = _http_json("POST", f"{args.api}/api/v1/deployments", body)
        except Exception:
            out = _http_json("PUT", f"{args.api}/api/v1/deployments/{name}", body)
        print(f"deployed {out['name']}: services "
              f"{[s['name'] for s in out['services']]}")
    elif args.verb == "manifests":
        from .crd import DynamoDeployment
        from .manifests import render_manifests, to_yaml

        with open(args.spec) as f:
            dep = DynamoDeployment.from_dict(json.load(f))
        print(to_yaml(render_manifests(dep)))
    elif args.verb == "render-platform":
        from .manifests import to_yaml
        from .platform import render_platform

        print(to_yaml(render_platform(
            args.name, args.namespace, args.image,
            ingress_host=args.ingress_host, store_pvc=args.store_pvc,
            hub_pvc=args.hub_pvc,
            with_metrics=not args.no_metrics,
        )))


if __name__ == "__main__":
    main()

"""In-process span recorder: monotonic clocks, ring buffer, near-zero
cost when disabled.

Every instrumentation point in the stack calls the module-level
:func:`span` / :func:`event` helpers. When tracing is off (the default)
those return a shared no-op context manager after ONE attribute check —
no allocation, no clock read — so the decode loop pays nothing for the
instrumentation being present.

When enabled, finished spans land in a bounded ring buffer and are
optionally handed to a *sink* (the bus exporter in worker processes, the
collector directly in single-process setups). Durations come from
``time.perf_counter`` (monotonic, high-resolution); the wall-clock
``ts`` anchors spans from different processes onto one timeline.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)

from .context import TraceContext, current_trace

# span dict keys (the wire/shape contract, see docs/tracing.md):
#   name, trace_id, span_id, parent_id, service, ts (wall s), dur_ms, attrs


class _SpanHandle:
    """One open span; ``__exit__`` / ``end()`` records it."""

    __slots__ = ("recorder", "name", "trace", "attrs", "ts", "_t0", "_done")

    def __init__(self, recorder: "SpanRecorder", name: str, trace: TraceContext, attrs: dict):
        self.recorder = recorder
        self.name = name
        self.trace = trace
        self.attrs = attrs
        self.ts = time.time()
        self._t0 = time.perf_counter()
        self._done = False

    def set(self, **attrs: Any) -> "_SpanHandle":
        self.attrs.update(attrs)
        return self

    def end(self) -> None:
        if self._done:
            return
        self._done = True
        self.recorder._record(
            self.name, self.trace, self.ts,
            (time.perf_counter() - self._t0) * 1e3, self.attrs,
        )

    def __enter__(self) -> "_SpanHandle":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self.end()
        return False


class _NullSpan:
    """Shared do-nothing span for the disabled path."""

    __slots__ = ()

    def set(self, **attrs: Any) -> "_NullSpan":
        return self

    def end(self) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *args) -> bool:
        return False


NULL_SPAN = _NullSpan()


class SpanRecorder:
    """Process-wide recorder. ``enabled`` gates everything."""

    def __init__(self, maxlen: int = 4096):
        self.enabled = False
        self.service = "proc"
        self._ring: deque = deque(maxlen=maxlen)
        self._sink: Optional[Callable[[dict], None]] = None
        self._lock = threading.Lock()  # spans land from executor threads too

    def configure(
        self,
        enabled: bool = True,
        service: Optional[str] = None,
        sink: Optional[Callable[[dict], None]] = None,
        maxlen: Optional[int] = None,
    ) -> "SpanRecorder":
        self.enabled = enabled
        if service is not None:
            self.service = service
        if sink is not None or not enabled:
            self._sink = sink
        if maxlen is not None:
            with self._lock:
                self._ring = deque(self._ring, maxlen=maxlen)
        return self

    # ---- recording ----
    def span(self, name: str, trace: Optional[TraceContext] = None, **attrs: Any):
        """Open a span (context manager or ``.end()`` by hand). Records
        only when enabled AND a trace is in scope — spans are always
        request-scoped."""
        if not self.enabled:
            return NULL_SPAN
        tc = trace or current_trace()
        if tc is None:
            return NULL_SPAN
        return _SpanHandle(self, name, tc.child(), attrs)

    def event(self, name: str, trace: Optional[TraceContext] = None, **attrs: Any) -> None:
        """Instant (zero-duration) span."""
        if not self.enabled:
            return
        tc = trace or current_trace()
        if tc is None:
            return
        self._record(name, tc.child(), time.time(), 0.0, attrs)

    def record_span(
        self,
        name: str,
        trace: TraceContext,
        ts: float,
        dur_ms: float,
        **attrs: Any,
    ) -> None:
        """Record a span whose start/duration were measured elsewhere
        (e.g. queue wait reconstructed at admission time)."""
        if not self.enabled:
            return
        self._record(name, trace.child(), ts, dur_ms, attrs)

    def _record(self, name, trace: TraceContext, ts, dur_ms, attrs) -> None:
        rec = {
            "name": name,
            "trace_id": trace.trace_id,
            "span_id": trace.span_id,
            "parent_id": trace.parent_id,
            "service": self.service,
            "ts": ts,
            "dur_ms": round(dur_ms, 3),
            "attrs": attrs,
        }
        with self._lock:
            self._ring.append(rec)
        sink = self._sink
        if sink is not None:
            try:
                sink(rec)
            except Exception:  # noqa: BLE001 — tracing must never fail a request
                logger.debug("span sink failed", exc_info=True)

    # ---- inspection ----
    def spans(self, trace_id: Optional[str] = None) -> list[dict]:
        with self._lock:
            out = list(self._ring)
        if trace_id is not None:
            out = [s for s in out if s["trace_id"] == trace_id]
        return out

    def drain(self) -> list[dict]:
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


#: the process-wide recorder every instrumentation point uses
RECORDER = SpanRecorder()


def configure(**kwargs: Any) -> SpanRecorder:
    return RECORDER.configure(**kwargs)


def enabled() -> bool:
    return RECORDER.enabled


def span(name: str, trace: Optional[TraceContext] = None, **attrs: Any):
    return RECORDER.span(name, trace, **attrs)


def event(name: str, trace: Optional[TraceContext] = None, **attrs: Any) -> None:
    RECORDER.event(name, trace, **attrs)

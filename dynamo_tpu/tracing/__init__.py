"""Distributed request tracing: end-to-end span propagation with
per-request TTFT decomposition.

A request crosses frontend -> router -> prefill queue -> decode worker
-> offload tier; this package gives it one trace id at ingress
(W3C-traceparent compatible, client-supplied ``traceparent`` honored),
carries it across every hop (contextvars in-process, the bus
RequestEnvelope / disagg handoff / TCP prologue across processes),
records spans in a near-zero-cost ring buffer, and assembles them into
per-request timelines with a canonical TTFT decomposition
(tokenize / route / queue wait / KV-transfer exposed-vs-hidden /
prefill / first decode). See docs/tracing.md.
"""

from .context import (
    TRACE_ANNOTATION,
    TRACEPARENT_HEADER,
    TraceContext,
    current_trace,
    current_traceparent,
    extract,
    inject,
    reset_trace,
    set_trace,
    use_trace,
)
from .collector import (
    TRACE_EVENTS_SUBJECT,
    TRACE_EVENTS_WILDCARD,
    BusExporter,
    TraceCollector,
    percentile,
)
from .span import (
    NULL_SPAN,
    RECORDER,
    SpanRecorder,
    configure,
    enabled,
    event,
    span,
)
from .ttft import COMPONENTS, decompose, measured_ttft_ms

__all__ = [
    "BusExporter",
    "COMPONENTS",
    "NULL_SPAN",
    "RECORDER",
    "SpanRecorder",
    "TRACE_ANNOTATION",
    "TRACEPARENT_HEADER",
    "TRACE_EVENTS_SUBJECT",
    "TRACE_EVENTS_WILDCARD",
    "TraceCollector",
    "TraceContext",
    "configure",
    "current_trace",
    "current_traceparent",
    "decompose",
    "enabled",
    "event",
    "extract",
    "inject",
    "measured_ttft_ms",
    "percentile",
    "reset_trace",
    "set_trace",
    "span",
    "use_trace",
]

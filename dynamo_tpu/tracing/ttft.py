"""Canonical per-request TTFT decomposition from a trace's spans.

TTFT (frontend receipt -> first generated token on the wire) decomposes
into the canonical components every perf argument in this repo should be
made with (see docs/tracing.md for the definitions):

    tokenize             chat-template render + tokenization
    route                KV-router scheduling decision
    queue_wait           engine admission wait + disagg prefill-queue wait
    kv_transfer_exposed  restore/transfer latency actually paid on TTFT
    prefill              prompt compute (local chunks or remote prefill)
    first_decode         the remainder: first-token sampling, stream
                         transport, scheduling gaps

``kv_transfer_hidden`` is reported alongside (PR 1's restore-latency
accounting plus the streamed disagg handoff's behind-prefill transfer
time: transfer overlapped behind scheduling/compute) but is NOT part of
the sum — hidden latency, by definition, cost the request nothing.

The components are measured leaf spans; ``first_decode`` is defined as
the un-attributed remainder, so the decomposition sums to the measured
TTFT exactly whenever the leaf spans nest cleanly inside it (the
acceptance bound is 5% to absorb cross-process clock skew).
"""

from __future__ import annotations

from typing import Optional

#: span names making up the request timeline (the instrumentation contract)
SPAN_REQUEST = "frontend.request"
EVENT_FIRST_TOKEN = "frontend.first_token"
SPAN_TOKENIZE = "tokenize"
SPAN_ROUTE = "router.schedule"
SPAN_QUEUE_WAIT = "engine.queue_wait"
SPAN_KV_RESTORE = "engine.kv_restore"
SPAN_PREFILL = "engine.prefill"
EVENT_ENGINE_FIRST_TOKEN = "engine.first_token"
SPAN_WORKER_HANDLE = "worker.handle"
SPAN_DISAGG_REMOTE = "disagg.remote_prefill"
SPAN_PREFILL_QUEUE_WAIT = "prefill.queue_wait"
SPAN_PREFILL_COMPUTE = "prefill.compute"
SPAN_PREFILL_KV_SEND = "prefill.kv_send"

#: decomposition keys, in canonical order (these sum to ttft_ms)
COMPONENTS = (
    "tokenize",
    "route",
    "queue_wait",
    "kv_transfer_exposed",
    "prefill",
    "first_decode",
)


def cost_observations(spans: list[dict]) -> list[tuple[str, int, float]]:
    """Transfer-cost observations carried by one trace's spans, as
    ``(link_class, nbytes, wall_ms)`` tuples — the bridge between the
    PR 2 decomposition (which already measures every transfer term per
    request) and the self-calibrating cost model (kv_router/costmodel):
    a collector can replay a trace's transfer activity into a
    ``TransferCostModel`` exactly as the worker observed it live.

    Sources: ``prefill.kv_send`` spans stamp ``link``/``nbytes`` (dcn
    for cross-host streamed sends, ici for the same-slice device path,
    local for the un-negotiated in-process pipe) with the measured send
    activity (hidden + exposed); kv_restore spans that stamp ``nbytes``
    count as the host class. Spans without a byte count are skipped —
    an observation without volume can't inform a bandwidth estimate."""
    out: list[tuple[str, int, float]] = []
    for s in spans:
        attrs = s.get("attrs", {}) or {}
        nbytes = int(attrs.get("nbytes", 0) or 0)
        if not nbytes:
            continue
        if s["name"] == SPAN_PREFILL_KV_SEND:
            wall = float(attrs.get("hidden_ms", 0.0) or 0.0) + float(
                attrs.get("exposed_ms", 0.0) or 0.0
            )
            link = str(attrs.get("link") or "dcn")
            if wall > 0:
                out.append((link, nbytes, wall))
        elif s["name"] == SPAN_KV_RESTORE:
            wall = float(attrs.get("hidden_ms", 0.0) or 0.0) + float(
                attrs.get("exposed_ms", 0.0) or 0.0
            )
            if wall > 0:
                out.append(("host", nbytes, wall))
    return out


def _sum_dur(spans: list[dict], name: str) -> float:
    return sum(s["dur_ms"] for s in spans if s["name"] == name)


def _sum_attr(spans: list[dict], name: str, attr: str) -> float:
    return sum(
        float(s.get("attrs", {}).get(attr, 0.0) or 0.0)
        for s in spans
        if s["name"] == name
    )


def measured_ttft_ms(spans: list[dict]) -> Optional[float]:
    """First-token wall time minus request receipt, from the frontend's
    own clock when it recorded both; falls back to the engine's
    first-token event against the request span (cross-process wall
    clocks — same host in every supported deployment shape)."""
    req = next((s for s in spans if s["name"] == SPAN_REQUEST), None)
    first = next(
        (s for s in spans if s["name"] == EVENT_FIRST_TOKEN), None
    ) or next((s for s in spans if s["name"] == EVENT_ENGINE_FIRST_TOKEN), None)
    if req is None or first is None:
        return None
    return max((first["ts"] - req["ts"]) * 1e3, 0.0)


def decompose(spans: list[dict]) -> Optional[dict]:
    """-> {"ttft_ms", components..., "kv_transfer_hidden"} or None when
    the trace lacks the request/first-token anchors."""
    ttft = measured_ttft_ms(spans)
    if ttft is None:
        return None
    tokenize = _sum_dur(spans, SPAN_TOKENIZE)
    route = _sum_dur(spans, SPAN_ROUTE)
    queue_wait = _sum_dur(spans, SPAN_QUEUE_WAIT) + _sum_dur(
        spans, SPAN_PREFILL_QUEUE_WAIT
    )
    kv_exposed = _sum_attr(spans, SPAN_KV_RESTORE, "exposed_ms")
    # hidden: PR 1's restore overlap + the streamed disagg handoff's
    # transfer activity overlapped behind prefill compute (the sender
    # stamps exposed/hidden on prefill.kv_send; its exposed tail is
    # already part of the decode side's remote-wait remainder below, so
    # only hidden folds in here — exposed would double-count)
    kv_hidden = _sum_attr(spans, SPAN_KV_RESTORE, "hidden_ms") + _sum_attr(
        spans, SPAN_PREFILL_KV_SEND, "hidden_ms"
    )
    prefill = _sum_dur(spans, SPAN_PREFILL) + _sum_dur(spans, SPAN_PREFILL_COMPUTE)
    # the BULK handoff's whole-stack d2h gather inside the disagg
    # prefill worker's compute span is pure HANDOFF work (nothing
    # overlaps it) — count it as kv_transfer. The streamed path's
    # per-segment gathers overlap the wire transfer of already-shipped
    # segments, so they stay inside prefill (seg_gather_ms attr)
    kv_gather = _sum_attr(spans, SPAN_PREFILL_COMPUTE, "kv_gather_ms")
    # the engine's kv-restore wait and the extraction gathers happen
    # INSIDE the prefill region (offload preamble of the first chunk /
    # the remote extract), so the prefill spans contain them — carve
    # them out so the components stay disjoint and the sum honest
    prefill = max(prefill - kv_exposed - kv_gather, 0.0)
    kv_exposed += kv_gather
    # remote prefill: the decode side's wait covers queue wait + compute +
    # transfer; what it paid beyond the accounted parts is KV transfer
    remote_wait = _sum_dur(spans, SPAN_DISAGG_REMOTE)
    if remote_wait:
        kv_exposed += max(
            remote_wait
            - _sum_dur(spans, SPAN_PREFILL_QUEUE_WAIT)
            - _sum_dur(spans, SPAN_PREFILL_COMPUTE),
            0.0,
        )
    attributed = tokenize + route + queue_wait + kv_exposed + prefill
    out = {
        "ttft_ms": round(ttft, 3),
        "tokenize": round(tokenize, 3),
        "route": round(route, 3),
        "queue_wait": round(queue_wait, 3),
        "kv_transfer_exposed": round(kv_exposed, 3),
        "prefill": round(prefill, 3),
        "first_decode": round(max(ttft - attributed, 0.0), 3),
        # informational, not part of the sum
        "kv_transfer_hidden": round(kv_hidden, 3),
    }
    return out

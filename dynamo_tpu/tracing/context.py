"""W3C-traceparent-style trace context, contextvar-propagated.

One request gets one ``TraceContext`` at the first ingress it crosses
(the HTTP frontend, or a worker endpoint for dyn:// callers that sent
none). The context is:

  * **header-encoded** as a ``traceparent`` string
    (``00-{trace_id:32x}-{span_id:16x}-{flags:02x}``, the W3C Trace
    Context wire form) so it can ride HTTP headers, the bus
    RequestEnvelope, the TCP response-plane prologue, and the disagg
    remote-prefill handoff without any of those layers knowing more
    than "an opaque string",
  * **contextvar-propagated** inside a process, so pipeline stages
    (preprocessor -> router -> client egress) pick it up without
    threading an argument through every ``generate`` signature.

When the caller supplied a traceparent we honor its ``trace_id`` (their
logs correlate with our spans); otherwise the trace id is derived from
the request id when that is already a 32-hex uuid, so ``/trace/{id}``
lookups need no extra mapping.
"""

from __future__ import annotations

import contextlib
import contextvars
import re
import uuid
from dataclasses import dataclass, field, replace
from typing import Iterator, Optional

TRACEPARENT_HEADER = "traceparent"
# key under which the traceparent rides request annotations / envelopes
TRACE_ANNOTATION = "traceparent"

_TRACEPARENT_RE = re.compile(
    r"^(?P<version>[0-9a-f]{2})-(?P<trace_id>[0-9a-f]{32})"
    r"-(?P<span_id>[0-9a-f]{16})-(?P<flags>[0-9a-f]{2})$"
)
_HEX32_RE = re.compile(r"^[0-9a-f]{32}$")


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass(frozen=True)
class TraceContext:
    """Identity of one span within one request's trace."""

    trace_id: str
    span_id: str = field(default_factory=_new_span_id)
    parent_id: Optional[str] = None
    sampled: bool = True

    # ---- construction ----
    @staticmethod
    def new(trace_id: Optional[str] = None) -> "TraceContext":
        return TraceContext(trace_id=trace_id or uuid.uuid4().hex)

    @staticmethod
    def for_request(
        request_id: Optional[str], traceparent: Optional[str] = None
    ) -> "TraceContext":
        """Root context at an ingress: continue the caller's trace when a
        valid ``traceparent`` came in (their span becomes our parent),
        else root a new trace — reusing a 32-hex request id as the trace
        id so request-id lookups are trace-id lookups."""
        if traceparent:
            parsed = TraceContext.from_traceparent(traceparent)
            if parsed is not None:
                return parsed.child()
        rid = (request_id or "").lower()
        if _HEX32_RE.match(rid):
            return TraceContext(trace_id=rid)
        return TraceContext.new()

    def child(self) -> "TraceContext":
        """A new span under this one (same trace)."""
        return replace(self, span_id=_new_span_id(), parent_id=self.span_id)

    # ---- wire form ----
    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.sampled else '00'}"

    @staticmethod
    def from_traceparent(header: Optional[str]) -> Optional["TraceContext"]:
        """Parse a traceparent; returns None on anything malformed (a bad
        header must never fail the request) or on the all-zero ids the
        spec reserves as invalid. Unknown versions parse leniently —
        forward compatibility per the W3C spec."""
        if not header or not isinstance(header, str):
            return None
        m = _TRACEPARENT_RE.match(header.strip().lower())
        if m is None:
            return None
        trace_id, span_id = m.group("trace_id"), m.group("span_id")
        if m.group("version") == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        sampled = bool(int(m.group("flags"), 16) & 0x01)
        return TraceContext(trace_id=trace_id, span_id=span_id, sampled=sampled)


# ---------------- contextvar propagation ----------------

_current: contextvars.ContextVar[Optional[TraceContext]] = contextvars.ContextVar(
    "dynamo_tpu_trace", default=None
)


def current_trace() -> Optional[TraceContext]:
    return _current.get()


def current_traceparent() -> Optional[str]:
    tc = _current.get()
    return tc.to_traceparent() if tc is not None else None


def set_trace(tc: Optional[TraceContext]) -> contextvars.Token:
    return _current.set(tc)


def reset_trace(token: contextvars.Token) -> None:
    _current.reset(token)


@contextlib.contextmanager
def use_trace(tc: Optional[TraceContext]) -> Iterator[Optional[TraceContext]]:
    token = _current.set(tc)
    try:
        yield tc
    finally:
        _current.reset(token)


# ---------------- annotation / envelope helpers ----------------

def inject(carrier: Optional[dict], tc: Optional[TraceContext] = None) -> Optional[dict]:
    """Write the (current) trace into a dict carrier (request annotations,
    an envelope header). Returns the carrier for chaining; no-op without
    an active trace."""
    tc = tc or _current.get()
    if tc is None or carrier is None:
        return carrier
    carrier[TRACE_ANNOTATION] = tc.to_traceparent()
    return carrier


def extract(carrier: Optional[dict]) -> Optional[TraceContext]:
    """Read a trace out of a dict carrier; None when absent/malformed."""
    if not carrier:
        return None
    return TraceContext.from_traceparent(carrier.get(TRACE_ANNOTATION))

"""Trace collector: subscribe ``trace-events``, assemble per-request
timelines, export Chrome-trace JSON + TTFT decompositions.

Workers and frontends export finished spans onto the bus (one
``trace-events`` subject per component, :class:`BusExporter`); the
collector subscribes — with a wildcard when it isn't pinned to one
component — and keeps a bounded LRU of assembled traces. Lookups accept
either a trace id or a request id (spans carry ``request_id`` as an
attribute wherever the ingress knew it).

Exports:
  * ``timeline(id)``        — spans sorted by wall-clock start,
  * ``ttft(id)``            — the canonical decomposition (tracing.ttft),
  * ``chrome_trace(id)``    — Chrome trace-event JSON (load it in
    ``chrome://tracing`` / Perfetto),
  * ``percentiles()``       — aggregate p50/p95/p99 per TTFT component,
    the feed for the metrics plane and bench artifacts.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
from collections import OrderedDict, deque
from typing import Optional

from . import ttft as ttft_mod

logger = logging.getLogger(__name__)

TRACE_EVENTS_SUBJECT = "trace-events"
#: subscribe-all pattern for collectors not pinned to one component
TRACE_EVENTS_WILDCARD = "*.*." + TRACE_EVENTS_SUBJECT


def percentile(values: list[float], p: float) -> float:
    """Nearest-rank percentile (p in [0,100]) on a small sample."""
    if not values:
        return 0.0
    xs = sorted(values)
    k = min(len(xs) - 1, max(0, int(round(p / 100.0 * (len(xs) - 1)))))
    return xs[k]


class TraceCollector:
    """Assembles spans into per-request timelines. Works standalone
    (feed :meth:`ingest` directly, e.g. as a recorder sink) or
    subscribed to a distributed runtime's bus via :meth:`start`."""

    def __init__(self, drt=None, component=None, max_traces: int = 1024,
                 max_samples: int = 2048):
        self.drt = drt
        self.component = component
        self.max_traces = max_traces
        self._traces: OrderedDict[str, list[dict]] = OrderedDict()
        self._aliases: OrderedDict[str, str] = OrderedDict()  # request_id -> trace_id
        # aggregate TTFT component samples (ms), bounded
        self._samples: dict[str, deque] = {}
        self._max_samples = max_samples
        self._decomposed: set[str] = set()
        self._lock = threading.Lock()
        self._sub = None
        self._task = None
        self.spans_total = 0

    # ---- bus plumbing ----
    @property
    def subject(self) -> str:
        if self.component is not None:
            return self.component.event_subject(TRACE_EVENTS_SUBJECT)
        return TRACE_EVENTS_WILDCARD

    async def start(self) -> "TraceCollector":
        assert self.drt is not None, "start() needs a DistributedRuntime"
        sub = self.drt.bus.subscribe(self.subject)
        ready = getattr(sub, "ready", None)
        if ready is not None:
            await ready
        self._sub = sub
        self._task = self.drt.runtime.spawn(self._consume(sub))
        return self

    async def close(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()
            self._sub = None
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _consume(self, sub) -> None:
        async for msg in sub:
            try:
                payload = json.loads(msg.payload)
                self.ingest(payload)
            except Exception:  # noqa: BLE001 — a bad batch must not kill the loop
                logger.exception("bad trace-events payload")

    # ---- ingestion ----
    def ingest(self, spans) -> None:
        """Accept one span dict or a batch list of them."""
        if isinstance(spans, dict):
            spans = [spans]
        with self._lock:
            for s in spans:
                tid = s.get("trace_id")
                if not tid:
                    continue
                bucket = self._traces.get(tid)
                if bucket is None:
                    bucket = self._traces[tid] = []
                    while len(self._traces) > self.max_traces:
                        old, _ = self._traces.popitem(last=False)
                        self._decomposed.discard(old)
                else:
                    self._traces.move_to_end(tid)
                    # dedupe by span id: a frontend collector subscribed
                    # to the wildcard also hears the frontend's OWN
                    # bus-exported batches — the same span must not
                    # enter the timeline (and the decomposition) twice
                    sid = s.get("span_id")
                    if sid is not None and any(
                        b.get("span_id") == sid for b in bucket
                    ):
                        continue
                bucket.append(s)
                self.spans_total += 1
                rid = (s.get("attrs") or {}).get("request_id")
                if rid:
                    self._aliases[rid] = tid
                    while len(self._aliases) > self.max_traces:
                        self._aliases.popitem(last=False)
            # fold finished timelines into the aggregate percentiles: a
            # trace is decomposable once BOTH anchors (request receipt +
            # first token) arrived — try on either anchor landing, since
            # the request span closes after the stream ends and batches
            # can deliver the two in any order
            for s in spans:
                tid = s.get("trace_id")
                if (
                    tid
                    and tid not in self._decomposed
                    and s.get("name") in (
                        ttft_mod.EVENT_FIRST_TOKEN,
                        ttft_mod.EVENT_ENGINE_FIRST_TOKEN,
                        ttft_mod.SPAN_REQUEST,
                    )
                ):
                    d = ttft_mod.decompose(self._traces.get(tid, []))
                    if d is not None:
                        self._decomposed.add(tid)
                        for k, v in d.items():
                            q = self._samples.get(k)
                            if q is None:
                                q = self._samples[k] = deque(
                                    maxlen=self._max_samples
                                )
                            q.append(v)

    # ---- lookup ----
    def resolve(self, id_: str) -> Optional[str]:
        with self._lock:
            if id_ in self._traces:
                return id_
            tid = self._aliases.get(id_)
            # an alias can outlive its LRU-evicted trace: answering with
            # the stale tid would fabricate an empty timeline downstream
            return tid if tid in self._traces else None

    def timeline(self, id_: str) -> Optional[list[dict]]:
        tid = self.resolve(id_)
        if tid is None:
            return None
        with self._lock:
            spans = list(self._traces.get(tid, []))
        return sorted(spans, key=lambda s: (s["ts"], -s["dur_ms"]))

    def ttft(self, id_: str) -> Optional[dict]:
        spans = self.timeline(id_)
        if spans is None:
            return None
        return ttft_mod.decompose(spans)

    def trace_ids(self) -> list[str]:
        with self._lock:
            return list(self._traces)

    # ---- exports ----
    def chrome_trace(self, id_: str) -> Optional[dict]:
        """Chrome trace-event JSON: complete ("X") events per span,
        instant ("i") events for zero-duration spans, one pid per
        service so frontend/router/worker/prefill rows separate."""
        spans = self.timeline(id_)
        if spans is None:
            return None
        events = []
        for s in spans:
            ev = {
                "name": s["name"],
                "cat": s.get("service", "proc"),
                "ts": s["ts"] * 1e6,  # wall seconds -> microseconds
                "pid": s.get("service", "proc"),
                "tid": s["trace_id"][:8],
                "args": dict(s.get("attrs") or {}),
            }
            if s["dur_ms"] > 0:
                ev["ph"] = "X"
                ev["dur"] = s["dur_ms"] * 1e3
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def render_trace(self, id_: str, fmt: str = "timeline") -> Optional[dict]:
        """The ``/trace/{id}`` response body."""
        tid = self.resolve(id_)
        if tid is None:
            return None
        if fmt == "chrome":
            return self.chrome_trace(tid)
        return {
            "trace_id": tid,
            "spans": self.timeline(tid),
            "ttft": self.ttft(tid),
        }

    # ---- aggregates ----
    def percentiles(self, ps=(50, 95, 99)) -> dict:
        """{component: {"p50": ms, ...}} across collected traces."""
        with self._lock:
            samples = {k: list(q) for k, q in self._samples.items()}
        return {
            k: {f"p{int(p)}": round(percentile(v, p), 3) for p in ps}
            for k, v in samples.items()
            if v
        }


class BusExporter:
    """Recorder sink publishing span batches onto the bus.

    Spans land from the event loop AND from executor threads (engine
    device work), so the sink buffers under a lock and flushes at most
    once per loop tick — one small publish per tick, never one per span.
    Best-effort: export failures are dropped, never surfaced to the
    request path."""

    def __init__(self, bus, subject: str, max_batch: int = 512):
        self.bus = bus
        self.subject = subject
        self.max_batch = max_batch
        self._buf: list[dict] = []
        self._lock = threading.Lock()
        self._flush_scheduled = False
        self._loop = asyncio.get_event_loop()

    def __call__(self, span: dict) -> None:
        with self._lock:
            self._buf.append(span)
            if len(self._buf) > self.max_batch:
                del self._buf[: -self.max_batch]
            if self._flush_scheduled:
                return
            self._flush_scheduled = True
        try:
            self._loop.call_soon_threadsafe(self._flush)
        except RuntimeError:  # loop closed: drop silently
            with self._lock:
                self._flush_scheduled = False

    def _flush(self) -> None:
        with self._lock:
            batch, self._buf = self._buf, []
            self._flush_scheduled = False
        if not batch:
            return
        try:
            # dynflow: publishes=TRACE_EVENTS_SUBJECT (constructor-injected
            # subject — dynamo_run wires component.event_subject of it)
            res = self.bus.publish(self.subject, json.dumps(batch).encode())
            if hasattr(res, "__await__"):  # remote hub bus
                task = self._loop.create_task(res)
                task.add_done_callback(lambda t: t.exception())
        except Exception:  # noqa: BLE001
            logger.debug("trace export failed", exc_info=True)

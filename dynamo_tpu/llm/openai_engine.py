"""Worker-side OpenAI engine: raw request dicts -> local pipeline -> chunks.

The frontend's discovery layer routes raw OpenAI request dicts to worker
endpoints (see dynamo_tpu.http.discovery). This adapter is the worker-side
counterpart: parse the dict, run the local preprocessor -> detokenizer ->
core-engine pipeline, and yield OpenAI chunk dicts. It is the TPU
equivalent of the reference's StaticFull engine wiring
(launch/dynamo-run/src/lib.rs EngineConfig::StaticFull).
"""

from __future__ import annotations

from typing import AsyncIterator

from ..protocols.openai import ChatCompletionRequest, CompletionRequest, RequestError
from ..runtime.annotated import Annotated
from ..runtime.engine import AsyncEngine, Context
from ..runtime.pipeline import link
from .backend import Backend
from .preprocessor import OpenAIPreprocessor
from .tokenizer import Tokenizer


class OpenAIWorkerEngine(AsyncEngine):
    def __init__(self, tokenizer: Tokenizer, core_engine: AsyncEngine):
        self._core = core_engine
        # text-level engines (pystr) emit text directly — the detokenizer
        # stage would overwrite it from their (empty) token ids, so skip it
        if getattr(core_engine, "text_mode", False):
            self._pipeline = link(OpenAIPreprocessor(tokenizer), core_engine)
        else:
            self._pipeline = link(
                OpenAIPreprocessor(tokenizer), Backend(tokenizer), core_engine
            )

    async def generate(self, request: Context) -> AsyncIterator[Annotated]:
        data = request.data
        if isinstance(data, dict):
            if "token_ids" in data:
                # already preprocessed upstream (KV-routed frontend does
                # tokenization for prefix hashing) -> run the core engine
                async for item in self._core.generate(request):
                    if not isinstance(item, Annotated):
                        item = Annotated.from_data(item)
                    yield item
                return
            try:
                typed = (
                    ChatCompletionRequest.from_dict(data)
                    if "messages" in data
                    else CompletionRequest.from_dict(data)
                )
            except RequestError as e:
                yield Annotated.from_error(str(e))
                return
        else:
            typed = data
        async for item in self._pipeline.generate(request.transfer(typed)):
            yield item

"""LLM library layer: tokenization, preprocessing, detokenization, model
cards (re-design of the reference's lib/llm crate, minus engines which live
in dynamo_tpu.engine)."""

from .tokenizer import (
    ByteTokenizer,
    DecodeStream,
    HFTokenizer,
    SPTokenizer,
    Tokenizer,
    load_tokenizer,
)
from .model_card import ModelDeploymentCard

__all__ = [
    "ByteTokenizer",
    "DecodeStream",
    "HFTokenizer",
    "ModelDeploymentCard",
    "SPTokenizer",
    "Tokenizer",
    "load_tokenizer",
]

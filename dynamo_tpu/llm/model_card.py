"""Model Deployment Card (MDC): the metadata record for a served model.

Re-design of the reference's model card (lib/llm/src/model_card/model.rs:94
ModelDeploymentCard + create.rs): display/service name, tokenizer location,
prompt-template source, context length, KV block size — published to the
bus object store bucket "mdc" with a TTL that the owning worker refreshes
(ref model.rs:42-49, 5-minute TTL), so dead workers' cards age out.
"""

from __future__ import annotations

import asyncio
import json
import os
from dataclasses import asdict, dataclass, field
from typing import Optional

MDC_BUCKET = "mdc"
MDC_TTL_SECONDS = 300.0


@dataclass
class ModelDeploymentCard:
    display_name: str
    service_name: str
    model_path: str = ""
    tokenizer_kind: str = "hf"  # "hf" | "sp" (SentencePiece) | "byte"
    context_length: int = 8192
    kv_block_size: int = 16
    model_type: str = "chat"  # "chat" | "completion" | "both"
    # architecture hints for the native engine
    architecture: str = ""
    dtype: str = "bfloat16"
    extra: dict = field(default_factory=dict)

    def to_json(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @staticmethod
    def from_json(raw: bytes) -> "ModelDeploymentCard":
        d = json.loads(raw)
        known = {k: d[k] for k in d if k in ModelDeploymentCard.__dataclass_fields__}
        return ModelDeploymentCard(**known)

    @staticmethod
    def from_local_path(path: str, service_name: Optional[str] = None) -> "ModelDeploymentCard":
        """Build from a HF-style checkout (ref model_card/create.rs:185)."""
        name = service_name or os.path.basename(os.path.normpath(path))
        card = ModelDeploymentCard(
            display_name=name, service_name=name, model_path=path
        )
        cfg_path = os.path.join(path, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
            card.architecture = (cfg.get("architectures") or [""])[0]
            card.context_length = int(
                cfg.get("max_position_embeddings", card.context_length)
            )
            card.dtype = cfg.get("torch_dtype", card.dtype)
        # same file probe as llm.tokenizer.load_tokenizer (ref
        # model_card/create.rs picks hf vs sp the same way)
        if os.path.exists(os.path.join(path, "tokenizer.json")):
            card.tokenizer_kind = "hf"
        elif os.path.exists(os.path.join(path, "tokenizer.model")):
            card.tokenizer_kind = "sp"
        return card

    # ---- object-store publication ----
    async def publish(self, bus, refresh: bool = False):
        put = bus.object_put(
            MDC_BUCKET, self.service_name, self.to_json(), ttl=MDC_TTL_SECONDS
        )
        if asyncio.iscoroutine(put):
            await put

    @staticmethod
    async def load(bus, service_name: str) -> Optional["ModelDeploymentCard"]:
        got = bus.object_get(MDC_BUCKET, service_name)
        if asyncio.iscoroutine(got):
            got = await got
        return ModelDeploymentCard.from_json(got) if got else None


class MdcRefresher:
    """Keep a card alive in the object store while the worker lives."""

    def __init__(self, bus, card: ModelDeploymentCard, interval: float = MDC_TTL_SECONDS / 3):
        self._bus = bus
        self._card = card
        self._interval = interval
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        while True:
            await self._card.publish(self._bus, refresh=True)
            await asyncio.sleep(self._interval)

    def stop(self) -> None:
        if self._task:
            self._task.cancel()
            self._task = None

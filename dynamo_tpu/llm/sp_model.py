"""SentencePiece model support without the sentencepiece wheel.

Checkpoints that ship only ``tokenizer.model`` (no ``tokenizer.json``)
could not be served before this module existed (VERDICT r3 missing #5;
ref lib/llm/src/tokenizers/sp.rs:25 wraps the sentencepiece crate for
the same reason). The sentencepiece package is not in this image, so
this is a native implementation of the two pieces serving needs:

  * a minimal protobuf **wire-format** reader for ``ModelProto``
    (sentencepiece_model.proto) — pieces with scores/types, the model
    type (unigram/BPE), and the normalizer's whitespace options;
  * the two segmenters: **unigram** (Viterbi over piece log-probs — the
    same dynamic program sentencepiece runs) and **BPE** (iterated
    best-scoring adjacent merge), both with byte-fallback.

Scope: encoding/decoding for serving. Training and sampling-based
segmentation are out of scope (the reference's sp.rs exposes exactly
encode/decode too).

Normalization: at runtime sentencepiece normalizes through the
``precompiled_charsmap`` ALONE (the name only records which ruleset
was compiled), so the faithful gating is on the charsmap, not the
name: an EMPTY charsmap is identity regardless of name
(llama/mistral); a non-empty charsmap under one of the four standard
names ("nfkc"/"nmt_nfkc"/"nfkc_cf"/"nmt_nfkc_cf") gets that ruleset's
native implementation — Unicode NFKC via ``unicodedata``, the NMT
cleanup (controls dropped, the Unicode space zoo collapsed to ASCII
space, zero-widths deleted), casefold + default-ignorable removal for
the "_cf" forms; a non-empty charsmap under ANY other name — including
"identity", whose standard ruleset is empty — is custom user rules
this reader cannot honor, and it refuses loudly rather than serving
wrong tokenizations (VERDICT r4 weak #4; repo rule: reject over wrong
logits).

Wire-format field numbers (sentencepiece_model.proto):
  ModelProto: 1=pieces(repeated SentencePiece), 2=trainer_spec,
              3=normalizer_spec
  SentencePiece: 1=piece(string), 2=score(float), 3=type(enum)
  TrainerSpec: 3=model_type (1=UNIGRAM, 2=BPE, 3=WORD, 4=CHAR)
  NormalizerSpec: 1=name, 2=precompiled_charsmap(bytes),
                  3=add_dummy_prefix(bool),
                  4=remove_extra_whitespaces(bool), 5=escape_whitespaces
"""

from __future__ import annotations

import logging
import struct
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)

#: one-time flag for the accepted-charsmap caveat below — serving loads
#: tokenizers repeatedly (model cards, warmup, workers) and the caveat
#: is per-process, not per-load
_warned_charsmap = False

WS = "▁"  # ▁ — sentencepiece's escaped space

# SentencePiece.Type enum
NORMAL, UNKNOWN, CONTROL, USER_DEFINED, UNUSED, BYTE = 1, 2, 3, 4, 5, 6

UNIGRAM, BPE = 1, 2


# ---------------- protobuf wire reading ----------------


def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one message's bytes.
    value is raw bytes for length-delimited fields, int for varint,
    int (LE bits) for fixed32/64."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:  # varint
            val, i = _read_varint(buf, i)
        elif wtype == 1:  # fixed64
            val = int.from_bytes(buf[i : i + 8], "little")
            i += 8
        elif wtype == 2:  # length-delimited
            ln, i = _read_varint(buf, i)
            val = buf[i : i + ln]
            i += ln
        elif wtype == 5:  # fixed32
            val = int.from_bytes(buf[i : i + 4], "little")
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wtype} (field {fnum})")
        yield fnum, wtype, val


# ---------------- model ----------------


@dataclass
class Piece:
    text: str
    score: float
    type: int = NORMAL


#: names whose compiled charsmap the native ruleset implementations
#: reproduce ("identity" is deliberately absent: its standard ruleset
#: is empty, so an identity proto CARRYING a charsmap is custom rules)
KNOWN_NORMALIZERS = ("nfkc", "nmt_nfkc", "nfkc_cf", "nmt_nfkc_cf")


@dataclass
class SentencePieceModel:
    pieces: list[Piece]
    model_type: int = UNIGRAM
    add_dummy_prefix: bool = True
    remove_extra_whitespaces: bool = True
    escape_whitespaces: bool = True
    normalizer_name: str = "identity"
    has_charsmap: bool = False
    # derived
    _index: dict = field(default_factory=dict, repr=False)
    _byte_ids: dict = field(default_factory=dict, repr=False)
    _unk_id: int = 0
    _max_piece_chars: int = 1

    def __post_init__(self):
        if (self.has_charsmap
                and self.normalizer_name not in KNOWN_NORMALIZERS):
            raise ValueError(
                f"normalizer {self.normalizer_name!r} carries a custom "
                "precompiled_charsmap this reader cannot honor — refusing "
                "rather than tokenizing wrongly (install-free SP support "
                "covers the standard normalizers only: "
                f"{KNOWN_NORMALIZERS})"
            )
        if self.has_charsmap:
            # accepted: a standard-named non-empty charsmap is served by
            # this module's NATIVE ruleset (unicodedata NFKC + NMT
            # cleanup), not by walking the compiled charsmap itself —
            # the approximation can diverge from sentencepiece's
            # compiled Darts table on exotic codepoints. Say so once.
            global _warned_charsmap
            if not _warned_charsmap:
                _warned_charsmap = True
                logger.warning(
                    "sentencepiece model carries a non-empty "
                    "precompiled_charsmap (normalizer %r); serving with "
                    "the native %s approximation — normalization may "
                    "diverge from sentencepiece's compiled ruleset on "
                    "edge-case codepoints",
                    self.normalizer_name, self.normalizer_name,
                )
        for i, p in enumerate(self.pieces):
            if p.type == BYTE:
                # byte pieces are spelled "<0xNN>"
                try:
                    self._byte_ids[int(p.text[1:-1], 16)] = i
                except (ValueError, IndexError):
                    pass
            elif p.type == UNKNOWN:
                self._unk_id = i
            if p.type in (NORMAL, USER_DEFINED):
                self._index[p.text] = i
                self._max_piece_chars = max(self._max_piece_chars, len(p.text))

    # ---- loading ----

    @staticmethod
    def load(path: str) -> "SentencePieceModel":
        with open(path, "rb") as f:
            return SentencePieceModel.from_bytes(f.read())

    @staticmethod
    def from_bytes(data: bytes) -> "SentencePieceModel":
        pieces: list[Piece] = []
        model_type = UNIGRAM
        add_dummy = remove_extra = escape_ws = True
        norm_name, has_charsmap = "identity", False
        for fnum, _, val in _fields(data):
            if fnum == 1:  # SentencePiece
                text, score, ptype = "", 0.0, NORMAL
                for pf, pw, pv in _fields(val):
                    if pf == 1:
                        text = pv.decode("utf-8")
                    elif pf == 2:
                        score = struct.unpack("<f", pv.to_bytes(4, "little"))[0]
                    elif pf == 3:
                        ptype = pv
                pieces.append(Piece(text, score, ptype))
            elif fnum == 2:  # TrainerSpec
                for tf, _, tv in _fields(val):
                    if tf == 3:
                        model_type = tv
            elif fnum == 3:  # NormalizerSpec
                for nf, _, nv in _fields(val):
                    if nf == 1:
                        norm_name = nv.decode("utf-8")
                    elif nf == 2:
                        has_charsmap = len(nv) > 0
                    elif nf == 3:
                        add_dummy = bool(nv)
                    elif nf == 4:
                        remove_extra = bool(nv)
                    elif nf == 5:
                        escape_ws = bool(nv)
        return SentencePieceModel(
            pieces, model_type, add_dummy, remove_extra, escape_ws,
            norm_name, has_charsmap,
        )

    # ---- normalization ----

    def _normalize(self, text: str) -> str:
        # character normalization lives in the charsmap: no charsmap, no
        # normalization (whatever the name says) — llama/mistral land here
        if self.has_charsmap:
            name = self.normalizer_name  # load guard pinned it known
            text = _unicode_normalize(
                text, nmt="nmt" in name, casefold=name.endswith("_cf"))
        if self.remove_extra_whitespaces:
            text = " ".join(s for s in text.split(" ") if s)
        if self.add_dummy_prefix:
            text = " " + text
        if self.escape_whitespaces:
            text = text.replace(" ", WS)
        return text

    # ---- encoding ----

    def encode(self, text: str) -> list[int]:
        if not text:
            return []  # sentencepiece: empty input short-circuits the
        s = self._normalize(text)  # normalizer (no lone dummy prefix)
        if not s:
            return []
        if self.model_type == BPE:
            return self._encode_bpe(s)
        return self._encode_unigram(s)

    def _char_fallback(self, ch: str) -> list[int]:
        """A character no piece covers: byte pieces if the model has
        them (llama-style), else one unk."""
        if self._byte_ids:
            return [
                self._byte_ids.get(b, self._unk_id) for b in ch.encode("utf-8")
            ]
        return [self._unk_id]

    def _encode_unigram(self, s: str) -> list[int]:
        """Viterbi: best[i] = max-score segmentation of s[:i]. O(n * L)
        with L = longest piece, exactly sentencepiece's lattice DP
        (scores are log-probs; byte/unk fallback scored below any real
        piece so it's only chosen when nothing covers a char)."""
        n = len(s)
        NEG = -1e18
        # fallback cost per char: below the worst real piece
        floor = min((p.score for p in self.pieces), default=0.0) - 10.0
        best = [NEG] * (n + 1)
        back: list = [None] * (n + 1)  # (start, ids)
        best[0] = 0.0
        for i in range(n):
            if best[i] == NEG:
                continue
            top = min(n, i + self._max_piece_chars)
            for j in range(i + 1, top + 1):
                pid = self._index.get(s[i:j])
                if pid is not None:
                    sc = best[i] + self.pieces[pid].score
                    if sc > best[j]:
                        best[j] = sc
                        back[j] = (i, [pid])
            # per-char fallback edge
            ids = self._char_fallback(s[i])
            sc = best[i] + floor * len(ids)
            if sc > best[i + 1]:
                best[i + 1] = sc
                back[i + 1] = (i, ids)
        out: list[int] = []
        j = n
        while j > 0:
            i, ids = back[j]
            out[:0] = ids
            j = i
        return out

    def _encode_bpe(self, s: str) -> list[int]:
        """Iterated best merge: repeatedly join the adjacent pair whose
        concatenation is a vocab piece with the highest score (SP-BPE
        scores encode merge priority)."""
        syms: list[str] = list(s)
        while len(syms) > 1:
            best_sc, best_i = None, -1
            for i in range(len(syms) - 1):
                pid = self._index.get(syms[i] + syms[i + 1])
                if pid is not None:
                    sc = self.pieces[pid].score
                    if best_sc is None or sc > best_sc:
                        best_sc, best_i = sc, i
            if best_i < 0:
                break
            syms[best_i : best_i + 2] = [syms[best_i] + syms[best_i + 1]]
        out: list[int] = []
        for sym in syms:
            pid = self._index.get(sym)
            if pid is not None:
                out.append(pid)
            else:
                for ch in sym:
                    out.extend(self._char_fallback(ch))
        return out

    # ---- decoding ----

    def decode(self, ids, skip_special: bool = True) -> str:
        """Pieces concatenate; ▁ becomes space; byte pieces regroup into
        UTF-8 runs; the dummy prefix's leading space strips."""
        parts: list[object] = []  # str | int (pending byte)
        for i in ids:
            if not 0 <= i < len(self.pieces):
                continue
            p = self.pieces[i]
            if p.type == BYTE:
                parts.append(int(p.text[1:-1], 16))
            elif p.type in (CONTROL, UNKNOWN):
                if not skip_special:
                    parts.append(p.text)
            else:
                parts.append(p.text)
        out: list[str] = []
        pending: list[int] = []
        for part in parts + [""]:
            if isinstance(part, int):
                pending.append(part)
                continue
            if pending:
                out.append(bytes(pending).decode("utf-8", errors="replace"))
                pending = []
            out.append(part)
        text = "".join(out).replace(WS, " ")
        return text[1:] if self.add_dummy_prefix and text.startswith(" ") else text


# the VISIBLE Unicode spaces the NMT rules collapse to ASCII space \u2014
# zero-widths (ZWSP U+200B, BOM U+FEFF, joiners) are deliberately NOT
# here: they are category Cf and must be DELETED, not become a space
_NMT_SPACES = frozenset(
    "\u00a0\u1680"  # NBSP, ogham space mark
    + "".join(chr(c) for c in range(0x2000, 0x200B))  # en/em/thin...
    + "\u2028\u2029\u202f\u205f\u3000"  # line/para sep, NNBSP,
)                                      # math space, ideographic space


def _unicode_normalize(text: str, *, nmt: bool, casefold: bool) -> str:
    """The four standard rulesets, natively: NFKC via unicodedata; the
    NMT variants first drop control/format characters (keeping \\n,
    mapping \\t to space) and collapse the visible Unicode spaces; the
    _cf variants casefold and \u2014 per ICU's NFKC_Casefold, which they
    compile \u2014 remove default-ignorable code points (approximated as
    category Cf: soft hyphen, ZWSP, joiners).  Custom charsmaps are
    rejected at load (module docstring)."""
    import unicodedata

    if nmt:
        out = []
        for ch in text:
            if ch in _NMT_SPACES or ch == "\t":
                out.append(" ")
            elif ch != "\n" and unicodedata.category(ch) in ("Cc", "Cf"):
                continue
            else:
                out.append(ch)
        text = "".join(out)
    elif casefold:
        # NFKC_Casefold's default-ignorable removal (nmt above already
        # dropped Cf)
        text = "".join(
            ch for ch in text if unicodedata.category(ch) != "Cf")
    text = unicodedata.normalize("NFKC", text)
    if casefold:
        text = text.casefold()
    return text


# ---------------- writing (fixtures) ----------------


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(fnum: int, wtype: int) -> bytes:
    return _varint((fnum << 3) | wtype)


def _len_field(fnum: int, payload: bytes) -> bytes:
    return _key(fnum, 2) + _varint(len(payload)) + payload


def serialize_model(model: SentencePieceModel) -> bytes:
    """ModelProto wire bytes for a model — the fixture writer the tests
    use (no sentencepiece wheel to train one), and the round-trip proof
    for the reader above."""
    out = bytearray()
    for p in model.pieces:
        body = _len_field(1, p.text.encode("utf-8"))
        body += _key(2, 5) + struct.pack("<f", p.score)
        body += _key(3, 0) + _varint(p.type)
        out += _len_field(1, body)
    trainer = _key(3, 0) + _varint(model.model_type)
    out += _len_field(2, trainer)
    norm = (
        _len_field(1, model.normalizer_name.encode("utf-8"))
        + _key(3, 0) + _varint(int(model.add_dummy_prefix))
        + _key(4, 0) + _varint(int(model.remove_extra_whitespaces))
        + _key(5, 0) + _varint(int(model.escape_whitespaces))
    )
    if model.normalizer_name != "identity":
        # normalization is charsmap-gated at load (the reader checks
        # non-emptiness, never the trie bytes) — a placeholder marks the
        # fixture's named ruleset as active
        norm += _len_field(2, b"\x01")
    out += _len_field(3, norm)
    return bytes(out)

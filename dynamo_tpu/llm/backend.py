"""Backend stage: incremental detokenization + stop-sequence jail.

Re-design of the reference's Backend (lib/llm/src/backend.rs:62-126): a
bidirectional pipeline operator between the preprocessor and the engine.
Forward: annotate the request with the tokenizer's eos ids. Backward (per
token, the hot path): incrementally decode token ids to text, "jail" any
emitted tail that could be the prefix of a stop sequence until it either
matches (finish with reason=stop, truncated at the match) or diverges
(release the held text) — the reference uses toktrie for the same purpose.
"""

from __future__ import annotations

from typing import AsyncIterator

from ..protocols.common import FinishReason, LLMEngineOutput, PreprocessedRequest
from ..runtime.annotated import Annotated
from ..runtime.engine import AsyncEngine, Context
from ..runtime.pipeline import Operator
from .tokenizer import DecodeStream, Tokenizer


class StopJail:
    """Holds back text that may be the start of a stop sequence."""

    def __init__(self, stops: list[str]):
        self._stops = [s for s in stops if s]
        self._held = ""

    def push(self, text: str) -> tuple[str, bool]:
        """Feed decoded text; returns (text_to_emit, hit_stop)."""
        if not self._stops:
            return text, False
        buf = self._held + text
        # full match anywhere in the buffer?
        cut = -1
        for s in self._stops:
            idx = buf.find(s)
            if idx != -1 and (cut == -1 or idx < cut):
                cut = idx
        if cut != -1:
            self._held = ""
            return buf[:cut], True
        # hold the longest tail that is a proper prefix of some stop string
        hold = 0
        for s in self._stops:
            for k in range(min(len(s) - 1, len(buf)), 0, -1):
                if buf.endswith(s[:k]):
                    hold = max(hold, k)
                    break
        if hold:
            self._held = buf[-hold:]
            return buf[:-hold], False
        self._held = ""
        return buf, False

    def flush(self) -> str:
        held, self._held = self._held, ""
        return held


class Backend(Operator):
    """Detokenizer stage (Context[PreprocessedRequest] ->
    Annotated[LLMEngineOutput] with .text filled in)."""

    def __init__(self, tokenizer: Tokenizer):
        self._tokenizer = tokenizer

    async def generate(
        self, request: Context, next_engine: AsyncEngine
    ) -> AsyncIterator[Annotated]:
        req: PreprocessedRequest = request.data
        if not req.eos_token_ids:
            req.eos_token_ids = self._tokenizer.eos_token_ids
        decoder = DecodeStream(self._tokenizer, skip_special_tokens=True)
        jail = StopJail(req.stop_conditions.stop)
        finished = False
        async for item in next_engine.generate(request):
            if finished:
                break
            if not isinstance(item, Annotated):
                item = Annotated.from_data(item)
            if item.data is None:
                yield item
                continue
            out: LLMEngineOutput = (
                item.data
                if isinstance(item.data, LLMEngineOutput)
                else LLMEngineOutput.from_dict(item.data)
            )
            text_parts = []
            pieces = []  # per-token INCREMENTAL text (may be "")
            for tid in out.token_ids:
                piece = decoder.step(tid)
                pieces.append(piece or "")
                if piece is not None:
                    text_parts.append(piece)
            if out.logprobs:
                # enrich id-level entries with token text (the engine
                # emits ids + floats; OpenAI responses carry strings).
                # The chosen token's text is the INCREMENTAL decode piece
                # — isolated decode of a byte-level BPE piece yields
                # U+FFFD and would drift text_offset off the streamed
                # text; an incomplete multibyte prefix contributes ""
                # and the completing token carries the full char.
                for tid, piece, entry in zip(
                    out.token_ids, pieces, out.logprobs
                ):
                    entry["token"] = piece
                    entry["top"] = [
                        {
                            "token": self._tokenizer.decode([i]),
                            "logprob": lp,
                        }
                        for i, lp in entry.get("top", [])
                    ]
            if out.is_final():
                tail = decoder.flush()
                if tail:
                    text_parts.append(tail)
            text = "".join(text_parts)
            emit, hit_stop = jail.push(text) if text else ("", False)
            if hit_stop:
                out.finish_reason = FinishReason.STOP
                finished = True
                # propagate upstream so a remote engine stops generating
                # instead of running to max_tokens into a dead stream
                request.context.stop_generating()
            if out.is_final() and not hit_stop:
                emit += jail.flush()
            out.text = emit
            yield Annotated(data=out, event=item.event, comment=item.comment, id=item.id)
            if out.is_final():
                finished = True

"""Model resolution: local path, HF cache, or hub download.

Re-design of the reference's hub fetcher (launch/dynamo-run/src/hub.rs:
`from_hf` downloads GGUF/safetensors repos into the HF cache layout).
Resolution order:

  1. an existing local directory is returned as-is;
  2. a repo id already present in the local HF cache
     (``~/.cache/huggingface/hub``) resolves to its newest snapshot —
     this keeps air-gapped TPU pods working with pre-seeded caches;
  3. otherwise ``huggingface_hub.snapshot_download`` fetches config,
     tokenizer, and ``*.safetensors`` (gated by network availability /
     ``HF_HUB_OFFLINE``).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

_NEEDED = ["*.safetensors*", "*.json", "*.model", "tokenizer*"]


def _cache_snapshot(repo_id: str, cache_dir: Optional[str] = None) -> Optional[str]:
    """Newest complete snapshot of ``repo_id`` in the local HF cache."""
    cache = cache_dir or os.path.expanduser(
        os.environ.get("HF_HUB_CACHE")
        or os.path.join(
            os.environ.get("HF_HOME", "~/.cache/huggingface"), "hub"
        )
    )
    repo_dir = os.path.join(
        os.path.expanduser(cache), f"models--{repo_id.replace('/', '--')}"
    )
    snaps = os.path.join(repo_dir, "snapshots")
    if not os.path.isdir(snaps):
        return None
    candidates = [
        os.path.join(snaps, s)
        for s in os.listdir(snaps)
        if os.path.isdir(os.path.join(snaps, s))
    ]
    # prefer the ref'd main revision when recorded, else newest mtime
    ref = os.path.join(repo_dir, "refs", "main")
    if os.path.isfile(ref):
        with open(ref) as f:
            pinned = os.path.join(snaps, f.read().strip())
        if os.path.isdir(pinned):
            return pinned
    if not candidates:
        return None
    return max(candidates, key=os.path.getmtime)


def resolve_model_path(name_or_path: str, cache_dir: Optional[str] = None) -> str:
    """Local dir | cached snapshot | hub download -> a local directory."""
    if os.path.isdir(name_or_path):
        return name_or_path
    if "/" not in name_or_path or name_or_path.count("/") != 1:
        raise FileNotFoundError(
            f"{name_or_path!r} is neither a local directory nor an "
            "org/name HF repo id"
        )
    cached = _cache_snapshot(name_or_path, cache_dir)
    # a usable snapshot needs actual weights — config.json alone is a
    # torn download, and serving it would mean random-init params
    if cached is not None and any(
        f.endswith(".safetensors") for f in os.listdir(cached)
    ):
        logger.info("resolved %s from local HF cache: %s", name_or_path, cached)
        return cached
    if os.environ.get("HF_HUB_OFFLINE"):
        raise FileNotFoundError(
            f"{name_or_path!r} not in the local HF cache and HF_HUB_OFFLINE "
            "is set — pre-seed the cache or pass a local path"
        )
    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:  # pragma: no cover - baked into this image
        raise FileNotFoundError(
            f"{name_or_path!r} needs huggingface_hub to download"
        ) from e
    logger.info("downloading %s from the HF hub", name_or_path)
    return snapshot_download(
        name_or_path, allow_patterns=_NEEDED, cache_dir=cache_dir
    )


def main(argv=None):  # pragma: no cover - exercised via rendered pods
    """``python -m dynamo_tpu.llm.hub <org/name-or-path>`` — the fetch
    entry the k8s initContainer runs (deploy/manifests.py
    _weight_distribution): resolve (downloading if needed) and print
    the local directory. Exit 1 with the error on stderr otherwise."""
    import argparse

    p = argparse.ArgumentParser("dynamo_tpu.llm.hub")
    p.add_argument("model", help="HF org/name repo id or local path")
    p.add_argument("--cache-dir", default=None)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    print(resolve_model_path(args.model, args.cache_dir))


if __name__ == "__main__":
    main()

"""Tokenizer abstraction + incremental detokenization.

Re-design of the reference's tokenizer layer (lib/llm/src/tokenizers.rs:
Encoder/Decoder traits + DecodeStream:158). Two implementations:

  * :class:`HFTokenizer` — wraps a HuggingFace tokenizer (the production
    path; the HF `tokenizers` Rust core is already the fastest option),
  * :class:`ByteTokenizer` — dependency-free byte-level tokenizer used by
    tests and echo engines (the reference tests against checked-in
    fixtures the same way).

:class:`DecodeStream` implements UTF-8-safe incremental detokenization: a
token boundary is not a character boundary, so we re-decode a sliding
window and emit only the confirmed new suffix (holding back trailing
replacement chars that indicate an incomplete multi-byte sequence).
"""

from __future__ import annotations

import abc
from typing import Optional, Sequence


class Tokenizer(abc.ABC):
    @abc.abstractmethod
    def encode(self, text: str, add_special_tokens: bool = False) -> list[int]:
        ...

    @abc.abstractmethod
    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        ...

    @property
    @abc.abstractmethod
    def eos_token_ids(self) -> list[int]:
        ...

    @property
    @abc.abstractmethod
    def vocab_size(self) -> int:
        ...

    @property
    def bos_token_id(self) -> Optional[int]:
        return None

    def apply_chat_template(
        self, messages: list[dict], add_generation_prompt: bool = True,
        tools: list | None = None,
    ) -> str:
        raise NotImplementedError("this tokenizer has no chat template")


class ByteTokenizer(Tokenizer):
    """ids 0-255 = raw bytes; 256 = BOS, 257 = EOS."""

    BOS = 256
    EOS = 257

    def encode(self, text: str, add_special_tokens: bool = False) -> list[int]:
        ids = list(text.encode("utf-8"))
        if add_special_tokens:
            ids = [self.BOS] + ids
        return ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        """ids >= 258 (a larger model served through the byte tokenizer,
        e.g. the llama3-8b-sim bench config) decode to one printable
        ASCII char derived from the id. NOT U+FFFD: the incremental
        DecodeStream treats a trailing replacement char as an incomplete
        multibyte sequence and holds output, which would stall streaming
        for every out-of-range token."""
        out = []
        for i in ids:
            if i < 256:
                out.append(bytes([i]))
            elif i >= 258:
                out.append(bytes([33 + (i % 94)]))
        return b"".join(out).decode("utf-8", errors="replace")

    @property
    def eos_token_ids(self) -> list[int]:
        return [self.EOS]

    @property
    def bos_token_id(self) -> Optional[int]:
        return self.BOS

    @property
    def vocab_size(self) -> int:
        return 258

    def apply_chat_template(
        self, messages: list[dict], add_generation_prompt: bool = True,
        tools: list | None = None,
    ) -> str:
        parts = [f"<|{m['role']}|>{m.get('content') or ''}" for m in messages]
        if tools:
            import json as _json

            parts.insert(0, f"<|tools|>{_json.dumps(tools, sort_keys=True)}")
        if add_generation_prompt:
            parts.append("<|assistant|>")
        return "".join(parts)


class HFTokenizer(Tokenizer):
    """HuggingFace tokenizer from a local checkout (tokenizer.json /
    tokenizer_config.json), ref tokenizers/hf.rs:23."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        eos = self._tok.eos_token_id
        self._eos_ids = [eos] if eos is not None else []
        # llama-3 style: some models define extra end-of-turn tokens
        for name in ("<|eot_id|>", "<|im_end|>", "<|end|>"):
            tid = self._tok.convert_tokens_to_ids(name)
            if tid is not None and tid >= 0 and tid not in self._eos_ids:
                unk = getattr(self._tok, "unk_token_id", None)
                if tid != unk:
                    self._eos_ids.append(tid)

    @property
    def hf(self):
        return self._tok

    def encode(self, text: str, add_special_tokens: bool = False) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_special_tokens)

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    @property
    def eos_token_ids(self) -> list[int]:
        return list(self._eos_ids)

    @property
    def bos_token_id(self) -> Optional[int]:
        return self._tok.bos_token_id

    @property
    def vocab_size(self) -> int:
        return len(self._tok)

    def apply_chat_template(
        self, messages: list[dict], add_generation_prompt: bool = True,
        tools: list | None = None,
    ) -> str:
        # tools flow into the jinja context — function-calling templates
        # (llama-3.1, qwen, mistral v3...) render the tool schemas into
        # the system prompt (ref: the engines the reference wraps pass
        # request.tools through the same HF API)
        return self._tok.apply_chat_template(
            messages, tokenize=False,
            add_generation_prompt=add_generation_prompt,
            tools=tools or None,
        )


class SPTokenizer(Tokenizer):
    """SentencePiece tokenizer from a checkpoint's ``tokenizer.model``
    (ref lib/llm/src/tokenizers/sp.rs:25) — for checkpoints that ship no
    ``tokenizer.json``. Backed by the in-repo model reader/segmenters
    (:mod:`.sp_model`; the sentencepiece wheel is not in this image).

    Special ids follow the checkpoint: ``tokenizer_config.json`` /
    ``special_tokens_map.json`` overrides win when present; otherwise
    the conventional ``<s>``/``</s>`` control pieces are used. A
    ``chat_template`` found in ``tokenizer_config.json`` renders via
    jinja2 (the same engine transformers uses)."""

    def __init__(self, path: str):
        import json
        import os

        model_file = (
            os.path.join(path, "tokenizer.model")
            if os.path.isdir(path) else path
        )
        from .sp_model import CONTROL, SentencePieceModel

        self._sp = SentencePieceModel.load(model_file)
        self._piece_id = {
            p.text: i for i, p in enumerate(self._sp.pieces)
        }
        self._chat_template = None
        self._bos_id = self._piece_id.get("<s>")
        self._eos_ids = [
            i for i, p in enumerate(self._sp.pieces)
            if p.type == CONTROL and p.text in ("</s>", "<|endoftext|>")
        ]
        cfg_dir = path if os.path.isdir(path) else os.path.dirname(path)
        for fname in ("special_tokens_map.json", "tokenizer_config.json"):
            try:
                with open(os.path.join(cfg_dir, fname)) as f:
                    cfg = json.load(f)
            except (OSError, ValueError):
                continue
            bos, eos = cfg.get("bos_token"), cfg.get("eos_token")
            if isinstance(bos, dict):
                bos = bos.get("content")
            if isinstance(eos, dict):
                eos = eos.get("content")
            if bos in self._piece_id:
                self._bos_id = self._piece_id[bos]
            if eos in self._piece_id:
                self._eos_ids = [self._piece_id[eos]]
            if cfg.get("chat_template"):
                self._chat_template = cfg["chat_template"]

    def encode(self, text: str, add_special_tokens: bool = False) -> list[int]:
        ids = self._sp.encode(text)
        if add_special_tokens and self._bos_id is not None:
            ids = [self._bos_id] + ids
        return ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._sp.decode(ids, skip_special=skip_special_tokens)

    @property
    def eos_token_ids(self) -> list[int]:
        return list(self._eos_ids)

    @property
    def bos_token_id(self) -> Optional[int]:
        return self._bos_id

    @property
    def vocab_size(self) -> int:
        return len(self._sp.pieces)

    def apply_chat_template(
        self, messages: list[dict], add_generation_prompt: bool = True,
        tools: list | None = None,
    ) -> str:
        if not self._chat_template:
            raise NotImplementedError("checkpoint has no chat template")
        import jinja2

        env = jinja2.Environment(trim_blocks=True, lstrip_blocks=True)
        bos = self._sp.pieces[self._bos_id].text if self._bos_id is not None else ""
        eos = self._sp.pieces[self._eos_ids[0]].text if self._eos_ids else ""
        return env.from_string(self._chat_template).render(
            messages=messages, add_generation_prompt=add_generation_prompt,
            tools=tools or None, bos_token=bos, eos_token=eos,
        )


def load_tokenizer(path: str) -> Tokenizer:
    """The checkpoint-dir tokenizer policy (one place): ``tokenizer.json``
    → :class:`HFTokenizer` (fast path), else ``tokenizer.model`` →
    :class:`SPTokenizer`. The reference factories pick hf.rs vs sp.rs by
    the same file probe (lib/llm/src/tokenizers.rs)."""
    import os

    if os.path.exists(os.path.join(path, "tokenizer.json")):
        return HFTokenizer(path)
    if os.path.exists(os.path.join(path, "tokenizer.model")):
        return SPTokenizer(path)
    raise FileNotFoundError(
        f"no tokenizer.json or tokenizer.model under {path!r}"
    )


class DecodeStream:
    """Incremental, UTF-8-safe detokenizer (ref tokenizers.rs:158
    DecodeStream; the sliding-window scheme matches what the engines the
    reference wraps do internally)."""

    def __init__(self, tokenizer: Tokenizer, skip_special_tokens: bool = True):
        self._tok = tokenizer
        self._skip_special = skip_special_tokens
        self._ids: list[int] = []
        self._prefix_offset = 0  # start of the re-decode window
        self._read_offset = 0  # tokens already surfaced as text

    def step(self, token_id: int) -> Optional[str]:
        """Feed one token id; return newly-confirmed text (or None)."""
        self._ids.append(token_id)
        prefix_text = self._tok.decode(
            self._ids[self._prefix_offset : self._read_offset], self._skip_special
        )
        new_text = self._tok.decode(self._ids[self._prefix_offset :], self._skip_special)
        if len(new_text) <= len(prefix_text) or new_text.endswith("�"):
            # incomplete multi-byte sequence — hold until the next token
            return None
        delta = new_text[len(prefix_text) :]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self._ids)
        return delta

    def flush(self) -> Optional[str]:
        """Emit whatever is still held back (end of stream)."""
        prefix_text = self._tok.decode(
            self._ids[self._prefix_offset : self._read_offset], self._skip_special
        )
        full = self._tok.decode(self._ids[self._prefix_offset :], self._skip_special)
        if len(full) > len(prefix_text):
            self._read_offset = len(self._ids)
            self._prefix_offset = self._read_offset
            return full[len(prefix_text) :]
        return None

    @property
    def token_count(self) -> int:
        return len(self._ids)

"""OpenAI preprocessor: template rendering + tokenization + delta generation.

Re-design of the reference's OpenAIPreprocessor (lib/llm/src/
preprocessor.rs:63-103 + protocols/openai/chat_completions/delta.rs): a
bidirectional operator. Forward: render the chat template (the model's
jinja2 template via the HF tokenizer, ref preprocessor/prompt/template/*),
tokenize, and extract stop/sampling options into a PreprocessedRequest.
Backward: turn detokenized LLMEngineOutputs into OpenAI
chat.completion.chunk / text_completion deltas, including the requested
``nvext.annotations`` (formatted_prompt, token_ids) as SSE events.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional, Union

from .. import tracing
from ..protocols.common import LLMEngineOutput, PreprocessedRequest
from ..protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    Usage,
    chat_chunk,
    completion_chunk,
    new_chat_id,
    new_cmpl_id,
)
from ..runtime.annotated import Annotated
from ..runtime.engine import AsyncEngine, Context
from ..runtime.pipeline import Operator
from .tokenizer import Tokenizer

ANNOTATION_FORMATTED_PROMPT = "formatted_prompt"
ANNOTATION_TOKEN_IDS = "token_ids"


class OpenAIPreprocessor(Operator):
    def __init__(self, tokenizer: Tokenizer):
        self._tokenizer = tokenizer

    # ---- forward ----
    def preprocess_chat(self, req: ChatCompletionRequest) -> tuple[PreprocessedRequest, str]:
        if req.nvext.use_raw_prompt and len(req.messages) == 1:
            prompt = req.messages[-1].content_text()
        else:
            prompt = self._tokenizer.apply_chat_template(
                [m.to_dict() for m in req.messages], add_generation_prompt=True,
                tools=req.tools,
            )
        token_ids = self._tokenizer.encode(prompt, add_special_tokens=False)
        pre = PreprocessedRequest(
            token_ids=token_ids,
            stop_conditions=req.stops,
            sampling_options=req.sampling,
            model=req.model,
            eos_token_ids=self._tokenizer.eos_token_ids,
            # text-level engines (pystr) consume the rendered prompt; the
            # reference's PreprocessedRequest carries it the same way
            annotations={ANNOTATION_FORMATTED_PROMPT: prompt},
        )
        return pre, prompt

    def preprocess_completion(self, req: CompletionRequest) -> tuple[PreprocessedRequest, str]:
        if isinstance(req.prompt, list) and req.prompt and isinstance(req.prompt[0], int):
            token_ids = list(req.prompt)
            prompt = self._tokenizer.decode(token_ids)
        else:
            prompt = req.prompt
            token_ids = self._tokenizer.encode(prompt, add_special_tokens=True)
        pre = PreprocessedRequest(
            token_ids=token_ids,
            stop_conditions=req.stops,
            sampling_options=req.sampling,
            model=req.model,
            eos_token_ids=self._tokenizer.eos_token_ids,
            annotations={ANNOTATION_FORMATTED_PROMPT: prompt},
        )
        return pre, prompt

    # ---- the operator ----
    async def generate(
        self, request: Context, next_engine: AsyncEngine
    ) -> AsyncIterator[Annotated]:
        req: Union[ChatCompletionRequest, CompletionRequest] = request.data
        is_chat = isinstance(req, ChatCompletionRequest)
        # template render + tokenization = the TTFT's "tokenize" component
        with tracing.span("tokenize", request_id=request.id) as tok_span:
            if is_chat:
                pre, prompt = self.preprocess_chat(req)
            else:
                pre, prompt = self.preprocess_completion(req)
            tok_span.set(tokens=len(pre.token_ids))

        # requested annotations ride the stream as events (ref nvext.rs)
        for ann in req.nvext.annotations:
            if ann == ANNOTATION_FORMATTED_PROMPT:
                yield Annotated.from_annotation(ANNOTATION_FORMATTED_PROMPT, prompt)
            elif ann == ANNOTATION_TOKEN_IDS:
                yield Annotated.from_annotation(ANNOTATION_TOKEN_IDS, pre.token_ids)

        n = getattr(req.sampling, "n", 1) or 1
        if n > 1:
            async for item in self._generate_n(
                request, next_engine, req, pre, is_chat, n
            ):
                yield item
            return

        delta = DeltaGenerator(req, is_chat=is_chat, prompt_tokens=len(pre.token_ids))
        first = True
        async for item in next_engine.generate(request.transfer(pre)):
            if not isinstance(item, Annotated):
                item = Annotated.from_data(item)
            if item.data is None:
                yield item
                continue
            out: LLMEngineOutput = (
                item.data
                if isinstance(item.data, LLMEngineOutput)
                else LLMEngineOutput.from_dict(item.data)
            )
            for chunk in delta.chunks(out, include_role=first):
                yield Annotated(data=chunk, id=item.id)
            first = False
            if out.is_final():
                break


    async def _generate_n(
        self, request: Context, next_engine: AsyncEngine, req, pre,
        is_chat: bool, n: int,
    ) -> AsyncIterator[Annotated]:
        """OpenAI ``n > 1``: fan the request out as n concurrent engine
        sub-streams (per-choice seeds so sampled choices differ; each
        sub-stream gets its own detokenizer state downstream), multiplex
        their chunks under one response id with per-choice indexes, and
        emit one summed usage on the final chunk."""
        import asyncio
        import dataclasses

        delta_id = new_chat_id() if is_chat else new_cmpl_id()
        queue: asyncio.Queue = asyncio.Queue()
        prompt_tokens = len(pre.token_ids)
        completion_total = 0
        # choice 0's prompt blocks are committed to the prefix cache the
        # moment its first token emits; siblings admitted AFTER that point
        # prefix-hit instead of racing n identical prefills through the
        # engine (advisor r2 weak #5 / VERDICT #8)
        first_token_evt = asyncio.Event()

        async def run_choice(i: int) -> None:
            so = dataclasses.replace(
                pre.sampling_options,
                n=1,
                seed=((pre.sampling_options.seed or 0) + i * 1_000_003)
                & 0x7FFFFFFF,
            )
            sub = dataclasses.replace(pre, sampling_options=so)
            delta = DeltaGenerator(
                req, is_chat=is_chat, prompt_tokens=prompt_tokens,
                id=delta_id, index=i, with_usage=False,
            )
            first = True
            try:
                async for item in next_engine.generate(request.transfer(sub)):
                    if not isinstance(item, Annotated):
                        item = Annotated.from_data(item)
                    if item.is_error():
                        queue.put_nowait(("err", item.error or "engine error", 0))
                        return
                    if item.data is None:
                        queue.put_nowait(("item", item, 0))
                        continue
                    out = (
                        item.data
                        if isinstance(item.data, LLMEngineOutput)
                        else LLMEngineOutput.from_dict(item.data)
                    )
                    for chunk in delta.chunks(out, include_role=first):
                        queue.put_nowait(
                            ("item", Annotated(data=chunk, id=item.id), 0)
                        )
                    if i == 0 and out.token_ids:
                        first_token_evt.set()
                    first = False
                    if out.is_final():
                        break
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — a dead choice must not
                # masquerade as a completed one
                first_token_evt.set()  # never strand the sibling launcher
                queue.put_nowait(("err", f"{type(e).__name__}: {e}", 0))
                return
            if i == 0:
                first_token_evt.set()  # zero-token finishes included
            queue.put_nowait(("done", None, delta.completion_tokens))

        loop = asyncio.get_running_loop()
        tasks = [loop.create_task(run_choice(0))]

        async def launch_siblings() -> None:
            await first_token_evt.wait()
            tasks.extend(loop.create_task(run_choice(i)) for i in range(1, n))

        launcher = loop.create_task(launch_siblings())
        try:
            done = 0
            while done < n:
                kind, item, toks = await queue.get()
                if kind == "err":
                    # fail the whole request, matching the n=1 path
                    yield Annotated.from_error(item)
                    return
                if kind == "done":
                    done += 1
                    completion_total += toks
                else:
                    yield item
        finally:
            launcher.cancel()
            for t in tasks:
                t.cancel()
        usage = Usage(
            prompt_tokens=prompt_tokens, completion_tokens=completion_total
        )
        from ..protocols.openai import _now

        yield Annotated(
            data={
                "id": delta_id,
                "object": "chat.completion.chunk" if is_chat
                else "text_completion",
                "created": _now(),
                "model": req.model,
                "choices": [],
                "usage": usage.to_dict(),
            }
        )


class DeltaGenerator:
    """LLMEngineOutput -> OpenAI chunk dicts (ref chat_completions/delta.rs:215)."""

    def __init__(self, req, is_chat: bool, prompt_tokens: int,
                 id: Optional[str] = None, index: int = 0,
                 with_usage: bool = True):
        self.req = req
        self.is_chat = is_chat
        self.id = id or (new_chat_id() if is_chat else new_cmpl_id())
        self.index = index
        self.with_usage = with_usage
        self.prompt_tokens = prompt_tokens
        self.completion_tokens = 0
        # running character offset of emitted logprob tokens in the
        # generated text (legacy completions text_offset field)
        self._lp_text_offset = 0

    def chunks(self, out: LLMEngineOutput, include_role: bool = False) -> list[dict]:
        self.completion_tokens += len(out.token_ids)
        result: list[dict] = []
        text = out.text or ""
        finish = out.finish_reason.to_openai() if out.finish_reason else None
        # usage always rides the final chunk; the HTTP layer strips it for
        # streaming clients that did not ask for include_usage, and the
        # aggregator folds it into non-streaming responses (OpenAI-required)
        usage = None
        if finish is not None and self.with_usage:
            usage = Usage(
                prompt_tokens=out.prompt_tokens or self.prompt_tokens,
                completion_tokens=out.completion_tokens or self.completion_tokens,
            )
        from ..protocols.openai import (
            chat_logprobs_block,
            completion_logprobs_block,
        )

        lps = out.logprobs or None
        if self.is_chat:
            delta: dict = {}
            if include_role:
                delta["role"] = "assistant"
            if text or include_role:
                delta["content"] = text
            if delta or finish is not None or lps:
                result.append(
                    chat_chunk(
                        self.id, self.req.model, delta,
                        finish_reason=finish, usage=usage,
                        index=self.index,
                        logprobs=chat_logprobs_block(lps) if lps else None,
                    )
                )
        else:
            if text or finish is not None or lps:
                lp_block = None
                if lps:
                    lp_block = completion_logprobs_block(
                        lps, start_offset=self._lp_text_offset
                    )
                    self._lp_text_offset += sum(
                        len(e.get("token", "")) for e in lps
                    )
                result.append(
                    completion_chunk(
                        self.id, self.req.model, text,
                        finish_reason=finish, usage=usage,
                        index=self.index,
                        logprobs=lp_block,
                    )
                )
        return result

"""Disaggregation wire types.

RemotePrefillRequest mirrors the reference's
``vllm/remote_prefill.py`` (patch:3584-3645): everything a prefill
worker needs to compute the prompt's KV and the first token, plus where
to deliver the result. ``skip_blocks`` carries the decode side's local
prefix-cache hit so only the uncached tail of the KV is shipped (the
reference instead RDMA-reads prefix-hit blocks from the decode worker —
same bytes saved, inverted direction).
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Optional


@dataclass
class RemotePrefillRequest:
    request_id: str
    # the full PreprocessedRequest as a dict (token_ids, sampling, stops)
    request: dict
    # decode-side blocks already holding the first `skip_blocks` prompt
    # blocks (prefix-cache hit) — transfer starts after them
    skip_blocks: int
    # where the prefill worker delivers KV + first token:
    # ConnectionInfo dict of the decode host's KvTransferServer
    connection: dict
    # decode engine identity (diagnostics / metrics)
    engine_id: int = 0
    # W3C traceparent continuing the request's trace on the prefill
    # worker (None when tracing is off)
    trace: Optional[str] = None
    # decode-side wall clock at enqueue — the prefill worker derives the
    # queue-wait span from it (cross-host wall skew applies; the queue
    # wait is seconds-scale where it matters, so skew stays in the noise)
    enqueue_ts: float = 0.0

    def to_bytes(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "RemotePrefillRequest":
        d = json.loads(raw)
        # ignore unknown keys: version-skew safety for fields newer peers
        # may add (this is how `trace` itself shipped)
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})


@dataclass
class DisaggConfig:
    """Conditional-disaggregation policy knobs
    (ref DisaggRouterConf, disagg_router.rs:25; docs/disagg_serving.md:46-52).

    A prompt goes to a remote prefill worker when its *uncached* prefill
    length exceeds ``max_local_prefill_length`` — unless the prefill
    queue is so deep that waiting would cost more than computing locally.
    """

    max_local_prefill_length: int = 512
    # remote prefill disabled above this queue depth (0 = no limit)
    max_prefill_queue_size: int = 0
    enabled: bool = True

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, raw) -> "DisaggConfig":
        if isinstance(raw, (bytes, bytearray)):
            raw = raw.decode()
        d = json.loads(raw)
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})


def disagg_config_key(namespace: str, model: str) -> str:
    """Store key for the hot-reloadable policy
    (ref etcd path ``public/components/disagg_router/models/chat/{model}``)."""
    return f"{namespace}/components/disagg_router/models/{model}"

"""ICI collective fast path for same-slice cross-mesh KV handoff.

The streamed disagg handoff (PR 6) already keeps same-process segments
device-resident through ``LocalKvPipe`` — but the landing side scatters
whatever layout the prefill engine's gather produced, and when the two
engines carve the slice into DIFFERENT meshes (prefill tp=2 feeding
decode tp=1, a pipeline stage feeding a flat decode pool) the implicit
re-layout XLA inserts at scatter time is an unplanned, per-op resolved
placement. This module makes the cheapest path explicit and negotiated:

* :func:`parallel.mesh.slice_fingerprint` identifies the physical slice;
  the decode side advertises ``kv_ici`` + its fingerprint in connection
  info (version-negotiated exactly like ``kv_stream`` — an old peer
  never sees the flag, a mismatched peer falls back to the TCP/streamed
  path), the prefill worker stamps ``ici: 1`` into the stream header
  only when its own fingerprint matches.

* :class:`IciSegmentMover` re-lays each arriving segment from the
  source engine's sharding onto the decode cache's sharding with a
  COMPILED program: an explicit ``shard_map`` over the slice's devices
  when the two shardings already agree shard-for-shard (the common
  same-topology case — the collective is the identity permutation, and
  the shard_map body structurally forbids a host hop), else a jitted
  identity with ``out_shardings``, the re-layout XLA lowers to the
  slice's own ``collective_permute``/all-gather over ICI. Either way
  the bytes never leave the devices: no gather→host→scatter hop, which
  is the whole point.

Programs are memoized by SEGMENT-GEOMETRY BUCKET (the same power-of-two
bucketing as the streamed scatter, ``offload._pad_idxs``), so a stream
of varying segment sizes compiles one mover program per bucket — the
``test_compiled_perf`` contract. Falls back cleanly: any negotiation or
geometry mismatch simply leaves the existing streamed path in charge,
and the ``_StreamAssembler`` redelivery/idempotency contract is
untouched because the mover is a pure per-segment transform applied
before the (idempotent) scatter.

The decode engine's cost model observes each moved segment's wall time
as the ``ici`` link class — which is what makes the router actually
prefer same-slice placement once the fast path exists (costmodel.py).
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import slice_fingerprint

logger = logging.getLogger(__name__)

#: negotiated in connection info (decode side) and echoed in the stream
#: header (prefill side). Receivers ignore unknown header keys (codec
#: forward-compat), so version skew degrades to the plain streamed path
KV_ICI_VERSION = 1


def ici_negotiated(connection: dict, engine, enabled: bool = True) -> bool:
    """Prefill-side gate: may this handoff take the ICI path? Requires
    the decode peer to have advertised a covering ``kv_ici`` version AND
    the same slice fingerprint as this engine's devices; multi-host
    mirrors are excluded (their extract is a lockstep broadcast that
    never yields in-process device arrays)."""
    if not enabled or getattr(engine, "mirror", None) is not None:
        return False
    try:
        return (
            int(connection.get("kv_ici") or 0) >= KV_ICI_VERSION
            and str(connection.get("ici_fp") or "") == slice_fingerprint()
        )
    except (TypeError, ValueError):
        return False


def _bucket_blocks(n: int) -> int:
    """Power-of-two segment-size bucket, same rule as offload._pad_idxs
    (kept in lockstep by test_ici_mover_program_count_bounded)."""
    from ..engine.offload import _pad_idxs

    return len(_pad_idxs(list(range(n))))


class IciSegmentMover:
    """Per-handoff device→device segment re-layout onto the decode
    cache's shardings. Construct once per negotiated stream (the decode
    sink owns it); ``move(k_seg, v_seg)`` returns the pair placed for
    the decode scatter, still on device."""

    def __init__(self, k_sharding, v_sharding):
        # decode-side cache shardings for [L, Hkv, n, bs, D] segments
        # (None = unsharded single-device engine: the mover still runs
        # its compiled program over a 1-device mesh so the path — and
        # its program-count contract — is exercised everywhere)
        self._k_sh = k_sharding
        self._v_sh = v_sharding
        self._fns: dict = {}
        self.segments_moved = 0
        self.permute_programs = 0
        self.reshard_programs = 0

    def programs(self) -> int:
        return len(self._fns)

    # ---- program construction ----

    def _dst_sharding(self, which: str):
        sh = self._k_sh if which == "k" else self._v_sh
        if sh is not None:
            return sh
        # unsharded engine: replicate over a 1-device mesh — the
        # degenerate slice, where the permutation is the identity
        return NamedSharding(Mesh(jax.devices()[:1], ("ici",)), P())

    @staticmethod
    def _one_axis_split(sharding, shape) -> Optional[tuple[int, list]]:
        """Describe ``sharding`` over ``shape`` as an even split of at
        most ONE array axis across its devices: returns (axis, devices
        in shard order) — axis -1 when every device holds the whole
        array (replicated / single device). None for anything richer
        (multi-axis splits take the reshard program instead)."""
        try:
            idx_map = sharding.devices_indices_map(tuple(shape))
        except Exception:  # noqa: BLE001 — exotic sharding
            return None
        split_axis = None
        keyed = []
        for d, idx in idx_map.items():
            axes = [
                a for a, s in enumerate(idx)
                if not (s.start in (0, None) and s.stop in (None, shape[a]))
            ]
            if len(axes) > 1:
                return None
            if axes:
                a = axes[0]
                if split_axis is None:
                    split_axis = a
                elif split_axis != a:
                    return None
                keyed.append((idx[a].start or 0, d))
            else:
                keyed.append((0, d))
        if split_axis is None:
            return -1, sorted((d for _s, d in keyed), key=lambda d: d.id)
        keyed.sort(key=lambda t: t[0])
        starts = [s for s, _d in keyed]
        if len(set(starts)) != len(starts):
            return None  # partial replication inside the split
        return split_axis, [d for _s, d in keyed]

    def _build(self, src_sharding, dst_sharding, shape, dtype):
        """One compiled mover program for this geometry bucket.

        Matched geometry — both engines split the same single axis into
        the same shard-per-device layout (including the degenerate
        replicated / 1-device slice) — compiles an explicit ``shard_map``
        program over the slice's devices: the per-segment collective is
        the identity permutation there, and the program pins the
        device-resident contract structurally (a host round-trip cannot
        hide inside a shard_map body). Anything richer — a tp regroup,
        a pp re-stage, shards in a different device order — compiles a
        jitted identity with ``out_shardings``: the one re-layout API
        XLA lowers to the slice's own collective_permute / all-gather
        over ICI. Both flavors stay device→device end to end; which one
        a handoff compiled is visible in ``permute_programs`` vs
        ``reshard_programs``."""
        from ..ops._pallas_compat import shard_map as _smap

        src = self._one_axis_split(src_sharding, shape) if src_sharding else None
        dst = self._one_axis_split(dst_sharding, shape)
        matched = (
            src is not None and dst is not None and src[0] == dst[0]
            and src[1] == dst[1]
        )
        if not matched:
            self.reshard_programs += 1
            return jax.jit(  # dynlint: disable=jit-in-function -- memoized per geometry bucket in self._fns (_move_one)
                lambda a: a, out_shardings=dst_sharding
            )
        axis, devs = dst
        mesh = Mesh(devs, ("ici",))
        spec = P() if axis < 0 else P(*([None] * axis), "ici")

        def body(a):
            # identity permutation: shards are already on the devices
            # the decode cache wants them on — the shard_map is the
            # structural no-host-hop guarantee, not a data move
            return a

        fn = _smap(body, mesh=mesh, in_specs=spec, out_specs=spec)
        self.permute_programs += 1
        return jax.jit(  # dynlint: disable=jit-in-function -- memoized per geometry bucket in self._fns (_move_one)
            fn, out_shardings=dst_sharding
        )

    # ---- the hot path ----

    def _move_one(self, x, which: str):
        dst = self._dst_sharding(which)
        n = int(x.shape[2])
        bucket = _bucket_blocks(n)
        if n < bucket:
            # pad to the geometry bucket BEFORE the compiled move so the
            # program keys on buckets, not per-request segment sizes
            # (eager pad, exactly like the streamed scatter's device
            # branch); the slice back below is a device-side view op
            pad = [(0, 0)] * x.ndim
            pad[2] = (0, bucket - n)
            x = jnp.pad(x, pad)
        key = (
            which, tuple(x.shape), str(x.dtype),
            getattr(x, "sharding", None) and repr(x.sharding),
        )
        fn = self._fns.get(key)
        if fn is None:
            fn = self._fns[key] = self._build(
                getattr(x, "sharding", None), dst, x.shape, x.dtype
            )
        out = fn(x)
        return out[:, :, :n] if n < bucket else out

    def move(self, k_seg, v_seg):
        k = self._move_one(k_seg, "k")
        v = self._move_one(v_seg, "v")
        self.segments_moved += 1
        return k, v

"""ICI collective fast path for same-slice cross-mesh KV handoff.

The streamed disagg handoff (PR 6) already keeps same-process segments
device-resident through ``LocalKvPipe`` — but the landing side scatters
whatever layout the prefill engine's gather produced, and when the two
engines carve the slice into DIFFERENT meshes (prefill tp=2 feeding
decode tp=1, a pipeline stage feeding a flat decode pool) the implicit
re-layout XLA inserts at scatter time is an unplanned, per-op resolved
placement. This module makes the cheapest path explicit and negotiated:

* :func:`parallel.mesh.slice_fingerprint` identifies the physical slice;
  the decode side advertises ``kv_ici`` + its fingerprint in connection
  info (version-negotiated exactly like ``kv_stream`` — an old peer
  never sees the flag, a mismatched peer falls back to the TCP/streamed
  path), the prefill worker stamps ``ici: 1`` into the stream header
  only when its own fingerprint matches. Negotiation keys on SLICE
  IDENTITY, not channel: in-process ``LocalKvPipe`` pairs hand device
  arrays straight through, and launched same-slice roles (one slice,
  several processes) get the same negotiated landing for their wire
  segments — the mover places each one explicitly onto the decode
  layout in a compiled program instead of letting the scatter resolve
  a foreign placement per op.

* :class:`IciSegmentMover` re-lays each arriving segment from the
  source engine's sharding onto the decode cache's sharding with a
  COMPILED program: an explicit ``shard_map`` over the slice's devices
  when the two shardings already agree shard-for-shard (the common
  same-topology case — the collective is the identity permutation, and
  the shard_map body structurally forbids a host hop), else a jitted
  identity with ``out_shardings``, the re-layout XLA lowers to the
  slice's own ``collective_permute``/all-gather over ICI. Either way
  the bytes never leave the devices: no gather→host→scatter hop, which
  is the whole point.

Programs are memoized by SEGMENT-GEOMETRY BUCKET (the same power-of-two
bucketing as the streamed scatter, ``offload._pad_idxs``), so a stream
of varying segment sizes compiles one mover program per bucket — the
``test_compiled_perf`` contract. Falls back cleanly: any negotiation or
geometry mismatch simply leaves the existing streamed path in charge,
and the ``_StreamAssembler`` redelivery/idempotency contract is
untouched because the mover is a pure per-segment transform applied
before the (idempotent) scatter.

The decode engine's cost model observes each moved segment's wall time
as the ``ici`` link class — which is what makes the router actually
prefer same-slice placement once the fast path exists (costmodel.py).
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import slice_fingerprint

logger = logging.getLogger(__name__)

#: negotiated in connection info (decode side) and echoed in the stream
#: header (prefill side). Receivers ignore unknown header keys (codec
#: forward-compat), so version skew degrades to the plain streamed path
KV_ICI_VERSION = 1


def ici_negotiated(connection: dict, engine, enabled: bool = True) -> bool:
    """Prefill-side gate: may this handoff take the ICI path? Requires
    the decode peer to have advertised a covering ``kv_ici`` version AND
    the same slice fingerprint as this engine's devices; multi-host
    mirrors are excluded (their extract is a lockstep broadcast that
    never yields in-process device arrays)."""
    if not enabled or getattr(engine, "mirror", None) is not None:
        return False
    try:
        return (
            int(connection.get("kv_ici") or 0) >= KV_ICI_VERSION
            and str(connection.get("ici_fp") or "") == slice_fingerprint()
        )
    except (TypeError, ValueError):
        return False


def _bucket_blocks(n: int) -> int:
    """Power-of-two segment-size bucket, same rule as offload._pad_idxs
    (kept in lockstep by test_ici_mover_program_count_bounded)."""
    from ..engine.offload import _pad_idxs

    return len(_pad_idxs(list(range(n))))


class IciSegmentMover:
    """Per-handoff device→device segment re-layout onto the decode
    cache's shardings. Construct once per negotiated stream (the decode
    sink owns it); ``move(k_seg, v_seg)`` returns the pair placed for
    the decode scatter, still on device.

    Program construction and memoization live in the shared
    :class:`~dynamo_tpu.parallel.morph.MeshMorpher` (the PR 11 private
    memo promoted there when elastic resharding needed the same compiled
    cross-mesh permutations for weights/KV) — this class only owns the
    segment-specific parts: the k/v destination shardings and the
    pad-to-geometry-bucket discipline that keeps the morpher's memo
    bounded by buckets."""

    def __init__(self, k_sharding, v_sharding, morpher=None):
        from ..parallel.morph import MeshMorpher

        # decode-side cache shardings for [L, Hkv, n, bs, D] segments
        # (None = unsharded single-device engine: the mover still runs
        # its compiled program over a 1-device mesh so the path — and
        # its program-count contract — is exercised everywhere)
        self._k_sh = k_sharding
        self._v_sh = v_sharding
        self._morpher = morpher if morpher is not None else MeshMorpher()
        self.segments_moved = 0

    def programs(self) -> int:
        return self._morpher.programs()

    @property
    def permute_programs(self) -> int:
        return self._morpher.permute_programs

    @property
    def reshard_programs(self) -> int:
        return self._morpher.reshard_programs

    def _dst_sharding(self, which: str):
        sh = self._k_sh if which == "k" else self._v_sh
        if sh is not None:
            return sh
        # unsharded engine: replicate over a 1-device mesh — the
        # degenerate slice, where the permutation is the identity
        return NamedSharding(Mesh(jax.devices()[:1], ("ici",)), P())

    # ---- the hot path ----

    def _move_one(self, x, which: str):
        dst = self._dst_sharding(which)
        n = int(x.shape[2])
        bucket = _bucket_blocks(n)
        if n < bucket:
            # pad to the geometry bucket BEFORE the compiled move so the
            # program keys on buckets, not per-request segment sizes
            # (eager pad, exactly like the streamed scatter's device
            # branch); the slice back below is a device-side view op
            pad = [(0, 0)] * x.ndim
            pad[2] = (0, bucket - n)
            x = jnp.pad(x, pad)
        out = self._morpher.apply(x, dst)
        return out[:, :, :n] if n < bucket else out

    def move(self, k_seg, v_seg):
        k = self._move_one(k_seg, "k")
        v = self._move_one(v_seg, "v")
        self.segments_moved += 1
        return k, v

"""Conditional disaggregation router.

Decides, per request, whether the prompt's prefill runs locally on the
decode worker or is offloaded to a remote prefill worker
(ref lib/llm/src/disagg_router.rs:25-135 for the etcd-watched config;
examples/llm/components/worker.py:151-171 + docs/disagg_serving.md:46-52
for the decision logic).

The policy lives in the control-plane store and hot-reloads via a prefix
watch — ops can retune ``max_local_prefill_length`` on a live fleet with
one ``kv_put``.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from .protocols import DisaggConfig, disagg_config_key

logger = logging.getLogger(__name__)


class ConditionalDisaggRouter:
    def __init__(
        self,
        drt,
        namespace: str,
        model: str,
        default: Optional[DisaggConfig] = None,
    ):
        self.drt = drt
        self.key = disagg_config_key(namespace, model)
        self.config = default or DisaggConfig()
        self._watch_task: Optional[asyncio.Task] = None
        self._watcher = None

    async def start(self) -> None:
        """Publish the current config if absent, then watch for updates."""
        try:
            created = self.drt.store.kv_create(
                self.key, self.config.to_json().encode()
            )
            if asyncio.iscoroutine(created):
                await created
        except Exception:  # noqa: BLE001 — already exists: adopt stored value
            logger.debug("disagg config kv_create raced", exc_info=True)
        entry = self.drt.store.kv_get(self.key)
        if asyncio.iscoroutine(entry):
            entry = await entry
        if entry is not None:
            self.config = DisaggConfig.from_json(entry.value)
        self._watcher = self.drt.store.watch_prefix(self.key)
        if asyncio.iscoroutine(self._watcher):
            self._watcher = await self._watcher
        self._watch_task = asyncio.get_running_loop().create_task(self._watch())

    async def stop(self) -> None:
        if self._watcher is not None:
            self._watcher.cancel()
        if self._watch_task is not None:
            self._watch_task.cancel()
            self._watch_task = None

    async def _watch(self) -> None:
        try:
            async for ev in self._watcher:
                if ev.kind.value == "put" and ev.value:
                    try:
                        self.config = DisaggConfig.from_json(ev.value)
                        logger.info("disagg config reloaded: %s", self.config)
                    except Exception:  # noqa: BLE001
                        logger.exception("bad disagg config at %s", self.key)
        except asyncio.CancelledError:
            pass

    async def update(self, config: DisaggConfig) -> None:
        put = self.drt.store.kv_put(self.key, config.to_json().encode())
        if asyncio.iscoroutine(put):
            await put
        self.config = config

    def prefill_remote(
        self, prefill_length: int, cached_prefix: int, queue_depth: int
    ) -> bool:
        """True → offload. ``prefill_length`` is the prompt length,
        ``cached_prefix`` the tokens already resident in the decode
        worker's prefix cache (only the remainder costs compute)."""
        cfg = self.config
        if not cfg.enabled:
            return False
        effective = prefill_length - cached_prefix
        if effective <= cfg.max_local_prefill_length:
            return False
        if cfg.max_prefill_queue_size and queue_depth >= cfg.max_prefill_queue_size:
            return False
        return True

"""Prefill work queue.

Thin typed wrapper over the bus work-queue (ack + visibility-timeout
redelivery), mirroring the reference's JetStream-backed PrefillQueue
(examples/llm/utils/{prefill_queue,nats_queue}.py). If a prefill worker
dies mid-request the item redelivers to another worker — elastic xPyD
(docs/disagg_serving.md:93-101)."""

from __future__ import annotations

from typing import Optional

from .protocols import RemotePrefillRequest

QUEUE_NAME = "prefill_queue"


class PrefillQueue:
    def __init__(self, bus, namespace: str = "dynamo", redeliver_after: float = 60.0):
        self.name = f"{namespace}.{QUEUE_NAME}"
        self._q = bus.work_queue(self.name, redeliver_after=redeliver_after)
        self._deliveries: dict[int, int] = {}

    async def enqueue(self, req: RemotePrefillRequest) -> int:
        r = self._q.push(req.to_bytes())
        if hasattr(r, "__await__"):
            r = await r
        return r

    async def dequeue(
        self, timeout: Optional[float] = None
    ) -> Optional[tuple[int, RemotePrefillRequest]]:
        item = await self._q.pop(timeout)
        if item is None:
            return None
        # keep a bounded map of delivery counts for poison-pill cutoffs
        if len(self._deliveries) > 4096:
            self._deliveries.clear()
        self._deliveries[item.id] = item.deliveries
        return item.id, RemotePrefillRequest.from_bytes(item.payload)

    def deliveries(self, item_id: int) -> int:
        return self._deliveries.get(item_id, 1)

    async def ack(self, item_id: int) -> bool:
        r = self._q.ack(item_id)
        if hasattr(r, "__await__"):
            r = await r
        return r

    async def nack(self, item_id: int) -> bool:
        r = self._q.nack(item_id)
        if hasattr(r, "__await__"):
            r = await r
        return r

    async def get_depth(self) -> int:
        d = self._q.depth
        if callable(d):  # remote hub queue: depth is an RPC
            d = await d()
        self.last_depth = d
        return d

    # depth snapshot for sync decision paths; refreshed by get_depth()
    last_depth: int = 0

    @property
    def depth(self) -> int:
        d = self._q.depth
        if callable(d):
            return self.last_depth
        return d

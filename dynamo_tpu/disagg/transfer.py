"""KV block transfer plane — the TPU-native stand-in for NIXL RDMA
(ref patch:811-1216 nixl.py, utils/nixl.py, docs/disagg_serving.md:58-91).

XLA exposes no one-sided remote writes, so the protocol is inverted into
a push stream. Two wire flavors share the framing (runtime two-part
codec — header JSON + raw bytes, same as the response plane):

* **bulk** (legacy, ``send_kv_blocks``): the prefill worker gathers the
  whole [L, Hkv, n, bs, D] stack after prefill completes and ships it
  layer-chunked — frame i carries layers [i*c, (i+1)*c) of both K and V
  so the wire transfer of layer chunk i overlaps the serialization of
  chunk i+1 (the overlap the reference gets from per-layer CUDA-stream
  triggered copies, kv/layer.rs:619-1132).
* **streamed** (``KvStreamSender``): the connection opens at prefill
  *start* (header declares the total geometry), and each prefill
  chunk's freshly computed blocks ship as a ``(b0, n)`` segment — still
  layer-chunked within the segment — the moment the chunk's compute
  finishes, so the transfer hides behind the remaining prefill compute
  (FlowKV, PAPERS.md). The final frame carries ``first_token`` /
  ``first_lp``; ONE end-to-end ack covers the whole stream, so the
  prefill queue's ack/redeliver semantics (resilience PR 4) are
  untouched: any mid-stream failure means no ack, and the sender
  redelivers from scratch (segment re-scatters are idempotent — the
  decode blocks are pre-allocated and uncommitted until admission).

The decode side either scatters segments incrementally through a
registered **sink** (DisaggEngine wires the engine's paged-cache
scatter) or — when no sink is registered, the sink declines (kv-head
layout / tp mismatch needs the full-stack ``kv_rearrange`` regroup), or
the peer still speaks bulk — falls back to assembling the full stack
exactly like the legacy path.

In-process prefill→decode (both engines in one process, e.g. two meshes
on one host) short-circuits through ``LocalKvPipe`` — the same streamed
semantics, but the segments are device-resident jax.Arrays handed
straight to the decode scatter: zero serialization, zero host hops.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..runtime.codec import (
    TwoPartMessage,
    read_frame,
    write_frame,
    write_frame_parts,
)
from ..runtime.tcp import ConnectionInfo

# shared with the disk-tier codec (utils/dtypes.py) so the two
# serialization planes can't drift on which dtypes round-trip
from ..utils.dtypes import np_dtype as _np_dtype

logger = logging.getLogger(__name__)

#: streamed-protocol version declared in the stream header. Receivers
#: ignore header keys they don't know (codec forward-compat contract),
#: and senders only stream when the decode side advertised the
#: capability in its connection info — an old decode peer never sees a
#: streamed header, and an old sender's bulk header still decodes here.
#: v2 adds the quantized-KV scale frames (``kv_quant`` header +
#: per-frame ``ks``/``vs`` scale slices); quantized payloads are
#: additionally gated on the receiver's explicit ``kv_quant``
#: capability key, so a v1/v2 skew alone never changes the bytes.
KV_STREAM_VERSION = 2

#: minimum peer version the streamed protocol itself requires: the v2
#: frame layout without scale frames IS the v1 layout, so a v1 peer
#: still takes full-width streams — only the quantized wire shape
#: needs v2 + the capability key. Downgrade checks compare against
#: this, not KV_STREAM_VERSION (or every version bump would silently
#: demote the whole fleet to bulk for an upgrade window).
KV_STREAM_BASE_VERSION = 1

#: wire codec capability key (connection info / KvPeerFetchRequest):
#: a receiver advertising ``{"kv_quant": 1}`` accepts int8/fp8 block
#: payloads + scale frames and dequantizes on landing; senders MUST
#: ship full-width bytes to peers that don't advertise it.
KV_QUANT_WIRE_VERSION = 1


class TransferError(Exception):
    """KV push failed or was not acknowledged — the queue item should be
    redelivered (nack), not treated as delivered."""


class SinkClosed(Exception):
    """The decode side abandoned the request while a stream was landing —
    remaining segments are drained and discarded, not an error."""




@dataclass
class KvDelivery:
    """What the decode side receives for one remote-prefilled request."""

    request_id: str
    first_token: int
    n_blocks: int
    # [L, Hkv, n_blocks, bs, D] host arrays (None when n_blocks == 0)
    k_data: Optional[np.ndarray]
    v_data: Optional[np.ndarray]
    error: Optional[str] = None
    # sender's kv-head ordering — the decode side regroups on mismatch
    # (ops/kv_rearrange.py; ref vllm patch:743-810 kv_rearrange)
    head_layout: str = "blocked"
    src_tp: int = 1
    # first token's logprob entry ({"logprob": f, "top": [[id, lp], ...]})
    # when the request asked for logprobs — computed where the logits are
    # (the prefill worker) and carried with the KV
    first_lp: Optional[dict] = None
    # True when the KV already landed incrementally through a stream
    # sink — k_data/v_data are None and the decode side must NOT expect
    # a bulk stack to scatter
    streamed: bool = False
    # chained seq hashes of the shipped blocks, prompt order (fleet
    # prefix-cache pulls: the peer may serve a shorter run than asked,
    # so the puller must know WHICH hashes the stack carries); None on
    # the disagg handoff, whose block identity is the reservation's
    hashes: Optional[list] = None
    # quantized wire payload (engine/kvquant.py): codec mode + the
    # [L, n] f32 per-(layer, block) scale arrays. "none" = k_data/
    # v_data are full-width and the scale fields are None. Only sent
    # to receivers that advertised the kv_quant capability.
    kv_quant: str = "none"
    k_scales: Optional[np.ndarray] = None
    v_scales: Optional[np.ndarray] = None


class _StreamAssembler:
    """Per-attempt landing policy for one streamed handoff, shared by the
    TCP server and the in-process pipe. ``begin()`` decides the mode:

    * **sink** — the registered sink accepted (layouts match): every
      full-layer segment scatters into the decode cache the moment it
      lands; the final delivery carries no data.
    * **buffer** — no sink, or the sink declined (kv-head layout / tp
      mismatch still needs the full-stack regroup): segments accumulate
      and the delivery is bit-identical to the legacy bulk path.
    * **discard** — nobody is waiting (the decode side abandoned the
      request): frames are consumed and acked so the sender doesn't
      retry a transfer whose result nobody wants (bulk semantics).

    A redelivered stream gets a FRESH assembler (and a fresh
    ``sink.begin``), so a half-landed first attempt leaves no state —
    segment re-scatters target the same pre-allocated, uncommitted
    blocks and are idempotent.
    """

    def __init__(self, request_id: str, head: dict, sink, discard: bool):
        self.request_id = request_id
        self.head = head
        self.n = int(head.get("n_blocks") or 0)
        # quantized stream (tolerant read — absent = full-width): the
        # segments carry int8/fp8 payloads + per-frame scale slices
        self.kv_quant = str(head.get("kv_quant") or "none")
        self._candidate = sink
        self.sink = None
        self.discard = discard
        self.parts: list[tuple] = []
        self.segments = 0
        self.covered = 0

    async def begin(self) -> None:
        if self.discard:
            return
        if self._candidate is not None and await self._candidate.begin(self.head):
            self.sink = self._candidate

    async def add_segment(self, b0: int, k_seg, v_seg,
                          ks=None, vs=None) -> None:
        """One full-layer segment ([L, Hkv, nseg, bs, D] pair) starting at
        block offset ``b0`` within the shipped range. ``ks``/``vs``
        ([L, nseg] f32) ride along on quantized streams."""
        if self.discard:
            return
        if b0 != self.covered:
            # segments are emitted in block order; an out-of-order or
            # duplicate b0 could sum to n_blocks while leaving real
            # blocks uncovered (recycled KV committed with a clean ack)
            raise ConnectionError(
                f"kv stream segment out of order: b0={b0}, expected "
                f"{self.covered}"
            )
        if self.kv_quant != "none" and ks is None:
            # a stream that declared the codec but ships scale-less
            # frames is malformed: landing raw int8 as KV would commit
            # garbage with a clean ack — no-ack/redeliver instead
            raise ConnectionError("kv stream quantized segment without scales")
        self.segments += 1
        self.covered += int(k_seg.shape[2])
        if self.sink is not None:
            try:
                if ks is not None:
                    await self.sink.segment(b0, k_seg, v_seg, ks, vs)
                else:
                    # positional-compat: full-width streams keep the
                    # pre-quant sink signature
                    await self.sink.segment(b0, k_seg, v_seg)
            except SinkClosed:
                # abandoned mid-stream: drain the rest and ack, exactly
                # like the bulk path consumes a delivery nobody awaits
                self.sink = None
                self.discard = True
                self.parts.clear()
            return
        self.parts.append((b0, k_seg, v_seg, ks, vs))

    @staticmethod
    def _concat(parts: list):
        if len(parts) == 1:
            return parts[0]
        if isinstance(parts[0], np.ndarray):
            return np.concatenate(parts, axis=2)
        import jax.numpy as jnp  # device-resident segments (local pipe)

        return jnp.concatenate(parts, axis=2)

    def check_complete(self) -> None:
        """Before the ack: every declared block must have landed. An
        incomplete stream delivering would commit a reservation whose
        missing pages still hold a previous request's recycled KV — it
        must take the no-ack/redeliver path like every other malformed
        stream (same hazard class as the intra-segment layer-gap check)."""
        if self.discard:
            return
        if self.covered != self.n:
            raise ConnectionError(
                f"kv stream incomplete: {self.covered}/{self.n} blocks"
            )

    def delivery(self, fin: dict) -> KvDelivery:
        first_token = int(fin.get("first_token", -1))
        first_lp = fin.get("first_lp")
        head = self.head
        if self.sink is not None or self.n == 0:
            return KvDelivery(
                self.request_id, first_token, self.n, None, None,
                head_layout=head.get("head_layout", "blocked"),
                src_tp=head.get("src_tp", 1), first_lp=first_lp,
                streamed=self.sink is not None,
            )
        # add_segment enforced in-order contiguous b0, so parts are
        # already block-ordered
        k = self._concat([p[1] for p in self.parts])
        v = self._concat([p[2] for p in self.parts])
        ks = vs = None
        if self.kv_quant != "none":
            ks = np.concatenate([p[3] for p in self.parts], axis=1)
            vs = np.concatenate([p[4] for p in self.parts], axis=1)
        return KvDelivery(
            self.request_id, first_token, self.n, k, v,
            head_layout=head.get("head_layout", "blocked"),
            src_tp=head.get("src_tp", 1), first_lp=first_lp,
            kv_quant=self.kv_quant, k_scales=ks, v_scales=vs,
        )


class KvTransferServer:
    """Decode-side listener. ``expect(request_id)`` registers a pending
    delivery and returns (ConnectionInfo, future); the prefill worker
    connects back with the data (mirror of the response plane's
    connect-back handshake, tcp/server.rs:74). ``expect`` optionally
    registers a stream *sink* — streamed-protocol segments then scatter
    into the decode cache as they arrive instead of buffering the full
    stack."""

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        advertise_host: Optional[str] = None,
    ):
        self._host = host
        self._port = port
        self._advertise = advertise_host
        self._server: Optional[asyncio.AbstractServer] = None
        self._pending: dict[str, asyncio.Future] = {}
        self._sinks: dict[str, object] = {}

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self._host, self._port
        )
        self._port = self._server.sockets[0].getsockname()[1]

    @property
    def address(self) -> ConnectionInfo:
        """The ADVERTISED address, shipped to prefill workers — must be
        routable from their hosts, not the bind address (which may be
        0.0.0.0)."""
        host = self._advertise
        if not host:
            host = self._host
            if host in ("0.0.0.0", "::"):
                import socket

                host = socket.gethostbyname(socket.gethostname())
        return ConnectionInfo(f"{host}:{self._port}", "kv")

    async def close(self) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.cancel()
        self._pending.clear()
        self._sinks.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def expect(self, request_id: str, sink=None) -> asyncio.Future:
        fut = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        if sink is not None:
            self._sinks[request_id] = sink
        return fut

    def abandon(self, request_id: str) -> None:
        self._sinks.pop(request_id, None)
        fut = self._pending.pop(request_id, None)
        if fut is not None and not fut.done():
            fut.cancel()

    def _resolve(self, request_id: str) -> Optional[asyncio.Future]:
        self._sinks.pop(request_id, None)
        return self._pending.pop(request_id, None)

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        fut: Optional[asyncio.Future] = None
        try:
            frame = await read_frame(reader)
            if frame is None:
                return
            head = json.loads(frame.header)
            # tolerant read + explicit validation (codec forward-compat
            # contract): a peer whose header schema drifted must surface
            # as a clean protocol error -> no-ack redelivery, never a
            # KeyError mid-decode
            req_id = head.get("request_id")
            if not req_id:
                raise ConnectionError(f"kv transfer header missing request_id: {head}")
            # look up (don't pop) — on a mid-stream failure the future must
            # stay pending so the sender's redelivery retry can complete it
            fut = self._pending.get(req_id)
            if head.get("error"):
                writer.write(b"ok")
                await writer.drain()
                fut = self._resolve(req_id)
                if fut is not None and not fut.done():
                    fut.set_result(
                        KvDelivery(req_id, -1, 0, None, None, error=head["error"])
                    )
                return
            if head.get("stream"):
                await self._handle_stream(reader, writer, head)
                return
            n = int(head.get("n_blocks") or 0)
            shape = tuple(head.get("shape") or ())  # [L, Hkv, n, bs, D]
            # MLA latent caches: k and v stacks have different trailing
            # dims, so the v shape rides its own header field and the
            # per-chunk blob splits at the k part's byte length
            v_shape = tuple(head.get("v_shape") or shape)
            if n and (
                len(shape) != 5 or len(v_shape) != 5
                or not head.get("dtype")
            ):
                # the protocol's stacks are rank-5 [L, Hkv, n, bs, D] —
                # a drifted rank would otherwise allocate garbage
                # geometry and could ack it
                raise ConnectionError(
                    f"kv transfer header missing geometry: {head}"
                )
            if shape[2:3] and int(shape[2]) != n:
                # a drifted header (n_blocks renamed/absent) must NOT
                # read as a legitimate zero-block delivery — acking a
                # real transfer as empty would hand the decode side a
                # phantom prefix hit. The block dim of the shape is the
                # cross-check: disagree -> protocol error -> redelivery
                raise ConnectionError(
                    f"kv transfer header geometry mismatch: {head}"
                )
            # resolve lazily: a zero-block delivery (full prefix hit on
            # the decode side) ships dtype "" — resolving it eagerly
            # crashed the receiver into a redelivery loop (dynflow
            # header-plane finding)
            dt = _np_dtype(head["dtype"]) if n else None
            layer_chunk = int(head.get("layer_chunk") or 1)
            # quantized bulk delivery (tolerant read; absent = full
            # width): per-chunk frames carry their layers' [l1-l0, n]
            # scale slices in the frame header
            kv_quant = str(head.get("kv_quant") or "none") if n else "none"
            L = shape[0] if shape else 0
            k = np.empty(shape, dt) if n else None
            v = np.empty(v_shape, dt) if n else None
            ks = vs = None
            if kv_quant != "none":
                ks = np.empty((L, n), np.float32)
                vs = np.empty((L, n), np.float32)
            l0 = 0
            while l0 < L and n:
                part = await read_frame(reader)
                if part is None:
                    raise ConnectionError("kv stream truncated")
                l1 = min(l0 + layer_chunk, L)
                sub_k = (l1 - l0,) + shape[1:]
                sub_v = (l1 - l0,) + v_shape[1:]
                cnt_k, cnt_v = int(np.prod(sub_k)), int(np.prod(sub_v))
                # frombuffer with count/offset: no intermediate bytes
                # slice copies of multi-MB payloads
                k[l0:l1] = np.frombuffer(part.data, dt, cnt_k).reshape(sub_k)
                v[l0:l1] = np.frombuffer(
                    part.data, dt, cnt_v, offset=cnt_k * dt.itemsize
                ).reshape(sub_v)
                if kv_quant != "none":
                    h = part.header_json() or {}
                    ks_sl, vs_sl = h.get("ks"), h.get("vs")
                    if ks_sl is None or vs_sl is None:
                        # a quantized delivery missing its scale slices
                        # must redeliver, never land raw int8 as KV
                        raise ConnectionError(
                            "kv transfer quantized chunk without scales"
                        )
                    # KB-sized [layers, n] scale slices — not the
                    # multi-MB payload class the rule guards
                    ks[l0:l1] = np.asarray(ks_sl, np.float32)  # dynlint: disable=async-blocking-call -- KB-sized scale slice, not a device buffer
                    vs[l0:l1] = np.asarray(vs_sl, np.float32)  # dynlint: disable=async-blocking-call -- KB-sized scale slice, not a device buffer
                l0 = l1
            writer.write(b"ok")
            await writer.drain()
            fut = self._resolve(req_id) or fut
            if fut is not None and not fut.done():
                fut.set_result(
                    KvDelivery(
                        req_id, head["first_token"], n, k, v,
                        head_layout=head.get("head_layout", "blocked"),
                        src_tp=head.get("src_tp", 1),
                        first_lp=head.get("first_lp"),
                        hashes=head.get("hashes"),
                        kv_quant=kv_quant, k_scales=ks, v_scales=vs,
                    )
                )
        except Exception:  # noqa: BLE001 — receive failed mid-stream: no
            # ack is sent, the sender sees a TransferError and redelivers;
            # the pending future survives for that retry (the decode side's
            # transfer_timeout is the terminal backstop)
            logger.exception("kv transfer receive failed; awaiting redelivery")
        finally:
            writer.close()
            try:
                # actually release the socket before the handler returns —
                # under churn (redelivery storms) half-closed sockets
                # otherwise pile up until the fd limit
                await writer.wait_closed()
            except (OSError, ConnectionError):
                pass

    async def _handle_stream(self, reader, writer, head: dict) -> None:
        """Streamed-protocol receive: header already read. Unknown header
        keys are ignored (codec forward-compat contract) so a newer
        sender's extra fields never break this peer; a mid-stream failure
        sends no ack and leaves the pending future for the redelivery."""
        req_id = head.get("request_id")
        if not req_id:
            raise ConnectionError(f"kv stream header missing request_id: {head}")
        fut = self._pending.get(req_id)
        sink = self._sinks.get(req_id)
        asm = _StreamAssembler(
            req_id, head, sink, discard=fut is None or fut.done()
        )
        await asm.begin()
        n = asm.n
        shape = tuple(head.get("shape") or ())
        v_shape = tuple(head.get("v_shape") or shape)
        if n and (
            len(shape) != 5 or len(v_shape) != 5 or not head.get("dtype")
        ):
            # rank-5 [L, Hkv, n, bs, D] or it is not our schema
            raise ConnectionError(f"kv stream header missing geometry: {head}")
        if shape[2:3] and int(shape[2]) != n:
            # same drift cross-check as the bulk path: n_blocks and the
            # shape's block dim must agree or this is not our schema
            raise ConnectionError(
                f"kv stream header geometry mismatch: {head}"
            )
        dt = _np_dtype(head["dtype"]) if n else None
        L = shape[0] if shape else 0
        quant = asm.kv_quant != "none"
        seg_b0, seg_filled = -1, 0
        seg_k = seg_v = seg_ks = seg_vs = None
        fin: Optional[dict] = None
        # read-ahead: the NEXT frame's socket read + deserialize overlap
        # the current segment's scatter, so the receiver never serializes
        # wire time behind device time (this is the decode-side half of
        # the stream's exposed tail)
        pending = asyncio.ensure_future(read_frame(reader))
        try:
            while fin is None:
                part = await pending
                if part is None:
                    raise ConnectionError("kv stream truncated")
                h = part.header_json() or {}
                if h.get("fin"):
                    fin = h
                    break
                pending = asyncio.ensure_future(read_frame(reader))
                if asm.discard:
                    # nobody is waiting: consume frames to reach fin/ack
                    # without paying the decode copies
                    continue
                # tolerant read + explicit validation: a peer whose frame
                # schema drifted must surface as a clean protocol error
                # (-> no-ack redelivery), not a KeyError mid-decode
                b0, ns, l0, l1 = (h.get("b0"), h.get("n"),
                                  h.get("l0"), h.get("l1"))
                if None in (b0, ns, l0, l1):
                    raise ConnectionError(
                        f"kv stream frame missing segment geometry: {h}"
                    )
                if b0 != seg_b0:
                    if seg_k is not None and seg_filled != L:
                        raise ConnectionError("kv stream segment interleaved")
                    seg_b0, seg_filled = b0, 0
                    seg_k = np.empty((L, shape[1], ns) + shape[3:], dt)
                    seg_v = np.empty((L, v_shape[1], ns) + v_shape[3:], dt)
                    if quant:
                        seg_ks = np.empty((L, ns), np.float32)
                        seg_vs = np.empty((L, ns), np.float32)
                if l0 != seg_filled:
                    # a layer-range gap would silently land uninitialized
                    # np.empty rows in the decode cache
                    raise ConnectionError(
                        f"kv stream layer gap: got [{l0},{l1}) at fill "
                        f"{seg_filled}"
                    )
                sub_k = (l1 - l0, shape[1], ns) + shape[3:]
                sub_v = (l1 - l0, v_shape[1], ns) + v_shape[3:]
                cnt_k, cnt_v = int(np.prod(sub_k)), int(np.prod(sub_v))
                # frombuffer with count/offset: no intermediate bytes
                # slice copies of multi-MB payloads on the hot path
                seg_k[l0:l1] = np.frombuffer(
                    part.data, dt, cnt_k
                ).reshape(sub_k)
                seg_v[l0:l1] = np.frombuffer(
                    part.data, dt, cnt_v, offset=cnt_k * dt.itemsize
                ).reshape(sub_v)
                if quant:
                    ks_sl, vs_sl = h.get("ks"), h.get("vs")
                    if ks_sl is None or vs_sl is None:
                        # a declared-quantized stream shipping scale-less
                        # frames must redeliver, never land raw int8
                        raise ConnectionError(
                            "kv stream quantized frame without scales"
                        )
                    seg_ks[l0:l1] = np.asarray(ks_sl, np.float32)  # dynlint: disable=async-blocking-call -- KB-sized scale slice, not a device buffer
                    seg_vs[l0:l1] = np.asarray(vs_sl, np.float32)  # dynlint: disable=async-blocking-call -- KB-sized scale slice, not a device buffer
                seg_filled = l1
                if l1 == L:
                    await asm.add_segment(b0, seg_k, seg_v, seg_ks, seg_vs)
                    seg_k = seg_v = seg_ks = seg_vs = None
        finally:
            if not pending.done():
                pending.cancel()
        if seg_k is not None and seg_filled != L:
            raise ConnectionError("kv stream ended mid-segment")
        asm.check_complete()
        writer.write(b"ok")
        await writer.drain()
        fut = self._resolve(req_id) or fut
        if fut is not None and not fut.done():
            fut.set_result(asm.delivery(fin))


async def send_kv_blocks(
    connection: ConnectionInfo | dict,
    request_id: str,
    first_token: int,
    k_data: Optional[np.ndarray],
    v_data: Optional[np.ndarray],
    layer_chunk: int = 4,
    error: Optional[str] = None,
    head_layout: str = "blocked",
    src_tp: int = 1,
    first_lp: Optional[dict] = None,
    hashes: Optional[list] = None,
    kv_quant: str = "none",
    k_scales: Optional[np.ndarray] = None,
    v_scales: Optional[np.ndarray] = None,
) -> None:
    """Prefill-side push of one request's KV (or an error notification).
    ``hashes`` names the shipped blocks' chained seq hashes for
    content-addressed deliveries (fleet prefix-cache pulls); receivers
    that don't know the key ignore it (codec forward-compat).
    ``kv_quant`` + ``k_scales``/``v_scales`` ([L, n] f32) ship a
    quantized payload — callers must have checked the receiver's
    ``kv_quant`` capability first (legacy peers get dequantized
    full-width bytes, never a stream they can't decode)."""
    if isinstance(connection, dict):
        connection = ConnectionInfo.from_dict(connection)
    host, port = connection.address.rsplit(":", 1)
    try:
        reader, writer = await asyncio.open_connection(host, int(port))
    except OSError as e:
        raise TransferError(f"connect to {connection.address} failed: {e}") from e
    try:
        n = 0 if k_data is None else int(k_data.shape[2])
        head = {
            "request_id": request_id,
            "first_token": int(first_token),
            "n_blocks": n,
            "shape": [] if k_data is None else list(k_data.shape),
            "v_shape": [] if v_data is None else list(v_data.shape),
            "dtype": "" if k_data is None else str(k_data.dtype),
            "layer_chunk": layer_chunk,
            "error": error,
            "head_layout": head_layout,
            "src_tp": src_tp,
            "first_lp": first_lp,
        }
        if hashes is not None:
            head["hashes"] = list(hashes)
        if n and kv_quant != "none":
            head["kv_quant"] = kv_quant
        await write_frame(writer, TwoPartMessage(json.dumps(head).encode(), b""))
        if n:
            L = k_data.shape[0]
            k_data = np.ascontiguousarray(k_data)
            v_data = np.ascontiguousarray(v_data)
            for l0 in range(0, L, layer_chunk):
                l1 = min(l0 + layer_chunk, L)
                fh = b""
                if kv_quant != "none":
                    # this chunk's layers' scale slices ride the frame
                    # header (f32 -> float round-trips exactly in JSON;
                    # KB-sized, unlike the payload views below)
                    fh = json.dumps({
                        "ks": np.asarray(  # dynlint: disable=async-blocking-call -- KB-sized scale slice, not a device buffer
                            k_scales[l0:l1], np.float32).tolist(),
                        "vs": np.asarray(  # dynlint: disable=async-blocking-call -- KB-sized scale slice, not a device buffer
                            v_scales[l0:l1], np.float32).tolist(),
                    }).encode()
                # zero-copy buffer views, and write_frame_parts drains
                # PER FRAME: the sender paces itself to the socket's
                # high-water mark instead of staging the whole multi-GB
                # stack through tobytes copies before the first drain
                await write_frame_parts(
                    writer, fh, (k_data[l0:l1], v_data[l0:l1])
                )
        await writer.drain()
        # require the receiver's ack — anything else (EOF from a mid-stream
        # receive failure) must surface as a retriable error, or the caller
        # would ack the queue item for a transfer that never landed
        ack = await asyncio.wait_for(reader.readexactly(2), timeout=30.0)
        if ack != b"ok":
            raise TransferError(f"receiver did not acknowledge (got {ack!r})")
    except (OSError, asyncio.TimeoutError, asyncio.IncompleteReadError) as e:
        raise TransferError(str(e)) from e
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except (OSError, ConnectionError):
            pass


class KvStreamSender:
    """Prefill-side streamed push: opened at prefill START, fed one
    segment per completed prefill chunk, finished with the sampled first
    token. Segment frames are layer-chunked with a per-frame drain
    (backpressure against the socket, bounded userspace buffering); the
    single end-to-end ack arrives in :meth:`finish`."""

    def __init__(self, reader, writer, request_id: str, head: dict):
        self._reader = reader
        self._writer = writer
        self.request_id = request_id
        self._layers = int(head["shape"][0]) if head.get("shape") else 0
        self._layer_chunk = max(int(head.get("layer_chunk") or 1), 1)
        self.segments = 0

    @classmethod
    async def open(
        cls, connection: ConnectionInfo | dict, request_id: str, head: dict
    ) -> "KvStreamSender":
        """Connect and ship the geometry header. ``head`` must carry
        request_id/stream/n_blocks/shape/v_shape/dtype/layer_chunk plus
        the sender's head_layout/src_tp."""
        if isinstance(connection, dict):
            connection = ConnectionInfo.from_dict(connection)
        host, port = connection.address.rsplit(":", 1)
        try:
            reader, writer = await asyncio.open_connection(host, int(port))
        except OSError as e:
            raise TransferError(
                f"connect to {connection.address} failed: {e}"
            ) from e
        sender = cls(reader, writer, request_id, head)
        try:
            await write_frame(
                writer, TwoPartMessage(json.dumps(head).encode(), b"")
            )
        except (OSError, ConnectionError) as e:
            await sender.aclose()
            raise TransferError(str(e)) from e
        return sender

    async def send_segment(self, b0: int, k_seg: np.ndarray, v_seg: np.ndarray,
                           ks: Optional[np.ndarray] = None,
                           vs: Optional[np.ndarray] = None) -> None:
        """Ship one segment (host arrays [L, Hkv, nseg, bs, D]) starting
        at block offset ``b0`` within the shipped range. Layer-chunk
        slices go to the socket as zero-copy buffer views — no
        ``tobytes`` staging copy, which would double the sender's memory
        traffic per segment. ``ks``/``vs`` ([L, nseg] f32, quantized
        streams only) ride each frame's header as that chunk's layers'
        scale slices."""
        ns = int(k_seg.shape[2])
        k_seg = np.ascontiguousarray(k_seg)
        v_seg = np.ascontiguousarray(v_seg)
        try:
            for l0 in range(0, self._layers, self._layer_chunk):
                l1 = min(l0 + self._layer_chunk, self._layers)
                h = {"b0": b0, "n": ns, "l0": l0, "l1": l1}
                if ks is not None:
                    h["ks"] = np.asarray(ks[l0:l1], np.float32).tolist()  # dynlint: disable=async-blocking-call -- KB-sized scale slice, not a device buffer
                    h["vs"] = np.asarray(vs[l0:l1], np.float32).tolist()  # dynlint: disable=async-blocking-call -- KB-sized scale slice, not a device buffer
                await write_frame_parts(
                    self._writer, json.dumps(h).encode(),
                    (k_seg[l0:l1], v_seg[l0:l1]),
                )
            self.segments += 1
        except (OSError, ConnectionError) as e:
            raise TransferError(str(e)) from e

    async def finish(
        self,
        first_token: int,
        first_lp: Optional[dict] = None,
        ack_timeout: float = 30.0,
    ) -> None:
        """Fin frame + the stream's single end-to-end ack."""
        try:
            fin = {"fin": 1, "first_token": int(first_token), "first_lp": first_lp}
            await write_frame(
                self._writer, TwoPartMessage(json.dumps(fin).encode(), b"")
            )
            ack = await asyncio.wait_for(
                self._reader.readexactly(2), timeout=ack_timeout
            )
            if ack != b"ok":
                raise TransferError(f"receiver did not acknowledge (got {ack!r})")
        except (OSError, asyncio.TimeoutError, asyncio.IncompleteReadError,
                ConnectionError) as e:
            raise TransferError(str(e)) from e
        finally:
            await self.aclose()

    async def aclose(self) -> None:
        self._writer.close()
        try:
            await self._writer.wait_closed()
        except (OSError, ConnectionError):
            pass


class LocalKvPipe:
    """In-process transfer: prefill and decode engines share the process
    (two meshes / two engines on one slice) — the arrays handed over are
    jax.Arrays still resident in HBM (prefill_extract keep_on_device), so
    the whole gather -> deliver -> scatter path is device-to-device with
    zero host copies. TCP (send_kv_blocks) is the cross-DCN fallback.

    ``open_stream`` is the streamed equivalent: per-chunk device arrays
    hand straight to the decode engine's donated scatter (through the
    registered sink), so same-slice disagg never leaves HBM AND never
    serializes on prefill completion."""

    def __init__(self):
        self._pending: dict[str, asyncio.Future] = {}
        self._sinks: dict[str, object] = {}

    def expect(self, request_id: str, sink=None) -> asyncio.Future:
        fut = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        if sink is not None:
            self._sinks[request_id] = sink
        return fut

    def abandon(self, request_id: str) -> None:
        self._sinks.pop(request_id, None)
        fut = self._pending.pop(request_id, None)
        if fut is not None and not fut.done():
            fut.cancel()

    async def deliver(
        self,
        request_id: str,
        first_token: int,
        k_data: Optional[np.ndarray],
        v_data: Optional[np.ndarray],
        error: Optional[str] = None,
        head_layout: str = "blocked",
        src_tp: int = 1,
        first_lp: Optional[dict] = None,
    ) -> None:
        self._sinks.pop(request_id, None)
        fut = self._pending.pop(request_id, None)
        if fut is None or fut.done():
            return
        n = 0 if k_data is None else int(k_data.shape[2])
        fut.set_result(
            KvDelivery(
                request_id, first_token, n, k_data, v_data, error,
                head_layout=head_layout, src_tp=src_tp, first_lp=first_lp,
            )
        )

    async def open_stream(self, request_id: str, head: dict) -> "LocalKvStream":
        """Streamed in-process handoff: same assembler policy as the TCP
        server (sink scatter / buffered bulk fallback / discard), zero
        serialization — segments are whatever arrays the caller holds
        (device-resident under keep_on_device)."""
        fut = self._pending.get(request_id)
        sink = self._sinks.get(request_id)
        asm = _StreamAssembler(
            request_id, head, sink, discard=fut is None or fut.done()
        )
        await asm.begin()
        return LocalKvStream(self, request_id, asm)


class LocalKvStream:
    """One streamed handoff over the in-process pipe (KvStreamSender's
    zero-copy twin): ``segment()`` per completed prefill chunk, then
    ``finish()`` resolves the decode side's delivery future."""

    def __init__(self, pipe: LocalKvPipe, request_id: str, asm: _StreamAssembler):
        self._pipe = pipe
        self.request_id = request_id
        self._asm = asm
        self.segments = 0

    async def send_segment(self, b0: int, k_seg, v_seg,
                           ks=None, vs=None) -> None:
        # the in-process pipe never quantizes (its segments stay
        # device-resident — quantizing would ADD work, not save wire)
        await self._asm.add_segment(b0, k_seg, v_seg, ks, vs)
        self.segments += 1

    async def finish(self, first_token: int, first_lp: Optional[dict] = None) -> None:
        try:
            self._asm.check_complete()
        except ConnectionError as e:
            # leave the decode future pending for the redelivery, exactly
            # like a TCP truncation — the sender must nack, not ack
            raise TransferError(str(e)) from e
        self._pipe._sinks.pop(self.request_id, None)
        fut = self._pipe._pending.pop(self.request_id, None)
        if fut is None or fut.done():
            return
        fut.set_result(
            self._asm.delivery({"first_token": first_token, "first_lp": first_lp})
        )

    async def aclose(self) -> None:
        """Abort: nothing to tear down — the decode side's future stays
        pending for the queue redelivery, mirroring a TCP truncation."""

"""KV block transfer plane — the TPU-native stand-in for NIXL RDMA
(ref patch:811-1216 nixl.py, utils/nixl.py, docs/disagg_serving.md:58-91).

XLA exposes no one-sided remote writes, so the protocol is inverted into
a push stream: the prefill worker gathers the computed KV blocks on
device ([L, Hkv, n, bs, D] stacks, one d2h fetch), then ships them over
a TCP connection to the decode host **layer-chunked** — frame i carries
layers [i*c, (i+1)*c) of both K and V — so the wire transfer of layer
chunk i overlaps the serialization of chunk i+1, the same overlap the
reference gets from per-layer CUDA-stream triggered copies
(kv/layer.rs:619-1132). The decode side reassembles and scatters into
its own paged cache with a donated jit scatter.

Frames use the runtime's two-part codec (header JSON + raw bytes), the
same framing as the response plane. In-process prefill→decode (both
engines in one process, e.g. two meshes on one host) short-circuits
through ``LocalKvPipe`` — no serialization at all.
"""

from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..runtime.codec import TwoPartMessage, read_frame, write_frame
from ..runtime.tcp import ConnectionInfo

logger = logging.getLogger(__name__)


class TransferError(Exception):
    """KV push failed or was not acknowledged — the queue item should be
    redelivered (nack), not treated as delivered."""


_DTYPES = {}


def _np_dtype(name: str):
    """dtype registry incl. bfloat16 (ml_dtypes ships with jax)."""
    if not _DTYPES:
        import ml_dtypes

        _DTYPES.update(
            {
                "bfloat16": np.dtype(ml_dtypes.bfloat16),
                "float32": np.dtype(np.float32),
                "float16": np.dtype(np.float16),
                "float8_e4m3fn": np.dtype(ml_dtypes.float8_e4m3fn),
                "int8": np.dtype(np.int8),
            }
        )
    return _DTYPES[name]


@dataclass
class KvDelivery:
    """What the decode side receives for one remote-prefilled request."""

    request_id: str
    first_token: int
    n_blocks: int
    # [L, Hkv, n_blocks, bs, D] host arrays (None when n_blocks == 0)
    k_data: Optional[np.ndarray]
    v_data: Optional[np.ndarray]
    error: Optional[str] = None
    # sender's kv-head ordering — the decode side regroups on mismatch
    # (ops/kv_rearrange.py; ref vllm patch:743-810 kv_rearrange)
    head_layout: str = "blocked"
    src_tp: int = 1
    # first token's logprob entry ({"logprob": f, "top": [[id, lp], ...]})
    # when the request asked for logprobs — computed where the logits are
    # (the prefill worker) and carried with the KV
    first_lp: Optional[dict] = None


class KvTransferServer:
    """Decode-side listener. ``expect(request_id)`` registers a pending
    delivery and returns (ConnectionInfo, future); the prefill worker
    connects back with the data (mirror of the response plane's
    connect-back handshake, tcp/server.rs:74)."""

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        advertise_host: Optional[str] = None,
    ):
        self._host = host
        self._port = port
        self._advertise = advertise_host
        self._server: Optional[asyncio.AbstractServer] = None
        self._pending: dict[str, asyncio.Future] = {}

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self._host, self._port
        )
        self._port = self._server.sockets[0].getsockname()[1]

    @property
    def address(self) -> ConnectionInfo:
        """The ADVERTISED address, shipped to prefill workers — must be
        routable from their hosts, not the bind address (which may be
        0.0.0.0)."""
        host = self._advertise
        if not host:
            host = self._host
            if host in ("0.0.0.0", "::"):
                import socket

                host = socket.gethostbyname(socket.gethostname())
        return ConnectionInfo(f"{host}:{self._port}", "kv")

    async def close(self) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.cancel()
        self._pending.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def expect(self, request_id: str) -> asyncio.Future:
        fut = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        return fut

    def abandon(self, request_id: str) -> None:
        fut = self._pending.pop(request_id, None)
        if fut is not None and not fut.done():
            fut.cancel()

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        fut: Optional[asyncio.Future] = None
        try:
            frame = await read_frame(reader)
            if frame is None:
                return
            head = json.loads(frame.header)
            req_id = head["request_id"]
            # look up (don't pop) — on a mid-stream failure the future must
            # stay pending so the sender's redelivery retry can complete it
            fut = self._pending.get(req_id)
            if head.get("error"):
                self._pending.pop(req_id, None)
                writer.write(b"ok")
                await writer.drain()
                if fut is not None and not fut.done():
                    fut.set_result(
                        KvDelivery(req_id, -1, 0, None, None, error=head["error"])
                    )
                return
            n = head["n_blocks"]
            shape = tuple(head["shape"])  # [L, Hkv, n, bs, D]
            # MLA latent caches: k and v stacks have different trailing
            # dims, so the v shape rides its own header field and the
            # per-chunk blob splits at the k part's byte length
            v_shape = tuple(head.get("v_shape") or shape)
            dt = _np_dtype(head["dtype"])
            layer_chunk = head["layer_chunk"]
            L = shape[0]
            k = np.empty(shape, dt) if n else None
            v = np.empty(v_shape, dt) if n else None
            l0 = 0
            while l0 < L and n:
                part = await read_frame(reader)
                if part is None:
                    raise ConnectionError("kv stream truncated")
                l1 = min(l0 + layer_chunk, L)
                blob = part.data
                sub_k = (l1 - l0,) + shape[1:]
                sub_v = (l1 - l0,) + v_shape[1:]
                k_bytes = int(np.prod(sub_k)) * dt.itemsize
                k[l0:l1] = np.frombuffer(blob[:k_bytes], dt).reshape(sub_k)
                v[l0:l1] = np.frombuffer(blob[k_bytes:], dt).reshape(sub_v)
                l0 = l1
            writer.write(b"ok")
            await writer.drain()
            self._pending.pop(req_id, None)
            if fut is not None and not fut.done():
                fut.set_result(
                    KvDelivery(
                        req_id, head["first_token"], n, k, v,
                        head_layout=head.get("head_layout", "blocked"),
                        src_tp=head.get("src_tp", 1),
                        first_lp=head.get("first_lp"),
                    )
                )
        except Exception:  # noqa: BLE001 — receive failed mid-stream: no
            # ack is sent, the sender sees a TransferError and redelivers;
            # the pending future survives for that retry (the decode side's
            # transfer_timeout is the terminal backstop)
            logger.exception("kv transfer receive failed; awaiting redelivery")
        finally:
            writer.close()


async def send_kv_blocks(
    connection: ConnectionInfo | dict,
    request_id: str,
    first_token: int,
    k_data: Optional[np.ndarray],
    v_data: Optional[np.ndarray],
    layer_chunk: int = 4,
    error: Optional[str] = None,
    head_layout: str = "blocked",
    src_tp: int = 1,
    first_lp: Optional[dict] = None,
) -> None:
    """Prefill-side push of one request's KV (or an error notification)."""
    if isinstance(connection, dict):
        connection = ConnectionInfo.from_dict(connection)
    host, port = connection.address.rsplit(":", 1)
    try:
        reader, writer = await asyncio.open_connection(host, int(port))
    except OSError as e:
        raise TransferError(f"connect to {connection.address} failed: {e}") from e
    try:
        n = 0 if k_data is None else int(k_data.shape[2])
        head = {
            "request_id": request_id,
            "first_token": int(first_token),
            "n_blocks": n,
            "shape": [] if k_data is None else list(k_data.shape),
            "v_shape": [] if v_data is None else list(v_data.shape),
            "dtype": "" if k_data is None else str(k_data.dtype),
            "layer_chunk": layer_chunk,
            "error": error,
            "head_layout": head_layout,
            "src_tp": src_tp,
            "first_lp": first_lp,
        }
        await write_frame(writer, TwoPartMessage(json.dumps(head).encode(), b""))
        if n:
            L = k_data.shape[0]
            for l0 in range(0, L, layer_chunk):
                l1 = min(l0 + layer_chunk, L)
                blob = k_data[l0:l1].tobytes() + v_data[l0:l1].tobytes()
                await write_frame(
                    writer, TwoPartMessage(b"", blob)
                )
        await writer.drain()
        # require the receiver's ack — anything else (EOF from a mid-stream
        # receive failure) must surface as a retriable error, or the caller
        # would ack the queue item for a transfer that never landed
        ack = await asyncio.wait_for(reader.readexactly(2), timeout=30.0)
        if ack != b"ok":
            raise TransferError(f"receiver did not acknowledge (got {ack!r})")
    except (OSError, asyncio.TimeoutError, asyncio.IncompleteReadError) as e:
        raise TransferError(str(e)) from e
    finally:
        writer.close()


class LocalKvPipe:
    """In-process transfer: prefill and decode engines share the process
    (two meshes / two engines on one slice) — the arrays handed over are
    jax.Arrays still resident in HBM (prefill_extract keep_on_device), so
    the whole gather -> deliver -> scatter path is device-to-device with
    zero host copies. TCP (send_kv_blocks) is the cross-DCN fallback."""

    def __init__(self):
        self._pending: dict[str, asyncio.Future] = {}

    def expect(self, request_id: str) -> asyncio.Future:
        fut = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        return fut

    def abandon(self, request_id: str) -> None:
        fut = self._pending.pop(request_id, None)
        if fut is not None and not fut.done():
            fut.cancel()

    async def deliver(
        self,
        request_id: str,
        first_token: int,
        k_data: Optional[np.ndarray],
        v_data: Optional[np.ndarray],
        error: Optional[str] = None,
        head_layout: str = "blocked",
        src_tp: int = 1,
        first_lp: Optional[dict] = None,
    ) -> None:
        fut = self._pending.pop(request_id, None)
        if fut is None or fut.done():
            return
        n = 0 if k_data is None else int(k_data.shape[2])
        fut.set_result(
            KvDelivery(
                request_id, first_token, n, k_data, v_data, error,
                head_layout=head_layout, src_tp=src_tp, first_lp=first_lp,
            )
        )

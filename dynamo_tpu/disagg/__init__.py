"""Disaggregated prefill/decode serving (ref docs/disagg_serving.md:5-101).

The reference's flagship capability, rebuilt TPU-native:

* ``protocols``  — RemotePrefillRequest and the disagg config schema
  (ref vllm patch remote_prefill.py:3584-3645, disagg_router.rs:25).
* ``router``     — conditional disaggregation: local vs remote prefill
  decision from prompt length / prefix-hit / queue depth, with the
  config hot-reloaded from a control-plane store watch
  (ref lib/llm/src/disagg_router.rs:25-135, examples worker.py:151-171).
* ``queue``      — prefill work queue with ack + redelivery
  (ref examples/llm/utils/prefill_queue.py, JetStream work-queue).
* ``transfer``   — the KV data plane. No RDMA one-sided writes on TPU:
  prefill gathers the computed KV blocks on device, ships them
  layer-chunked over a TCP stream (two-part codec frames) to the decode
  host, which scatters them into its own paged cache (ref NIXL path,
  patch:811-1216; kv_rearrange for layout mismatch).
* ``worker``     — PrefillWorker (queue consumer) + DisaggEngine (the
  decode-side AsyncEngine that orchestrates remote prefill).
"""

from .protocols import DisaggConfig, RemotePrefillRequest
from .queue import PrefillQueue
from .router import ConditionalDisaggRouter
from .transfer import (
    KV_STREAM_VERSION,
    KvStreamSender,
    KvTransferServer,
    LocalKvPipe,
    TransferError,
    send_kv_blocks,
)
from .worker import DisaggEngine, PrefillWorker

__all__ = [
    "ConditionalDisaggRouter",
    "DisaggConfig",
    "DisaggEngine",
    "KV_STREAM_VERSION",
    "KvStreamSender",
    "KvTransferServer",
    "LocalKvPipe",
    "PrefillQueue",
    "PrefillWorker",
    "RemotePrefillRequest",
    "TransferError",
    "send_kv_blocks",
]

"""Disaggregated serving workers.

``DisaggEngine`` is the decode-side AsyncEngine: per request it consults
the ConditionalDisaggRouter; local prompts flow straight into the wrapped
JaxEngine, long prompts are pre-allocated (begin_remote), enqueued on the
PrefillQueue, and completed when the prefill worker's KV lands on the
transfer plane (ref examples/llm/components/worker.py:45-189).

``PrefillWorker`` is the queue consumer: prefill + first-token sample on
its own engine/mesh, then push the KV to the requesting decode host
(ref examples/llm/components/prefill_worker.py:84-141). Failures nack the
item so it redelivers to another worker — elastic xPyD
(docs/disagg_serving.md:93-101)."""

from __future__ import annotations

import asyncio
import logging
import time
from typing import AsyncIterator, Optional, Union

from .. import tracing
from ..engine.engine import JaxEngine, OutOfBlocks
from ..protocols.common import LLMEngineOutput, PreprocessedRequest
from ..resilience import faultpoints
from ..resilience.faultpoints import FaultInjected
from ..runtime.engine import AsyncEngine, AsyncEngineContext, Context
from .protocols import RemotePrefillRequest
from .queue import PrefillQueue
from .router import ConditionalDisaggRouter
from .transfer import KvTransferServer, LocalKvPipe, TransferError, send_kv_blocks

logger = logging.getLogger(__name__)


class PrefillWorker:
    def __init__(
        self,
        engine: JaxEngine,
        queue: PrefillQueue,
        local_pipe: Optional[LocalKvPipe] = None,
        layer_chunk: int = 4,
        head_layout: Optional[str] = None,
    ):
        self.engine = engine
        self.queue = queue
        self.local_pipe = local_pipe
        self.layer_chunk = layer_chunk
        # wire-declared kv-head ordering; override only when wrapping an
        # engine whose extraction really produces a non-natural order
        self.head_layout = head_layout or engine.cfg.kv_head_layout
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()
        self.stats = {"prefills_total": 0, "prefill_errors": 0, "nacks": 0}

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self.run())

    async def close(self) -> None:
        self._stop.set()
        if self._task is not None:
            self._task.cancel()
            self._task = None

    MAX_DELIVERIES = 5  # poison-pill cutoff: after this, fail the request

    async def run(self) -> None:
        while not self._stop.is_set():
            try:
                await self._run_once()
            except asyncio.CancelledError:
                return
            except FaultInjected:
                # harness kill: the consume loop DIES (no retry) — the
                # un-acked item redelivers to a surviving consumer, not
                # back to this one
                logger.warning("prefill worker killed by fault point")
                self._stop.set()
                return
            except Exception:  # noqa: BLE001 — transient bus/hub error:
                # the fleet must not silently lose a prefill consumer
                logger.exception("prefill consume loop error; retrying")
                await asyncio.sleep(0.5)

    async def _run_once(self) -> None:
        got = await self.queue.dequeue(timeout=0.5)
        if got is None:
            return
        item_id, rpr = got
        try:
            await self._process(rpr)
        except FaultInjected:
            # harness kill mid-processing: die like a real crash — no
            # ack, no nack, no error notification; the queue's
            # visibility timeout redelivers the item to a survivor
            raise
        except OutOfBlocks:
            # pool full: hand the item back for another worker (or
            # ourselves, once running prefills free their blocks)
            self.stats["nacks"] += 1
            await self.queue.nack(item_id)
            await asyncio.sleep(0.05)
            return
        except TransferError as e:
            # the KV never landed: retriable — unless this item has
            # already bounced enough to look like a dead decode host
            if self.queue.deliveries(item_id) < self.MAX_DELIVERIES:
                logger.warning("kv transfer failed (%s); redelivering", e)
                self.stats["nacks"] += 1
                await self.queue.nack(item_id)
                await asyncio.sleep(0.1)
                return
            logger.error("kv transfer failed %d times: %s", self.MAX_DELIVERIES, e)
            self.stats["prefill_errors"] += 1
            await self._notify_error(rpr, str(e))
        except Exception as e:  # noqa: BLE001 — a COMPUTE failure is
            # deterministic (bad request, model error): another worker
            # would fail identically, so notify the decode side and ack
            logger.exception("remote prefill failed: %s", rpr.request_id)
            self.stats["prefill_errors"] += 1
            await self._notify_error(rpr, str(e))
        # the WAL item is acked only here — AFTER the KV handoff
        # committed (or after a deterministic failure was delivered): a
        # worker killed anywhere above leaves the item in flight and the
        # prefill redelivers instead of silently dropping
        await self.queue.ack(item_id)

    async def _process(self, rpr: RemotePrefillRequest) -> None:
        req = PreprocessedRequest.from_dict(rpr.request)
        ctx = AsyncEngineContext(rpr.request_id)
        trace_token = None
        if tracing.enabled() and rpr.trace:
            # continue the decode side's trace across the queue handoff
            tc = tracing.TraceContext.for_request(rpr.request_id, rpr.trace)
            trace_token = tracing.set_trace(tc)
            if rpr.enqueue_ts:
                # queue wait reconstructed from the decode side's enqueue
                # stamp (cross-host wall clocks; see protocols.py)
                waited_s = max(time.time() - rpr.enqueue_ts, 0.0)
                tracing.RECORDER.record_span(
                    "prefill.queue_wait", tc, ts=rpr.enqueue_ts,
                    dur_ms=waited_s * 1e3, request_id=rpr.request_id,
                )
        try:
            # in-process pipe => same device slice: keep KV on device end to
            # end (gather -> pipe -> decode scatter, no host hop); the TCP
            # path needs host bytes anyway
            local = bool(rpr.connection.get("local")) and self.local_pipe is not None
            with tracing.span(
                "prefill.compute", request_id=rpr.request_id,
                prompt_tokens=len(req.token_ids), skip_blocks=rpr.skip_blocks,
            ):
                first, first_lp, k, v = await self.engine.prefill_extract(
                    req, ctx, skip_blocks=rpr.skip_blocks, keep_on_device=local
                )
            self.stats["prefills_total"] += 1
            layout = self.head_layout
            tp = self.engine.cfg.mesh.tp if self.engine.cfg.mesh else 1
            await faultpoints.hit("mid_kv_transfer", request_id=rpr.request_id)
            with tracing.span(
                "prefill.kv_send", request_id=rpr.request_id,
                local=bool(rpr.connection.get("local")),
            ):
                try:
                    if rpr.connection.get("local"):
                        assert self.local_pipe is not None, "local connection without pipe"
                        await self.local_pipe.deliver(
                            rpr.request_id, first, k, v, head_layout=layout, src_tp=tp,
                            first_lp=first_lp,
                        )
                    else:
                        await send_kv_blocks(
                            rpr.connection, rpr.request_id, first, k, v,
                            layer_chunk=self.layer_chunk, head_layout=layout, src_tp=tp,
                            first_lp=first_lp,
                        )
                except (TransferError, FaultInjected):
                    raise
                except Exception as e:  # noqa: BLE001 — ANY handoff-stage
                    # failure (connection reset writing the stream,
                    # serialization trouble) means the KV never committed
                    # on the decode side: it must redeliver like a
                    # TransferError, never ack-with-error (which would
                    # strand the decode side waiting out its full
                    # transfer timeout on a prefill nobody will redo)
                    raise TransferError(f"kv handoff failed: {e}") from e
        finally:
            if trace_token is not None:
                tracing.reset_trace(trace_token)

    async def _notify_error(self, rpr: RemotePrefillRequest, message: str) -> None:
        try:
            if rpr.connection.get("local"):
                if self.local_pipe is not None:
                    await self.local_pipe.deliver(
                        rpr.request_id, -1, None, None, error=message
                    )
            else:
                await send_kv_blocks(
                    rpr.connection, rpr.request_id, -1, None, None, error=message
                )
        except Exception:  # noqa: BLE001 — decode side also has a timeout
            logger.exception("error notification failed: %s", rpr.request_id)


class DisaggEngine(AsyncEngine):
    """Decode-side conditional-disaggregation front (AsyncEngine over
    PreprocessedRequest -> LLMEngineOutput stream)."""

    def __init__(
        self,
        engine: JaxEngine,
        router: ConditionalDisaggRouter,
        queue: PrefillQueue,
        transfer: Union[KvTransferServer, LocalKvPipe],
        engine_id: int = 0,
        transfer_timeout: float = 120.0,
    ):
        self.engine = engine
        self.router = router
        self.queue = queue
        self.transfer = transfer
        self.engine_id = engine_id
        self.transfer_timeout = transfer_timeout
        self.stats = {"remote_prefills": 0, "local_prefills": 0, "remote_errors": 0}

    def _connection(self) -> dict:
        if isinstance(self.transfer, LocalKvPipe):
            return {"local": True}
        return self.transfer.address.to_dict()

    async def generate(self, request: Context) -> AsyncIterator[LLMEngineOutput]:
        req = request.data
        if isinstance(req, dict):
            req = PreprocessedRequest.from_dict(req)
            request = request.transfer(req)
        prompt_len = len(req.token_ids or [])
        handle = None
        remote = False
        # fast path: a prompt under the threshold can never go remote
        # (cached prefix only shortens it) — skip the reservation churn
        # and the queue-depth RPC entirely
        if (
            self.router.config.enabled
            and prompt_len > self.router.config.max_local_prefill_length
        ):
            handle = self.engine.begin_remote(request)
        if handle is not None:
            depth = await self.queue.get_depth()
            remote = self.router.prefill_remote(
                prompt_len, handle.seq.cached_prefix, depth
            )
        if not remote:
            if handle is not None:
                self.engine.release_remote(handle)
            self.stats["local_prefills"] += 1
            async for out in self.engine.generate(request):
                yield out
            return

        self.stats["remote_prefills"] += 1
        self.engine.start()
        req_id = request.id
        fut = self.transfer.expect(req_id)
        rpr = RemotePrefillRequest(
            request_id=req_id,
            request=req.to_dict(),
            skip_blocks=handle.skip_blocks,
            connection=self._connection(),
            engine_id=self.engine_id,
            trace=tracing.current_traceparent(),
            enqueue_ts=time.time() if tracing.enabled() else 0.0,
        )
        # decode-side wait for the whole remote leg (queue + prefill +
        # KV transfer); the decomposition subtracts the worker-side spans
        # to isolate the transfer cost
        remote_span = tracing.span(
            "disagg.remote_prefill", request_id=req_id,
            prompt_tokens=prompt_len, skip_blocks=handle.skip_blocks,
        )
        try:
            await self.queue.enqueue(rpr)
            delivery = await asyncio.wait_for(fut, self.transfer_timeout)
        except asyncio.CancelledError:
            # caller went away: clean up the reservation, propagate
            remote_span.set(error="cancelled")
            self.transfer.abandon(req_id)
            self.engine.abort_remote(handle, "cancelled")
            raise
        except Exception as e:  # noqa: BLE001 — timeout, enqueue or
            # transfer-stream failure: blocks must return to the pool
            remote_span.set(error=type(e).__name__)
            self.transfer.abandon(req_id)
            self.stats["remote_errors"] += 1
            self.engine.abort_remote(handle, f"remote prefill failed: {e}")
            yield await handle.seq.out_queue.get()
            return
        finally:
            # the remote leg ends when the delivery future resolves (or
            # fails) — everything after is local scatter/decode work
            remote_span.end()
        if delivery.error:
            self.stats["remote_errors"] += 1
            self.engine.abort_remote(handle, delivery.error)
            yield await handle.seq.out_queue.get()
            return
        k_data, v_data = delivery.k_data, delivery.v_data
        my_layout = self.engine.cfg.kv_head_layout
        my_tp = self.engine.cfg.mesh.tp if self.engine.cfg.mesh else 1
        # interleaved orderings are tp-dependent: same-layout peers with
        # different tp still need the regroup (ref kv_rearrange, patch:743-810)
        mismatched = k_data is not None and (
            delivery.head_layout != my_layout
            or (delivery.head_layout == "interleaved" and delivery.src_tp != my_tp)
        )
        if mismatched:
            from ..ops.kv_rearrange import rearrange_for_decode

            try:
                k_data = rearrange_for_decode(
                    k_data, delivery.src_tp, my_tp, delivery.head_layout, my_layout
                )
                v_data = rearrange_for_decode(
                    v_data, delivery.src_tp, my_tp, delivery.head_layout, my_layout
                )
            except Exception as e:  # noqa: BLE001 — bad peer metadata must
                # not leak the reservation (blocks) or hang the caller
                self.stats["remote_errors"] += 1
                self.engine.abort_remote(handle, f"kv rearrange failed: {e}")
                yield await handle.seq.out_queue.get()
                return
        out_queue = await self.engine.complete_remote(
            handle, delivery.first_token, k_data, v_data,
            first_lp=delivery.first_lp,
        )
        while True:
            out = await out_queue.get()
            if out is None:
                return
            yield out
            if out.is_final():
                return

"""Disaggregated serving workers.

``DisaggEngine`` is the decode-side AsyncEngine: per request it consults
the ConditionalDisaggRouter; local prompts flow straight into the wrapped
JaxEngine, long prompts are pre-allocated (begin_remote), enqueued on the
PrefillQueue, and completed when the prefill worker's KV lands on the
transfer plane (ref examples/llm/components/worker.py:45-189).

``PrefillWorker`` is the queue consumer: prefill + first-token sample on
its own engine/mesh, then push the KV to the requesting decode host
(ref examples/llm/components/prefill_worker.py:84-141). Failures nack the
item so it redelivers to another worker — elastic xPyD
(docs/disagg_serving.md:93-101)."""

from __future__ import annotations

import asyncio
import logging
import time
from typing import AsyncIterator, Optional, Union

import numpy as np

from .. import tracing
from ..engine.engine import JaxEngine, OutOfBlocks
from ..protocols.common import LLMEngineOutput, PreprocessedRequest
from ..resilience import faultpoints
from ..resilience.faultpoints import FaultInjected
from ..runtime.engine import AsyncEngine, AsyncEngineContext, Context
from .protocols import RemotePrefillRequest
from .queue import PrefillQueue
from .transfer import (
    KV_QUANT_WIRE_VERSION,
    KV_STREAM_BASE_VERSION,
    KV_STREAM_VERSION,
    KvStreamSender,
    KvTransferServer,
    LocalKvPipe,
    SinkClosed,
    TransferError,
    send_kv_blocks,
)
from .router import ConditionalDisaggRouter

logger = logging.getLogger(__name__)

#: per-segment wall bound for the streamed handoff's socket sends — the
#: sender's backpressure reaches into prefill compute (device lock held),
#: so a peer that stops reading must fail fast into nack/redelivery
SEGMENT_SEND_TIMEOUT_S = 60.0


class PrefillWorker:
    def __init__(
        self,
        engine: JaxEngine,
        queue: PrefillQueue,
        local_pipe: Optional[LocalKvPipe] = None,
        layer_chunk: int = 4,
        head_layout: Optional[str] = None,
        kv_stream: bool = True,
        segment_blocks: int = 0,
        concurrency: int = 1,
        kv_ici: bool = True,
    ):
        self.engine = engine
        self.queue = queue
        self.local_pipe = local_pipe
        self.layer_chunk = layer_chunk
        # wire-declared kv-head ordering; override only when wrapping an
        # engine whose extraction really produces a non-natural order
        self.head_layout = head_layout or engine.cfg.kv_head_layout
        # streamed layer-wise handoff (FlowKV): open the transfer at
        # prefill start, ship each chunk's blocks as its compute lands.
        # Engages only when the decode peer advertised the capability in
        # its connection info — old peers keep getting the bulk protocol
        self.kv_stream = kv_stream
        self.segment_blocks = segment_blocks
        # ICI same-slice fast path (disagg/ici.py): stamp streamed
        # headers ``ici`` when the decode peer advertised a covering
        # kv_ici version AND the same slice fingerprint — the decode
        # sink then re-lays segments device→device instead of letting
        # the scatter resolve a foreign placement implicitly. Any
        # mismatch silently keeps the plain streamed/TCP path.
        self.kv_ici = kv_ici
        # per-block wire quantization (engine/kvquant.py, the engine's
        # --kv-quant mode): TCP handoffs ship int8/fp8 payloads + scale
        # frames to decode peers that advertised the kv_quant
        # capability — half the DCN bytes per handoff. Local-pipe and
        # ICI handoffs stay full width (they never serialize), and
        # legacy peers get dequantized full-width bytes. getattr: test
        # harnesses wrap engines whose cfg predates the knob.
        self.kv_quant = getattr(engine.cfg, "kv_quant", "none")
        # consume-loop fan-out: with the engine's streamed extract taking
        # the device lock per CHUNK, N concurrent prompts interleave
        # chunk-wise and each streams its segments as its own chunks
        # land — M queued prompts advance together instead of
        # head-of-line blocking on whole-prompt prefills (the disagg
        # twin of the mixed-batch packer). Each loop owns its item's
        # full dequeue->process->ack lifecycle, so the PR 4 no-ack/
        # redeliver semantics are untouched.
        self.concurrency = max(int(concurrency), 1)
        self._tasks: list[asyncio.Task] = []
        self._stop = asyncio.Event()
        # prefill-role send-side counters: asserted by the disagg tests
        # and bench directly from this dict; the router only routes
        # DECODE workers, so none of these belong in WorkerLoad
        self.stats = {
            "prefills_total": 0, "prefill_errors": 0, "nacks": 0,  # dynlint: disable=unscraped-stat -- prefill-role diagnostics; the scrape plane describes decode workers
            "kv_stream_sends": 0, "kv_stream_segments": 0, "kv_bulk_sends": 0,  # dynlint: disable=unscraped-stat -- prefill-role diagnostics; the scrape plane describes decode workers
            "kv_ici_sends": 0,  # dynlint: disable=unscraped-stat -- prefill-role diagnostics; the scrape plane describes decode workers
            "kv_quant_sends": 0,  # dynlint: disable=unscraped-stat -- prefill-role diagnostic; the decode-side tier counters are the gauges
        }

    def _wire_quant(self, connection: dict, local: bool) -> str:
        """Negotiated wire codec for one handoff: this worker's
        --kv-quant mode, IF the channel serializes (never the local
        pipe) and the decode peer advertised the kv_quant capability.
        Everything else — legacy peers above all — gets full width."""
        if (
            self.kv_quant != "none"
            and not local
            and int(connection.get("kv_quant") or 0) >= KV_QUANT_WIRE_VERSION
        ):
            return self.kv_quant
        return "none"

    def start(self) -> None:
        if not self._tasks:
            loop = asyncio.get_running_loop()
            self._tasks = [
                loop.create_task(self.run()) for _ in range(self.concurrency)
            ]

    async def close(self) -> None:
        self._stop.set()
        for t in self._tasks:
            t.cancel()
        self._tasks = []

    MAX_DELIVERIES = 5  # poison-pill cutoff: after this, fail the request

    async def run(self) -> None:
        while not self._stop.is_set():
            try:
                await self._run_once()
            except asyncio.CancelledError:
                return
            except FaultInjected:
                # harness kill: the consume loop DIES (no retry) — the
                # un-acked item redelivers to a surviving consumer, not
                # back to this one
                logger.warning("prefill worker killed by fault point")
                self._stop.set()
                return
            except Exception:  # noqa: BLE001 — transient bus/hub error:
                # the fleet must not silently lose a prefill consumer
                logger.exception("prefill consume loop error; retrying")
                await asyncio.sleep(0.5)

    async def _run_once(self) -> None:
        got = await self.queue.dequeue(timeout=0.5)
        if got is None:
            return
        item_id, rpr = got
        try:
            await self._process(rpr)
        except FaultInjected:
            # harness kill mid-processing: die like a real crash — no
            # ack, no nack, no error notification; the queue's
            # visibility timeout redelivers the item to a survivor
            raise
        except OutOfBlocks:
            # pool full: hand the item back for another worker (or
            # ourselves, once running prefills free their blocks)
            self.stats["nacks"] += 1
            await self.queue.nack(item_id)
            await asyncio.sleep(0.05)
            return
        except TransferError as e:
            # the KV never landed: retriable — unless this item has
            # already bounced enough to look like a dead decode host
            if self.queue.deliveries(item_id) < self.MAX_DELIVERIES:
                logger.warning("kv transfer failed (%s); redelivering", e)
                self.stats["nacks"] += 1
                await self.queue.nack(item_id)
                await asyncio.sleep(0.1)
                return
            logger.error("kv transfer failed %d times: %s", self.MAX_DELIVERIES, e)
            self.stats["prefill_errors"] += 1
            await self._notify_error(rpr, str(e))
        except Exception as e:  # noqa: BLE001 — a COMPUTE failure is
            # deterministic (bad request, model error): another worker
            # would fail identically, so notify the decode side and ack
            logger.exception("remote prefill failed: %s (decode engine %x)",
                             rpr.request_id, rpr.engine_id)
            self.stats["prefill_errors"] += 1
            await self._notify_error(rpr, str(e))
        # the WAL item is acked only here — AFTER the KV handoff
        # committed (or after a deterministic failure was delivered): a
        # worker killed anywhere above leaves the item in flight and the
        # prefill redelivers instead of silently dropping
        await self.queue.ack(item_id)

    async def _process(self, rpr: RemotePrefillRequest) -> None:
        req = PreprocessedRequest.from_dict(rpr.request)
        ctx = AsyncEngineContext(rpr.request_id)
        trace_token = None
        if tracing.enabled() and rpr.trace:
            # continue the decode side's trace across the queue handoff
            tc = tracing.TraceContext.for_request(rpr.request_id, rpr.trace)
            trace_token = tracing.set_trace(tc)
            if rpr.enqueue_ts:
                # queue wait reconstructed from the decode side's enqueue
                # stamp (cross-host wall clocks; see protocols.py)
                waited_s = max(time.time() - rpr.enqueue_ts, 0.0)
                tracing.RECORDER.record_span(
                    "prefill.queue_wait", tc, ts=rpr.enqueue_ts,
                    dur_ms=waited_s * 1e3, request_id=rpr.request_id,
                )
        try:
            # in-process pipe => same device slice: keep KV on device end to
            # end (gather -> pipe -> decode scatter, no host hop); the TCP
            # path needs host bytes anyway. A local-advertising decode may
            # ALSO carry a TCP connect-back address (DisaggEngine
            # tcp_fallback) — a pipe-less worker then delivers over TCP,
            # which is what lets one queue mix same-slice and remote
            # prefill workers (and redeliveries cross between them).
            local = bool(rpr.connection.get("local")) and self.local_pipe is not None
            has_addr = bool(rpr.connection.get("address"))
            if rpr.connection.get("local") and not local and not has_addr:
                # no channel at all: nack/redeliver to a worker that has
                # one instead of failing the request deterministically
                raise TransferError("local connection without pipe")
            # graceful downgrade: stream only when the decode peer
            # advertised a protocol version covering the BASE streamed
            # layout — an old peer (no kv_stream key, or below the
            # base) silently gets the bulk protocol it already speaks.
            # v1 peers still take v2 streams (the v2 scale frames only
            # engage behind the separate kv_quant capability below)
            streamed = (
                self.kv_stream
                and int(rpr.connection.get("kv_stream") or 0)
                >= KV_STREAM_BASE_VERSION
                and hasattr(self.engine, "prefill_extract_stream")
                and (local or has_addr or not rpr.connection.get("local"))
            )
            if streamed:
                await self._process_streamed(rpr, req, ctx, local)
                return
            timings: dict = {}
            compute_span = tracing.span(
                "prefill.compute", request_id=rpr.request_id,
                prompt_tokens=len(req.token_ids), skip_blocks=rpr.skip_blocks,
            )
            with compute_span:
                first, first_lp, k, v = await self.engine.prefill_extract(
                    req, ctx, skip_blocks=rpr.skip_blocks, keep_on_device=local,
                    timings=timings,
                )
                # the d2h gather inside the extract is handoff time, not
                # prompt compute — ttft.py carves it out of this span
                # into the kv_transfer decomposition
                compute_span.set(
                    kv_gather_ms=round(timings.get("gather_ms", 0.0), 3)
                )
            self.stats["prefills_total"] += 1
            layout = self.head_layout
            tp = self.engine.cfg.mesh.tp if self.engine.cfg.mesh else 1
            wire_q = self._wire_quant(rpr.connection, local)
            k_scales = v_scales = None
            if wire_q != "none" and k is not None and k.shape[2]:
                from ..engine import kvquant

                # multi-MB per-block quantize: executor thread, like the
                # d2h it follows — half the DCN bytes for the send below
                k, v, k_scales, v_scales = (
                    await asyncio.get_running_loop().run_in_executor(
                        None, kvquant.quantize_stack, k, v, wire_q
                    )
                )
                self.stats["kv_quant_sends"] += 1
            await faultpoints.hit("mid_kv_transfer", request_id=rpr.request_id)
            send_span = tracing.span(
                "prefill.kv_send", request_id=rpr.request_id,
                local=bool(rpr.connection.get("local")),
            )
            with send_span:
                t0 = time.perf_counter()
                try:
                    if local:
                        await self.local_pipe.deliver(
                            rpr.request_id, first, k, v, head_layout=layout, src_tp=tp,
                            first_lp=first_lp,
                        )
                    else:
                        await send_kv_blocks(
                            rpr.connection, rpr.request_id, first, k, v,
                            layer_chunk=self.layer_chunk, head_layout=layout, src_tp=tp,
                            first_lp=first_lp, kv_quant=wire_q,
                            k_scales=k_scales, v_scales=v_scales,
                        )
                except (TransferError, FaultInjected):
                    raise
                except Exception as e:  # noqa: BLE001 — ANY handoff-stage
                    # failure (connection reset writing the stream,
                    # serialization trouble) means the KV never committed
                    # on the decode side: it must redeliver like a
                    # TransferError, never ack-with-error (which would
                    # strand the decode side waiting out its full
                    # transfer timeout on a prefill nobody will redo)
                    raise TransferError(f"kv handoff failed: {e}") from e
                # bulk handoff: the ENTIRE send sits after prefill, so it
                # is all exposed transfer time (ttft.py reads these attrs)
                send_span.set(
                    exposed_ms=round((time.perf_counter() - t0) * 1e3, 3),
                    hidden_ms=0.0,
                )
            self.stats["kv_bulk_sends"] += 1
        finally:
            if trace_token is not None:
                tracing.reset_trace(trace_token)

    async def _process_streamed(
        self, rpr: RemotePrefillRequest, req: PreprocessedRequest, ctx, local: bool
    ) -> None:
        """Streamed handoff: open the transfer BEFORE prefill compute,
        pump each chunk's blocks through a bounded send queue while the
        next chunk computes, finish with the sampled first token and the
        stream's single end-to-end ack. Failure semantics match the bulk
        path exactly: transfer trouble -> TransferError (nack/redeliver),
        fault kill -> crash-like no-ack, compute error -> propagates for
        the deterministic error notification."""
        engine = self.engine
        layout = self.head_layout
        tp = engine.cfg.mesh.tp if engine.cfg.mesh else 1
        n_prompt = engine.n_prompt_blocks(len(req.token_ids))
        n = max(n_prompt - rpr.skip_blocks, 0)
        kc, vc = engine.k_cache, engine.v_cache
        # ICI fast path: negotiated on SLICE IDENTITY, not channel —
        # the decode peer advertised a covering kv_ici version and the
        # same slice fingerprint as this engine's devices. In-process
        # (LocalKvPipe) handoffs stay device-resident end to end;
        # launched same-slice roles ship wire segments but the decode
        # sink still lands them through the compiled per-bucket mover
        # programs onto its cache layout (mesh-agnostic placement)
        # instead of letting the scatter resolve a foreign placement
        # implicitly. A kv-head-layout mismatch drops it (the decode
        # sink's regroup owns that case), keeping the fallback matrix
        # clean
        from .ici import ici_negotiated

        ici = (
            ici_negotiated(rpr.connection, engine, enabled=self.kv_ici)
            and layout == rpr.connection.get("ici_layout", layout)
        )
        # streamed wire quantization: negotiated like the bulk path,
        # plus the receiver must speak the v2 frame layout; ICI
        # handoffs stay full width (their segments land device→device
        # through the mover — quantizing would add a host round-trip)
        wire_q = self._wire_quant(rpr.connection, local)
        if ici or int(rpr.connection.get("kv_stream") or 0) < KV_STREAM_VERSION:
            wire_q = "none"
        if wire_q != "none":
            from ..engine.kvquant import quant_dtype

            wire_dtype = str(quant_dtype(wire_q))
        else:
            wire_dtype = str(kc.dtype)
        head = {
            "request_id": rpr.request_id,
            "stream": KV_STREAM_VERSION,
            "n_blocks": n,
            "shape": [kc.shape[0], kc.shape[1], n, kc.shape[3], kc.shape[4]],
            "v_shape": [vc.shape[0], vc.shape[1], n, vc.shape[3], vc.shape[4]],
            "dtype": wire_dtype,
            "layer_chunk": self.layer_chunk,
            "head_layout": layout,
            "src_tp": tp,
        }
        if wire_q != "none":
            head["kv_quant"] = wire_q
        if ici:
            from ..parallel.mesh import slice_fingerprint

            head["ici"] = 1
            head["ici_fp"] = slice_fingerprint()
        await faultpoints.hit("mid_kv_transfer", request_id=rpr.request_id)
        send_span = tracing.span(
            "prefill.kv_send", request_id=rpr.request_id, local=local,
            streamed=True,
        )
        # the connection opens at prefill START — segment i's wire time
        # hides behind chunk i+1's compute (FlowKV, ROADMAP item 1)
        if local:
            assert self.local_pipe is not None
            stream = await self.local_pipe.open_stream(rpr.request_id, head)
        else:
            try:
                stream = await KvStreamSender.open(
                    rpr.connection, rpr.request_id, head
                )
            except TransferError as e:
                send_span.set(error=type(e).__name__)
                send_span.end()
                raise
        sendq: asyncio.Queue = asyncio.Queue(maxsize=2)

        send_ms = 0.0

        async def pump() -> None:
            nonlocal send_ms
            while True:
                item = await sendq.get()
                if item is None:
                    return
                t_s = time.perf_counter()
                try:
                    # the pump's backpressure reaches into prefill compute
                    # (emit_upto blocks on the queue under the DEVICE
                    # lock), so a half-open peer that stops reading must
                    # become a bounded TransferError -> nack, not a
                    # forever-wedged prefill engine
                    await asyncio.wait_for(
                        stream.send_segment(*item), SEGMENT_SEND_TIMEOUT_S
                    )
                except (TransferError, FaultInjected):
                    raise
                except asyncio.TimeoutError as e:
                    raise TransferError(
                        f"kv segment send stalled > {SEGMENT_SEND_TIMEOUT_S}s"
                    ) from e
                except Exception as e:  # noqa: BLE001 — same contract as
                    # the bulk handoff stage: an uncommitted segment must
                    # redeliver, never ack-with-error
                    raise TransferError(f"kv segment handoff failed: {e}") from e
                send_ms += (time.perf_counter() - t_s) * 1e3
                self.stats["kv_stream_segments"] += 1

        pump_task = asyncio.get_running_loop().create_task(pump())

        async def put_or_fail(item) -> None:
            # never block on a queue whose consumer died: race the put
            # against the pump so a send failure surfaces immediately
            put = asyncio.ensure_future(sendq.put(item))
            done, _ = await asyncio.wait(
                {put, pump_task}, return_when=asyncio.FIRST_COMPLETED
            )
            if put in done:
                return
            put.cancel()
            exc = pump_task.exception()
            raise exc if exc else TransferError("kv stream sender stopped")

        async def on_segment(b0: int, k_seg, v_seg) -> None:
            await faultpoints.hit("mid_kv_transfer", request_id=rpr.request_id)
            if not local:
                # segment-sized (multi-MB) device->host materialization
                # (+ the per-block wire quantize when negotiated): off
                # the loop, or the whole engine freezes for the copy
                # while prefill compute should be hiding it
                def _materialize():
                    k_np, v_np = np.asarray(k_seg), np.asarray(v_seg)
                    if wire_q != "none":
                        from ..engine import kvquant

                        return kvquant.quantize_stack(k_np, v_np, wire_q)
                    return k_np, v_np, None, None
                k_np, v_np, ks, vs = (
                    await asyncio.get_running_loop().run_in_executor(
                        None, _materialize
                    )
                )
                if ks is not None:
                    await put_or_fail((b0, k_np, v_np, ks, vs))
                    return
                await put_or_fail((b0, k_np, v_np))
                return
            await put_or_fail((b0, k_seg, v_seg))

        ok = False
        timings: dict = {}
        try:
            compute_span = tracing.span(
                "prefill.compute", request_id=rpr.request_id,
                prompt_tokens=len(req.token_ids), skip_blocks=rpr.skip_blocks,
            )
            with compute_span:
                first, first_lp, _sent = await engine.prefill_extract_stream(
                    req, ctx, skip_blocks=rpr.skip_blocks, keep_on_device=local,
                    segment_blocks=self.segment_blocks, on_segment=on_segment,
                    timings=timings,
                )
                # per-segment gathers OVERLAP the wire transfer of the
                # segments already shipped — unlike the bulk path's
                # whole-stack gather (which nothing overlaps, so it's
                # carved into kv_transfer_exposed via kv_gather_ms),
                # they are pipeline stages, recorded for observability
                # but left inside the prefill region
                compute_span.set(
                    seg_gather_ms=round(timings.get("gather_ms", 0.0), 3)
                )
            self.stats["prefills_total"] += 1
            t_done = time.perf_counter()
            await put_or_fail(None)
            await pump_task  # drains the tail; raises on send failure
            await stream.finish(first, first_lp)
            ok = True
            self.stats["kv_stream_sends"] += 1
            if ici:
                self.stats["kv_ici_sends"] += 1
            if wire_q != "none":
                self.stats["kv_quant_sends"] += 1
            # exposed = the post-compute tail (final drain + fin + ack);
            # hidden = ACTUAL send activity that overlapped compute (the
            # pump's measured per-segment send time minus the part that
            # ran in the tail) — not the open-to-finish window, which
            # would misreport the whole prefill duration as transfer.
            # ttft.py folds these into the PR 2 decomposition
            now = time.perf_counter()
            exposed_ms = (now - t_done) * 1e3
            nbytes = n * (
                getattr(engine, "kv_wire_block_bytes", 0)
                if wire_q != "none"
                else getattr(engine, "kv_block_bytes", 0)
            )
            send_span.set(
                exposed_ms=round(exposed_ms, 3),
                hidden_ms=round(max(send_ms - exposed_ms, 0.0), 3),
                segments=stream.segments,
                n_blocks=n,
                # link class + volume: the span doubles as a transfer-
                # cost observation (tracing/ttft.cost_observations)
                link="ici" if ici else ("local" if local else "dcn"),
                nbytes=nbytes,
            )
            # calibrate the sender's cost model from its own measured
            # send activity: cross-host streamed sends are the "dcn"
            # class (the ici class is observed decode-side, where the
            # mover+scatter wall is the honest number)
            cost = getattr(engine, "cost", None)
            if cost is not None and not local and send_ms > 0 and nbytes:
                cost.observe("dcn", nbytes, send_ms / 1e3)
        finally:
            if not pump_task.done():
                pump_task.cancel()
                # cancel alone is NOT enough: if it lands while the pump
                # awaits a segment scatter riding run_in_executor, the
                # executor future is uncancellable once its fn is running
                # — asyncio swallows the cancellation waiting it out, and
                # the pump then parks on sendq.get() forever, deadlocking
                # this drain against a producer that is already unwinding
                # (found as a ~40% hang of the mid-stream kill tests).
                # Feed the shutdown sentinel so a cancel-surviving pump
                # exits through its normal path (pending segments are
                # discarded — this attempt is abandoned, and no-ack means
                # the queue redelivers it whole), and bound the drain so
                # teardown can never wedge the consume loop regardless.
                while not sendq.empty():
                    sendq.get_nowait()
                try:
                    sendq.put_nowait(None)
                except asyncio.QueueFull:
                    pass  # pump is mid-get of the last item; the
                    # sentinel slot frees by the time it looks again
                try:
                    await asyncio.wait_for(pump_task, SEGMENT_SEND_TIMEOUT_S)
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
            if not ok:
                await stream.aclose()
            send_span.end()

    async def _notify_error(self, rpr: RemotePrefillRequest, message: str) -> None:
        try:
            if rpr.connection.get("local") and self.local_pipe is not None:
                await self.local_pipe.deliver(
                    rpr.request_id, -1, None, None, error=message
                )
            elif rpr.connection.get("address"):
                await send_kv_blocks(
                    rpr.connection, rpr.request_id, -1, None, None, error=message
                )
        except Exception:  # noqa: BLE001 — decode side also has a timeout
            logger.exception("error notification failed: %s", rpr.request_id)


class _RemoteScatterSink:
    """Decode-side landing policy for ONE streamed remote prefill: each
    segment scatters into the request's pre-allocated pages the moment
    it arrives (engine.scatter_remote_segment), so the full-stack buffer
    never materializes and only the final segment's tail can sit on
    TTFT. A kv-head-layout / tp mismatch no longer declines the stream:
    the head-axis permutation (ops/kv_rearrange) is block-independent,
    so each segment regroups ON ARRIVAL — mismatched peers stream too
    (ROADMAP item 1's last leftover). ``begin`` validates the
    permutation against the declared geometry and only falls back to
    the buffered bulk path when no valid regroup exists (bad peer
    metadata — the bulk delivery's regroup then surfaces the error
    through the existing abort path). ``aclose`` waits out any
    in-flight scatter before the caller frees the reservation, so an
    abandoned stream can never write into recycled pages."""

    def __init__(self, engine: JaxEngine, handle, stats: dict):
        self._engine = engine
        self._handle = handle
        self._stats = stats
        self._closed = False
        self._lock = asyncio.Lock()
        self._regroup = None  # (src_tp, dst_tp, src_layout, dst_layout)
        self._ici = None  # IciSegmentMover when the ICI path negotiated
        self.segments = 0

    async def begin(self, head: dict) -> bool:
        if self._closed:
            return False
        my_layout = self._engine.cfg.kv_head_layout
        my_tp = self._engine.cfg.mesh.tp if self._engine.cfg.mesh else 1
        layout = head.get("head_layout", "blocked")
        src_tp = head.get("src_tp", 1)
        self._regroup = None
        self._ici = None
        from ..ops.kv_rearrange import layout_mismatched

        if layout_mismatched(layout, src_tp, my_layout, my_tp):
            from ..ops.kv_rearrange import rearrange_for_decode

            # validate the permutation NOW against both declared head
            # geometries (k and v differ for MLA latents): a geometry
            # the regroup can't cover must take the bulk fallback at
            # begin-time, not poison the stream mid-flight
            shape = tuple(head.get("shape") or ())
            v_shape = tuple(head.get("v_shape") or shape)
            try:
                for hkv in {shape[1], v_shape[1]}:
                    rearrange_for_decode(
                        np.empty((1, hkv, 0, 1, 1), np.int8),
                        src_tp, my_tp, layout, my_layout,
                    )
            except Exception:  # noqa: BLE001 — bad peer metadata
                return False
            self._regroup = (src_tp, my_tp, layout, my_layout)
        if head.get("ici") and self._regroup is None:
            # ICI fast path: the sender negotiated the same-slice
            # device→device handoff (fingerprint re-checked here —
            # defense against a stale connection dict) and the layouts
            # agree. Mover construction failure just leaves the plain
            # streamed landing in charge; the stream stays valid.
            from ..disagg.ici import IciSegmentMover
            from ..parallel.mesh import cache_sharding, slice_fingerprint

            try:
                if head.get("ici_fp") in (None, slice_fingerprint()):
                    eng = self._engine
                    sh = (
                        cache_sharding(eng.mesh, eng.cfg.model)
                        if eng.mesh is not None else None
                    )
                    self._ici = IciSegmentMover(sh, sh)
                    self._stats["ici_handoffs"] = (
                        self._stats.get("ici_handoffs", 0) + 1
                    )
            except Exception:  # noqa: BLE001 — fast path is optional
                logger.debug("ici mover setup failed; plain streamed "
                             "landing", exc_info=True)
                self._ici = None
        # a redelivered stream restarts from block 0 — re-scatters over
        # the same uncommitted pages are idempotent
        self.segments = 0
        return True

    async def segment(self, b0: int, k_seg, v_seg,
                      k_scales=None, v_scales=None) -> None:
        async with self._lock:
            if self._closed:
                raise SinkClosed(self._handle.seq.context.id)
            if self._regroup is not None:
                from ..ops.kv_rearrange import rearrange_for_decode

                src_tp, dst_tp, sl, dl = self._regroup
                # pure head-axis gather; on device-resident segments
                # (local pipe) XLA fuses it into the scatter. Valid on
                # quantized payloads unchanged: the codec's scales are
                # per (layer, block) — deliberately kv-head-free
                k_seg = rearrange_for_decode(k_seg, src_tp, dst_tp, sl, dl)
                v_seg = rearrange_for_decode(v_seg, src_tp, dst_tp, sl, dl)
                self._stats["kv_stream_regroups"] = (
                    self._stats.get("kv_stream_regroups", 0) + 1
                )
            t0 = time.perf_counter()
            if self._ici is not None:
                # ICI fast path: explicit device→device re-layout onto
                # the decode cache's sharding (compiled per geometry
                # bucket) — the scatter below then lands same-placed
                # arrays instead of resolving a foreign one implicitly
                k_seg, v_seg = self._ici.move(k_seg, v_seg)
                self._stats["ici_segments"] = (
                    self._stats.get("ici_segments", 0) + 1
                )
            if k_scales is not None:
                await self._engine.scatter_remote_segment(
                    self._handle, b0, k_seg, v_seg, k_scales, v_scales
                )
            else:
                # positional-compat with the pre-quant signature:
                # full-width segments keep the 4-arg call shape
                await self._engine.scatter_remote_segment(
                    self._handle, b0, k_seg, v_seg
                )
            if self._ici is not None:
                # the moved+scattered wall is the decode side's honest
                # per-segment ICI cost — folding it into the engine's
                # cost model is what lets routing learn this link class
                cost = getattr(self._engine, "cost", None)
                nbytes = getattr(k_seg, "nbytes", 0) + getattr(
                    v_seg, "nbytes", 0
                )
                if cost is not None and nbytes:
                    cost.observe(
                        "ici", nbytes,
                        max(time.perf_counter() - t0, 1e-9),
                    )
            self.segments += 1
            self._stats["kv_stream_segments"] += 1

    async def aclose(self) -> None:
        self._closed = True
        async with self._lock:
            pass


class DisaggEngine(AsyncEngine):
    """Decode-side conditional-disaggregation front (AsyncEngine over
    PreprocessedRequest -> LLMEngineOutput stream)."""

    def __init__(
        self,
        engine: JaxEngine,
        router: ConditionalDisaggRouter,
        queue: PrefillQueue,
        transfer: Union[KvTransferServer, LocalKvPipe],
        engine_id: int = 0,
        transfer_timeout: float = 120.0,
        kv_stream: bool = True,
        kv_ici: bool = True,
        tcp_fallback: Optional[KvTransferServer] = None,
    ):
        self.engine = engine
        self.router = router
        self.queue = queue
        self.transfer = transfer
        self.engine_id = engine_id
        self.transfer_timeout = transfer_timeout
        # optional second delivery channel for LocalKvPipe engines: the
        # connection then carries BOTH the in-process flag and a real
        # TCP address, so one prefill queue can serve same-slice workers
        # (pipe, ICI fast path) and remote workers (TCP) — and a
        # redelivery after a same-slice worker dies mid-stream lands
        # over TCP from a survivor. Ignored unless transfer is a pipe.
        self._tcp = (
            tcp_fallback if isinstance(transfer, LocalKvPipe) else None
        )
        # advertise the streamed-handoff capability to prefill workers;
        # off = force the legacy bulk protocol end to end
        self.kv_stream = kv_stream
        # advertise the ICI same-slice fast path (disagg/ici.py):
        # version + slice fingerprint + kv-head layout ride connection
        # info; a prefill worker on the same slice then marks its
        # streamed headers ``ici`` and the scatter sink re-lays segments
        # device→device. Off = plain streamed/bulk everywhere.
        self.kv_ici = kv_ici
        # delivery-flavor counters ride to gauges (streamed_deliveries/
        # bulk_deliveries/kv_stream_segments/ici_handoffs in WorkerLoad);
        # the rest are handoff diagnostics the disagg tests assert on
        # directly
        self.stats = {
            "remote_prefills": 0, "local_prefills": 0, "remote_errors": 0,  # dynlint: disable=unscraped-stat -- disagg-path diagnostics asserted by tests/bench; not router inputs
            "streamed_deliveries": 0, "bulk_deliveries": 0,
            "kv_stream_segments": 0, "kv_stream_regroups": 0,  # dynlint: disable=unscraped-stat -- regroup count is a handoff diagnostic, not a router input
            "ici_handoffs": 0, "ici_segments": 0,  # dynlint: disable=unscraped-stat -- per-segment volume is a diagnostic; ici_handoffs is the gauge
        }

    def _connection(self) -> dict:
        if isinstance(self.transfer, LocalKvPipe):
            conn = {"local": True}
            if self._tcp is not None:
                conn.update(self._tcp.address.to_dict())
        else:
            conn = self.transfer.address.to_dict()
        if self.kv_stream:
            conn["kv_stream"] = KV_STREAM_VERSION
        if self.engine.mirror is None:
            # wire-codec capability: this decode side dequantizes
            # int8/fp8 deliveries on landing (scales through the
            # device-side scatter), independent of its OWN --kv-quant
            # mode. Mirror-backed engines scatter via lockstep
            # broadcasts that are full-width only — they must not
            # advertise it.
            conn["kv_quant"] = KV_QUANT_WIRE_VERSION
        if self.kv_ici and self.kv_stream and self.engine.mirror is None:
            from ..parallel.mesh import slice_fingerprint
            from .ici import KV_ICI_VERSION

            conn["kv_ici"] = KV_ICI_VERSION
            conn["ici_fp"] = slice_fingerprint()
            conn["ici_layout"] = self.engine.cfg.kv_head_layout
        return conn

    def _expect(self, req_id: str, sink) -> asyncio.Future:
        """Register the pending delivery on every advertised channel
        (pipe + optional TCP fallback) and return one future resolving
        with whichever lands first. The shared sink is attempt-safe:
        ``begin`` re-inits per stream, and the post-delivery sink close
        turns a racing loser's late segments into discards."""
        fut = self.transfer.expect(req_id, sink=sink)
        if self._tcp is None:
            return fut
        fut2 = self._tcp.expect(req_id, sink=sink)

        async def race():
            done, _pending = await asyncio.wait(
                {fut, fut2}, return_when=asyncio.FIRST_COMPLETED
            )
            # both channels can resolve in one loop tick (a late error
            # notification racing the redelivered push): prefer a real
            # KV delivery over an error — failing a request whose KV
            # landed on the other channel would recompute for nothing
            best = None
            for f in done:
                if f.cancelled():
                    continue
                d = f.result()
                if best is None or (
                    getattr(best, "error", None)
                    and not getattr(d, "error", None)
                ):
                    best = d
            if best is None:
                raise asyncio.CancelledError()
            return best

        return asyncio.ensure_future(race())

    def _abandon(self, req_id: str) -> None:
        self.transfer.abandon(req_id)
        if self._tcp is not None:
            self._tcp.abandon(req_id)

    async def generate(self, request: Context) -> AsyncIterator[LLMEngineOutput]:
        req = request.data
        if isinstance(req, dict):
            req = PreprocessedRequest.from_dict(req)
            request = request.transfer(req)
        prompt_len = len(req.token_ids or [])
        handle = None
        remote = False
        # fast path: a prompt under the threshold can never go remote
        # (cached prefix only shortens it) — skip the reservation churn
        # and the queue-depth RPC entirely
        if (
            self.router.config.enabled
            and prompt_len > self.router.config.max_local_prefill_length
        ):
            handle = self.engine.begin_remote(request)
        if handle is not None:
            depth = await self.queue.get_depth()
            remote = self.router.prefill_remote(
                prompt_len, handle.seq.cached_prefix, depth
            )
        if not remote:
            if handle is not None:
                self.engine.release_remote(handle)
            self.stats["local_prefills"] += 1
            async for out in self.engine.generate(request):
                yield out
            return

        self.stats["remote_prefills"] += 1
        self.engine.start()
        req_id = request.id
        sink = (
            _RemoteScatterSink(self.engine, handle, self.stats)
            if self.kv_stream else None
        )
        fut = self._expect(req_id, sink)
        rpr = RemotePrefillRequest(
            request_id=req_id,
            request=req.to_dict(),
            skip_blocks=handle.skip_blocks,
            connection=self._connection(),
            engine_id=self.engine_id,
            trace=tracing.current_traceparent(),
            enqueue_ts=time.time() if tracing.enabled() else 0.0,
        )
        # decode-side wait for the whole remote leg (queue + prefill +
        # KV transfer); the decomposition subtracts the worker-side spans
        # to isolate the transfer cost
        remote_span = tracing.span(
            "disagg.remote_prefill", request_id=req_id,
            prompt_tokens=prompt_len, skip_blocks=handle.skip_blocks,
        )
        t_handoff = time.perf_counter()
        try:
            await self.queue.enqueue(rpr)
            delivery = await asyncio.wait_for(fut, self.transfer_timeout)
            # whole remote leg (queue + prefill + KV transfer) into the
            # worker's handoff distribution (SLO observatory plane)
            self.engine.hist["handoff_ms"].observe(
                (time.perf_counter() - t_handoff) * 1e3
            )
        except asyncio.CancelledError:
            # caller went away: clean up the reservation, propagate.
            # The sink must close BEFORE abort_remote frees the blocks —
            # an in-flight streamed scatter may still be writing them
            remote_span.set(error="cancelled")
            self._abandon(req_id)
            if sink is not None:
                await sink.aclose()
            self.engine.abort_remote(handle, "cancelled")
            raise
        except Exception as e:  # noqa: BLE001 — timeout, enqueue or
            # transfer-stream failure: blocks must return to the pool
            remote_span.set(error=type(e).__name__)
            self._abandon(req_id)
            if sink is not None:
                await sink.aclose()
            self.stats["remote_errors"] += 1
            self.engine.abort_remote(handle, f"remote prefill failed: {e}")
            yield await handle.seq.out_queue.get()
            return
        finally:
            # the remote leg ends when the delivery future resolves (or
            # fails) — everything after is local scatter/decode work
            remote_span.end()
        # one channel delivered: retire the OTHER channel's pending
        # entry (no-op single-channel) so a late duplicate push into a
        # recycled request id can never land — it discards+acks instead
        self._abandon(req_id)
        if delivery.error:
            self.stats["remote_errors"] += 1
            if sink is not None:
                await sink.aclose()
            self.engine.abort_remote(handle, delivery.error)
            yield await handle.seq.out_queue.get()
            return
        if delivery.streamed:
            self.stats["streamed_deliveries"] += 1
        else:
            self.stats["bulk_deliveries"] += 1
        if sink is not None:
            # the delivery is complete: a STALE concurrent attempt (a
            # visibility-timeout redelivery racing the winner) must not
            # scatter into these pages once they commit and go live for
            # decode — closing the sink turns its late segments into
            # SinkClosed -> discard, and waits out any in-flight scatter
            # before the commit below
            await sink.aclose()
        k_data, v_data = delivery.k_data, delivery.v_data
        my_layout = self.engine.cfg.kv_head_layout
        my_tp = self.engine.cfg.mesh.tp if self.engine.cfg.mesh else 1
        from ..ops.kv_rearrange import layout_mismatched

        mismatched = k_data is not None and layout_mismatched(
            delivery.head_layout, delivery.src_tp, my_layout, my_tp
        )
        if mismatched:
            from ..ops.kv_rearrange import rearrange_for_decode

            try:
                # head-axis permutation only — valid on quantized
                # payloads as-is (the block scales are kv-head-free)
                k_data = rearrange_for_decode(
                    k_data, delivery.src_tp, my_tp, delivery.head_layout, my_layout
                )
                v_data = rearrange_for_decode(
                    v_data, delivery.src_tp, my_tp, delivery.head_layout, my_layout
                )
            except Exception as e:  # noqa: BLE001 — bad peer metadata must
                # not leak the reservation (blocks) or hang the caller
                self.stats["remote_errors"] += 1
                self.engine.abort_remote(handle, f"kv rearrange failed: {e}")
                yield await handle.seq.out_queue.get()
                return
        out_queue = await self.engine.complete_remote(
            handle, delivery.first_token, k_data, v_data,
            first_lp=delivery.first_lp,
            k_scales=delivery.k_scales, v_scales=delivery.v_scales,
        )
        while True:
            out = await out_queue.get()
            if out is None:
                return
            yield out
            if out.is_final():
                return

"""dynamo_tpu — a TPU-native distributed LLM inference serving framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of NVIDIA Dynamo
(reference: /root/reference): disaggregated prefill/decode serving, KV-cache
aware routing over a radix prefix index, a multi-tier paged KV block manager
(TPU HBM <-> host DRAM), an OpenAI-compatible HTTP frontend, and a
distributed asyncio runtime (lease-based discovery + message bus + TCP
response streaming).

Layer map (mirrors reference SURVEY.md section 1, re-architected for TPU):

  L0  transports      dynamo_tpu.runtime.{store,bus,tcp}   control/request/response planes
  L1  runtime         dynamo_tpu.runtime                   Runtime, DistributedRuntime, components
  L2  pipeline        dynamo_tpu.runtime.{engine,pipeline} AsyncEngine, typed operator graph
  L3  llm library     dynamo_tpu.{protocols,llm,kv_router,kv,http}
  L4  launch          dynamo_tpu.launch                    dynamo-run equivalent CLI
  L6  sdk             dynamo_tpu.sdk                       service graphs + supervisor
  --  tpu engine      dynamo_tpu.{models,ops,parallel,engine}  the native JAX worker
"""

__version__ = "0.1.0"
